"""Windowed ECDSA-P256 verify on the flat field layer (Pallas & XLA).

Round-2 rework of the hot kernel per VERDICT.md #1: replaces the 1-bit
Shamir ladder (256 complete adds) of ops/weierstrass.py with

  u1*G:  a fixed-base comb — COMB_WINDOWS windows of COMB_W bits over a
         host-precomputed table of affine points (k * 2^(COMB_W*j) * G),
         selected per batch
         element by an exact one-hot f32 matmul (MXU; limbs <= 2^12 are
         exact in f32) and accumulated with COMB_WINDOWS mixed (Z2=1) adds;
  u2*Q:  a 4-bit unsigned windowed ladder — a per-batch 16-entry Jacobian
         table (7 dbl + 7 add), then 65 windows of (4 dbl + 1 add) over
         the MSB-first digits of u2;

~4.4k field muls per verify vs ~8.6k for the round-1 ladder, with every
field op scan-free (ops/flatfield.py) so the whole verify lowers into one
flat XLA program (a fused Pallas variant was tried through round 4
and removed in round 5: the axon libtpu compile helper SIGABRTs on its
AOT path, and the XLA lane already saturates the relayed transport).

Degenerate-case handling (adversarial completeness):
  * ladder adds: acc = v*Q with v = 16*prefix(u2) in [16, n); the addend is
    d*Q, d in [1,15].  v == d is impossible (v >= 16); v == n - d (i.e.
    P == -Q -> infinity) IS reachable for digits d with n =- d mod 16, so
    adds patch h==0 -> infinity; v == n + d is unreachable (v < n).  The
    P == Q (doubling) case therefore cannot occur for an on-curve Q of
    order n (P-256 has cofactor 1: every finite point has order n); for
    off-curve/garbage Q the formula may produce garbage, which is gated by
    the caller's on-curve verdict bit.  Infinity operands are tracked by an
    explicit flag, not by Z == 0 tests.
  * comb adds: acc = w*G with w < 2^(Wk) and addend d*2^(Wk)*G; w == +-d*2^(Wk)
    mod n requires u1 == n, excluded since u1 < n.  Only d == 0 / acc == inf
    need patching.
  * the final comb+ladder combine uses a fully complete add (P == +-Q is
    reachable there when u1*G == +-u2*Q, craftable by a key owner).

Semantics target (bit-identical accept/reject): the reference's verifyECDSA
/root/reference/bccsp/sw/ecdsa.go:41-58 with mandatory low-S
(bccsp/utils/ecdsa.go:84), digest-only inputs (msp/identities.go:178).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import bignum as bn
from . import flatfield as ff
from .flatfield import FlatMod, L, LB, MASK

# Curve constants (SEC2 secp256r1)
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
HALF_N = (N - 1) // 2

# 8-bit comb windows: 32 windows x 256 entries.  Vs the round-2..4
# 6-bit comb (43 windows), each verify saves 22 of its 86 mixed adds
# (~25% of the field muls); the wider one-hot lookup matmul is MXU-cheap
# and still exact (table limbs < 2^12, exact in f32).  Table cost:
# (8192, 44) f32 = 1.44 MB/key in the device bank, ~3x the host build
# time — amortized by residency (ops/device_bank.py).
COMB_W = 8
COMB_WINDOWS = 32            # 32*8 = 256 bits
COMB_ENTRIES = 1 << COMB_W
LADDER_W = 4
LADDER_WINDOWS = 64          # u2 < n < 2^256

fp = FlatMod(P, "p256.p")
fn = FlatMod(N, "p256.n")

_B_M = fp.const_mont(B)
_A_M = fp.const_mont(A)


# ---------------------------------------------------------------------------
# Host-side affine arithmetic + comb table (pure python ints)
# ---------------------------------------------------------------------------

def _aff_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1 + A) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def _aff_mul(k, pt):
    acc = None
    while k:
        if k & 1:
            acc = _aff_add(acc, pt)
        pt = _aff_add(pt, pt)
        k >>= 1
    return acc


_COMB_CACHE = {}


def comb_table_f32() -> np.ndarray:
    """(COMB_WINDOWS * COMB_ENTRIES, 2 * L) f32: rows of Montgomery-form
    affine limbs [x limbs || y limbs] for k * 2^(COMB_W*j) * G; row
    j*COMB_ENTRIES+k.  k=0 rows are zero (patched at lookup time via the
    digit==0 select).

    Exactness: limbs < 2^12 are exactly representable in f32, and a one-hot
    matmul sums exactly one row — no rounding anywhere.
    """
    if "t" in _COMB_CACHE:
        return _COMB_CACHE["t"]
    from . import p256_tables
    _COMB_CACHE["t"] = p256_tables.comb_table_for_point(GX, GY)
    return _COMB_CACHE["t"]


# ---------------------------------------------------------------------------
# Jacobian point ops (lazy-reduction flat field, explicit infinity flags)
# ---------------------------------------------------------------------------
# A point is (X, Y, Z, inf) with inf a (B,) int32 flag; X,Y,Z Montgomery-
# form LAZILY-REDUCED limbs with the static per-coordinate invariant
#
#     value(X) < 11p,  value(Y) < 4p,  value(Z) < 6p
#
# maintained by every op below with ZERO conditional subtractions (the
# round-2 formulas paid one ~70-op Kogge-Stone cond-sub per mod_add /
# mod_sub / mul_small — about half the cost of a dbl again on top of its
# muls).  Safety rests on two CIOS facts (flatfield mul): operands may
# carry values up to ~16p, and a product a*b <= 256*p^2 emerges < 2p
# (out < p + ab/R with p < R/256).  Each op's bound is derived in a
# trailing comment: "# <k.kp" means value < k.k * p at that point.

def dbl(Pt):
    """Jacobian doubling (dbl-2001-b shape, a = -3), lazy reduction.
    Input invariant (11p, 4p, 6p) -> output (10.2p, 3.4p, 4.5p).  8 muls,
    no cond-subs.  Doubling a 2-torsion point can't arise on P-256 (odd
    order); a Z3=0 output would still be safe downstream."""
    X, Y, Z, inf = Pt
    delta = fp.sqr(Z)                    # 36p^2   -> <1.15p
    gamma = fp.sqr(Y)                    # 16p^2   -> <1.07p
    beta = fp.mul(X, gamma)              # 11.8p^2 -> <1.05p
    t1 = fp.subl(X, delta, 2)            # <13p
    t2 = fp.addl(X, delta)               # <12.2p
    alpha = fp.smalll(fp.mul(t1, t2), 3)  # 159p^2 -> <1.63p; x3 -> <4.9p
    X3 = fp.subl(fp.sqr(alpha), fp.smalll(beta, 8), 9)   # <1.1p + 9p = 10.1p
    w = fp.subl(fp.smalll(beta, 4), X3, 11)              # <15.2p
    # 8*gamma^2 as a MUL output (not a post-scale) keeps Y3's bound small
    m3 = fp.mul(gamma, fp.smalll(gamma, 8))              # 9.2p^2 -> <1.04p
    Y3 = fp.subl(fp.mul(alpha, w), m3, 2)                # <1.3p + 2p = 3.3p
    s = fp.sqr(fp.addl(Y, Z))                            # 100p^2 -> <1.4p
    Z3 = fp.subl(s, fp.addl(gamma, delta), 3)            # <4.4p
    return X3, Y3, Z3, inf


def add_nodbl(Pt, Qt):
    """Complete-except-doubling Jacobian add (see module docstring for the
    reachability argument).  Patches: P inf, Q inf, P == -Q -> infinity.
    P == Q would produce Z3 = 0 (treated as infinity downstream) — only
    possible for inputs outside the guaranteed domain (garbage Q, gated).
    Lazy bounds: inputs (11p, 4p, 6p) -> outputs (5.1p, 3.1p, 1.1p)."""
    X1, Y1, Z1, inf1 = Pt
    X2, Y2, Z2, inf2 = Qt
    z1z1 = fp.sqr(Z1)                    # <1.15p
    z2z2 = fp.sqr(Z2)                    # <1.15p
    u1 = fp.mul(X1, z2z2)                # 12.7p^2 -> <1.05p
    u2 = fp.mul(X2, z1z1)                # <1.05p
    s1 = fp.mul(Y1, fp.mul(Z2, z2z2))    # 6.9p^2 -> <1.03p; then <1.02p
    s2 = fp.mul(Y2, fp.mul(Z1, z1z1))    # <1.02p
    h = fp.subl(u2, u1, 2)               # <3.05p
    r = fp.subl(s2, s1, 2)               # <3.04p
    h2 = fp.sqr(h)                       # 9.3p^2 -> <1.04p
    h3 = fp.mul(h, h2)                   # <1.02p
    u1h2 = fp.mul(u1, h2)                # <1.01p
    X3 = fp.subl(fp.sqr(r),
                 fp.addl(h3, fp.smalll(u1h2, 2)), 4)     # <1.04p + 4p = 5.04p
    w = fp.subl(u1h2, X3, 6)                             # <7.05p
    Y3 = fp.subl(fp.mul(r, w), fp.mul(s1, h3), 2)        # 21.4p^2 -> <3.1p
    Z3 = fp.mul(fp.mul(Z1, Z2), h)       # 36p^2 -> <1.15p; 3.5p^2 -> <1.02p

    # h == 0 means P == -Q (cancel) for in-domain inputs; P == Q is
    # unreachable (module docstring) and maps to infinity too, which is
    # wrong only for garbage Q already gated by the on-curve bit.
    h_zero = fp.is_zero_k(h, 4)
    i1b, i2b = inf1 != 0, inf2 != 0
    cancel = h_zero & ~i1b & ~i2b
    inf3 = (cancel | (i1b & i2b)).astype(jnp.int32)
    sel = fp.select
    X3 = sel(i1b, X2, sel(i2b, X1, X3))
    Y3 = sel(i1b, Y2, sel(i2b, Y1, Y3))
    Z3 = sel(i1b, Z2, sel(i2b, Z1, Z3))
    return X3, Y3, Z3, inf3


def add_complete(Pt, Qt):
    """Fully complete add: also handles P == Q via an embedded doubling.
    Same lazy bounds as add_nodbl; output X bound is max(5.1p, dbl's
    10.2p, the 11p inputs) = 11p."""
    X1, Y1, Z1, inf1 = Pt
    X2, Y2, Z2, inf2 = Qt
    z1z1 = fp.sqr(Z1)
    z2z2 = fp.sqr(Z2)
    u1 = fp.mul(X1, z2z2)
    u2 = fp.mul(X2, z1z1)
    s1 = fp.mul(Y1, fp.mul(Z2, z2z2))
    s2 = fp.mul(Y2, fp.mul(Z1, z1z1))
    h = fp.subl(u2, u1, 2)               # <3.05p
    r = fp.subl(s2, s1, 2)               # <3.04p
    h2 = fp.sqr(h)
    h3 = fp.mul(h, h2)
    u1h2 = fp.mul(u1, h2)
    X3 = fp.subl(fp.sqr(r),
                 fp.addl(h3, fp.smalll(u1h2, 2)), 4)
    w = fp.subl(u1h2, X3, 6)
    Y3 = fp.subl(fp.mul(r, w), fp.mul(s1, h3), 2)
    Z3 = fp.mul(fp.mul(Z1, Z2), h)

    h_zero = fp.is_zero_k(h, 4)
    r_zero = fp.is_zero_k(r, 4)
    Dx, Dy, Dz, _ = dbl(Qt)
    i1b, i2b = inf1 != 0, inf2 != 0
    is_dbl = h_zero & r_zero & ~i1b & ~i2b
    cancel = h_zero & ~r_zero & ~i1b & ~i2b
    sel = fp.select
    X3 = sel(is_dbl, Dx, X3)
    Y3 = sel(is_dbl, Dy, Y3)
    Z3 = sel(is_dbl, Dz, Z3)
    inf3 = (cancel | (i1b & i2b)).astype(jnp.int32)
    X3 = sel(i1b, X2, sel(i2b, X1, X3))
    Y3 = sel(i1b, Y2, sel(i2b, Y1, Y3))
    Z3 = sel(i1b, Z2, sel(i2b, Z1, Z3))
    return X3, Y3, Z3, inf3


def add_mixed(Pt, x2, y2, q_absent):
    """Mixed add (Z2 = 1) for the comb: addend is an affine table entry
    with canonical (< p) coordinates.

    q_absent: (B,) bool — digit == 0, addend is the identity.
    No P == +-Q patches (unreachable; module docstring).  11 muls.
    Lazy bounds: input (11p, 4p, 6p) -> output (5.2p, 3.2p, 1.3p)."""
    X1, Y1, Z1, inf1 = Pt
    z1z1 = fp.sqr(Z1)                    # <1.15p
    u2 = fp.mul(x2, z1z1)                # <1.01p
    s2 = fp.mul(y2, fp.mul(Z1, z1z1))    # <1.01p
    h = fp.subl(u2, X1, 11)              # <12.01p
    r = fp.subl(s2, Y1, 4)               # <5.01p
    h2 = fp.sqr(h)                       # 144p^2 -> <1.57p
    h3 = fp.mul(h, h2)                   # 18.9p^2 -> <1.08p
    u1h2 = fp.mul(X1, h2)                # 17.3p^2 -> <1.07p
    X3 = fp.subl(fp.sqr(r),
                 fp.addl(h3, fp.smalll(u1h2, 2)), 4)     # <1.1p + 4p = 5.1p
    w = fp.subl(u1h2, X3, 6)                             # <7.17p
    Y3 = fp.subl(fp.mul(r, w), fp.mul(Y1, h3), 2)        # 35.9p^2 -> <3.2p
    Z3 = fp.mul(Z1, h)                   # 72p^2 -> <1.3p
    one = fp.one_bc(X1.shape[1:])
    sel = fp.select
    i1b = inf1 != 0
    # P infinite -> take the affine addend; digit 0 -> keep P unchanged.
    X3 = sel(i1b, x2, X3)
    Y3 = sel(i1b, y2, Y3)
    Z3 = sel(i1b, one, Z3)
    X3 = sel(q_absent, X1, X3)
    Y3 = sel(q_absent, Y1, Y3)
    Z3 = sel(q_absent, Z1, Z3)
    inf3 = (i1b & q_absent).astype(jnp.int32)
    return X3, Y3, Z3, inf3


def select_point(cond, Pt, Qt):
    sel = fp.select
    return (sel(cond, Pt[0], Qt[0]), sel(cond, Pt[1], Qt[1]),
            sel(cond, Pt[2], Qt[2]), jnp.where(cond, Pt[3], Qt[3]))


def infinity(bshape):
    # the inf flag is int32 0/1, not bool: Mosaic cannot select i1 vectors
    one = fp.one_bc(bshape)
    return one, one, fp.zero_bc(bshape), jnp.ones(bshape, jnp.int32)


def _infinity_like(bshape, like):
    """infinity() made data-dependent on `like` ((L, B) limbs) by adding
    zeros derived from it: under shard_map, scan carries must share the
    body output's varying-axis type, which constants lack."""
    z = like[0] * 0
    X, Y, Z, inf = infinity(bshape)
    return X + z[None], Y + z[None], Z + z[None], inf + z


# ---------------------------------------------------------------------------
# Digit extraction (flat)
# ---------------------------------------------------------------------------

def ladder_digits(u2_can):
    """(L, B) canonical limbs -> list of LADDER_WINDOWS (B,) int32 digits,
    MSB-first.  4-bit windows align with 12-bit limbs (3 per limb)."""
    digits = []
    for w in range(LADDER_WINDOWS):
        limb = w // 3
        shift = (w % 3) * 4
        digits.append((u2_can[limb] >> shift) & 0xF)
    return digits[::-1]


def comb_digits(u1_can):
    """(L, B) canonical -> list of COMB_WINDOWS (B,) int32 COMB_W-bit
    digits, LSB-first (window j covers bits [W*j, W*j+W))."""
    out = []
    for j in range(COMB_WINDOWS):
        bitpos = COMB_W * j
        limb = bitpos // LB
        off = bitpos % LB
        v = u1_can[limb] >> off
        if off > LB - COMB_W and limb + 1 < L:
            v = v | (u1_can[limb + 1] << (LB - off))
        out.append(v & (COMB_ENTRIES - 1))
    return out


# ---------------------------------------------------------------------------
# Fixed-base comb accumulation (shared by the G half of every verify and
# by the per-key fast path in ops/p256_fixed.py)
# ---------------------------------------------------------------------------

def comb_accumulate(tab_f32, u_can, bshape):
    """u * T for a canonical scalar u (< n, (L, B) limbs) against a comb
    table (COMB_WINDOWS*COMB_ENTRIES, 2L) whose base point T has order n.

    Table lookups are exact one-hot f32 matmuls (MXU; limbs <= 2^12 are
    exactly representable, and one-hot sums select a single row).  Runs
    as a lax.scan when traced; eagerly (python loop over per-primitive
    jits) on concrete inputs — XLA:CPU cannot compile the big scan bodies
    in reasonable time.
    """
    from jax import lax as _lax
    eager = ff._is_concrete(u_can)
    cd = jnp.stack(comb_digits(u_can))                       # (W, B)
    tab = jnp.asarray(tab_f32).reshape(COMB_WINDOWS, COMB_ENTRIES, 2 * L)

    if eager:
        def comb_body(acc, d, rows):
            iota = jnp.arange(COMB_ENTRIES, dtype=jnp.int32).reshape(
                COMB_ENTRIES, *([1] * len(bshape)))
            onehot = (iota == d[None]).astype(jnp.float32)
            # HIGHEST: TPU f32 matmuls default to bf16 passes, which
            # cannot represent 12-bit limbs exactly
            sel = jnp.tensordot(
                rows.T, onehot, axes=1,
                precision=_lax.Precision.HIGHEST).astype(jnp.int32)
            return add_mixed(acc, sel[:L], sel[L:], d == 0)

        acc = infinity(bshape)
        for j in range(COMB_WINDOWS):
            acc = comb_body(acc, cd[j], tab[j])
        return acc

    # Traced: ALL window lookups ride ONE batched matmul up front (43
    # small per-window matmuls inside the scan measured ~26 ms/comb at
    # B=16k — half the fixed-path step — the batched form keeps the MXU
    # busy instead of paying 43 tiny dispatches).
    iota = jnp.arange(COMB_ENTRIES, dtype=jnp.int32).reshape(1, COMB_ENTRIES, 1)
    onehot = (iota == cd[:, None, :]).astype(jnp.float32)    # (W, E, B)
    sel = _lax.dot_general(
        tab, onehot,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        precision=_lax.Precision.HIGHEST).astype(jnp.int32)  # (W, 2L, B)

    def body(acc, xs):
        s, d = xs
        return add_mixed(acc, s[:L], s[L:], d == 0), None

    acc, _ = _lax.scan(body, _infinity_like(bshape, u_can), (sel, cd))
    return acc


def comb_accumulate_rows(bank_f32, row_key, u_can, bshape):
    """Row-grouped multikey comb: u * T[row_key[r]] over a (R, C) grid.

    The round-3 multikey kernel (comb_accumulate_multikey) one-hots over
    the JOINT (key, digit) index, so its lookup matmul cost scales with
    NK — the provider capped NK at 4 and spilled real networks' dozens
    of endorser/client keys to the generic ladder (VERDICT r03 weak #1).
    This kernel removes the cap: the host packs signatures key-MAJOR
    into rows of C lanes where every element of row r shares one key,
    the per-row tables are gathered ONCE per dispatch (R coalesced
    table-row reads — nothing like the catastrophic per-element gather),
    and the digit lookup is a batched one-hot matmul whose cost per
    element is IDENTICAL to the single-key comb, independent of how
    many distinct keys the dispatch carries.

    bank_f32: (K, COMB_WINDOWS*COMB_ENTRIES, 2L) stacked per-key comb
    tables (KeyTableCache layout); row_key: (R,) int32 into the bank;
    u_can: (L, R, C) canonical scalars; bshape == (R, C).
    """
    from jax import lax as _lax
    eager = ff._is_concrete(u_can)
    R, C = bshape
    bank = jnp.asarray(bank_f32, jnp.float32)
    rows = bank[row_key].reshape(R, COMB_WINDOWS, COMB_ENTRIES, 2 * L)
    rows = rows.transpose(1, 0, 3, 2)                    # (W, R, 2L, E)
    cd = jnp.stack(comb_digits(u_can))                   # (W, R, C)
    iota = jnp.arange(COMB_ENTRIES, dtype=jnp.int32).reshape(
        1, 1, COMB_ENTRIES, 1)
    if eager:
        acc = infinity(bshape)
        for j in range(COMB_WINDOWS):
            onehot = (iota[0] == cd[j][:, None, :]).astype(jnp.float32)
            sel = _lax.dot_general(
                rows[j], onehot,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                precision=_lax.Precision.HIGHEST).astype(jnp.int32)
            sel = sel.transpose(1, 0, 2)                 # (2L, R, C)
            acc = add_mixed(acc, sel[:L], sel[L:], cd[j] == 0)
        return acc

    onehot = (iota == cd[:, :, None, :]).astype(jnp.float32)  # (W, R, E, C)
    sel = _lax.dot_general(
        rows, onehot,
        dimension_numbers=(((3,), (2,)), ((0, 1), (0, 1))),
        precision=_lax.Precision.HIGHEST)                # (W, R, 2L, C)
    sel = sel.transpose(0, 2, 1, 3).astype(jnp.int32)    # (W, 2L, R, C)

    def body(acc, xs):
        s, d = xs
        return add_mixed(acc, s[:L], s[L:], d == 0), None

    acc, _ = _lax.scan(body, _infinity_like(bshape, u_can), (sel, cd))
    return acc


# ---------------------------------------------------------------------------
# The verify body (flat jnp; runs under XLA or inside a Pallas kernel)
# ---------------------------------------------------------------------------

def verify_body(qx_l, qy_l, r_l, s_l, e_l, comb_tab_f32, require_low_s=True):
    """Batched ECDSA-P256 verify over canonical integer limbs (L, B).

    comb_tab_f32: (COMB_WINDOWS*COMB_ENTRIES, 2L) f32 table.
    Returns (B,) bool.
    """
    bshape = qx_l.shape[1:]

    # --- range/key checks (reference: ecdsa.go:44-53, utils/ecdsa.go:84) ---
    r_ok = ff.lt_const(r_l, N) & ~ff.is_zero_limbs(r_l)
    s_ok = ff.lt_const(s_l, N) & ~ff.is_zero_limbs(s_l)
    if require_low_s:
        s_ok = s_ok & ff.lt_const(s_l, HALF_N + 1)
    q_ok = ff.lt_const(qx_l, P) & ff.lt_const(qy_l, P)

    qx_m = fp.to_mont(qx_l)
    qy_m = fp.to_mont(qy_l)
    # on-curve: y^2 == x^3 - 3x + b  (lazy: lhs <1.01p, rhs <2.01p)
    lhs = fp.sqr(qy_m)
    rhs = fp.addl(
        fp.mul(fp.addl(fp.sqr(qx_m), ff.const_col(_A_M, 2)), qx_m),
        ff.const_col(_B_M, 2))
    q_ok = q_ok & fp.eq_k(lhs, rhs, 3, 5)

    # --- u1 = e/s, u2 = r/s mod n ---
    s_mn = fn.to_mont(s_l)
    e_mn = fn.to_mont(e_l)
    r_mn = fn.to_mont(r_l)
    w = _inv_n(s_mn, bshape)
    u1 = fn.from_mont(fn.mul(e_mn, w))
    u2 = fn.from_mont(fn.mul(r_mn, w))

    # --- u1*G via comb (lax.scan when traced, python loop when eager) ---
    from jax import lax as _lax
    eager = ff._is_concrete(u1)
    acc_g = comb_accumulate(comb_tab_f32, u1, bshape)

    # --- u2*Q via 4-bit windowed ladder (lax.scan over 64 windows) ---
    # The 16-entry table is built as 2Q = dbl(Q), then a scan of kQ =
    # (k-1)Q + Q for k = 3..15 — the k-1 == +-1 doubling/cancel cases are
    # unreachable there (k-1 >= 2) for an order-n Q, and the scan keeps
    # the traced program small (13 adds compile as ONE body; the round-2
    # unrolled dbl/add tree was ~20k extra HLO ops of pure compile time).
    Q1 = (qx_m, qy_m, fp.one_bc(bshape), jnp.zeros(bshape, jnp.int32))
    T0 = infinity(bshape) if eager else _infinity_like(bshape, qx_m)
    T2 = dbl(Q1)
    if eager:
        T = [T0, Q1, T2]
        for k in range(3, 16):
            T.append(add_nodbl(T[k - 1], Q1))
        TX = jnp.stack([t[0] for t in T])
        TY = jnp.stack([t[1] for t in T])
        TZ = jnp.stack([t[2] for t in T])
        TI = jnp.stack([t[3] for t in T])
    else:
        def tab_body(acc, _):
            nxt = add_nodbl(acc, Q1)
            return nxt, nxt

        _, rest = _lax.scan(tab_body, T2, None, length=13)
        TX, TY, TZ, TI = (
            jnp.concatenate([jnp.stack([a, b, c]), r], axis=0)
            for a, b, c, r in zip(T0, Q1, T2, rest))

    ld = jnp.stack(ladder_digits(u2))                        # (64, B) MSB first

    def ladder_body(acc, d):
        if eager:
            for _ in range(LADDER_W):
                acc = dbl(acc)
        else:
            # fori_loop: the dbl body compiles once, not LADDER_W times
            acc = _lax.fori_loop(0, LADDER_W, lambda _, a: dbl(a), acc)
        ent = (TX[0], TY[0], TZ[0], TI[0])
        for k in range(1, 16):
            ent = select_point(d == k, (TX[k], TY[k], TZ[k], TI[k]), ent)
        return add_nodbl(acc, ent), None

    # first window: no doublings needed (acc starts at infinity, and
    # dbl(infinity) stays infinity anyway — uniform body is correct)
    if eager:
        acc = infinity(bshape)
        for i in range(LADDER_WINDOWS):
            acc, _ = ladder_body(acc, ld[i])
    else:
        acc, _ = _lax.scan(ladder_body, _infinity_like(bshape, u2), ld)
    # --- combine (fully complete: u1*G == +-u2*Q is reachable) ---
    X, Y, Z, inf = add_complete(acc_g, acc)

    nonzero = (inf == 0) & ~fp.is_zero_k(Z, 6)

    # --- projective x-coordinate check: X == (r + k*n)*Z^2, k in {0,1} ---
    # X carries the lazy 11p bound; the mul results are < 2p.
    z2 = fp.sqr(Z)
    r_mp = fp.to_mont(r_l)
    eq1 = fp.eq_k(X, fp.mul(r_mp, z2), 2, 13)
    rn_l = ff.split_rounds(r_l + ff.const_col(bn.int_to_limbs(N),
                                              len(bshape) + 1), 3)
    rn_lt_p = ff.lt_const(rn_l, P)
    eq2 = rn_lt_p & fp.eq_k(X, fp.mul(fp.to_mont(rn_l), z2), 2, 13)

    return r_ok & s_ok & q_ok & nonzero & (eq1 | eq2)


def _inv_n(s_mn, bshape):
    """w = s^-1 mod n on Montgomery forms.

    Traced 1-D batches use the Montgomery-trick product tree (~3 muls per
    element instead of a ~330-mul Fermat ladder); zero elements (s == 0
    mod n — always rejected by the range checks) are pre-selected to 1 so
    they cannot poison the tree, their garbage inverse being gated by
    s_ok.  2-D (row-grid) batches flatten through the same tree.
    Eager/odd-shaped inputs keep the Fermat path.
    """
    if not ff._is_concrete(s_mn):
        if len(bshape) == 2:
            total = bshape[0] * bshape[1]
            if total >= 128 and total % 2 == 0:
                flat = s_mn.reshape(s_mn.shape[0], total)
                s_zero = fn.is_zero_k(flat, 2)
                s_safe = fn.select(s_zero, fn.one_bc((total,)), flat)
                return fn.inv_tree(s_safe).reshape(s_mn.shape)
        elif (len(bshape) == 1 and bshape[0] >= 128
                and bshape[0] % 2 == 0):
            s_zero = fn.is_zero_k(s_mn, 2)
            s_safe = fn.select(s_zero, fn.one_bc(bshape), s_mn)
            return fn.inv_tree(s_safe)
    return fn.inv(s_mn)


def verify_words_xla(qx, qy, r, s, e, require_low_s: bool = True):
    """Plain-XLA entry point: (8, B) uint32 big-endian words -> (B,) bool.

    Deliberately NOT jitted: XLA:CPU's algebraic simplifier loops
    pathologically on the fully-inlined flat graph (minutes per compile).
    Eagerly the scans' bodies still compile, and this path only serves
    CPU tests / functional fallback; TPU production jits verify_body via
    the provider (bccsp/jaxtpu.py)."""
    args = [bn.words_be_to_limbs(v) for v in (qx, qy, r, s, e)]
    return verify_body(*args, comb_table_f32(), require_low_s=require_low_s)
