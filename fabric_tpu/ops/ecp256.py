"""Windowed ECDSA-P256 verify on the flat field layer (Pallas & XLA).

Round-2 rework of the hot kernel per VERDICT.md #1: replaces the 1-bit
Shamir ladder (256 complete adds) of ops/weierstrass.py with

  u1*G:  a fixed-base comb — 43 windows of 6 bits over a host-precomputed
         table of 43*64 affine points (k * 2^(6j) * G), selected per batch
         element by an exact one-hot f32 matmul (MXU; limbs <= 2^12 are
         exact in f32) and accumulated with 43 mixed (Z2=1) adds;
  u2*Q:  a 4-bit unsigned windowed ladder — a per-batch 16-entry Jacobian
         table (7 dbl + 7 add), then 65 windows of (4 dbl + 1 add) over
         the MSB-first digits of u2;

~4.4k field muls per verify vs ~8.6k for the round-1 ladder, with every
field op scan-free (ops/flatfield.py) so the whole verify lowers into one
flat Pallas kernel body (ops/p256_pallas.py) or plain XLA (CPU tests).

Degenerate-case handling (adversarial completeness):
  * ladder adds: acc = v*Q with v = 16*prefix(u2) in [16, n); the addend is
    d*Q, d in [1,15].  v == d is impossible (v >= 16); v == n - d (i.e.
    P == -Q -> infinity) IS reachable for digits d with n =- d mod 16, so
    adds patch h==0 -> infinity; v == n + d is unreachable (v < n).  The
    P == Q (doubling) case therefore cannot occur for an on-curve Q of
    order n (P-256 has cofactor 1: every finite point has order n); for
    off-curve/garbage Q the formula may produce garbage, which is gated by
    the caller's on-curve verdict bit.  Infinity operands are tracked by an
    explicit flag, not by Z == 0 tests.
  * comb adds: acc = w*G with w < 2^(6k) and addend d*2^(6k)*G; w == +-d*2^(6k)
    mod n requires u1 == n, excluded since u1 < n.  Only d == 0 / acc == inf
    need patching.
  * the final comb+ladder combine uses a fully complete add (P == +-Q is
    reachable there when u1*G == +-u2*Q, craftable by a key owner).

Semantics target (bit-identical accept/reject): the reference's verifyECDSA
/root/reference/bccsp/sw/ecdsa.go:41-58 with mandatory low-S
(bccsp/utils/ecdsa.go:84), digest-only inputs (msp/identities.go:178).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import bignum as bn
from . import flatfield as ff
from .flatfield import FlatMod, L, LB, MASK

# Curve constants (SEC2 secp256r1)
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
HALF_N = (N - 1) // 2

COMB_W = 6
COMB_WINDOWS = 43            # 43*6 = 258 >= 256
LADDER_W = 4
LADDER_WINDOWS = 64          # u2 < n < 2^256

fp = FlatMod(P, "p256.p")
fn = FlatMod(N, "p256.n")

_B_M = fp.const_mont(B)
_A_M = fp.const_mont(A)


# ---------------------------------------------------------------------------
# Host-side affine arithmetic + comb table (pure python ints)
# ---------------------------------------------------------------------------

def _aff_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1 + A) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def _aff_mul(k, pt):
    acc = None
    while k:
        if k & 1:
            acc = _aff_add(acc, pt)
        pt = _aff_add(pt, pt)
        k >>= 1
    return acc


_COMB_CACHE = {}


def comb_table_f32() -> np.ndarray:
    """(COMB_WINDOWS * 64, 2 * L) f32: rows of Montgomery-form affine limbs
    [x limbs || y limbs] for k * 2^(6j) * G; row j*64+k.  k=0 rows are zero
    (patched at lookup time via the digit==0 select).

    Exactness: limbs < 2^12 are exactly representable in f32, and a one-hot
    matmul sums exactly one row — no rounding anywhere.
    """
    if "t" in _COMB_CACHE:
        return _COMB_CACHE["t"]
    rows = np.zeros((COMB_WINDOWS * 64, 2 * L), dtype=np.float32)
    base = (GX, GY)
    for j in range(COMB_WINDOWS):
        pt = None
        for k in range(64):
            if k > 0:
                pt = _aff_add(pt, base)
                xm = bn.int_to_limbs(pt[0] * fp.R % P)
                ym = bn.int_to_limbs(pt[1] * fp.R % P)
                rows[j * 64 + k, :L] = xm
                rows[j * 64 + k, L:] = ym
        # base <- 2^6 * base
        for _ in range(COMB_W):
            base = _aff_add(base, base)
    _COMB_CACHE["t"] = rows
    return rows


# ---------------------------------------------------------------------------
# Jacobian point ops (flat field, explicit infinity flags)
# ---------------------------------------------------------------------------
# A point is (X, Y, Z, inf) with inf a (B,) bool; X,Y,Z relaxed Montgomery.

def dbl(Pt):
    """dbl-2001-b for a = -3; complete for Y=0 (gives Z3=0 -> flagged inf
    by the is_zero in add patches never needed: doubling a 2-torsion point
    can't arise on P-256 (odd order), but Z3=0 output is still safe."""
    X, Y, Z, inf = Pt
    delta = fp.sqr(Z)
    gamma = fp.sqr(Y)
    beta = fp.mul(X, gamma)
    alpha = fp.mul_small(fp.mul(fp.mod_sub(X, delta), fp.mod_add(X, delta)), 3)
    beta8 = fp.mul_small(beta, 8)
    X3 = fp.mod_sub(fp.sqr(alpha), beta8)
    Z3 = fp.mod_sub(fp.sqr(fp.mod_add(Y, Z)), fp.mod_add(gamma, delta))
    Y3 = fp.mod_sub(fp.mul(alpha, fp.mod_sub(fp.mul_small(beta, 4), X3)),
                    fp.mul_small(fp.sqr(gamma), 8))
    return X3, Y3, Z3, inf


def add_nodbl(Pt, Qt):
    """Complete-except-doubling Jacobian add (see module docstring for the
    reachability argument).  Patches: P inf, Q inf, P == -Q -> infinity.
    P == Q would produce Z3 = 0 (treated as infinity downstream) — only
    possible for inputs outside the guaranteed domain (garbage Q, gated)."""
    X1, Y1, Z1, inf1 = Pt
    X2, Y2, Z2, inf2 = Qt
    z1z1 = fp.sqr(Z1)
    z2z2 = fp.sqr(Z2)
    u1 = fp.mul(X1, z2z2)
    u2 = fp.mul(X2, z1z1)
    s1 = fp.mul(Y1, fp.mul(Z2, z2z2))
    s2 = fp.mul(Y2, fp.mul(Z1, z1z1))
    h = fp.mod_sub(u2, u1)
    r = fp.mod_sub(s2, s1)
    h2 = fp.sqr(h)
    h3 = fp.mul(h, h2)
    u1h2 = fp.mul(u1, h2)
    X3 = fp.mod_sub(fp.mod_sub(fp.sqr(r), h3), fp.mul_small(u1h2, 2))
    Y3 = fp.mod_sub(fp.mul(r, fp.mod_sub(u1h2, X3)), fp.mul(s1, h3))
    Z3 = fp.mul(fp.mul(Z1, Z2), h)

    # h == 0 means P == -Q (cancel) for in-domain inputs; P == Q is
    # unreachable (module docstring) and maps to infinity too, which is
    # wrong only for garbage Q already gated by the on-curve bit.
    h_zero = fp.is_zero(h)
    i1b, i2b = inf1 != 0, inf2 != 0
    cancel = h_zero & ~i1b & ~i2b
    inf3 = (cancel | (i1b & i2b)).astype(jnp.int32)
    sel = fp.select
    X3 = sel(i1b, X2, sel(i2b, X1, X3))
    Y3 = sel(i1b, Y2, sel(i2b, Y1, Y3))
    Z3 = sel(i1b, Z2, sel(i2b, Z1, Z3))
    return X3, Y3, Z3, inf3


def add_complete(Pt, Qt):
    """Fully complete add: also handles P == Q via an embedded doubling."""
    X1, Y1, Z1, inf1 = Pt
    X2, Y2, Z2, inf2 = Qt
    z1z1 = fp.sqr(Z1)
    z2z2 = fp.sqr(Z2)
    u1 = fp.mul(X1, z2z2)
    u2 = fp.mul(X2, z1z1)
    s1 = fp.mul(Y1, fp.mul(Z2, z2z2))
    s2 = fp.mul(Y2, fp.mul(Z1, z1z1))
    h = fp.mod_sub(u2, u1)
    r = fp.mod_sub(s2, s1)
    h2 = fp.sqr(h)
    h3 = fp.mul(h, h2)
    u1h2 = fp.mul(u1, h2)
    X3 = fp.mod_sub(fp.mod_sub(fp.sqr(r), h3), fp.mul_small(u1h2, 2))
    Y3 = fp.mod_sub(fp.mul(r, fp.mod_sub(u1h2, X3)), fp.mul(s1, h3))
    Z3 = fp.mul(fp.mul(Z1, Z2), h)

    h_zero = fp.is_zero(h)
    r_zero = fp.is_zero(r)
    Dx, Dy, Dz, _ = dbl(Qt)
    i1b, i2b = inf1 != 0, inf2 != 0
    is_dbl = h_zero & r_zero & ~i1b & ~i2b
    cancel = h_zero & ~r_zero & ~i1b & ~i2b
    sel = fp.select
    X3 = sel(is_dbl, Dx, X3)
    Y3 = sel(is_dbl, Dy, Y3)
    Z3 = sel(is_dbl, Dz, Z3)
    inf3 = (cancel | (i1b & i2b)).astype(jnp.int32)
    X3 = sel(i1b, X2, sel(i2b, X1, X3))
    Y3 = sel(i1b, Y2, sel(i2b, Y1, Y3))
    Z3 = sel(i1b, Z2, sel(i2b, Z1, Z3))
    return X3, Y3, Z3, inf3


def add_mixed(Pt, x2, y2, q_absent):
    """Mixed add (Z2 = 1) for the comb: addend is an affine table entry.

    q_absent: (B,) bool — digit == 0, addend is the identity.
    No P == +-Q patches (unreachable; module docstring).  11 muls.
    """
    X1, Y1, Z1, inf1 = Pt
    z1z1 = fp.sqr(Z1)
    u2 = fp.mul(x2, z1z1)
    s2 = fp.mul(y2, fp.mul(Z1, z1z1))
    h = fp.mod_sub(u2, X1)
    r = fp.mod_sub(s2, Y1)
    h2 = fp.sqr(h)
    h3 = fp.mul(h, h2)
    u1h2 = fp.mul(X1, h2)
    X3 = fp.mod_sub(fp.mod_sub(fp.sqr(r), h3), fp.mul_small(u1h2, 2))
    Y3 = fp.mod_sub(fp.mul(r, fp.mod_sub(u1h2, X3)), fp.mul(Y1, h3))
    Z3 = fp.mul(Z1, h)
    one = fp.one_bc(X1.shape[1:])
    sel = fp.select
    i1b = inf1 != 0
    # P infinite -> take the affine addend; digit 0 -> keep P unchanged.
    X3 = sel(i1b, x2, X3)
    Y3 = sel(i1b, y2, Y3)
    Z3 = sel(i1b, one, Z3)
    X3 = sel(q_absent, X1, X3)
    Y3 = sel(q_absent, Y1, Y3)
    Z3 = sel(q_absent, Z1, Z3)
    inf3 = (i1b & q_absent).astype(jnp.int32)
    return X3, Y3, Z3, inf3


def select_point(cond, Pt, Qt):
    sel = fp.select
    return (sel(cond, Pt[0], Qt[0]), sel(cond, Pt[1], Qt[1]),
            sel(cond, Pt[2], Qt[2]), jnp.where(cond, Pt[3], Qt[3]))


def infinity(bshape):
    # the inf flag is int32 0/1, not bool: Mosaic cannot select i1 vectors
    one = fp.one_bc(bshape)
    return one, one, fp.zero_bc(bshape), jnp.ones(bshape, jnp.int32)


# ---------------------------------------------------------------------------
# Digit extraction (flat)
# ---------------------------------------------------------------------------

def ladder_digits(u2_can):
    """(L, B) canonical limbs -> list of LADDER_WINDOWS (B,) int32 digits,
    MSB-first.  4-bit windows align with 12-bit limbs (3 per limb)."""
    digits = []
    for w in range(LADDER_WINDOWS):
        limb = w // 3
        shift = (w % 3) * 4
        digits.append((u2_can[limb] >> shift) & 0xF)
    return digits[::-1]


def comb_digits(u1_can):
    """(L, B) canonical -> list of COMB_WINDOWS (B,) int32 6-bit digits,
    LSB-first (window j covers bits [6j, 6j+6))."""
    out = []
    for j in range(COMB_WINDOWS):
        bitpos = 6 * j
        limb = bitpos // LB
        off = bitpos % LB
        v = u1_can[limb] >> off
        if off > LB - COMB_W and limb + 1 < L:
            v = v | (u1_can[limb + 1] << (LB - off))
        out.append(v & 63)
    return out


# ---------------------------------------------------------------------------
# The verify body (flat jnp; runs under XLA or inside a Pallas kernel)
# ---------------------------------------------------------------------------

def verify_body(qx_l, qy_l, r_l, s_l, e_l, comb_tab_f32, require_low_s=True):
    """Batched ECDSA-P256 verify over canonical integer limbs (L, B).

    comb_tab_f32: (COMB_WINDOWS*64, 2L) f32 table from comb_table_f32().
    Returns (B,) bool.
    """
    bshape = qx_l.shape[1:]

    # --- range/key checks (reference: ecdsa.go:44-53, utils/ecdsa.go:84) ---
    r_ok = ff.lt_const(r_l, N) & ~ff.is_zero_limbs(r_l)
    s_ok = ff.lt_const(s_l, N) & ~ff.is_zero_limbs(s_l)
    if require_low_s:
        s_ok = s_ok & ff.lt_const(s_l, HALF_N + 1)
    q_ok = ff.lt_const(qx_l, P) & ff.lt_const(qy_l, P)

    qx_m = fp.to_mont(qx_l)
    qy_m = fp.to_mont(qy_l)
    # on-curve: y^2 == x^3 - 3x + b
    lhs = fp.sqr(qy_m)
    rhs = fp.mod_add(fp.mul(fp.mod_add(fp.sqr(qx_m), ff.const_col(_A_M, 2)), qx_m),
                     ff.const_col(_B_M, 2))
    q_ok = q_ok & fp.eq(lhs, rhs)

    # --- u1 = e/s, u2 = r/s mod n ---
    s_mn = fn.to_mont(s_l)
    e_mn = fn.to_mont(e_l)
    r_mn = fn.to_mont(r_l)
    w = fn.inv(s_mn)
    u1 = fn.from_mont(fn.mul(e_mn, w))
    u2 = fn.from_mont(fn.mul(r_mn, w))

    # --- u1*G via comb: lax.scan when traced, python loop when eager
    # (XLA:CPU cannot compile the big scan bodies in reasonable time; the
    # eager path drives small per-primitive jits instead) ---
    from jax import lax as _lax
    eager = ff._is_concrete(u1)
    cd = jnp.stack(comb_digits(u1))                          # (43, B)
    tab = jnp.asarray(comb_tab_f32).reshape(COMB_WINDOWS, 64, 2 * L)

    def comb_body(acc, xs):
        d, rows = xs
        iota = jnp.arange(64, dtype=jnp.int32).reshape(64, *([1] * len(bshape)))
        onehot = (iota == d[None]).astype(jnp.float32)
        # HIGHEST: TPU f32 matmuls default to bf16 passes, which cannot
        # represent 12-bit limbs exactly
        sel = jnp.tensordot(rows.T, onehot, axes=1,
                            precision=_lax.Precision.HIGHEST).astype(jnp.int32)
        return add_mixed(acc, sel[:L], sel[L:], d == 0), None

    if eager:
        acc_g = infinity(bshape)
        for j in range(COMB_WINDOWS):
            acc_g, _ = comb_body(acc_g, (cd[j], tab[j]))
    else:
        acc_g, _ = _lax.scan(comb_body, infinity(bshape), (cd, tab))

    # --- u2*Q via 4-bit windowed ladder (lax.scan over 64 windows) ---
    Q1 = (qx_m, qy_m, fp.one_bc(bshape), jnp.zeros(bshape, jnp.int32))
    T = [infinity(bshape), Q1]
    T.append(dbl(Q1))                            # 2Q
    for k in range(3, 16):
        if k % 2 == 0:
            T.append(dbl(T[k // 2]))
        else:
            T.append(add_nodbl(T[k - 1], Q1))
    ld = jnp.stack(ladder_digits(u2))                        # (64, B) MSB first
    TX = jnp.stack([t[0] for t in T])
    TY = jnp.stack([t[1] for t in T])
    TZ = jnp.stack([t[2] for t in T])
    TI = jnp.stack([t[3] for t in T])

    def ladder_body(acc, d):
        for _ in range(LADDER_W):
            acc = dbl(acc)
        ent = (TX[0], TY[0], TZ[0], TI[0])
        for k in range(1, 16):
            ent = select_point(d == k, (TX[k], TY[k], TZ[k], TI[k]), ent)
        return add_nodbl(acc, ent), None

    # first window: no doublings needed (acc starts at infinity, and
    # dbl(infinity) stays infinity anyway — uniform body is correct)
    if eager:
        acc = infinity(bshape)
        for i in range(LADDER_WINDOWS):
            acc, _ = ladder_body(acc, ld[i])
    else:
        acc, _ = _lax.scan(ladder_body, infinity(bshape), ld)
    # --- combine (fully complete: u1*G == +-u2*Q is reachable) ---
    X, Y, Z, inf = add_complete(acc_g, acc)

    nonzero = (inf == 0) & ~fp.is_zero(Z)

    # --- projective x-coordinate check: X == (r + k*n)*Z^2, k in {0,1} ---
    z2 = fp.sqr(Z)
    r_mp = fp.to_mont(r_l)
    eq1 = fp.eq(X, fp.mul(r_mp, z2))
    rn_l = ff.split_rounds(r_l + ff.const_col(bn.int_to_limbs(N),
                                              len(bshape) + 1), 3)
    rn_lt_p = ff.lt_const(rn_l, P)
    eq2 = rn_lt_p & fp.eq(X, fp.mul(fp.to_mont(rn_l), z2))

    return r_ok & s_ok & q_ok & nonzero & (eq1 | eq2)


def verify_words_xla(qx, qy, r, s, e, require_low_s: bool = True):
    """Plain-XLA entry point: (8, B) uint32 big-endian words -> (B,) bool.

    Deliberately NOT jitted: XLA:CPU's algebraic simplifier loops
    pathologically on the fully-inlined flat graph (minutes per compile).
    Eagerly the scans' bodies still compile, and this path only serves
    CPU tests / functional fallback; the TPU production path is the
    Pallas kernel in ops/p256_pallas.py."""
    args = [bn.words_be_to_limbs(v) for v in (qx, qy, r, s, e)]
    return verify_body(*args, comb_table_f32(), require_low_s=require_low_s)
