"""Pallas TPU kernel for batched ECDSA-P256 verification.

One fused kernel per batch tile: range/on-curve checks, scalar inversion
(windowed Fermat via fori_loop), fixed-base comb for u1*G (one-hot f32
matmuls against the host-precomputed table — MXU), 4-bit windowed ladder
for u2*Q (VMEM-resident 16-entry Jacobian table), final complete add and
projective x-check — all on the scan-free flat field ops
(fabric_tpu/ops/flatfield.py), with the whole working set tiled into VMEM.

The algorithm and edge-case semantics are EXACTLY those of
ecp256.verify_body (the plain-XLA path used on CPU and as fallback);
tests/test_ecp256.py cross-checks the two paths bit-for-bit on TPU.

Why a kernel at all: under XLA, chained field ops on (22, B) arrays are
memory-scheduled through HBM and nested scans pay loop overhead (round-1
measured ~6.4 ms per ladder iteration at B=16k); here each tile's
intermediates stay in VMEM and the graph is flat.

Reference semantics: /root/reference/bccsp/sw/ecdsa.go:41-58 (low-S per
bccsp/utils/ecdsa.go:84), digests-only per msp/identities.go:178.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import bignum as bn
from . import flatfield as ff
from . import ecp256 as ec
from .flatfield import L, LB, MASK

TILE = int(os.environ.get("FABRIC_TPU_P256_TILE", "4096"))

_N_M2_DIGITS = None


def _inv_digits_n() -> np.ndarray:
    """4-bit MSB-first digits of n-2 (the Fermat exponent), shape (64,)."""
    global _N_M2_DIGITS
    if _N_M2_DIGITS is None:
        e = ec.N - 2
        ds = [(e >> (4 * i)) & 0xF for i in range(64)]
        _N_M2_DIGITS = np.asarray(ds[::-1], dtype=np.int32)
    return _N_M2_DIGITS


def _kernel(expn_ref, comb_ref, qx_ref, qy_ref, r_ref, s_ref, e_ref,
            out_ref, ldig_ref, cdig_ref, require_low_s: bool = True):
    fp, fn = ec.fp, ec.fn
    qx_l, qy_l, r_l, s_l, e_l = (qx_ref[:], qy_ref[:], r_ref[:], s_ref[:],
                                 e_ref[:])
    bshape = qx_l.shape[1:]

    # --- range & curve membership ---
    r_ok = ff.lt_const(r_l, ec.N) & ~ff.is_zero_limbs(r_l)
    s_ok = ff.lt_const(s_l, ec.N) & ~ff.is_zero_limbs(s_l)
    if require_low_s:
        s_ok = s_ok & ff.lt_const(s_l, ec.HALF_N + 1)
    q_ok = ff.lt_const(qx_l, ec.P) & ff.lt_const(qy_l, ec.P)
    qx_m = fp.to_mont(qx_l)
    qy_m = fp.to_mont(qy_l)
    lhs = fp.sqr(qy_m)
    rhs = fp.addl(
        fp.mul(fp.addl(fp.sqr(qx_m), ff.const_col(ec._A_M, 2)), qx_m),
        ff.const_col(ec._B_M, 2))
    q_ok = q_ok & fp.eq_k(lhs, rhs, 3, 5)

    # --- w = s^-1 mod n: windowed Fermat, exponent digits from SMEM ---
    s_mn = fn.to_mont(s_l)
    tab = [fn.one_bc(bshape), s_mn]
    for k in range(2, 16):
        tab.append(fn.mul(tab[k - 1], s_mn))

    def inv_body(i, acc):
        acc = fn.sqr(fn.sqr(fn.sqr(fn.sqr(acc))))
        d = expn_ref[i]
        ent = tab[0]
        for k in range(1, 16):
            ent = jnp.where(d == k, tab[k], ent)
        return fn.mul(acc, ent)

    w0 = tab[0]
    d0 = expn_ref[0]
    for k in range(1, 16):
        w0 = jnp.where(d0 == k, tab[k], w0)
    w = lax.fori_loop(1, 64, inv_body, w0)

    u1 = fn.from_mont(fn.mul(fn.to_mont(e_l), w))
    u2 = fn.from_mont(fn.mul(fn.to_mont(r_l), w))

    # --- digit scratches ---
    lds = ec.ladder_digits(u2)          # list, MSB-first
    for i, d in enumerate(lds):
        ldig_ref[i] = d
    cds = ec.comb_digits(u1)
    for j, d in enumerate(cds):
        cdig_ref[j] = d

    # --- u1*G comb: fori over 43 windows ---
    inf0 = jnp.ones(bshape, jnp.int32)
    one = fp.one_bc(bshape)

    def comb_body(j, acc):
        d = cdig_ref[pl.ds(j, 1), :][0]
        iota = lax.broadcasted_iota(
            jnp.int32, (ec.COMB_ENTRIES,) + tuple(bshape), 0)
        onehot = (iota == d[None]).astype(jnp.float32)
        rows = comb_ref[pl.ds(j * ec.COMB_ENTRIES, ec.COMB_ENTRIES), :]
        sel = jax.lax.dot_general(
            rows, onehot, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)       # (2L, B)
        sel = sel.astype(jnp.int32)
        return ec.add_mixed(acc, sel[:L], sel[L:], d == 0)

    acc_g = lax.fori_loop(0, ec.COMB_WINDOWS, comb_body,
                          (one, one, fp.zero_bc(bshape), inf0))

    # --- u2*Q ladder ---
    Q1 = (qx_m, qy_m, one, jnp.zeros(bshape, jnp.int32))
    T = [ec.infinity(bshape), Q1, ec.dbl(Q1)]
    for k in range(3, 16):
        T.append(ec.dbl(T[k // 2]) if k % 2 == 0 else ec.add_nodbl(T[k - 1], Q1))

    TX = jnp.stack([t[0] for t in T])
    TY = jnp.stack([t[1] for t in T])
    TZ = jnp.stack([t[2] for t in T])
    TI = jnp.stack([t[3] for t in T])

    def ladder_body(i, acc):
        for _ in range(ec.LADDER_W):
            acc = ec.dbl(acc)
        d = ldig_ref[pl.ds(i, 1), :][0]
        ent = (TX[0], TY[0], TZ[0], TI[0])
        for k in range(1, 16):
            ent = ec.select_point(d == k, (TX[k], TY[k], TZ[k], TI[k]), ent)
        return ec.add_nodbl(acc, ent)

    acc_q = lax.fori_loop(0, ec.LADDER_WINDOWS, ladder_body,
                          ec.infinity(bshape))

    # --- combine + projective x check (lazy bounds: X < 11p, Z < 6p) ---
    X, Y, Z, inf = ec.add_complete(acc_g, acc_q)
    nonzero = (inf == 0) & ~fp.is_zero_k(Z, 6)
    z2 = fp.sqr(Z)
    eq1 = fp.eq_k(X, fp.mul(fp.to_mont(r_l), z2), 2, 13)
    rn_l = ff.split_rounds(r_l + ff.const_col(bn.int_to_limbs(ec.N),
                                              len(bshape) + 1), 3)
    eq2 = (ff.lt_const(rn_l, ec.P)
           & fp.eq_k(X, fp.mul(fp.to_mont(rn_l), z2), 2, 13))

    ok = r_ok & s_ok & q_ok & nonzero & (eq1 | eq2)
    out_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32)[None, :],
                                  out_ref.shape)


# late import so the module parses without pallas on exotic builds
from jax.experimental import pallas as pl          # noqa: E402
from jax.experimental.pallas import tpu as pltpu   # noqa: E402


_CONST_POOL = None            # np (NCONST, L) int32
_CONST_INDEX = None           # dict bytes -> row


def _collect_const_pool():
    """Trace the verify math once with a recording hook to enumerate every
    (L,)-limb constant it materializes, in deterministic order."""
    global _CONST_POOL, _CONST_INDEX
    if _CONST_POOL is not None:
        return
    rows, index = [], {}

    def recorder(flat):
        key = flat.tobytes()
        if key not in index:
            if flat.shape[0] != L:
                raise AssertionError("non-L constant in kernel math")
            index[key] = len(rows)
            rows.append(flat.copy())
        return jnp.asarray(flat)

    prev = ff.set_const_hook(recorder)
    try:
        dummy = jax.ShapeDtypeStruct((L, 8), jnp.int32)
        jax.eval_shape(
            lambda a, b, c, d, e: ec.verify_body(
                a, b, c, d, e, ec.comb_table_f32()),
            dummy, dummy, dummy, dummy, dummy)
    finally:
        ff.set_const_hook(prev)
    _CONST_POOL = np.stack(rows).astype(np.int32)
    _CONST_INDEX = index


def _kernel_with_pool(cpool_ref, *args, require_low_s=True):
    """Serve flatfield constants from the pool ref while tracing _kernel."""
    pool = cpool_ref[:]          # one load; rows then index statically

    def from_pool(flat):
        row = _CONST_INDEX[flat.tobytes()]
        return pool[row]

    prev = ff.set_const_hook(from_pool)
    try:
        _kernel(*args, require_low_s=require_low_s)
    finally:
        ff.set_const_hook(prev)


@functools.partial(jax.jit, static_argnames=("require_low_s", "n_tiles"))
def _run_tiles(cpool, expn, comb, qx, qy, r, s, e, require_low_s, n_tiles):
    kern = functools.partial(_kernel_with_pool, require_low_s=require_low_s)
    grid = (n_tiles,)
    limb_spec = pl.BlockSpec((L, TILE), lambda i: (0, i),
                             memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((8, n_tiles * TILE), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(cpool.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),       # constant pool
            pl.BlockSpec(memory_space=pltpu.SMEM),       # exponent digits
            pl.BlockSpec((ec.COMB_WINDOWS * ec.COMB_ENTRIES, 2 * L),
                         lambda i: (0, 0),
                         memory_space=pltpu.VMEM),       # comb table
            limb_spec, limb_spec, limb_spec, limb_spec, limb_spec,
        ],
        out_specs=pl.BlockSpec((8, TILE), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((ec.LADDER_WINDOWS, TILE), jnp.int32),
            pltpu.VMEM((ec.COMB_WINDOWS, TILE), jnp.int32),
        ],
    )(cpool, expn, comb, qx, qy, r, s, e)


def verify_limbs_pallas(qx_l, qy_l, r_l, s_l, e_l, require_low_s=True):
    """(L, B) canonical limb arrays -> (B,) bool via the TPU kernel."""
    B = qx_l.shape[1]
    n_tiles = max(1, -(-B // TILE))
    pad = n_tiles * TILE - B
    args = []
    for a in (qx_l, qy_l, r_l, s_l, e_l):
        a = jnp.asarray(a, jnp.int32)
        if pad:
            a = jnp.concatenate(
                [a, jnp.zeros((L, pad), jnp.int32)], axis=1)
        args.append(a)
    _collect_const_pool()
    out = _run_tiles(jnp.asarray(_CONST_POOL),
                     jnp.asarray(_inv_digits_n()),
                     jnp.asarray(ec.comb_table_f32()),
                     *args, require_low_s=require_low_s, n_tiles=n_tiles)
    return out[0, :B] != 0


def verify_words(qx, qy, r, s, e, require_low_s: bool = True):
    """(8, B) uint32 big-endian words -> (B,) bool; TPU kernel if available,
    plain-XLA windowed path otherwise (CPU, or FABRIC_TPU_NO_PALLAS=1)."""
    # Experimental: the fused Mosaic kernel currently trips an internal
    # check in the axon libtpu AOT compiler on some program shapes
    # (limits[i] <= dim(i)); opt in explicitly.
    use_pallas = (os.environ.get("FABRIC_TPU_PALLAS") == "1"
                  and jax.default_backend() not in ("cpu",))
    if not use_pallas:
        return ec.verify_words_xla(qx, qy, r, s, e, require_low_s=require_low_s)
    args = [bn.words_be_to_limbs(v) for v in (qx, qy, r, s, e)]
    return verify_limbs_pallas(*args, require_low_s=require_low_s)
