"""Batched ed25519 (RFC 8032) signature verification on TPU.

NEW capability vs the reference (no ed25519 exists in /root/reference —
SURVEY.md §2 bccsp/sw note); required by BASELINE.json configs 2-3.

Split of labor:
- host (provider layer): SHA-512(R || A || M) over the variable-length
  message, reduced mod L — hashing never goes on device (mirrors the
  reference's design where bccsp.Verify receives a fixed-size digest,
  msp/identities.go:178);
- device (this module): the cofactorless equation [S]B == R + [k]A,
  matching RFC 8032 / OpenSSL / Go crypto/ed25519, computed as
  [S]B + [k](-A) and compared against the ENCODED R by recompression
  (one batch-amortized inversion instead of a ~250-squaring sqrt per
  signature — R never needs decompressing).

Two lanes (the P-256 two-lane design, bccsp/jaxtpu.py):
  verify_words       — generic: decompress A on device, [S]B via the
                       fixed-base signed comb, [k](-A) via a 4-bit
                       windowed ladder of complete adds;
  verify_words_rows  — fast: A's table is cached (ops/ed25519_tables),
                       BOTH halves are fixed-base combs; signatures
                       pack key-major into a (R, C) row grid exactly
                       like ops/p256_fixed.verify_words_rows.

Kernel inputs are (8, B) uint32 big-endian words of the *integer values*
(the host unpacks the little-endian wire encoding) plus (B,) sign bits.
"""

from __future__ import annotations

import hashlib

import numpy as np
import jax.numpy as jnp

from . import bignum as bn
from . import edwards as ed
from . import flatfield as ff


def _sb_comb(s_l, bshape):
    from . import ed25519_tables as tabs
    return ed.comb_accumulate(tabs.basepoint_table(), s_l, bshape)


def verify_words(ay, a_sign, ry, r_sign, s, k) -> jnp.ndarray:
    """Generic-lane batched ed25519 verify (uncached A).

    ay, ry: (8, B) uint32 big-endian words of the A / R y-coordinates
    a_sign, r_sign: (B,) int32 x-parity bits from the encodings
    s: (8, B) words of S (checked < L here)
    k: (8, B) words of SHA512(R||A||M) already reduced mod L by the host
    Returns (B,) bool.
    """
    ay_l = bn.words_be_to_limbs(ay)
    ry_l = bn.words_be_to_limbs(ry)
    s_l = bn.words_be_to_limbs(s)
    k_l = bn.words_be_to_limbs(k)
    bshape = s_l.shape[1:]

    s_ok = ff.lt_const(s_l, ed.L)
    (ax_m, ay_m), a_ok = ed.decompress(ay_l, a_sign)

    lhs = ed.add(_sb_comb(s_l, bshape),
                 ed.windowed_mul(k_l, ed.neg(ed.from_affine(ax_m, ay_m)),
                                 bshape))
    # gate the inversion on a_ok: garbage "points" from a failed
    # decompression may break the completeness guarantee (Z == 0 would
    # poison the product tree); their verdict is False regardless.
    zinv = ed.batch_zinv(lhs[2], a_ok)
    return s_ok & a_ok & ed.compressed_equals(lhs, ry_l, r_sign, zinv)


def verify_words_rows(bank_f32, row_key, ry, r_sign, s, k) -> jnp.ndarray:
    """Fast-lane batched verify over a key-major (R, C) row grid.

    bank_f32: (K, COMB_WINDOWS*COMB_ROWS, 3L) stacked niels tables of
    the NEGATED public keys (Ed25519KeyTableCache layout); row_key:
    (R,) int32; ry/s/k: (8, R, C) uint32 words; r_sign: (R, C) int32.
    Returns (R, C) bool.  A-validity was established at table build.
    """
    ry_l = bn.words_be_to_limbs(ry)
    s_l = bn.words_be_to_limbs(s)
    k_l = bn.words_be_to_limbs(k)
    R, C = s_l.shape[1], s_l.shape[2]

    def flat(x):
        return x.reshape(x.shape[0], R * C)

    s_ok = ff.lt_const(flat(s_l), ed.L)
    acc_b = _sb_comb(flat(s_l), (R * C,))
    acc_a = ed.comb_accumulate_rows(bank_f32, row_key, k_l, (R, C))
    lhs = ed.add(acc_b, tuple(
        flat(c) if c.ndim == 3 else c.reshape(R * C) for c in acc_a))
    # every point here is a valid curve point (tables are built from
    # validated keys; combs of valid points stay valid): completeness
    # guarantees Z != 0, so the tree is safe ungated.
    ones = jnp.ones((R * C,), bool)
    zinv = ed.batch_zinv(lhs[2], ones)
    ok = s_ok & ed.compressed_equals(lhs, flat(ry_l),
                                     r_sign.reshape(R * C), zinv)
    return ok.reshape(R, C)


# ---------------------------------------------------------------------------
# Host-side packing: RFC 8032 wire format -> kernel inputs
# ---------------------------------------------------------------------------

def pack_verify_inputs(pubkeys: list, sigs: list, msgs: list):
    """(32B pubkey, 64B sig, message) triples -> kernel input arrays.

    Returns (ay, a_sign, ry, r_sign, s, k) ready for verify_words.
    Malformed-length inputs raise ValueError (callers pre-screen).

    Numpy-vectorized except the SHA-512 + mod-L fold, which is
    per-signature by nature; the previous per-word python packing was
    ~10 us/sig — most of the ed25519 lane's host time.
    """
    B = len(pubkeys)
    if B == 0:
        z = np.zeros((8, 0), dtype=np.uint32)
        zb = np.zeros((0,), dtype=np.int32)
        return z, zb, z, zb, z.copy(), z.copy()
    for pk, sig in zip(pubkeys, sigs):
        if len(pk) != 32 or len(sig) != 64:
            raise ValueError("ed25519: bad pubkey/signature length")
    pkw = np.frombuffer(b"".join(pubkeys), "<u4").reshape(B, 8)
    sgw = np.frombuffer(b"".join(sigs), "<u4").reshape(B, 16)
    rw, sw_le = sgw[:, :8], sgw[:, 8:]
    a_sign = (pkw[:, 7] >> 31).astype(np.int32)
    r_sign = (rw[:, 7] >> 31).astype(np.int32)

    def be_words(lew, mask_top=False):
        # LE 32B value -> (8, B) big-endian word order (native uint32)
        w = np.ascontiguousarray(lew[:, ::-1].T).astype(np.uint32)
        if mask_top:
            w[0] &= 0x7FFFFFFF
        return w

    ay = be_words(pkw, True)
    ry = be_words(rw, True)
    sw = be_words(sw_le)
    sha512 = hashlib.sha512
    Lmod = ed.L
    kb = bytearray()
    for pk, sig, msg in zip(pubkeys, sigs, msgs):
        k = int.from_bytes(sha512(sig[:32] + pk + msg).digest(),
                           "little") % Lmod
        kb += k.to_bytes(32, "big")
    kw = np.ascontiguousarray(
        np.frombuffer(bytes(kb), ">u4").reshape(B, 8).T).astype(np.uint32)
    return ay, a_sign, ry, r_sign, sw, kw
