"""Batched ed25519 (RFC 8032) signature verification on TPU.

NEW capability vs the reference (no ed25519 exists in /root/reference —
SURVEY.md §2 bccsp/sw note); required by BASELINE.json configs 2-3.

Split of labor:
- host (provider layer): SHA-512(R || A || M) over the variable-length
  message, reduced mod L — hashing never goes on device (mirrors the
  reference's design where bccsp.Verify receives a fixed-size digest,
  msp/identities.go:178);
- device (this module): batched decompression of A and R, scalar ladder
  [S]B + [k](-A), projective comparison against R.  Cofactorless equation
  ([S]B == R + [k]A), matching RFC 8032 / OpenSSL / Go crypto/ed25519.

Kernel inputs are (8, B) uint32 big-endian words of the *integer values*
(the host unpacks the little-endian wire encoding) plus (B,) sign bits.
"""

from __future__ import annotations

import hashlib

import numpy as np
import jax.numpy as jnp

from . import bignum as bn
from . import edwards as ed


def verify_words(ay, a_sign, ry, r_sign, s, k) -> jnp.ndarray:
    """Batched ed25519 verify.

    ay, ry: (8, B) uint32 big-endian words of the A / R y-coordinates
    a_sign, r_sign: (B,) int32 x-parity bits from the encodings
    s: (8, B) words of S (checked < L here)
    k: (8, B) words of SHA512(R||A||M) already reduced mod L by the host
    Returns (B,) bool.
    """
    fp = ed.fp
    ay_l = bn.words_be_to_limbs(ay)
    ry_l = bn.words_be_to_limbs(ry)
    s_l = bn.words_be_to_limbs(s)
    k_l = bn.words_be_to_limbs(k)

    s_ok = bn.limbs_lt_const(s_l, ed.L)
    (ax_m, ay_m), a_ok = ed.decompress(ay_l, a_sign)
    (rx_m, ry_m), r_ok = ed.decompress(ry_l, r_sign)

    A = ed.from_affine(ax_m, ay_m)
    R = ed.from_affine(rx_m, ry_m)
    # [S]B + [k](-A) == R
    lhs = ed.shamir(s_l, k_l, ed.neg(A), n_bits=253)
    ok_eq = ed.eq_points(lhs, R)
    return s_ok & a_ok & r_ok & ok_eq


# ---------------------------------------------------------------------------
# Host-side packing: RFC 8032 wire format -> kernel inputs
# ---------------------------------------------------------------------------

def pack_verify_inputs(pubkeys: list, sigs: list, msgs: list):
    """(32B pubkey, 64B sig, message) triples -> kernel input arrays.

    Returns (ay, a_sign, ry, r_sign, s, k) ready for verify_words.
    Malformed-length inputs raise ValueError (callers pre-screen).
    """
    B = len(pubkeys)
    ay = np.zeros((8, B), dtype=np.uint32)
    ry = np.zeros((8, B), dtype=np.uint32)
    sw = np.zeros((8, B), dtype=np.uint32)
    kw = np.zeros((8, B), dtype=np.uint32)
    a_sign = np.zeros((B,), dtype=np.int32)
    r_sign = np.zeros((B,), dtype=np.int32)
    for i, (pk, sig, msg) in enumerate(zip(pubkeys, sigs, msgs)):
        if len(pk) != 32 or len(sig) != 64:
            raise ValueError("ed25519: bad pubkey/signature length")
        rb, sb = sig[:32], sig[32:]
        a_int = int.from_bytes(pk, "little")
        r_int = int.from_bytes(rb, "little")
        a_sign[i] = (a_int >> 255) & 1
        r_sign[i] = (r_int >> 255) & 1
        _fill_words(ay, i, a_int & ((1 << 255) - 1))
        _fill_words(ry, i, r_int & ((1 << 255) - 1))
        _fill_words(sw, i, int.from_bytes(sb, "little"))
        k = int.from_bytes(hashlib.sha512(rb + pk + msg).digest(), "little") % ed.L
        _fill_words(kw, i, k)
    return ay, a_sign, ry, r_sign, sw, kw


def _fill_words(arr: np.ndarray, col: int, val: int) -> None:
    for wi in range(8):
        arr[wi, col] = (val >> (32 * (7 - wi))) & 0xFFFFFFFF
