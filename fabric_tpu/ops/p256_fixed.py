"""ECDSA-P256 batched verify for a KNOWN (cached) public key — fast path.

For a public key whose per-key comb table has been built host-side
(ops/p256_tables.py), u2*Q becomes a second fixed-base comb: 43 mixed
adds against the key table instead of the 256-doubling windowed ladder
of the generic path (ops/ecp256.py).  Both scalar halves (u1*G, u2*Q)
are then pure comb accumulations, which cuts the per-signature field-mul
count from ~2.9k to ~1.0k and roughly triples throughput.  The public
key itself never reaches the device: on-curve membership was verified
once at table-build time.

The provider (bccsp/jaxtpu.py) groups a block's signatures by pubkey and
routes groups with a cached table here; everything else takes the
generic path.  Semantics (bit-identical accept/reject vs the reference's
verifyECDSA, /root/reference/bccsp/sw/ecdsa.go:41-58 with mandatory
low-S) are cross-checked against the generic path and the OpenSSL oracle
in tests/test_ecp256.py.

Adversarial completeness mirrors ecp256.verify_body: both comb halves
satisfy the prefix-reachability argument (u1, u2 < n), the final combine
is the fully complete add, and the projective x-check admits r and r+n.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import bignum as bn
from . import ecp256 as ec
from . import flatfield as ff

fp, fn = ec.fp, ec.fn


def _verify_core(r_l, s_l, e_l, q_comb, require_low_s):
    """Shared fixed-base verify tail: range checks, u1/u2, the G comb,
    the key-side comb supplied by `q_comb(u2, bshape)`, the complete
    combine and the projective x-check.  Both entry points below (and the
    differential tests) share this single implementation so the fast
    paths cannot drift from each other."""
    bshape = r_l.shape[1:]

    # --- range checks (reference: ecdsa.go:44-53, utils/ecdsa.go:84) ---
    r_ok = ff.lt_const(r_l, ec.N) & ~ff.is_zero_limbs(r_l)
    s_ok = ff.lt_const(s_l, ec.N) & ~ff.is_zero_limbs(s_l)
    if require_low_s:
        s_ok = s_ok & ff.lt_const(s_l, ec.HALF_N + 1)

    # --- u1 = e/s, u2 = r/s mod n ---
    s_mn = fn.to_mont(s_l)
    w = ec._inv_n(s_mn, bshape)
    u1 = fn.from_mont(fn.mul(fn.to_mont(e_l), w))
    u2 = fn.from_mont(fn.mul(fn.to_mont(r_l), w))

    # --- two fixed-base combs + complete combine ---
    acc_g = ec.comb_accumulate(ec.comb_table_f32(), u1, bshape)
    acc_q = q_comb(u2, bshape)
    X, Y, Z, inf = ec.add_complete(acc_g, acc_q)

    nonzero = (inf == 0) & ~fp.is_zero_k(Z, 6)

    # --- projective x-coordinate check: X == (r + k*n)*Z^2, k in {0,1} ---
    z2 = fp.sqr(Z)
    eq1 = fp.eq_k(X, fp.mul(fp.to_mont(r_l), z2), 2, 13)
    rn_l = ff.split_rounds(r_l + ff.const_col(bn.int_to_limbs(ec.N),
                                              len(bshape) + 1), 3)
    eq2 = (ff.lt_const(rn_l, ec.P)
           & fp.eq_k(X, fp.mul(fp.to_mont(rn_l), z2), 2, 13))

    return r_ok & s_ok & nonzero & (eq1 | eq2)


def verify_body_fixed(key_tab_f32, r_l, s_l, e_l, g_tab_f32,
                      require_low_s=True):
    """Batched verify over canonical integer limbs (L, B) for one key.

    key_tab_f32: (COMB_WINDOWS*COMB_ENTRIES, 2L) f32 comb table of the
    public key (p256_tables.comb_table_for_point).  Returns (B,) bool.
    """
    del g_tab_f32   # the G table is global (ec.comb_table_f32)
    return _verify_core(
        r_l, s_l, e_l,
        lambda u2, bshape: ec.comb_accumulate(key_tab_f32, u2, bshape),
        require_low_s)


def verify_words_fixed(key_tab_f32, r, s, e, require_low_s: bool = True):
    """(8, B) uint32 big-endian words + key table -> (B,) bool."""
    args = [bn.words_be_to_limbs(v) for v in (r, s, e)]
    return verify_body_fixed(key_tab_f32, *args, ec.comb_table_f32(),
                             require_low_s=require_low_s)


def verify_words_rows(bank_f32, row_key, r, s, e,
                      require_low_s: bool = True):
    """Row-grouped multikey batched verify: ONE dispatch for signatures
    under ANY number of cached public keys.

    The host packs signatures key-major into a (R, C) grid (every
    element of row r shares the key row_key[r]); per-sig cost matches
    the single-key comb regardless of the number of distinct keys —
    the redesign that removed the round-3 NK<=4 fast-lane cap
    (ec.comb_accumulate_rows).

    bank_f32: (K, COMB_WINDOWS*COMB_ENTRIES, 2L) stacked tables;
    row_key: (R,) int32; r/s/e: (8, R, C) uint32 words.
    Returns (R, C) bool.
    """
    r_l, s_l, e_l = (bn.words_be_to_limbs(v) for v in (r, s, e))
    R, C = r_l.shape[1], r_l.shape[2]
    L = ec.L

    def flat(x):
        return x.reshape(x.shape[0], R * C)

    def q_comb(u2_flat, bshape):
        # the shared verify tail runs on the flat (R*C,) batch (1-D is
        # what the G comb and the inversion tree are shaped for); only
        # the key-side lookup needs the row structure back
        u2_rc = u2_flat.reshape(u2_flat.shape[0], R, C)
        X, Y, Z, inf = ec.comb_accumulate_rows(
            bank_f32, row_key, u2_rc, (R, C))
        return flat(X), flat(Y), flat(Z), inf.reshape(R * C)

    out = _verify_core(flat(r_l), flat(s_l), flat(e_l), q_comb,
                       require_low_s)
    return out.reshape(R, C)
