"""Batched short-Weierstrass (y^2 = x^3 + ax + b) Jacobian point arithmetic.

TPU-native replacement for the reference's per-signature Go scalar
multiplication inside crypto/ecdsa (reached from
/root/reference/bccsp/sw/ecdsa.go:41): here the whole signature batch moves
through one jitted double-scalar ladder, limbs-first (L, B) int32 arrays.

Completeness: `dbl` is complete as written (Z=0 or Y=0 inputs produce the
point at infinity); `add` computes the generic chord formula and then
branchlessly patches the degenerate cases (either operand at infinity,
P == Q, P == -Q), so adversarially-chosen signatures cannot derail the
ladder — there is no data-dependent control flow anywhere.

Points are Jacobian triples (X, Y, Z) of Montgomery-form field elements;
infinity is Z == 0 (X = Y = 1 by convention).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from . import bignum as bn


class ShortCurve:
    """A short-Weierstrass curve over F_p with batched Jacobian arithmetic."""

    def __init__(self, p: int, a: int, b: int, gx: int, gy: int, n: int, name: str = ""):
        self.fp = bn.Mont(p, name + ".p")
        self.fn = bn.Mont(n, name + ".n")
        self.a_int = a % p
        self.b_int = b % p
        self.gx_int, self.gy_int = gx, gy
        self.n_int = n
        self.name = name
        self.a_is_minus3 = (a % p) == (p - 3)
        self.a_m = self.fp.const(a)
        self.b_m = self.fp.const(b)
        self.g_m = (self.fp.const(gx), self.fp.const(gy))  # affine, Montgomery

    # -- point helpers ------------------------------------------------------

    def infinity(self, bshape) -> tuple:
        one = self.fp.one_bc(bshape)
        zero = jnp.zeros((bn.N_LIMBS,) + tuple(bshape), dtype=jnp.int32)
        return one, one, zero

    def is_infinity(self, P) -> jnp.ndarray:
        return self.fp.is_zero(P[2])

    def to_jacobian(self, x_m, y_m) -> tuple:
        one = self.fp.one_bc(jnp.asarray(x_m).shape[1:])
        return jnp.asarray(x_m), jnp.asarray(y_m), one

    def select_point(self, cond, P, Q) -> tuple:
        """(B,) bool select between two Jacobian points."""
        f = self.fp.select
        return f(cond, P[0], Q[0]), f(cond, P[1], Q[1]), f(cond, P[2], Q[2])

    def on_curve_affine(self, x_m, y_m) -> jnp.ndarray:
        """y^2 == x^3 + ax + b for affine Montgomery-form coordinates."""
        f = self.fp
        lhs = f.sqr(y_m)
        rhs = f.add(f.mul(f.add(f.sqr(x_m), self.a_m), x_m), self.b_m)
        return f.eq(lhs, rhs)

    # -- group law ----------------------------------------------------------

    def dbl(self, P) -> tuple:
        """Complete Jacobian doubling (handles Z=0 and Y=0 -> infinity)."""
        f = self.fp
        X, Y, Z = P
        if self.a_is_minus3:
            # dbl-2001-b: delta = Z^2, gamma = Y^2, beta = X*gamma,
            # alpha = 3*(X-delta)*(X+delta)
            delta = f.sqr(Z)
            gamma = f.sqr(Y)
            beta = f.mul(X, gamma)
            alpha = f.mul_small(f.mul(f.sub(X, delta), f.add(X, delta)), 3)
            X3 = f.sub(f.sqr(alpha), f.mul_small(beta, 8))
            Z3 = f.sub(f.sub(f.sqr(f.add(Y, Z)), gamma), delta)
            Y3 = f.sub(f.mul(alpha, f.sub(f.mul_small(beta, 4), X3)),
                       f.mul_small(f.sqr(gamma), 8))
        else:
            # generic a: alpha = 3*X^2 + a*Z^4
            gamma = f.sqr(Y)
            beta = f.mul(X, gamma)
            z2 = f.sqr(Z)
            alpha = f.add(f.mul_small(f.sqr(X), 3), f.mul(self.a_m, f.sqr(z2)))
            X3 = f.sub(f.sqr(alpha), f.mul_small(beta, 8))
            Z3 = f.mul_small(f.mul(Y, Z), 2)
            Y3 = f.sub(f.mul(alpha, f.sub(f.mul_small(beta, 4), X3)),
                       f.mul_small(f.sqr(gamma), 8))
        return X3, Y3, Z3

    def add(self, P, Q) -> tuple:
        """Complete Jacobian addition (branchless patch of degenerate cases)."""
        f = self.fp
        X1, Y1, Z1 = P
        X2, Y2, Z2 = Q
        z1z1 = f.sqr(Z1)
        z2z2 = f.sqr(Z2)
        u1 = f.mul(X1, z2z2)
        u2 = f.mul(X2, z1z1)
        s1 = f.mul(Y1, f.mul(Z2, z2z2))
        s2 = f.mul(Y2, f.mul(Z1, z1z1))
        h = f.sub(u2, u1)
        r = f.sub(s2, s1)
        h2 = f.sqr(h)
        h3 = f.mul(h, h2)
        u1h2 = f.mul(u1, h2)
        X3 = f.sub(f.sub(f.sqr(r), h3), f.mul_small(u1h2, 2))
        Y3 = f.sub(f.mul(r, f.sub(u1h2, X3)), f.mul(s1, h3))
        Z3 = f.mul(f.mul(Z1, Z2), h)
        R = (X3, Y3, Z3)

        h_zero = f.is_zero(h)
        r_zero = f.is_zero(r)
        p_inf = f.is_zero(Z1)
        q_inf = f.is_zero(Z2)
        # same x: either P == Q (double) or P == -Q (infinity)
        R = self.select_point(h_zero & r_zero, self.dbl(P), R)
        R = self.select_point(h_zero & ~r_zero, self.infinity(X3.shape[1:]), R)
        R = self.select_point(q_inf, P, R)
        R = self.select_point(p_inf, Q, R)
        return R

    # -- scalar multiplication ----------------------------------------------

    def shamir(self, u1_limbs, u2_limbs, Q, n_bits: int = 256) -> tuple:
        """u1*G + u2*Q via interleaved (Shamir) double-and-add.

        u1_limbs/u2_limbs: canonical integer limbs (L, B); Q: Jacobian point.
        One lax.scan over n_bits iterations: double, then a 4-way
        branchless table select {inf, G, Q, G+Q} and one complete add.
        """
        f = self.fp
        bshape = jnp.asarray(u1_limbs).shape[1:]
        G = self.to_jacobian(
            jnp.broadcast_to(jnp.asarray(self.g_m[0]), (bn.N_LIMBS,) + tuple(bshape)),
            jnp.broadcast_to(jnp.asarray(self.g_m[1]), (bn.N_LIMBS,) + tuple(bshape)))
        GQ = self.add(G, Q)
        u1b = bn.to_bits(u1_limbs, n_bits)[::-1]  # MSB first, (n_bits, B)
        u2b = bn.to_bits(u2_limbs, n_bits)[::-1]

        def sel3(c, A, Bp):
            return self.select_point(c, A, Bp)

        def body(acc, bits):
            b1, b2 = bits
            acc = self.dbl(acc)
            # 4-way select of the addend
            t = self.select_point(b1 != 0, G, self.infinity(bshape))
            t = sel3((b1 == 0) & (b2 != 0), Q, t)
            t = sel3((b1 != 0) & (b2 != 0), GQ, t)
            acc = self.add(acc, t)
            return acc, None

        # tie the init to the scalars so its shard_map variance matches
        init = tuple(c + jnp.asarray(u1_limbs) * 0 for c in self.infinity(bshape))
        acc, _ = lax.scan(body, init, (u1b, u2b))
        return acc
