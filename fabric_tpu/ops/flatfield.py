"""Scan-free 256-bit modular arithmetic for TPU kernels (Pallas & XLA).

Round-2 replacement for the hot paths of bignum.Mont: the round-1 kernel
spent its time on nested lax.scan loop overhead (a 22-step CIOS scan inside
carry scans inside the 256-iteration ladder scan).  Every op here is a flat
composition of elementwise/broadcast int32 ops on (L, ...) limb arrays —
no lax.scan, no while_loop, no gather/scatter — so the same code lowers
both through XLA (CPU tests, fallback) and through Mosaic inside a Pallas
kernel (historically a fused Pallas kernel; the XLA lane is production).

Layout: limbs-first int32 arrays (L, B), 12-bit limbs, L=22 (264 bits),
identical to bignum (results interchangeable; same R = 2^264, same n0inv).

Representations:
  canonical: limbs in [0, 2^12), value in [0, p)
  relaxed:   limbs in (-2^7, 2^12 + 2^7), value in [0, 2p)
mul/mod_add/mod_sub take and return relaxed values; canon()/is_zero()/eq()
resolve exactly via a ternary Kogge-Stone carry prefix (O(log L) depth,
handles borrows), never a scan.

Numerical contract of mul (fused-m CIOS, fully unrolled):
  operands: limbs |l| < 2^13, values < 16p  ->  output value < 2p.
  (CIOS bound: out < p + a*b/R; a*b <= (16p)^2 = 256 p^2 < R*p since
   p < 2^256 = R/256.)
Int32 overflow: per-step per-limb additions are a_i*b_j + m*p_j with
|a_i|,|b_j| < 2^13, m,p_j < 2^12: < 2^26 + 2^24; a limb accumulates through
at most L=22 steps plus carry rows: < 22 * 1.1*2^26 + 2^19 < 2^31.  OK.

Reference semantics target: the ECDSA verify math reached from
/root/reference/bccsp/sw/ecdsa.go:41 (Go big.Int there; limbed int32 here).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import bignum as bn

L = bn.N_LIMBS            # 22
LB = bn.LIMB_BITS         # 12
MASK = bn.LIMB_MASK       # 0xFFF


# ---------------------------------------------------------------------------
# Constant materialization hook
#
# Pallas kernels may not close over concrete arrays — constants must arrive
# through refs.  Every (L,)-limb constant in this module funnels through
# const_col(); a Pallas wrapper installs a hook that (pass 1) records the
# distinct constants while tracing the same math under jax.make_jaxpr, then
# (pass 2) serves them as rows of a single VMEM "constant pool" input.
# ---------------------------------------------------------------------------

_CONST_HOOK = None


def set_const_hook(hook):
    """Install hook(flat_np_int32_of_len_L) -> jnp (L,); returns previous."""
    global _CONST_HOOK
    prev = _CONST_HOOK
    _CONST_HOOK = hook
    return prev


def const_col(limbs_np, ndim: int):
    """(L,)-ish numpy limb constant -> jnp array shaped (L, 1, ..1) for
    broadcasting against (L, B...) arrays, via the hook if installed."""
    flat = np.ascontiguousarray(limbs_np, dtype=np.int32).reshape(-1)
    arr = jnp.asarray(flat) if _CONST_HOOK is None else _CONST_HOOK(flat)
    return arr.reshape((flat.shape[0],) + (1,) * (ndim - 1))


# ---------------------------------------------------------------------------
# Per-primitive jit cache (CPU/eager path)
#
# XLA:CPU compiles one huge LLVM function per jitted graph; the full flat
# verify (~1M ops) takes minutes to compile.  Eager execution instead pays
# per-op dispatch on ~300 ops per field-mul.  Sweet spot: jit each FIELD
# PRIMITIVE (mul, add, compare...) as its own small program and drive the
# curve layer eagerly from Python.  Inside a trace (jit/pallas) the
# primitives inline as before — the wrapper only activates on concrete
# arrays.
# ---------------------------------------------------------------------------

_PRIM_CACHE: dict = {}


def _is_concrete(*arrays) -> bool:
    import jax.core
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


def _prim_jit(key, fn):
    jf = _PRIM_CACHE.get(key)
    if jf is None:
        import jax
        jf = jax.jit(fn)
        _PRIM_CACHE[key] = jf
    return jf


# ---------------------------------------------------------------------------
# Flat carry machinery
# ---------------------------------------------------------------------------

def _pad_axis0(x, before: int, after: int, fill=0):
    """jnp.pad along axis 0 only — used instead of concatenate towers:
    XLA:CPU's algebraic simplifier loops on concat(slice(concat(...)))
    chains, while pad(slice) folds cleanly."""
    cfg = ((before, after),) + ((0, 0),) * (x.ndim - 1)
    return jnp.pad(x, cfg, constant_values=fill)


def shift_up(x):
    """Limbs one position toward the MSB; the top limb is dropped (callers
    guarantee it carries nothing)."""
    return _pad_axis0(x[:x.shape[0] - 1], 1, 0)


def split_rounds(x, rounds: int = 2):
    """Value-preserving carry-save rounds (arithmetic shift: borrows OK)."""
    for _ in range(rounds):
        x = (x & MASK) + shift_up(x >> LB)
    return x


def _ks_prefix(x):
    """Ternary Kogge-Stone carry prefix for limbs in [-1, 2^12 + 1].

    Returns the per-position carry map F_i = f_i . ... . f_0 as a 3-tuple
    (F(-1), F(0), F(1)); each f(c) = floor((l + c) / 2^LB) in {-1, 0, 1}.
    """
    F = ((x - 1) >> LB, x >> LB, (x + 1) >> LB)

    def compose(g, f):
        gm1, g0, g1 = g
        return tuple(jnp.where(fx < 0, gm1, jnp.where(fx > 0, g1, g0)) for fx in f)

    n = x.shape[0]
    shift = 1
    while shift < n:
        def sh(a, fill):
            return _pad_axis0(a[:a.shape[0] - shift], shift, 0, fill)
        F = compose(F, (sh(F[0], -1), sh(F[1], 0), sh(F[2], 1)))
        shift *= 2
    return F


def _split_keep_top(x, rounds: int):
    """Carry-save rounds that never split the top limb (no drops): exact
    for any value, positive or negative.  Low limbs end in [-1, 2^12 + 1];
    the top limb accumulates its incoming carries unchanged."""
    for _ in range(rounds):
        n = x.shape[0]
        low = _pad_axis0(x[:n - 1] & MASK, 0, 1) + _pad_axis0(x[n - 1:], n - 1, 0)
        carries = _pad_axis0(x[:n - 1] >> LB, 1, 0)
        x = low + carries
    return x


def resolve(x):
    """Exact canonicalization of limbs |l| < 2^30 whose value is
    non-negative and fits x.shape[0] limbs -> limbs in [0, 2^12).

    One pad limb is appended internally so transient top borrows (possible
    with relaxed negative limbs) resolve exactly, then dropped: for an
    in-contract value the padded top resolves to zero.  No (1, B)-shaped
    intermediates anywhere (Mosaic/libtpu mishandle dim-1 buffers)."""
    x = _pad_axis0(x, 0, 1)
    n = x.shape[0]
    x = _split_keep_top(x, 3)
    low = x[:n - 1]
    F = _ks_prefix(low)
    carry_in = _pad_axis0(F[1][:n - 2], 1, 0)
    return (low + carry_in) & MASK


def is_negative(x):
    """(B,) bool: the value represented by limbs |l| < 2^30 is negative.

    Computes only the signed top (original top limb + carry out of the
    lower limbs) — negative iff the value is."""
    x = _pad_axis0(x, 0, 1)
    n = x.shape[0]
    x = _split_keep_top(x, 3)
    low = x[:n - 1]
    F = _ks_prefix(low)
    # positive indices only: Mosaic lowers negative value-indexing to an
    # unsupported dynamic_slice
    return (x[n - 1] + F[1][n - 2]) < 0


# ---------------------------------------------------------------------------
# Modulus context
# ---------------------------------------------------------------------------

class FlatMod:
    """Flat Montgomery context for an odd modulus p < 2^256, R = 2^264."""

    def __init__(self, modulus: int, name: str = ""):
        if modulus % 2 == 0 or modulus >= (1 << 256):
            raise ValueError("modulus must be odd and < 2^256")
        self.p = modulus
        self.name = name
        self.R = 1 << (L * LB)
        self.n0inv = np.int32((-pow(modulus, -1, 1 << LB)) % (1 << LB))
        self.p_np = bn.int_to_limbs(modulus).astype(np.int32)
        self.p2_np = bn.int_to_limbs(2 * modulus).astype(np.int32)
        self.r2_int = (self.R * self.R) % modulus
        self.one_int = self.R % modulus

    # -- constant helpers ---------------------------------------------------

    def _col(self, limbs_np, ndim):
        return const_col(limbs_np, ndim)

    def const_mont(self, x: int) -> np.ndarray:
        """(L, 1) canonical limbs of x in Montgomery form (numpy)."""
        return bn.int_to_limbs((x % self.p) * self.R % self.p).reshape(L, 1)

    def one_bc(self, bshape):
        base = const_col(bn.int_to_limbs(self.one_int), len(bshape) + 1)
        return jnp.broadcast_to(base, (L,) + tuple(bshape)).astype(jnp.int32)

    def zero_bc(self, bshape):
        return jnp.zeros((L,) + tuple(bshape), jnp.int32)

    # -- core multiply (fused-m CIOS, unrolled, scan-free) -------------------

    def mul(self, a, b):
        if _is_concrete(a, b):
            return _prim_jit(("mul", self.p), self._mul_impl)(
                jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32))
        return self._mul_impl(a, b)

    def _mul_impl(self, a, b):
        a = jnp.asarray(a, jnp.int32)
        b = jnp.asarray(b, jnp.int32)
        bshape = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
        b = jnp.broadcast_to(b, (L,) + bshape)
        p_col = self._col(self.p_np, len(bshape) + 1)
        zero = jnp.zeros((1,) + bshape, jnp.int32)
        acc = jnp.zeros((L,) + bshape, jnp.int32)
        c_row = jnp.zeros(bshape, jnp.int32)
        b0 = b[0]
        for i in range(L):
            ai = a[i]
            t0 = acc[0] + c_row + ai * b0
            m = (t0 * self.n0inv) & MASK
            acc = acc + ai * b + m * p_col
            c_row = (acc[0] + c_row) >> LB
            acc = _pad_axis0(acc[1:], 0, 1)
        acc = acc + _pad_axis0(c_row[None], 0, L - 1)
        return split_rounds(acc, 2)

    def sqr(self, a):
        return self.mul(a, a)

    # -- normalized add/sub (outputs < 2p, relaxed limbs) --------------------

    def _cond_sub_2p(self, s):
        """s in [0, 4p) relaxed -> value mod'd into [0, 2p)."""
        p2 = self._col(self.p2_np, s.ndim)
        d = s - p2
        neg = is_negative(d)
        return jnp.where(neg[None], s, split_rounds(d, 2))

    def mod_add(self, a, b):
        """(a + b) for values < 2p each -> < 2p."""
        if _is_concrete(a, b):
            return _prim_jit(("mod_add", self.p), self._mod_add_impl)(a, b)
        return self._mod_add_impl(a, b)

    def _mod_add_impl(self, a, b):
        return self._cond_sub_2p(split_rounds(jnp.asarray(a) + jnp.asarray(b), 2))

    def mod_sub(self, a, b):
        """(a - b) mod 2p-window for values < 2p each -> < 2p."""
        if _is_concrete(a, b):
            return _prim_jit(("mod_sub", self.p), self._mod_sub_impl)(a, b)
        return self._mod_sub_impl(a, b)

    def _mod_sub_impl(self, a, b):
        p2 = self._col(self.p2_np, jnp.asarray(a).ndim)
        return self._cond_sub_2p(
            split_rounds(jnp.asarray(a) + p2 - jnp.asarray(b), 2))

    def neg(self, a):
        """(-a) for value < 2p -> < 2p."""
        if _is_concrete(a):
            return _prim_jit(("neg", self.p), self._neg_impl)(a)
        return self._neg_impl(a)

    def _neg_impl(self, a):
        p2 = self._col(self.p2_np, jnp.asarray(a).ndim)
        return self._cond_sub_2p(split_rounds(p2 - jnp.asarray(a), 2))

    def mul_small(self, a, k: int):
        """a * k for 0 <= k <= 8, value < 2p in, < 2p out."""
        if _is_concrete(a):
            return _prim_jit(("mul_small", self.p, k),
                             lambda x: self._mul_small_impl(x, k))(a)
        return self._mul_small_impl(a, k)

    def _mul_small_impl(self, a, k: int):
        if not 0 <= k <= 8:
            raise ValueError("k out of range")
        if k == 0:
            return self.zero_bc(jnp.asarray(a).shape[1:])
        s = split_rounds(jnp.asarray(a) * k, 2)
        # s < 2kp: halve the bound each step by conditionally subtracting
        # 2p * 2^t for t = ceil(log2 k)-1 .. 0:  < 2^(t+2) p -> < 2^(t+1) p.
        t = (k - 1).bit_length() - 1
        while t >= 0:
            sub = self._col(bn.int_to_limbs(2 * self.p * (1 << t)).astype(np.int32),
                            s.ndim)
            d = s - sub
            neg = is_negative(d)
            s = jnp.where(neg[None], s, split_rounds(d, 2))
            t -= 1
        return s

    # -- lazy-reduction ops (round-3 hot-path API) ---------------------------
    #
    # The round-2 mod_add/mod_sub/mul_small each paid a Kogge-Stone-based
    # conditional subtraction (~60-80 elementwise ops — comparable to half
    # a field mul) to normalize every intermediate back to < 2p.  That is
    # wasted work: the CIOS mul tolerates operand VALUES up to ~16p (and
    # products up to 256p^2 still emerge < 2p), so curve formulas can run
    # entirely on lazily-reduced values whose bound the CALLER tracks
    # statically (ops/ecp256.py documents the per-coordinate invariants).
    # Only limb magnitudes must stay < 2^13 for the CIOS int32 headroom —
    # one value-preserving carry-save round (split_rounds(.., 1), ~4 ops)
    # after each add/sub/scale is enough.  No conditional subtractions
    # anywhere in the hot loop.

    def _kp_np(self, k: int) -> np.ndarray:
        key = ("kp", k)
        cached = _PRIM_CACHE.get((self.p, key))
        if cached is None:
            cached = bn.int_to_limbs(k * self.p).astype(np.int32)
            _PRIM_CACHE[(self.p, key)] = cached
        return cached

    def addl(self, a, b):
        """Lazy add: value(a)+value(b); bound = sum of bounds (caller
        tracks; keep mul operands <= ~16p).  ~4 elementwise ops."""
        if _is_concrete(a, b):
            return _prim_jit(("addl", self.p), self._addl_impl)(a, b)
        return self._addl_impl(a, b)

    def _addl_impl(self, a, b):
        return split_rounds(jnp.asarray(a) + jnp.asarray(b), 1)

    def subl(self, a, b, k: int):
        """Lazy subtract: a - b + k*p, REQUIRES value(b) < k*p so the
        result is non-negative.  Bound = bound(a) + k*p."""
        if _is_concrete(a, b):
            return _prim_jit(("subl", self.p, k),
                             lambda x, y: self._subl_impl(x, y, k))(a, b)
        return self._subl_impl(a, b, k)

    def _subl_impl(self, a, b, k: int):
        a = jnp.asarray(a)
        kp = self._col(self._kp_np(k), a.ndim)
        return split_rounds(a - jnp.asarray(b) + kp, 1)

    def smalll(self, a, c: int):
        """Lazy small-scalar multiply (1 <= c <= 8): bound = c * bound(a)."""
        if _is_concrete(a):
            return _prim_jit(("smalll", self.p, c),
                             lambda x: self._smalll_impl(x, c))(a)
        return self._smalll_impl(a, c)

    def _smalll_impl(self, a, c: int):
        if not 1 <= c <= 8:
            raise ValueError("smalll scale out of range")
        return split_rounds(jnp.asarray(a) * c, 1)

    def reduce_to_2p(self, a, kbound: int):
        """Lazily-bounded value < kbound*p -> value < 2p (for handoff to
        the canonical predicates).  ceil(log2(kbound))-1 conditional
        subtractions — use only OUTSIDE hot loops."""
        if _is_concrete(a):
            return _prim_jit(("red2p", self.p, kbound),
                             lambda x: self._reduce_to_2p_impl(x, kbound))(a)
        return self._reduce_to_2p_impl(a, kbound)

    def _reduce_to_2p_impl(self, a, kbound: int):
        s = jnp.asarray(a)
        t = max(0, (kbound - 1).bit_length() - 1)
        while t >= 1:
            sub = self._col(self._kp_np(1 << t), s.ndim)
            d = s - sub
            neg = is_negative(d)
            s = jnp.where(neg[None], s, split_rounds(d, 2))
            t -= 1
        return s

    def reduce_to_kp(self, a, kbound: int, target_k: int = 2):
        """Lazily-bounded value < kbound*p -> value < target_k*p via
        conditional subtractions of halving multiples (target_k a power
        of two).  Cheaper than reduce_to_2p when the consumer tolerates a
        larger bound (e.g. tower-field accumulators)."""
        if _is_concrete(a):
            return _prim_jit(("redkp", self.p, kbound, target_k),
                             lambda x: self._reduce_to_kp_impl(
                                 x, kbound, target_k))(a)
        return self._reduce_to_kp_impl(a, kbound, target_k)

    def _reduce_to_kp_impl(self, a, kbound: int, target_k: int):
        s = jnp.asarray(a)
        t = max(0, (kbound - 1).bit_length() - 1)
        floor_t = max(1, (target_k - 1).bit_length())
        while t >= floor_t:
            sub = self._col(self._kp_np(1 << t), s.ndim)
            d = s - sub
            neg = is_negative(d)
            s = jnp.where(neg[None], s, split_rounds(d, 2))
            t -= 1
        return s

    def is_zero_k(self, a, kbound: int):
        """value(a) == 0 mod p for a lazily-bounded value < kbound*p:
        (B,) bool.  One exact resolve + kbound limb comparisons."""
        if _is_concrete(a):
            return _prim_jit(("is0k", self.p, kbound),
                             lambda x: self._is_zero_k_impl(x, kbound))(a)
        return self._is_zero_k_impl(a, kbound)

    def _is_zero_k_impl(self, a, kbound: int):
        r = resolve(jnp.asarray(a))
        acc = None
        for j in range(kbound):
            jp = self._col(self._kp_np(j), r.ndim) if j else None
            hit = (jnp.all(r == jp, axis=0) if j
                   else jnp.all(r == 0, axis=0))
            acc = hit if acc is None else (acc | hit)
        return acc

    def eq_k(self, a, b, kbound_b: int, kbound_sum: int):
        """value(a) == value(b) mod p; b bounded < kbound_b*p, and
        kbound_sum >= bound(a)/p + kbound_b."""
        return self.is_zero_k(self.subl(a, b, kbound_b), kbound_sum)

    def inv_tree(self, a, min_width: int = 64):
        """Batched modular inverse via Montgomery's simultaneous-inversion
        trick as a product tree over the batch axis: ~3 muls per element
        plus one Fermat chain on a min_width-wide stub — replaces the
        ~330-mul per-element Fermat ladder.

        a: (L, B) Montgomery-form values, B a power of two, with NO zero
        elements (callers must pre-select zeros to 1; zero poisons the
        whole product tree).  inv of the Montgomery form x gives the
        Montgomery form of x^-1.
        """
        a = jnp.asarray(a)
        stack = []
        cur = a
        while cur.shape[1] > min_width and cur.shape[1] % 2 == 0:
            left, right = cur[:, 0::2], cur[:, 1::2]
            stack.append((left, right))
            cur = self.mul(left, right)
        inv = self.pow_const_scan(cur, self.p - 2)
        for left, right in reversed(stack):
            inv_left = self.mul(inv, right)
            inv_right = self.mul(inv, left)
            # interleave back: (L, 2, m) -> (L, 2m)
            inv = jnp.stack([inv_left, inv_right], axis=2).reshape(
                inv_left.shape[0], -1)
        return inv

    # -- conversions / predicates -------------------------------------------

    def to_mont(self, a):
        return self.mul(a, const_col(bn.int_to_limbs(self.r2_int), 2))

    def from_mont(self, a):
        one = np.zeros((L,), dtype=np.int32)
        one[0] = 1
        out = self.mul(a, const_col(one, 2))
        return self.canon(out)

    def canon(self, a):
        """Relaxed (< 2p) -> canonical [0, p) limbs."""
        if _is_concrete(a):
            return _prim_jit(("canon", self.p), self._canon_impl)(a)
        return self._canon_impl(a)

    def _canon_impl(self, a):
        r = resolve(a)
        p_l = self._col(self.p_np, r.ndim)
        d = r - p_l
        neg = is_negative(d)
        return jnp.where(neg[None], r, resolve(jnp.where(neg[None], r, d)))

    def is_zero(self, a):
        """value(a) == 0 mod p for relaxed a < 2p: (B,) bool."""
        if _is_concrete(a):
            return _prim_jit(("is_zero", self.p), self._is_zero_impl)(a)
        return self._is_zero_impl(a)

    def _is_zero_impl(self, a):
        r = resolve(a)
        p_l = self._col(self.p_np, r.ndim)
        return jnp.all(r == 0, axis=0) | jnp.all(r == p_l, axis=0)

    def eq(self, a, b):
        return self.is_zero(self.mod_sub(a, b))

    def select(self, cond, a, b):
        return jnp.where(cond[None], a, b)

    # -- exponentiation ------------------------------------------------------

    def pow_const(self, a, e: int, window: int = 4):
        """a^e for a fixed python-int exponent; flat windowed ladder.

        ~(bits + bits/window) muls, fully unrolled: use where flat graphs
        are acceptable (inside Pallas kernels or modest exponents).
        """
        if e < 0:
            raise ValueError("negative exponent")
        bshape = jnp.asarray(a).shape[1:]
        if e == 0:
            return self.one_bc(bshape)
        tab = [self.one_bc(bshape), jnp.asarray(a)]
        for k in range(2, 1 << window):
            tab.append(self.mul(tab[k - 1], a))
        digits = []
        x = e
        while x:
            digits.append(x & ((1 << window) - 1))
            x >>= window
        digits.reverse()
        acc = tab[digits[0]]
        for d in digits[1:]:
            for _ in range(window):
                acc = self.sqr(acc)
            if d:
                acc = self.mul(acc, tab[d])
        return acc

    def pow_const_scan(self, a, e: int, window: int = 4):
        """pow_const with the window loop as a lax.scan over the exponent's
        digit array: same math, small traced graph.  For XLA contexts; the
        Pallas kernel unrolls its own fori_loop version instead (lax.scan
        digit consumption works there too, but the kernel prefers explicit
        scratch-backed digit reads)."""
        import jax.numpy as _jnp
        from jax import lax as _lax
        if e <= 0:
            return self.pow_const(a, e, window)
        bshape = _jnp.asarray(a).shape[1:]
        tab = [self.one_bc(bshape), _jnp.asarray(a)]
        for k in range(2, 1 << window):
            tab.append(self.mul(tab[k - 1], a))
        digits = []
        x = e
        while x:
            digits.append(x & ((1 << window) - 1))
            x >>= window
        digits.reverse()
        acc = tab[digits[0]]
        tab_arr = _jnp.stack(tab)          # (16, L, B)

        def body(acc, d):
            for _ in range(window):
                acc = self.sqr(acc)
            ent = tab_arr[0]
            for k in range(1, 1 << window):
                ent = _jnp.where(d == k, tab_arr[k], ent)
            return self.mul(acc, ent), None

        acc, _ = _lax.scan(body, acc,
                           _jnp.asarray(digits[1:], dtype=_jnp.int32))
        return acc

    def inv(self, a):
        """a^(p-2) (Fermat; p prime). inv(0) = 0."""
        if _is_concrete(a):
            # eager: python-unrolled windows over jitted primitives
            return self.pow_const(a, self.p - 2)
        return self.pow_const_scan(a, self.p - 2)


# ---------------------------------------------------------------------------
# Canonical-limb comparisons (range checks on inputs)
# ---------------------------------------------------------------------------

def lt_const(x, c: int):
    """(L', B) limbs (|l| < 2^13) < python int c -> (B,) bool."""
    def impl(x):
        c_l = const_col(bn.int_to_limbs(c, x.shape[0]), x.ndim)
        return is_negative(x - c_l)
    if _is_concrete(x):
        return _prim_jit(("lt_const", c, x.shape[0]), impl)(x)
    return impl(x)


def eq_const(x, c: int):
    r = resolve(x)
    c_l = const_col(bn.int_to_limbs(c, x.shape[0]), x.ndim)
    return jnp.all(r == c_l, axis=0)


def is_zero_limbs(x):
    if _is_concrete(x):
        return _prim_jit(("is_zero_limbs",),
                         lambda y: jnp.all(resolve(y) == 0, axis=0))(x)
    return jnp.all(resolve(x) == 0, axis=0)
