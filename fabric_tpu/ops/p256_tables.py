"""Host-side per-key fixed-base comb tables for ECDSA-P256 verification.

The verify workload this framework exists for (SURVEY.md §3.2) is heavily
key-repetitive: a 10k-tx block carries ~3 endorsement signatures per tx
from a handful of stable org endorser certificates (the reference's own
msp/cache exists because identities repeat, msp/cache/cache.go).  For a
repeated public key Q the u2*Q half of the verification can use the same
fixed-base comb the generator G already enjoys (ops/ecp256.py):
COMB_WINDOWS windows of COMB_W bits over a precomputed table of
k * 2^(COMB_W*j) * Q — replacing the 256-doubling windowed ladder
entirely and roughly tripling per-sig throughput (ops/p256_fixed.py).

This module builds those tables on the host with python-int Jacobian
arithmetic + one Montgomery-trick batched inversion (~150 ms per key) and
caches them by SEC1 pubkey, so the cost amortizes across blocks.  The
on-curve check happens ONCE here at build time; the device kernel for
cached keys never sees Q at all.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from . import bignum as bn
from . import ecp256 as ec

P = ec.P
COMB_W = ec.COMB_W
COMB_WINDOWS = ec.COMB_WINDOWS
COMB_ENTRIES = ec.COMB_ENTRIES
L = ec.L


# -- python-int Jacobian arithmetic (no inversions until the end) ------------

def _jdbl(pt):
    X, Y, Z = pt
    delta = Z * Z % P
    gamma = Y * Y % P
    beta = X * gamma % P
    alpha = 3 * (X - delta) * (X + delta) % P
    X3 = (alpha * alpha - 8 * beta) % P
    Z3 = ((Y + Z) * (Y + Z) - gamma - delta) % P
    Y3 = (alpha * (4 * beta - X3) - 8 * gamma * gamma) % P
    return X3, Y3, Z3


def _jadd(p1, p2):
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    if Z1 == 0:
        return p2
    if Z2 == 0:
        return p1
    z1z1 = Z1 * Z1 % P
    z2z2 = Z2 * Z2 % P
    u1 = X1 * z2z2 % P
    u2 = X2 * z1z1 % P
    s1 = Y1 * Z2 * z2z2 % P
    s2 = Y2 * Z1 * z1z1 % P
    h = (u2 - u1) % P
    r = (s2 - s1) % P
    if h == 0:
        if r == 0:
            return _jdbl(p1)
        return (1, 1, 0)
    h2 = h * h % P
    h3 = h * h2 % P
    u1h2 = u1 * h2 % P
    X3 = (r * r - h3 - 2 * u1h2) % P
    Y3 = (r * (u1h2 - X3) - s1 * h3) % P
    Z3 = Z1 * Z2 * h % P
    return X3, Y3, Z3


def _batch_to_affine(points):
    """Jacobian -> affine for a list of points with one modular inversion
    (Montgomery's trick).  No infinities allowed."""
    zs = [pt[2] for pt in points]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % P)
    inv_all = pow(prefix[-1], P - 2, P)
    out = [None] * len(points)
    for i in range(len(points) - 1, -1, -1):
        z_inv = inv_all * prefix[i] % P
        inv_all = inv_all * zs[i] % P
        z2 = z_inv * z_inv % P
        X, Y, _ = points[i]
        out[i] = (X * z2 % P, Y * z2 % P * z_inv % P)
    return out


def on_curve(qx: int, qy: int) -> bool:
    if not (0 <= qx < P and 0 <= qy < P):
        return False
    return (qy * qy - (qx * qx * qx + ec.A * qx + ec.B)) % P == 0


def comb_table_for_point(qx: int, qy: int) -> np.ndarray:
    """(COMB_WINDOWS * COMB_ENTRIES, 2L) f32 comb table for Q = (qx, qy):
    row j*COMB_ENTRIES+k holds the Montgomery-form affine limbs of
    k * 2^(COMB_W*j) * Q (k = 0 rows are zero, patched at lookup time —
    ec.comb_table_f32's G table uses the same builder).

    Raises ValueError for points not on the curve — this is the single
    on-curve gate for the fixed-base fast path.
    """
    if not on_curve(qx, qy):
        raise ValueError("point not on P-256")
    jac = []                      # (window, k) in order, k = 1..2^W-1
    base = (qx, qy, 1)
    for j in range(COMB_WINDOWS):
        acc = base
        jac.append(acc)
        for _ in range(COMB_ENTRIES - 2):
            acc = _jadd(acc, base)
            jac.append(acc)
        for _ in range(COMB_W):
            base = _jdbl(base)
    affine = _batch_to_affine(jac)
    rows = np.zeros((COMB_WINDOWS * COMB_ENTRIES, 2 * L), dtype=np.float32)
    R = ec.fp.R
    idx = 0
    for j in range(COMB_WINDOWS):
        for k in range(1, COMB_ENTRIES):
            x, y = affine[idx]
            idx += 1
            rows[j * COMB_ENTRIES + k, :L] = bn.int_to_limbs(x * R % P)
            rows[j * COMB_ENTRIES + k, L:] = bn.int_to_limbs(y * R % P)
    return rows


class KeyTableCache:
    """LRU cache of HOST-side per-key comb tables, keyed by SEC1 pubkey.

    Thread-safe.  A table is (8192, 44) f32 = 1.44 MB; 64 keys ~ 92 MB.
    The production provider keeps tables DEVICE-resident instead
    (ops/device_bank.DeviceBank); this host cache serves tests and
    host-only tooling.
    """

    def __init__(self, max_keys: int = 64):
        self.max_keys = max_keys
        self._lru: "OrderedDict[bytes, np.ndarray]" = OrderedDict()
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "builds": 0, "rejects": 0}

    def __contains__(self, pubkey: bytes) -> bool:
        with self._lock:
            return pubkey in self._lru

    def get(self, pubkey: bytes) -> Optional[np.ndarray]:
        with self._lock:
            tab = self._lru.get(pubkey)
            if tab is not None:
                self._lru.move_to_end(pubkey)
                self.stats["hits"] += 1
            return tab

    def get_or_build(self, pubkey: bytes) -> Optional[np.ndarray]:
        """Build (and cache) the table for an uncompressed SEC1 pubkey;
        returns None for malformed/off-curve keys."""
        tab = self.get(pubkey)
        if tab is not None:
            return tab
        if len(pubkey) != 65 or pubkey[0] != 0x04:
            self.stats["rejects"] += 1
            return None
        qx = int.from_bytes(pubkey[1:33], "big")
        qy = int.from_bytes(pubkey[33:65], "big")
        try:
            tab = comb_table_for_point(qx, qy)
        except ValueError:
            self.stats["rejects"] += 1
            return None
        with self._lock:
            self.stats["builds"] += 1
            self._lru[pubkey] = tab
            while len(self._lru) > self.max_keys:
                self._lru.popitem(last=False)
        return tab
