"""Host-side niels-form comb tables for ed25519 verification.

The ed25519 verify equation [S]B - [k]A == R has a FIXED base B (the
RFC 8032 generator) and, on the key-repetitive workloads this framework
exists for (SURVEY.md §3.2 — endorser/client identities repeat; the
reference's msp/cache embodies the same assumption), a heavily repeated
A.  Both scalar halves therefore run as fixed-base signed combs over
host-precomputed tables (ops/edwards.py comb_accumulate*), the exact
strategy of the P-256 fast lane (ops/p256_tables.py).

Tables store "niels" triples (y-x, y+x, 2dxy) — Montgomery-form,
canonical — because the mixed add then costs 7 muls and signed digits
negate by a swap.  Row j*COMB_ROWS + m = niels(m * 2^(7j) * T) for
m = 1..64; row j*COMB_ROWS + 0 = niels(identity) = (1, 1, 0), which the
complete formulas absorb with no masking.

Per-key tables are built for -A (the verification equation needs the
negation), keyed by the 32-byte compressed public key; decompression
and the on-curve/canonicality checks happen ONCE here at build time.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from . import bignum as bn
from . import edwards as ed

P = ed.P
D = ed.D
COMB_W = ed.COMB_W
COMB_WINDOWS = ed.COMB_WINDOWS
COMB_ROWS = ed.COMB_ROWS
L = bn.N_LIMBS


# -- python-int extended-coordinate arithmetic -------------------------------

def _ext_add(p1, p2):
    X1, Y1, Z1, T1 = p1
    X2, Y2, Z2, T2 = p2
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * D * T1 % P * T2 % P
    Dv = 2 * Z1 * Z2 % P
    E, F, G, H = B - A, Dv - C, Dv + C, B + A
    return E * F % P, G * H % P, F * G % P, E * H % P


def _ext_dbl(p):
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + B
    E = H - (X1 + Y1) * (X1 + Y1)
    G = A - B
    F = C + G
    return E * F % P, G * H % P, F * G % P, E * H % P


def _batch_to_affine(points):
    """Extended -> affine with one inversion (Montgomery's trick)."""
    zs = [pt[2] for pt in points]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % P)
    inv_all = pow(prefix[-1], P - 2, P)
    out = [None] * len(points)
    for i in range(len(points) - 1, -1, -1):
        z_inv = inv_all * prefix[i] % P
        inv_all = inv_all * zs[i] % P
        X, Y, _, _ = points[i]
        out[i] = (X * z_inv % P, Y * z_inv % P)
    return out


def on_curve(x: int, y: int) -> bool:
    """-x^2 + y^2 == 1 + d x^2 y^2 (twisted Edwards, a = -1)."""
    x2, y2 = x * x % P, y * y % P
    return (y2 - x2 - 1 - D * x2 % P * y2) % P == 0


def decompress_int(pk: bytes) -> Optional[tuple]:
    """RFC 8032 §5.1.3 decompression with python ints; None if invalid."""
    if len(pk) != 32:
        return None
    enc = int.from_bytes(pk, "little")
    sign = (enc >> 255) & 1
    y = enc & ((1 << 255) - 1)
    if y >= P:
        return None
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    x = u * pow(v, P - 2, P) % P           # x^2
    cand = pow(x, (P + 3) // 8, P)
    if cand * cand % P != x:
        cand = cand * ed.SQRT_M1 % P
        if cand * cand % P != x:
            return None
    if cand == 0 and sign == 1:
        return None
    if cand & 1 != sign:
        cand = (-cand) % P
    return cand, y


def comb_table_for_point(x: int, y: int) -> np.ndarray:
    """(COMB_WINDOWS * COMB_ROWS, 3L) f32 niels comb table for T=(x,y).

    Raises ValueError for points not on the curve — the single on-curve
    gate for the fixed-base fast path (the kernel never sees T).
    """
    if not (0 <= x < P and 0 <= y < P and on_curve(x, y)):
        raise ValueError("point not on edwards25519")
    ext = []
    base = (x, y, 1, x * y % P)
    for j in range(COMB_WINDOWS):
        acc = base
        ext.append(acc)
        for _ in range(COMB_ROWS - 2):
            acc = _ext_add(acc, base)
            ext.append(acc)
        for _ in range(COMB_W):
            base = _ext_dbl(base)
    affine = _batch_to_affine(ext)
    rows = np.zeros((COMB_WINDOWS * COMB_ROWS, 3 * L), dtype=np.float32)
    R = ed.fp.R
    one_m = bn.int_to_limbs(R % P)
    idx = 0
    for j in range(COMB_WINDOWS):
        # row 0: identity niels (1, 1, 0) in Montgomery form
        rows[j * COMB_ROWS, :L] = one_m
        rows[j * COMB_ROWS, L:2 * L] = one_m
        for m in range(1, COMB_ROWS):
            px, py = affine[idx]
            idx += 1
            rows[j * COMB_ROWS + m, :L] = bn.int_to_limbs(
                (py - px) % P * R % P)
            rows[j * COMB_ROWS + m, L:2 * L] = bn.int_to_limbs(
                (py + px) % P * R % P)
            rows[j * COMB_ROWS + m, 2 * L:] = bn.int_to_limbs(
                2 * D % P * px % P * py % P * R % P)
    return rows


_B_CACHE = {}


def basepoint_table() -> np.ndarray:
    """The global comb table for the RFC 8032 basepoint B."""
    if "t" not in _B_CACHE:
        _B_CACHE["t"] = comb_table_for_point(ed.BX, ed.BY)
    return _B_CACHE["t"]


class Ed25519KeyTableCache:
    """LRU cache of per-key niels comb tables for -A, keyed by the
    32-byte compressed public key.  ~640 KB per key."""

    def __init__(self, max_keys: int = 128):
        self.max_keys = max_keys
        self._lru: "OrderedDict[bytes, np.ndarray]" = OrderedDict()
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "builds": 0, "rejects": 0}

    def __contains__(self, pubkey: bytes) -> bool:
        with self._lock:
            return pubkey in self._lru

    def get(self, pubkey: bytes) -> Optional[np.ndarray]:
        with self._lock:
            tab = self._lru.get(pubkey)
            if tab is not None:
                self._lru.move_to_end(pubkey)
                self.stats["hits"] += 1
            return tab

    def get_or_build(self, pubkey: bytes) -> Optional[np.ndarray]:
        tab = self.get(pubkey)
        if tab is not None:
            return tab
        aff = decompress_int(bytes(pubkey))
        if aff is None:
            self.stats["rejects"] += 1
            return None
        ax, ay = aff
        tab = comb_table_for_point((-ax) % P, ay)    # table is for -A
        with self._lock:
            self.stats["builds"] += 1
            self._lru[pubkey] = tab
            while len(self._lru) > self.max_keys:
                self._lru.popitem(last=False)
        return tab
