"""Batched BN254 ate pairing on TPU — the BASELINE config-4 kernel.

The host Idemix plane (fabric_tpu/idemix/bn254.py) verifies one
presentation in ~2 s because a python-int pairing runs at ~1.4
pairings/s.  This kernel evaluates e(P_i, Q) for a BATCH of G1 points
against a FIXED G2 point: the ate Miller loop's line functions depend
only on multiples of Q, so the host precomputes every step's sparse
line constants once (bn254.ate_precompute) and the device's per-element
work is pure Fp tower arithmetic on the flatfield layer —
(L, B) int32 limb arrays, Fp2 by Karatsuba, Fp12 as six Fp2
coefficients over w^6 = 1+i, one conditional-subtraction normalization
per Fp12 product (BN254's p is ~2^254 against R = 2^264, so lazily-
reduced values up to ~64p stay CIOS-safe).

Fixed-Q batching is exactly the Idemix verification shape: the pairing
checks of a presentation batch share the issuer's w / g2 on the G2 side
(credential.verify_presentation), mirroring how the P-256 fast path
keys on repeated public keys.

The final exponentiation is a plain square-and-multiply over
(p^12-1)/r (~2800 bits) — correct and compile-friendly; the known
10x-class refinements (easy/hard split with a tower inversion,
cyclotomic squarings, BN exponent chains) are documented headroom, not
yet built.

Differential testing: component ops + a Miller-loop prefix match the
host oracle on CPU (tests/test_bn254_batch.py); the full pairing is
cross-checked on TPU by experiments/bench_pairing.py.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np
import jax.numpy as jnp

from fabric_tpu.idemix import bn254 as hb

from . import bignum as bn
from . import flatfield as ff
from .flatfield import FlatMod, L

fpb = FlatMod(hb.P, "bn254.p")

# Fp2 element: (c0, c1) of (L, B) int32 limb arrays, Montgomery form,
# lazily reduced.  Fp12: tuple of 6 Fp2.  Stable bound discipline:
# every Fp12-product component is normalized to < 8p (reduce_to_kp), so
# Karatsuba sums stay < 16p, products < 256 p^2, CIOS outputs < ~1.3p.

_RED_K = 96        # accumulated component bound before normalization
_TGT_K = 8


def f2_add(a, b):
    return (fpb.addl(a[0], b[0]), fpb.addl(a[1], b[1]))


def f2_sub(a, b, k: int):
    return (fpb.subl(a[0], b[0], k), fpb.subl(a[1], b[1], k))


def f2_neg(a, k: int):
    z = fpb.zero_bc(jnp.asarray(a[0]).shape[1:])
    return (fpb.subl(z, a[0], k), fpb.subl(z, a[1], k))


def f2_mul(a, b):
    """Karatsuba (i^2 = -1): inputs < 16p per component."""
    t0 = fpb.mul(a[0], b[0])
    t1 = fpb.mul(a[1], b[1])
    t2 = fpb.mul(fpb.addl(a[0], a[1]), fpb.addl(b[0], b[1]))
    re = fpb.subl(t0, t1, 2)                       # < ~4p
    im = fpb.subl(t2, fpb.addl(t0, t1), 4)         # < ~6p
    return (re, im)


def f2_scale(a, s):
    """Fp2 x Fp scalar (s an (L, B) Fp element)."""
    return (fpb.mul(a[0], s), fpb.mul(a[1], s))


def f2_mul_xi(a, k: int):
    """* XI = (1 + i):  (c0 - c1, c0 + c1)."""
    return (fpb.subl(a[0], a[1], k), fpb.addl(a[0], a[1]))


def f12_norm(x):
    return tuple((fpb.reduce_to_kp(c[0], _RED_K, _TGT_K),
                  fpb.reduce_to_kp(c[1], _RED_K, _TGT_K)) for c in x)


def f12_mul(a, b):
    """Schoolbook over w^6 = XI, then one normalization pass."""
    acc = [None] * 6
    for i in range(6):
        for j in range(6):
            prod = f2_mul(a[i], b[j])
            k = i + j
            if k >= 6:
                prod = f2_mul_xi(prod, 8)
                k -= 6
            acc[k] = prod if acc[k] is None else f2_add(acc[k], prod)
    return f12_norm(tuple(acc))


def f12_sqr(a):
    return f12_mul(a, a)


def f12_mul_sparse013(a, b0, b1, b3):
    """a (dense) * sparse line: components {0: Fp b0, 1: Fp2 b1,
    3: Fp2 b3} — 30 Fp muls instead of 108."""
    acc = [None] * 6
    for i in range(6):
        # j = 0 (Fp scalar)
        p0 = f2_scale(a[i], b0)
        acc[i] = p0 if acc[i] is None else f2_add(acc[i], p0)
        # j = 1
        k = i + 1
        p1 = f2_mul(a[i], b1)
        if k >= 6:
            p1 = f2_mul_xi(p1, 8)
            k -= 6
        acc[k] = p1 if acc[k] is None else f2_add(acc[k], p1)
        # j = 3
        k = i + 3
        p3 = f2_mul(a[i], b3)
        if k >= 6:
            p3 = f2_mul_xi(p3, 8)
            k -= 6
        acc[k] = p3 if acc[k] is None else f2_add(acc[k], p3)
    return f12_norm(tuple(acc))


def f12_select(cond, a, b):
    return tuple((fpb.select(cond, x[0], y[0]), fpb.select(cond, x[1], y[1]))
                 for x, y in zip(a, b))


def f12_one(bshape):
    one = fpb.one_bc(bshape)
    zero = fpb.zero_bc(bshape)
    return ((one, zero),) + (((zero, zero),) * 5)


# ---------------------------------------------------------------------------
# host-side constant packing
# ---------------------------------------------------------------------------

def _mont_limbs(x: int) -> np.ndarray:
    return bn.int_to_limbs((x % hb.P) * fpb.R % hb.P).astype(np.int32)


def pack_steps(steps) -> dict:
    """bn254.ate_precompute output -> stacked numpy constants:
    flags (S,), A/B as (S, 2, L) Montgomery limbs."""
    flags = np.asarray([s[0] for s in steps], dtype=np.int32)
    A = np.stack([[_mont_limbs(s[1][0]), _mont_limbs(s[1][1])]
                  for s in steps])
    B = np.stack([[_mont_limbs(s[2][0]), _mont_limbs(s[2][1])]
                  for s in steps])
    return {"flags": flags, "A": A, "B": B}


_EXP = (hb.P ** 12 - 1) // hb.R
_EXP_BITS = np.asarray([int(b) for b in bin(_EXP)[2:]], dtype=np.int32)

# |u| for the BN parameter (X_BN < 0), MSB-first bits after the leading 1
_ABS_U_BITS = np.asarray([int(b) for b in bin(-hb.X_BN)[3:]],
                         dtype=np.int32)

# ---------------------------------------------------------------------------
# the batched pairing
# ---------------------------------------------------------------------------

def miller_loop(packed, xP_l, yP_l, n_steps: int = None, eager: bool = None):
    """f_{lambda,Q}(P) over canonical G1 limb inputs (L, B).

    n_steps limits the loop (differential prefix tests); eager drives a
    python loop for CPU testing instead of lax.scan.
    """
    from jax import lax

    eager = ff._is_concrete(xP_l) if eager is None else eager
    bshape = jnp.asarray(xP_l).shape[1:]
    xP = fpb.to_mont(xP_l)
    yP = fpb.to_mont(yP_l)

    flags = jnp.asarray(packed["flags"])
    A = jnp.asarray(packed["A"])          # (S, 2, L)
    B = jnp.asarray(packed["B"])
    if n_steps is not None:
        flags, A, B = flags[:n_steps], A[:n_steps], B[:n_steps]

    def body(f, xs):
        flag, a_c, b_c = xs
        fsq = f12_sqr(f)
        f = f12_select(jnp.broadcast_to(flag != 0, bshape), fsq, f)
        a2 = (jnp.broadcast_to(a_c[0][:, None], (L,) + tuple(bshape)),
              jnp.broadcast_to(a_c[1][:, None], (L,) + tuple(bshape)))
        b2 = (jnp.broadcast_to(b_c[0][:, None], (L,) + tuple(bshape)),
              jnp.broadcast_to(b_c[1][:, None], (L,) + tuple(bshape)))
        line1 = f2_scale(a2, xP)          # A * xP   (component 1)
        f = f12_mul_sparse013(f, yP, line1, b2)
        return f, None

    f = f12_one(bshape)
    if eager:
        for i in range(int(flags.shape[0])):
            f, _ = body(f, (flags[i], (A[i, 0], A[i, 1]),
                            (B[i, 0], B[i, 1])))
        return f
    f, _ = lax.scan(
        lambda carry, xs: body(carry, (xs[0], (xs[1][0], xs[1][1]),
                                       (xs[2][0], xs[2][1]))),
        f, (flags, A, B))
    return f


def final_exp(f, eager: bool = None):
    """f ^ ((p^12 - 1) / r) by square-and-multiply (documented headroom:
    easy/hard split + cyclotomic arithmetic)."""
    from jax import lax

    eager = ff._is_concrete(f[0][0]) if eager is None else eager
    bshape = jnp.asarray(f[0][0]).shape[1:]
    base = f
    acc = f  # MSB of the exponent is 1

    bits = jnp.asarray(_EXP_BITS[1:])

    def body(acc, bit):
        acc = f12_sqr(acc)
        mul = f12_mul(acc, base)
        return f12_select(jnp.broadcast_to(bit != 0, bshape), mul, acc), None

    if eager:
        for i in range(int(bits.shape[0])):
            acc, _ = body(acc, bits[i])
        return acc
    acc, _ = lax.scan(body, acc, bits)
    return acc


def miller_loop_dual(packed1, packed2, x1_l, y1_l, x2_l, y2_l,
                     n_steps: int = None, eager: bool = None):
    """Combined Miller loop for TWO fixed-Q pairings with SHARED
    squarings: f_{lam,Q1}(P1) * f_{lam,Q2}(P2).

    Both precomputes come from the same loop scalar (bn254.ATE_LAMBDA),
    so their step sequences align 1:1 — each step squares f once (when
    flag=1) and multiplies BOTH sparse lines in.  This halves the f12
    squaring chain vs two separate loops and, with the single final
    exponentiation of pairing_check_batch, makes the product-equals-one
    form of an equality check ~2x cheaper than two full pairings.
    """
    from jax import lax

    eager = ff._is_concrete(x1_l) if eager is None else eager
    bshape = jnp.asarray(x1_l).shape[1:]
    xs_m = [fpb.to_mont(v) for v in (x1_l, y1_l, x2_l, y2_l)]
    x1m, y1m, x2m, y2m = xs_m

    flags = jnp.asarray(packed1["flags"])
    A1 = jnp.asarray(packed1["A"])
    B1 = jnp.asarray(packed1["B"])
    A2 = jnp.asarray(packed2["A"])
    B2 = jnp.asarray(packed2["B"])
    assert packed1["flags"].shape == packed2["flags"].shape, \
        "dual loop requires aligned step sequences"
    if n_steps is not None:
        flags, A1, B1, A2, B2 = (v[:n_steps]
                                 for v in (flags, A1, B1, A2, B2))

    def bcast(c):
        return (jnp.broadcast_to(c[0][:, None], (L,) + tuple(bshape)),
                jnp.broadcast_to(c[1][:, None], (L,) + tuple(bshape)))

    def body(f, xs):
        flag, a1, b1, a2, b2 = xs
        fsq = f12_sqr(f)
        f = f12_select(jnp.broadcast_to(flag != 0, bshape), fsq, f)
        f = f12_mul_sparse013(f, y1m, f2_scale(bcast(a1), x1m), bcast(b1))
        f = f12_mul_sparse013(f, y2m, f2_scale(bcast(a2), x2m), bcast(b2))
        return f, None

    f = f12_one(bshape)
    if eager:
        for i in range(int(flags.shape[0])):
            f, _ = body(f, (flags[i], (A1[i, 0], A1[i, 1]),
                            (B1[i, 0], B1[i, 1]),
                            (A2[i, 0], A2[i, 1]),
                            (B2[i, 0], B2[i, 1])))
        return f
    f, _ = lax.scan(
        lambda carry, xs: body(carry, (
            xs[0], (xs[1][0], xs[1][1]), (xs[2][0], xs[2][1]),
            (xs[3][0], xs[3][1]), (xs[4][0], xs[4][1]))),
        f, (flags, A1, B1, A2, B2))
    return f


def pairing_check_batch(packed1, packed2, x1_l, y1_l, x2_l, y2_l):
    """Batched equality check e(P1_i, Q1) == e(-P2_i, Q2)^-1, i.e.
    e(P1_i, Q1) * e(P2_i, Q2) == 1 — callers pass P2 = -Abar to check
    e(A', w) == e(Abar, g2), the idemix presentation pairing equation
    (fabric_tpu/idemix/credential.py verify_presentation check (1);
    reference: /root/reference/idemix/signature.go:230 Ver).

    Inputs are canonical (L, B) limb G1 coordinates; returns (B,) bool.
    On-curve membership is the CALLER's gate (idemix verify rejects
    off-curve points before collecting — soundness requires it).
    """
    f = miller_loop_dual(packed1, packed2, x1_l, y1_l, x2_l, y2_l)
    f = final_exp(f)
    one = fpb.one_bc(jnp.asarray(x1_l).shape[1:])
    ok = fpb.eq_k(f[0][0], one, 2, 18) & fpb.is_zero_k(f[0][1], 16)
    for c0, c1 in f[1:]:
        ok = ok & fpb.is_zero_k(c0, 16) & fpb.is_zero_k(c1, 16)
    return ok


def pairing_batch(packed, xP_l, yP_l):
    """Reduced ate pairing e(P_i, Q) -> Fp12 of canonical (L, B) limb
    arrays (matching the host oracle bit-for-bit after from_mont)."""
    f = miller_loop(packed, xP_l, yP_l)
    f = final_exp(f)
    return tuple((fpb.from_mont(fpb.reduce_to_kp(c[0], 16, 2)),
                  fpb.from_mont(fpb.reduce_to_kp(c[1], 16, 2)))
                 for c in f)


def to_host_ints(f12_limbs, b: int) -> tuple:
    """Canonical device output -> host Fp12 tuple for element b."""
    out = []
    for c0, c1 in f12_limbs:
        a0 = bn.limbs_to_int(np.asarray(c0)[:, b])
        a1 = bn.limbs_to_int(np.asarray(c1)[:, b])
        out.append((a0 % hb.P, a1 % hb.P))
    return tuple(out)
