"""Device-resident per-key comb-table banks (HBM slot allocator).

The round-4 fast lane rebuilt and re-shipped its key-table bank from
host to device on EVERY dispatch — per-key tables padded to a
power-of-two bucket, ~124 MB per dispatch on the realistic 67-key block
workload — which made the lane slower than the generic ladder it was
built to beat.  This module is the fix: each key's comb table is
uploaded to the device ONCE when it is built (or restored after
eviction), into a fixed-shape f32 bank held in HBM, and dispatches
carry only int32 slot indices.  The bank shape never changes, so it
also leaves the compiled-program signature: one XLA program per row
bucket instead of one per (row bucket x bank bucket).

The reference analogue is msp/cache (msp/cache/cache.go) — identities
repeat, so per-identity work is cached; here the cached artifact lives
in device memory because that is where it is consumed.

Capacity economics: a P-256 comb table is (8192, 44) f32 = 1.44 MB;
the default 256 slots hold ~370 MB of HBM — far more distinct *hot*
keys than any real channel has endorsing orgs or enrolled clients, and
~2% of a v5e chip's 16 GB.  (CPU test backends default to far fewer
slots — the zeros bank is host RAM there.)  Eviction is LRU over whole
slots; an evicted key's next qualifying batch simply rebuilds (host,
~150 ms) and re-uploads (1.4 MB) its table.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Optional, Tuple

import numpy as np


class DeviceBank:
    """Fixed-capacity slot allocator over one device-resident f32 bank.

    build_fn(pubkey) -> np.ndarray of `entry_shape` (host comb table),
    or None for malformed/off-curve keys (the single on-curve gate of
    the fast path).  Thread-safe; the bank array itself is immutable
    jax data — in-flight dispatches that captured an older version stay
    valid, updates swap the reference under the lock.
    """

    def __init__(self, max_keys: int, entry_shape: Tuple[int, ...],
                 build_fn: Callable[[bytes], Optional[np.ndarray]],
                 mesh=None):
        self.max_keys = int(max_keys)
        self.entry_shape = tuple(entry_shape)
        self.build_fn = build_fn
        self.mesh = mesh
        self._slots: "OrderedDict[bytes, int]" = OrderedDict()
        self._free = list(range(self.max_keys - 1, -1, -1))
        self._bank = None
        self._upd = None
        self._lock = threading.RLock()
        # refcounted pins: a slot claimed by an in-flight batch (from
        # lane choice until its dispatch captured the bank array) must
        # not be evicted — by THIS batch's later builds or by a
        # CONCURRENT batch on another thread (the provider is shared
        # across channels).  Callers pin via lookup/get_or_build
        # (pin=True) and release with unpin() after dispatching.
        self._pinned: dict = {}
        self.stats = {"hits": 0, "builds": 0, "rejects": 0,
                      "evictions": 0, "pinned_spills": 0, "h2d_bytes": 0}

    def __contains__(self, pubkey: bytes) -> bool:
        with self._lock:
            return pubkey in self._slots

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    # -- device plumbing ----------------------------------------------------

    def _ensure_bank(self):
        if self._bank is not None:
            return
        import jax
        import jax.numpy as jnp

        shape = (self.max_keys,) + self.entry_shape
        zeros = np.zeros(shape, np.float32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            sharding = NamedSharding(self.mesh, PartitionSpec())
            self._bank = jax.device_put(zeros, sharding)
            self._upd = jax.jit(
                lambda b, t, i: b.at[i].set(t), out_shardings=sharding)
        else:
            self._bank = jnp.asarray(zeros)
            # no donation: in-flight dispatches may still hold the old
            # bank; the on-device copy (~tens of MB at HBM bandwidth)
            # is negligible at table-build frequency
            self._upd = jax.jit(lambda b, t, i: b.at[i].set(t))

    def array(self):
        """The device-resident (max_keys, *entry_shape) f32 bank."""
        with self._lock:
            self._ensure_bank()
            return self._bank

    # -- slot allocation ----------------------------------------------------

    def lookup(self, pubkey: bytes, pin: bool = False) -> Optional[int]:
        """Slot index for a resident key (refreshes LRU), else None.
        pin=True atomically pins the returned slot against eviction."""
        with self._lock:
            slot = self._slots.get(pubkey)
            if slot is not None:
                self._slots.move_to_end(pubkey)
                self.stats["hits"] += 1
                if pin:
                    self._pinned[slot] = self._pinned.get(slot, 0) + 1
            return slot

    def unpin(self, slots) -> None:
        """Release pins taken via lookup/get_or_build(pin=True)."""
        with self._lock:
            for s in slots:
                n = self._pinned.get(s, 0) - 1
                if n <= 0:
                    self._pinned.pop(s, None)
                else:
                    self._pinned[s] = n

    def get_or_build(self, pubkey: bytes,
                     pin: bool = False) -> Optional[int]:
        """Slot index for the key, building + uploading its table if
        needed; None for malformed/off-curve keys or when every
        evictable slot is pinned by an in-flight batch (the new key
        spills to the generic lane instead)."""
        slot = self.lookup(pubkey, pin=pin)
        if slot is not None:
            return slot
        tab = self.build_fn(pubkey)
        if tab is None:
            self.stats["rejects"] += 1
            return None
        tab = np.ascontiguousarray(tab, dtype=np.float32)
        if tab.shape != self.entry_shape:
            raise ValueError(
                f"table shape {tab.shape} != bank entry {self.entry_shape}")
        import jax.numpy as jnp
        with self._lock:
            # lost race: another thread built it while we were building
            got = self._slots.get(pubkey)
            if got is not None:
                if pin:
                    self._pinned[got] = self._pinned.get(got, 0) + 1
                return got
            self._ensure_bank()
            if self._free:
                slot = self._free.pop()
            else:
                slot = None
                for old_pk, s in self._slots.items():      # LRU order
                    if not self._pinned.get(s):
                        slot = s
                        del self._slots[old_pk]
                        break
                if slot is None:
                    self.stats["pinned_spills"] += 1
                    return None
                self.stats["evictions"] += 1
            self.stats["builds"] += 1
            self.stats["h2d_bytes"] += tab.nbytes
            self._bank = self._upd(self._bank, jnp.asarray(tab),
                                   np.int32(slot))
            self._slots[pubkey] = slot
            if pin:
                self._pinned[slot] = self._pinned.get(slot, 0) + 1
        return slot
