"""Cluster `top`: a refreshing per-node view of the pipeline economics.

    python -m fabric_tpu.node.top --targets 127.0.0.1:9443,127.0.0.1:9444
    python -m fabric_tpu.node.top --targets ... --interval 2
    python -m fabric_tpu.node.top --targets ... --once      # one frame

Polls each node's ops surface — `/metrics` (Prometheus text),
`/spans/stats`, `/slo`, `/faults`, `/healthz` — and renders one row per
node: ledger height, throughput, validation stage p50/p99, device batch
occupancy, live collect-under-verify overlap, breaker/fault state and
SLO verdicts.  Read-only: the dashboard only issues GETs against the
control-plane HTTP server, so watching a node never perturbs the data
path.  Everything is stdlib (urllib + a small exposition parser); any
endpoint a node doesn't serve degrades to a blank cell, so mixed
topologies (peers + orderers) render fine.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
_UNESCAPE = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape(v: str) -> str:
    if "\\" not in v:
        return v
    return re.sub(r'\\[\\"n]', lambda m: _UNESCAPE[m.group(0)], v)


def parse_metrics(text: str) -> Dict[str, List[Tuple[dict, float]]]:
    """Prometheus text exposition -> {name: [(labels, value), ...]}."""
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, val = line.rsplit(None, 1)
            if "{" in head:
                name, rest = head.split("{", 1)
                labels = {k: _unescape(v) for k, v in
                          _LABEL_RE.findall(rest.rsplit("}", 1)[0])}
            else:
                name, labels = head, {}
            out.setdefault(name, []).append((labels, float(val)))
        except Exception:
            continue
    return out


def _get_json(addr: str, path: str, timeout: float = 2.0):
    with urllib.request.urlopen(f"http://{addr}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


def _get_text(addr: str, path: str, timeout: float = 2.0) -> str:
    with urllib.request.urlopen(f"http://{addr}{path}",
                                timeout=timeout) as r:
        return r.read().decode()


def _quantile_ms(buckets: Dict[str, float], q: float) -> Optional[float]:
    """p-quantile (ms) from /spans/stats per-bin bucket counts."""
    bins = []
    for k, c in buckets.items():
        ub = float("inf") if k == "+Inf" else float(k)
        bins.append((ub, c))
    bins.sort()
    n = sum(c for _, c in bins)
    if n == 0:
        return None
    target = q * n
    cum = 0
    last_finite = 0.0
    for ub, c in bins:
        if ub != float("inf"):
            last_finite = ub
        cum += c
        if cum >= target:
            return (ub if ub != float("inf") else last_finite) * 1e3
    return last_finite * 1e3


def _sum(series, label_filter=None) -> float:
    total = 0.0
    for labels, v in series or ():
        if label_filter is None or all(labels.get(k) == val
                                       for k, val in label_filter.items()):
            total += v
    return total


def collect_node(addr: str, timeout: float = 2.0) -> dict:
    """One node's dashboard row (raw values; render() formats)."""
    row: dict = {"addr": addr, "up": False}
    try:
        metrics = parse_metrics(_get_text(addr, "/metrics", timeout))
        row["up"] = True
    except Exception as exc:
        row["error"] = str(exc)[:60]
        return row
    row["height"] = max((v for _, v in metrics.get("ledger_height", ())),
                        default=None)
    row["txs"] = _sum(metrics.get("committed_txs_total"))
    row["blocks"] = _sum(metrics.get("committed_blocks_total"))
    pad = _sum(metrics.get("provider_pad_slots_total"))
    slots = _sum(metrics.get("provider_lane_slots_total"))
    row["occupancy"] = (1.0 - pad / slots) if slots else None
    ov = [v for _, v in
          metrics.get("pipeline_collect_under_verify_frac", ())]
    row["overlap"] = (sum(ov) / len(ov)) if ov else None
    row["queue_depth"] = _sum(metrics.get("provider_dispatch_queue_depth"))
    row["breakers_open"] = _sum(metrics.get("gateway_orderer_breaker_open"))
    row["faults_fired"] = _sum(metrics.get("fault_injected_total"))

    try:
        doc = _get_json(addr, "/spans/stats", timeout)
        stats = doc.get("spans", {})    # {enabled, sample_rate, spans}
    except Exception:
        stats = {}
    for col, span in (("collect", "validator.collect"),
                      ("dispatch", "validator.dispatch_wait"),
                      ("gate", "validator.gate"),
                      ("commit", "committer.store_block")):
        st = stats.get(span)
        row[col] = ((_quantile_ms(st["buckets"], 0.5),
                     _quantile_ms(st["buckets"], 0.99))
                    if st and st.get("buckets") else None)

    try:
        slo = _get_json(addr, "/slo", timeout)
        objs = slo.get("objectives", [])
        row["slo_total"] = len(objs)
        row["slo_alerting"] = sorted(
            o["name"] for o in objs if o.get("state") == "alerting")
    except Exception:
        row["slo_total"] = None
        row["slo_alerting"] = []

    try:
        f = _get_json(addr, "/faults", timeout)
        row["fault_plan"] = f.get("name") if f.get("active") else None
    except Exception:
        row["fault_plan"] = None
    try:
        row["health"] = _get_json(addr, "/healthz", timeout).get("status")
    except Exception as exc:
        # /healthz answers 503 with a JSON body while degraded
        body = getattr(exc, "read", lambda: b"")()
        try:
            row["health"] = json.loads(body).get("status")
        except Exception:
            row["health"] = "?"
    return row


def _fmt_pair(p) -> str:
    if not p or p[0] is None:
        return "-"
    return f"{p[0]:.0f}/{p[1]:.0f}"


def _fmt_pct(v) -> str:
    return "-" if v is None else f"{v * 100:.0f}%"


def _rate(row: dict, prev: dict) -> Optional[float]:
    if not prev or row.get("txs") is None or prev.get("txs") is None:
        return None
    dt = row["_t"] - prev["_t"]
    return (row["txs"] - prev["txs"]) / dt if dt > 0 else None


_COLS = ("NODE", "HT", "TX/S", "COLLECT", "DISP", "GATE", "COMMIT",
         "OCC", "OVLP", "QD", "BRKR", "FAULTS", "SLO", "HEALTH")
_WIDTHS = (21, 6, 8, 9, 9, 9, 9, 5, 5, 4, 5, 7, 12, 8)


def render(rows: List[dict]) -> str:
    """Fixed-width table; stage cells are `p50/p99` in ms."""
    lines = ["  ".join(c.ljust(w) for c, w in zip(_COLS, _WIDTHS))]
    for r in rows:
        if not r.get("up"):
            lines.append(f"{r['addr']:<21}  DOWN  {r.get('error', '')}")
            continue
        alerting = r.get("slo_alerting") or []
        if r.get("slo_total") is None:
            slo = "-"
        elif alerting:
            slo = "ALERT:" + ",".join(alerting)
        else:
            slo = f"ok({r['slo_total']})"
        faults = f"{r['faults_fired']:.0f}"
        if r.get("fault_plan"):
            faults += f"[{r['fault_plan']}]"
        cells = (
            r["addr"],
            "-" if r["height"] is None else f"{r['height']:.0f}",
            "-" if r.get("rate") is None else f"{r['rate']:.1f}",
            _fmt_pair(r.get("collect")), _fmt_pair(r.get("dispatch")),
            _fmt_pair(r.get("gate")), _fmt_pair(r.get("commit")),
            _fmt_pct(r.get("occupancy")), _fmt_pct(r.get("overlap")),
            f"{r.get('queue_depth', 0):.0f}",
            f"{r.get('breakers_open', 0):.0f}",
            faults, slo, str(r.get("health", "?")))
        lines.append("  ".join(str(c).ljust(w)
                               for c, w in zip(cells, _WIDTHS)))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fabric_tpu.node.top",
        description="cluster dashboard over the ops plane")
    ap.add_argument("--targets", required=True,
                    help="comma-separated host:port ops addresses")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval in seconds")
    ap.add_argument("--once", action="store_true",
                    help="render a single frame and exit")
    ap.add_argument("--timeout", type=float, default=2.0)
    args = ap.parse_args(argv)
    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    prev: Dict[str, dict] = {}
    try:
        while True:
            rows = []
            for t in targets:
                row = collect_node(t, args.timeout)
                row["_t"] = time.monotonic()
                row["rate"] = _rate(row, prev.get(t, {}))
                prev[t] = row
                rows.append(row)
            frame = (time.strftime("%H:%M:%S")
                     + f"  fabric-tpu top — {len(targets)} node(s)\n"
                     + render(rows))
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
