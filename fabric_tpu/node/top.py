"""Cluster `top`: a refreshing per-node view of the pipeline economics.

    python -m fabric_tpu.node.top --targets 127.0.0.1:9443,127.0.0.1:9444
    python -m fabric_tpu.node.top --targets ... --interval 2
    python -m fabric_tpu.node.top --targets ... --once      # one frame
    python -m fabric_tpu.node.top --targets ... --sort occ  # order rows
    python -m fabric_tpu.node.top --targets ... --watch-alerts
                                   # stream SLO fired/cleared transitions

Polls each node's ops surface — `/metrics` (Prometheus text),
`/spans/stats`, `/slo`, `/faults`, `/healthz` — and renders one row per
node: ledger height, throughput, validation stage p50/p99, device batch
occupancy, live collect-under-verify overlap, breaker/fault state and
SLO verdicts.  Read-only: the dashboard only issues GETs against the
control-plane HTTP server, so watching a node never perturbs the data
path.  Everything is stdlib (urllib + a small exposition parser); any
endpoint a node doesn't serve degrades to a blank cell, so mixed
topologies (peers + orderers) render fine.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
_UNESCAPE = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape(v: str) -> str:
    if "\\" not in v:
        return v
    return re.sub(r'\\[\\"n]', lambda m: _UNESCAPE[m.group(0)], v)


def parse_metrics(text: str) -> Dict[str, List[Tuple[dict, float]]]:
    """Prometheus text exposition -> {name: [(labels, value), ...]}."""
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, val = line.rsplit(None, 1)
            if "{" in head:
                name, rest = head.split("{", 1)
                labels = {k: _unescape(v) for k, v in
                          _LABEL_RE.findall(rest.rsplit("}", 1)[0])}
            else:
                name, labels = head, {}
            out.setdefault(name, []).append((labels, float(val)))
        except Exception:
            continue
    return out


def _get_json(addr: str, path: str, timeout: float = 2.0):
    with urllib.request.urlopen(f"http://{addr}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


def _get_text(addr: str, path: str, timeout: float = 2.0) -> str:
    with urllib.request.urlopen(f"http://{addr}{path}",
                                timeout=timeout) as r:
        return r.read().decode()


def _quantile_ms(buckets: Dict[str, float], q: float) -> Optional[float]:
    """p-quantile (ms) from /spans/stats per-bin bucket counts."""
    bins = []
    for k, c in buckets.items():
        ub = float("inf") if k == "+Inf" else float(k)
        bins.append((ub, c))
    bins.sort()
    n = sum(c for _, c in bins)
    if n == 0:
        return None
    target = q * n
    cum = 0
    last_finite = 0.0
    for ub, c in bins:
        if ub != float("inf"):
            last_finite = ub
        cum += c
        if cum >= target:
            return (ub if ub != float("inf") else last_finite) * 1e3
    return last_finite * 1e3


def _sum(series, label_filter=None) -> float:
    total = 0.0
    for labels, v in series or ():
        if label_filter is None or all(labels.get(k) == val
                                       for k, val in label_filter.items()):
            total += v
    return total


def collect_node(addr: str, timeout: float = 2.0) -> dict:
    """One node's dashboard row (raw values; render() formats)."""
    row: dict = {"addr": addr, "up": False}
    try:
        metrics = parse_metrics(_get_text(addr, "/metrics", timeout))
        row["up"] = True
    except Exception as exc:
        row["error"] = str(exc)[:60]
        return row
    row["height"] = max((v for _, v in metrics.get("ledger_height", ())),
                        default=None)
    row["txs"] = _sum(metrics.get("committed_txs_total"))
    row["blocks"] = _sum(metrics.get("committed_blocks_total"))
    pad = _sum(metrics.get("provider_pad_slots_total"))
    slots = _sum(metrics.get("provider_lane_slots_total"))
    row["occupancy"] = (1.0 - pad / slots) if slots else None
    # per-device occupancy from the device-labeled slot counters (the
    # sharded provider attributes real/pad slots per chip)
    devices: Dict[str, List[float]] = {}
    for labels, v in metrics.get("provider_lane_slots_total", ()) or ():
        d = labels.get("device")
        if d:
            devices.setdefault(d, [0.0, 0.0])[0] += v
    for labels, v in metrics.get("provider_pad_slots_total", ()) or ():
        d = labels.get("device")
        if d:
            devices.setdefault(d, [0.0, 0.0])[1] += v
    row["devices"] = {
        d: (1.0 - p / s) if s else None for d, (s, p) in devices.items()}
    ov = [v for _, v in
          metrics.get("pipeline_collect_under_verify_frac", ())]
    row["overlap"] = (sum(ov) / len(ov)) if ov else None
    # fused device validation: demotions to the host path by reason,
    # policy_width (the k<=8 truth-table cap) called out; the per-
    # channel split lives on GET /state
    dem = metrics.get("validator_device_demotions_total", ()) or ()
    if dem:
        by_reason: Dict[str, float] = {}
        for labels, v in dem:
            r = labels.get("reason", "?")
            by_reason[r] = by_reason.get(r, 0.0) + v
        row["devval_demotions"] = by_reason
        row["devval_policy_width"] = by_reason.get("policy_width", 0.0)
    else:
        row["devval_demotions"] = None
        row["devval_policy_width"] = None
    row["queue_depth"] = _sum(metrics.get("provider_dispatch_queue_depth"))
    row["breakers_open"] = _sum(metrics.get("gateway_orderer_breaker_open"))
    row["faults_fired"] = _sum(metrics.get("fault_injected_total"))
    # admission plane: current shed state + lifetime shed count
    row["shed_total"] = _sum(metrics.get("gateway_shed_total"))
    adm = [v for _, v in metrics.get("gateway_admission_state", ()) or ()]
    row["admission_state"] = max(adm) if adm else None
    # byzantine plane: quarantined identities by reason + scored offenses
    byz_series = metrics.get("byzantine_quarantines_total")
    row["byz_quarantines"] = (_sum(byz_series)
                              if byz_series is not None else None)
    row["byz_reasons"] = sorted(
        {labels.get("reason", "?") for labels, v in byz_series or ()
         if v})
    row["byz_offenses"] = _sum(metrics.get("byzantine_offenses_total"))
    # pardon plane (r18): lifetime pardons + the live decaying standing
    # score, read from /byzantine (the counters alone can't show decay —
    # a counter never goes down, but standing scores do)
    try:
        byz = _get_json(addr, "/byzantine", timeout)
        row["byz_pardons"] = byz.get("pardons")
        row["byz_score"] = sum(
            int(ent.get("score", 0) or 0)
            for ent in (byz.get("identities") or {}).values())
    except Exception:
        row["byz_pardons"] = None
        row["byz_score"] = None
    # verify-once plane: cache hit rate over all lookups, and the
    # rolling fraction of committed verify items whose verdicts were
    # speculatively cached before the block arrived
    vh = _sum(metrics.get("verify_cache_hits_total"))
    vm = _sum(metrics.get("verify_cache_misses_total"))
    row["vcache"] = vh / (vh + vm) if (vh + vm) else None
    spec = [v for _, v in metrics.get("speculative_coverage_frac", ())]
    row["spec"] = (sum(spec) / len(spec)) if spec else None
    # state plane: shard count + total keys from the per-shard gauge,
    # last crash-consistent checkpoint height from the checkpoint gauge
    shard_series = metrics.get("state_shard_keys", ()) or ()
    shards = {labels.get("shard") for labels, _ in shard_series}
    row["state_shards"] = len(shards) or None
    row["state_keys"] = (_sum(metrics.get("state_shard_keys"))
                         if shard_series else None)
    ck = [v for _, v in metrics.get("state_checkpoint_height", ()) or ()]
    row["ckpt_height"] = max(ck) if ck else None
    # resource telemetry (ops_plane/resources.py): present only on
    # nodes with the `resources` sub-dict enabled; blank cell otherwise
    rss = [v for _, v in metrics.get("process_resident_memory_bytes",
                                     ()) or ()]
    row["rss"] = max(rss) if rss else None
    fds = [v for _, v in metrics.get("process_open_fds", ()) or ()]
    row["fds"] = max(fds) if fds else None

    try:
        doc = _get_json(addr, "/spans/stats", timeout)
        stats = doc.get("spans", {})    # {enabled, sample_rate, spans}
    except Exception:
        stats = {}
    for col, span in (("collect", "validator.collect"),
                      ("dispatch", "validator.dispatch_wait"),
                      ("gate", "validator.gate"),
                      ("commit", "committer.store_block")):
        st = stats.get(span)
        row[col] = ((_quantile_ms(st["buckets"], 0.5),
                     _quantile_ms(st["buckets"], 0.99))
                    if st and st.get("buckets") else None)

    try:
        slo = _get_json(addr, "/slo", timeout)
        objs = slo.get("objectives", [])
        row["slo_total"] = len(objs)
        row["slo_alerting"] = sorted(
            o["name"] for o in objs if o.get("state") == "alerting")
    except Exception:
        row["slo_total"] = None
        row["slo_alerting"] = []

    try:
        f = _get_json(addr, "/faults", timeout)
        row["fault_plan"] = f.get("name") if f.get("active") else None
    except Exception:
        row["fault_plan"] = None
    # incident capture (r19): bundle count + last bundle's objective;
    # blank on nodes running with `incidents` disabled
    try:
        inc = _get_json(addr, "/incidents", timeout)
        row["inc_count"] = inc.get("count")
        incidents = inc.get("incidents") or []
        last = incidents[-1] if incidents else {}
        row["inc_last"] = last.get("objective")
        row["inc_partial"] = bool(last.get("partial"))
    except Exception:
        row["inc_count"] = None
        row["inc_last"] = None
        row["inc_partial"] = False
    try:
        hz = _get_json(addr, "/healthz", timeout)
    except Exception as exc:
        # /healthz answers 503 with a JSON body while degraded
        body = getattr(exc, "read", lambda: b"")()
        try:
            hz = json.loads(body)
        except Exception:
            hz = {}
    row["health"] = hz.get("status", "?")
    # fleet lifecycle (r18): serving / draining / drained, surfaced on
    # /healthz by nodes that expose drain() — blank on older nodes
    row["lifecycle"] = hz.get("lifecycle")
    return row


def _fmt_pair(p) -> str:
    if not p or p[0] is None:
        return "-"
    return f"{p[0]:.0f}/{p[1]:.0f}"


def _fmt_pct(v) -> str:
    return "-" if v is None else f"{v * 100:.0f}%"


def _rate(row: dict, prev: dict) -> Optional[float]:
    if not prev or row.get("txs") is None or prev.get("txs") is None:
        return None
    dt = row["_t"] - prev["_t"]
    return (row["txs"] - prev["txs"]) / dt if dt > 0 else None


def _fmt_devices(devs) -> str:
    """Compact per-device occupancy: `8×91-97%` (count × min-max), or
    `-` when the node has no device-labeled slot series yet."""
    vals = sorted(v for v in (devs or {}).values() if v is not None)
    if not vals:
        return "-"
    lo, hi = vals[0] * 100, vals[-1] * 100
    if round(lo) == round(hi):
        return f"{len(vals)}×{hi:.0f}%"
    return f"{len(vals)}×{lo:.0f}-{hi:.0f}%"


_COLS = ("NODE", "HT", "TX/S", "COLLECT", "DISP", "GATE", "COMMIT",
         "OCC", "DEV", "DEVVAL", "OVLP", "VCACHE", "SPEC", "STATE",
         "RES", "QD", "BRKR", "SHED", "FAULTS", "BYZ", "LIFE", "INC",
         "SLO", "HEALTH")
_WIDTHS = (21, 6, 8, 9, 9, 9, 9, 5, 10, 9, 5, 6, 5, 11, 9, 4, 5, 9, 7,
           12, 8, 10, 12, 8)

# gateway_admission_state gauge value -> short cell tag
_ADM_SHORT = {0: "ok", 1: "EVAL", 2: "PROB", 3: "HARD"}


def _fmt_shed(row: dict) -> str:
    """`<state>/<shed count>`: `ok/0` while admitting, `PROB/1234` mid-
    shed; `-` when the node runs no gateway (orderers)."""
    st = row.get("admission_state")
    shed = row.get("shed_total") or 0.0
    if st is None and not shed:
        return "-"
    name = _ADM_SHORT.get(int(st or 0), "?")
    return f"{name}/{shed:.0f}"


def _fmt_byz(row: dict) -> str:
    """`<quarantined>[reason,..]/<offense score>~<standing>+<pardons>p`:
    `0` is the healthy steady state (the byzantine plane is live and has
    convicted nobody); `~N` is the LIVE decaying standing score summed
    over known identities (offense counters only ever rise — the `~`
    tail is what actually shrinks as clean windows elapse); `+Np` counts
    pardons granted (offense quarantines restored after a clean window);
    `-` means the node exports no byzantine series (plane disabled)."""
    q = row.get("byz_quarantines")
    if q is None:
        return "-"
    cell = f"{q:.0f}"
    reasons = row.get("byz_reasons") or []
    if reasons:
        cell += "[" + ",".join(r[:5] for r in reasons) + "]"
    off = row.get("byz_offenses") or 0.0
    if off:
        cell += f"/{off:.0f}"
    score = row.get("byz_score")
    if score:
        cell += f"~{score:.0f}"
    pardons = row.get("byz_pardons")
    if pardons:
        cell += f"+{pardons:.0f}p"
    return cell


def _fmt_inc(row: dict) -> str:
    """`<bundles>[last objective]` with a `!` suffix when the newest
    bundle is partial (a peer was unreachable during fan-out); `-` on
    nodes without the incident recorder, `0` when armed but quiet."""
    n = row.get("inc_count")
    if n is None:
        return "-"
    cell = f"{n:.0f}"
    last = row.get("inc_last")
    if last:
        cell += f"[{str(last)[:6]}]"
    if row.get("inc_partial"):
        cell += "!"
    return cell


def _fmt_life(row: dict) -> str:
    """Fleet lifecycle cell: serving / draining / drained (from
    /healthz); `-` on nodes without a drain-capable ops plane."""
    lc = row.get("lifecycle")
    if not lc:
        return "-"
    return str(lc)


def _fmt_state(row: dict) -> str:
    """`<shards>sh/<keys>@<ckpt height>`: sharded-state keyspace size +
    last durable checkpoint height; `-` before any shard gauge lands."""
    n = row.get("state_shards")
    if not n:
        return "-"
    keys = row.get("state_keys") or 0.0
    k = f"{keys / 1000.0:.0f}k" if keys >= 1000 else f"{keys:.0f}"
    ck = row.get("ckpt_height")
    return f"{n}sh/{k}" + ("" if ck is None else f"@{ck:.0f}")


def _fmt_devval(row: dict) -> str:
    """`<demotions>[pw:N]`: fused-device-validation demotions to the
    host path, with the policy_width share (blocks demoted by the k<=8
    truth-table cap — the cap's real-world demotion rate) called out;
    `-` until the plane demotes (or on nodes running host MVCC only)."""
    dem = row.get("devval_demotions")
    if dem is None:
        return "-"
    cell = f"{sum(dem.values()):.0f}"
    pw = dem.get("policy_width", 0.0)
    if pw:
        cell += f"[pw:{pw:.0f}]"
    return cell


def _fmt_res(row: dict) -> str:
    """`<RSS MB>M/<fd count>`: the resource collector's footprint cell;
    `-` on nodes that run with `resources` disabled."""
    rss, fds = row.get("rss"), row.get("fds")
    if rss is None and fds is None:
        return "-"
    cell = "?" if rss is None else f"{rss / 1048576.0:.0f}M"
    return cell + ("" if fds is None else f"/{fds:.0f}")


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values, width: int = 16) -> str:
    """Unicode sparkline over the last `width` points, scaled to the
    window's own min/max (shape, not absolute level)."""
    vals = [float(v) for v in values if v is not None][-width:]
    if not vals:
        return "-"
    lo, hi = min(vals), max(vals)
    if hi - lo < 1e-12:
        return _SPARK_BLOCKS[0] * len(vals)
    top = len(_SPARK_BLOCKS) - 1
    return "".join(_SPARK_BLOCKS[round((v - lo) / (hi - lo) * top)]
                   for v in vals)


def collect_spark(addr: str, name: str, window_s: float = 120.0,
                  timeout: float = 2.0) -> Optional[List[float]]:
    """One node's history points for a series (`/metrics/history`);
    None when the node has no store or series (cell renders `-`)."""
    try:
        doc = _get_json(
            addr, f"/metrics/history?name={name}&window={window_s}",
            timeout)
    except Exception:
        return None
    return [p[1] for p in doc.get("points", ())]

# --sort column -> row key; None values sort last, numeric descending
# (the interesting rows — hottest, furthest ahead, most alerting — rise)
_SORT_KEYS = {
    "node": "addr", "ht": "height", "tx/s": "rate", "occ": "occupancy",
    "ovlp": "overlap", "qd": "queue_depth", "brkr": "breakers_open",
    "faults": "faults_fired", "slo": "slo_alerting", "height": "height",
    "rate": "rate", "occupancy": "occupancy", "dev": "devices",
    "vcache": "vcache", "spec": "spec", "shed": "shed_total",
    "state": "state_keys", "byz": "byz_quarantines", "res": "rss",
    "life": "lifecycle", "devval": "devval_policy_width",
    "inc": "inc_count",
}


def sort_rows(rows: List[dict], column: str) -> List[dict]:
    key = _SORT_KEYS.get(column.lower())
    if key is None:
        raise SystemExit(f"--sort: unknown column {column!r} "
                         f"(one of {', '.join(sorted(_SORT_KEYS))})")
    if key == "addr":
        return sorted(rows, key=lambda r: r["addr"])

    def rank(r):
        v = r.get(key)
        if key == "slo_alerting":
            v = len(v) if v is not None else None
        elif key == "devices":
            vals = [x for x in (v or {}).values() if x is not None]
            v = min(vals) if vals else None
        elif key == "lifecycle":
            # nodes leaving the fleet rise to the top
            v = {"drained": 2.0, "draining": 1.0, "serving": 0.0}.get(v)
        if not isinstance(v, (int, float)):
            return (1, 0.0)
        return (0, -float(v))
    return sorted(rows, key=rank)


def render(rows: List[dict], spark_name: Optional[str] = None) -> str:
    """Fixed-width table; stage cells are `p50/p99` in ms.  With
    `spark_name` an extra trailing column renders each node's history
    sparkline for that series (rows carry it as r["spark"])."""
    cols, widths = _COLS, _WIDTHS
    if spark_name:
        cols = cols + (spark_name[:18].upper(),)
        widths = widths + (18,)
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        if not r.get("up"):
            lines.append(f"{r['addr']:<21}  DOWN  {r.get('error', '')}")
            continue
        alerting = r.get("slo_alerting") or []
        if r.get("slo_total") is None:
            slo = "-"
        elif alerting:
            slo = "ALERT:" + ",".join(alerting)
        else:
            slo = f"ok({r['slo_total']})"
        faults = f"{r['faults_fired']:.0f}"
        if r.get("fault_plan"):
            faults += f"[{r['fault_plan']}]"
        cells = (
            r["addr"],
            "-" if r["height"] is None else f"{r['height']:.0f}",
            "-" if r.get("rate") is None else f"{r['rate']:.1f}",
            _fmt_pair(r.get("collect")), _fmt_pair(r.get("dispatch")),
            _fmt_pair(r.get("gate")), _fmt_pair(r.get("commit")),
            _fmt_pct(r.get("occupancy")), _fmt_devices(r.get("devices")),
            _fmt_devval(r),
            _fmt_pct(r.get("overlap")),
            _fmt_pct(r.get("vcache")), _fmt_pct(r.get("spec")),
            _fmt_state(r), _fmt_res(r),
            f"{r.get('queue_depth', 0):.0f}",
            f"{r.get('breakers_open', 0):.0f}",
            _fmt_shed(r),
            faults, _fmt_byz(r), _fmt_life(r), _fmt_inc(r), slo,
            str(r.get("health", "?")))
        if spark_name:
            cells = cells + (r.get("spark") or "-",)
        lines.append("  ".join(str(c).ljust(w)
                               for c, w in zip(cells, widths)))
    return "\n".join(lines)


def watch_alerts(targets: List[str], timeout: float, interval: float,
                 once: bool = False) -> int:
    """Stream SLO alert transitions: one timestamped line per
    (node, objective) fired/cleared edge instead of a refreshing table —
    tail-able, grep-able, and safe to pipe into an incident log."""
    active: Dict[Tuple[str, str], bool] = {}
    first = True
    while True:
        now = time.strftime("%H:%M:%S")
        for t in targets:
            try:
                objs = _get_json(t, "/slo", timeout).get("objectives", [])
            except Exception as exc:
                key = (t, "__reach__")
                if not active.get(key):
                    print(f"{now}  {t}  UNREACHABLE  {str(exc)[:60]}")
                    active[key] = True
                continue
            if active.pop((t, "__reach__"), None):
                print(f"{now}  {t}  REACHABLE")
            for o in objs:
                key = (t, o.get("name", "?"))
                alerting = o.get("state") == "alerting"
                was = active.get(key, False)
                if alerting and not was:
                    print(f"{now}  {t}  FIRED    {key[1]}  "
                          f"burn={o.get('burn_rate', '?')}")
                elif was and not alerting:
                    print(f"{now}  {t}  CLEARED  {key[1]}")
                elif alerting and first and once:
                    pass
                active[key] = alerting
        if first:
            live = sorted(k for k, v in active.items()
                          if v and k[1] != "__reach__")
            if not live:
                print(f"{now}  no active alerts on {len(targets)} node(s)")
            first = False
        if once:
            return 0
        sys.stdout.flush()
        time.sleep(interval)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fabric_tpu.node.top",
        description="cluster dashboard over the ops plane")
    ap.add_argument("--targets", required=True,
                    help="comma-separated host:port ops addresses")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval in seconds")
    ap.add_argument("--once", action="store_true",
                    help="render a single frame and exit")
    ap.add_argument("--sort", metavar="COLUMN",
                    help="order rows by a column (e.g. occ, tx/s, qd, "
                         "slo); numeric descending, missing values last")
    ap.add_argument("--watch-alerts", action="store_true",
                    help="stream SLO fired/cleared transition lines "
                         "instead of the table")
    ap.add_argument("--spark", metavar="NAME",
                    help="extra column: unicode sparkline of this "
                         "series from each node's /metrics/history "
                         "(e.g. process_resident_memory_bytes)")
    ap.add_argument("--spark-window", type=float, default=120.0,
                    help="history window (s) behind --spark")
    ap.add_argument("--timeout", type=float, default=2.0)
    args = ap.parse_args(argv)
    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    if args.sort:
        sort_rows([], args.sort)        # validate the column name up front
    try:
        if args.watch_alerts:
            return watch_alerts(targets, args.timeout, args.interval,
                                once=args.once)
        prev: Dict[str, dict] = {}
        while True:
            rows = []
            for t in targets:
                row = collect_node(t, args.timeout)
                row["_t"] = time.monotonic()
                row["rate"] = _rate(row, prev.get(t, {}))
                if args.spark and row.get("up"):
                    row["spark"] = _sparkline(
                        collect_spark(t, args.spark, args.spark_window,
                                      args.timeout) or ())
                prev[t] = row
                rows.append(row)
            if args.sort:
                rows = sort_rows(rows, args.sort)
            frame = (time.strftime("%H:%M:%S")
                     + f"  fabric-tpu top — {len(targets)} node(s)\n"
                     + render(rows, spark_name=args.spark))
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
