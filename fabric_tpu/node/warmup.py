"""AOT warmup: pre-compile the provider's kernel set into the cache.

Round-2/3 verdicts flagged node cold-start: every (kernel, bucket-shape)
pair costs minutes of XLA compilation on first dispatch.  This tool runs
each configured kernel once per bucket shape so the persistent
compilation cache (bccsp/factory.enable_compile_cache) is hot before a
node starts serving — run it at provisioning time or from the node's
init.

The prebake recipe (turns the BENCH_r05 146.6 s compile+first-call into
a cache hit for every later process on the host):

    # provisioning time: compile every kernel into a shared artifact dir
    python -m fabric_tpu.node.warmup --cache-dir /var/cache/fabric_tpu_xla

    # node start: point the node at the same artifact
    FABRIC_TPU_PEER_COMPILE_CACHE_DIR=/var/cache/fabric_tpu_xla ...
    # (or "compile_cache_dir" in the node JSON config)

Without --cache-dir the JAX_COMPILATION_CACHE_DIR env var or
~/.cache/fabric_tpu_xla is used.  The same artifact lets the slow-marked
kernel test modules rejoin the quick pytest gate: they drop their `slow`
mark when bccsp.factory.compile_cache_is_warm() sees a prebaked dir.
"""

from __future__ import annotations

import argparse
import sys
import time


def gen_p256_sigs(n: int, n_keys: int, seed: int = 2026):
    import hashlib
    import random

    from fabric_tpu.crypto import hashes
    from fabric_tpu.crypto import ec
    from fabric_tpu.crypto import (
        decode_dss_signature, encode_dss_signature)
    from fabric_tpu.crypto import (
        Encoding, PublicFormat)

    from fabric_tpu.bccsp import SCHEME_P256, VerifyItem
    from fabric_tpu.ops import p256

    rng = random.Random(seed)
    keys = [ec.generate_private_key(ec.SECP256R1()) for _ in range(n_keys)]
    pubs = [k.public_key().public_bytes(Encoding.X962,
                                        PublicFormat.UncompressedPoint)
            for k in keys]
    items = []
    for i in range(n):
        msg = rng.randbytes(48)
        digest = hashlib.sha256(msg).digest()
        r, s = decode_dss_signature(
            keys[i % n_keys].sign(msg, ec.ECDSA(hashes.SHA256())))
        if s > p256.HALF_N:
            s = p256.N - s
        items.append(VerifyItem(SCHEME_P256, pubs[i % n_keys],
                                encode_dss_signature(r, s), digest))
    return items


def gen_ed25519_sigs(n: int, n_keys: int = 4, seed: int = 7):
    import random

    from fabric_tpu.crypto import (
        Ed25519PrivateKey)
    from fabric_tpu.crypto import (
        Encoding, PublicFormat)

    from fabric_tpu.bccsp import SCHEME_ED25519, VerifyItem

    rng = random.Random(seed)
    keys = [Ed25519PrivateKey.generate() for _ in range(n_keys)]
    pubs = [k.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
            for k in keys]
    items = []
    for i in range(n):
        msg = rng.randbytes(48)
        items.append(VerifyItem(SCHEME_ED25519, pubs[i % n_keys],
                                keys[i % n_keys].sign(msg), msg))
    return items


def warmup(buckets, schemes=("p256", "p256-rows", "ed25519", "idemix"),
           verbose: bool = True, cache_dir=None) -> dict:
    from fabric_tpu.bccsp.factory import FactoryOpts, init_factories

    provider = init_factories(FactoryOpts(default="JAXTPU",
                                          compile_cache_dir=cache_dir))
    timings = _warm_kernels(provider, buckets, schemes, verbose)
    _write_manifest(cache_dir, buckets, schemes, timings)
    return timings


def _warm_kernels(provider, buckets, schemes, verbose: bool) -> dict:
    timings = {}
    if "idemix" in schemes:
        # the BN254 dual-pairing lane: the batch dimension buckets in
        # powers of two from IDEMIX_MIN_BUCKET, one program each —
        # warm the first few (covers <=64 presentations per issuer
        # per block; larger blocks pay one further compile each)
        import numpy as np
        b0 = provider.IDEMIX_MIN_BUCKET
        for b in (b0, b0 * 2, b0 * 4):
            fn, green, _red = provider.idemix_pair_probe(b)
            t0 = time.perf_counter()
            assert bool(np.asarray(fn(*green)).all())
            timings[f"idemix-pair@{b}"] = round(time.perf_counter() - t0, 1)
        if verbose:
            print("idemix-pair:", {k: v for k, v in timings.items()
                                   if k.startswith("idemix")}, flush=True)
    for bucket in buckets:
        if "p256" in schemes:
            items = gen_p256_sigs(min(bucket, 64), n_keys=8)
            reps = (bucket // len(items)) + 1
            t0 = time.perf_counter()
            provider.batch_verify((items * reps)[:bucket])
            timings[f"p256@{bucket}"] = round(time.perf_counter() - t0, 1)
        if "p256-rows" in schemes:
            items = gen_p256_sigs(min(bucket, 64), n_keys=2, seed=5)
            for it in items:
                provider.key_tables.get_or_build(it.pubkey)
            reps = (bucket // len(items)) + 1
            t0 = time.perf_counter()
            provider.batch_verify((items * reps)[:bucket])
            timings[f"p256-rows@{bucket}"] = round(
                time.perf_counter() - t0, 1)
        if "ed25519" in schemes:
            items = gen_ed25519_sigs(min(bucket, 64))
            reps = (bucket // len(items)) + 1
            t0 = time.perf_counter()
            provider.batch_verify((items * reps)[:bucket])
            timings[f"ed25519@{bucket}"] = round(time.perf_counter() - t0, 1)
        if verbose:
            print(f"bucket {bucket}: "
                  + ", ".join(f"{k.split('@')[0]}={v}s"
                              for k, v in timings.items()
                              if k.endswith(f"@{bucket}")), flush=True)
    return timings


def _write_manifest(cache_dir, buckets, schemes, timings) -> None:
    """Stamp the completed prebake: compile_cache_is_warm() requires
    this manifest, so incidental cache entries from ordinary runs never
    flip the warm check — only a finished warmup does."""
    import json
    import os

    from fabric_tpu.bccsp.factory import WARMUP_MANIFEST, default_cache_dir

    d = cache_dir or default_cache_dir()
    try:
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, WARMUP_MANIFEST), "w") as f:
            json.dump({"buckets": list(buckets), "schemes": list(schemes),
                       "timings": timings, "completed_unix": time.time()},
                      f, indent=1)
    except OSError:
        pass    # cache dir unwritable: warmed this process, no artifact


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fabric-tpu-warmup")
    ap.add_argument("--buckets", default="12288,16384,32768",
                    help="comma-separated batch sizes (12288 lands the "
                         "96-row grid bucket; 16384/32768 the 128/256)")
    ap.add_argument("--schemes",
                    default="p256,p256-rows,ed25519,idemix")
    ap.add_argument("--cache-dir", default=None,
                    help="persistent XLA compilation cache dir to prebake "
                         "(default: JAX_COMPILATION_CACHE_DIR or "
                         "~/.cache/fabric_tpu_xla); point nodes at the "
                         "same dir via compile_cache_dir in their config")
    args = ap.parse_args(argv)
    timings = warmup([int(b) for b in args.buckets.split(",")],
                     tuple(args.schemes.split(",")),
                     cache_dir=args.cache_dir)
    from fabric_tpu.bccsp.factory import compile_cache_is_warm, \
        default_cache_dir
    d = args.cache_dir or default_cache_dir()
    state = "warm" if compile_cache_is_warm(d) else "EMPTY"
    print("warm:", timings)
    print(f"cache artifact: {d} ({state})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
