"""Peer admin CLI — channel and chaincode verbs over the RPC plane.

Reference parity: the `peer channel join` / `peer lifecycle chaincode
package|install|approveformyorg|commit|querycommitted` command surface
(/root/reference/internal/peer/{channel,lifecycle}).  Each verb is a
thin client of the running nodes' authenticated RPC plane — nothing
here touches node state directly.

    python -m fabric_tpu.node.admin --client client.json \
        --msp-config <node.json|channel_config.bin> \
        channel join --peer 127.0.0.1:7051 --config chB.bin [--height N]
        channel list --peer ...
        chaincode package --label asset --code-file cc.py --out pkg.bin
        chaincode install --peer ... --package pkg.bin
        chaincode installed --peer ...
        chaincode approve --peer ... --orderer ... --channel ch \
            --name asset --version 1.0 --sequence 1 [--policy EXPR]
        chaincode commit  --peer ... --orderer ... (same flags)
        chaincode querycommitted --peer ... --channel ch --name asset
        gateway evaluate --peer ... --channel ch --name asset \
            --fn read --arg a1
        gateway submit --peer ... --channel ch --name asset \
            --fn create --arg a1 --arg alice --arg 100

The gateway verbs go through the peer's gateway service
(fabric_tpu/gateway): one peer connection drives the whole endorse ->
order -> commit-status lifecycle instead of the client dialing every
peer and orderer itself.

`--msp-config` supplies the verification MSPs for the transport
handshake: a node JSON (its channel_config_hex) or a serialized
ChannelConfig.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _addr(s: str):
    host, port = s.rsplit(":", 1)
    return host, int(port)


def _load_client(path: str):
    from fabric_tpu.node.orderer import load_signing_identity
    with open(path) as f:
        c = json.load(f)
    return load_signing_identity(c["mspid"], c["cert_pem"].encode(),
                                 c["key_pem"].encode())


def _load_msps(path: str):
    from fabric_tpu.config import Bundle, ChannelConfig
    if path.endswith(".json"):
        with open(path) as f:
            cfg = json.load(f)
        raw = bytes.fromhex(cfg["channel_config_hex"])
    else:
        with open(path, "rb") as f:
            raw = f.read()
    return Bundle(ChannelConfig.deserialize(raw)).msps


def _connect(addr_s: str, signer, msps):
    from fabric_tpu.comm.rpc import connect
    return connect(_addr(addr_s), signer, msps, timeout=10.0)


# -- chaincode tx flow (proposal -> endorse -> broadcast -> committed) -------

def _lifecycle_tx(args, signer, msps, fn: str, fnargs) -> str:
    """Drive one `_lifecycle` invoke end-to-end; returns the txid."""
    from fabric_tpu.chaincode import LIFECYCLE_NS
    from fabric_tpu.endorser.proposal import (ProposalResponse,
                                              assemble_transaction,
                                              signed_proposal)
    from fabric_tpu.protocol.types import Endorsement

    sp = signed_proposal(args.channel, LIFECYCLE_NS, fn, fnargs, signer)
    responses = []
    for peer_addr in args.peer:
        conn = _connect(peer_addr, signer, msps)
        try:
            out = conn.call("endorse", {
                "channel": args.channel,
                "proposal": sp.proposal_bytes,
                "signature": sp.signature,
            }, timeout=30.0)
        finally:
            conn.close()
        if out["status"] != 200:
            raise SystemExit(f"endorsement failed on {peer_addr}: "
                             f"{out['message']}")
        responses.append(ProposalResponse(
            out["status"], out["message"], out["payload"],
            Endorsement(out["endorser"], out["endorsement_sig"])))
    env = assemble_transaction(sp, responses, signer)

    oconn = _connect(args.orderer, signer, msps)
    try:
        resp = oconn.call("broadcast", {"envelope": env.serialize()},
                          timeout=30.0)
        if resp["status"] != 200:
            raise SystemExit(f"broadcast rejected: {resp}")
    finally:
        oconn.close()

    txid = env.header().channel_header.txid
    # wait until a peer has the tx committed (qscc.GetTransactionByID)
    deadline = time.time() + float(args.timeout)
    conn = _connect(args.peer[0], signer, msps)
    try:
        while time.time() < deadline:
            try:
                conn.call("qscc.tx_by_id",
                          {"channel": args.channel, "txid": txid},
                          timeout=10.0)
                return txid
            except Exception:
                time.sleep(0.3)
    finally:
        conn.close()
    raise SystemExit(f"tx {txid} not committed within {args.timeout}s")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fabric-tpu-admin")
    ap.add_argument("--client", required=True,
                    help="client identity json (mspid/cert_pem/key_pem)")
    ap.add_argument("--msp-config", required=True,
                    help="node json or serialized ChannelConfig for "
                         "handshake MSPs")
    sub = ap.add_subparsers(dest="group", required=True)

    chan = sub.add_parser("channel").add_subparsers(dest="verb",
                                                    required=True)
    j = chan.add_parser("join")
    j.add_argument("--peer", required=True)
    j.add_argument("--config", required=True,
                   help="serialized ChannelConfig file")
    j.add_argument("--height", type=int, default=0)
    ls = chan.add_parser("list")
    ls.add_argument("--peer", required=True)

    cc = sub.add_parser("chaincode").add_subparsers(dest="verb",
                                                    required=True)
    pk = cc.add_parser("package")
    pk.add_argument("--label", required=True)
    pk.add_argument("--code-file", required=True)
    pk.add_argument("--out", required=True)
    for name in ("install", "installed"):
        p = cc.add_parser(name)
        p.add_argument("--peer", required=True)
        if name == "install":
            p.add_argument("--package", required=True)
    for name in ("approve", "commit"):
        p = cc.add_parser(name)
        p.add_argument("--peer", action="append", required=True,
                       help="endorsing peer addr (repeatable)")
        p.add_argument("--orderer", required=True)
        p.add_argument("--channel", required=True)
        p.add_argument("--name", required=True)
        p.add_argument("--version", required=True)
        p.add_argument("--sequence", required=True)
        p.add_argument("--policy", default="")
        p.add_argument("--timeout", default="30")
    q = cc.add_parser("querycommitted")
    q.add_argument("--peer", action="append", required=True)
    q.add_argument("--orderer", default="")
    q.add_argument("--channel", required=True)
    q.add_argument("--name", required=True)

    gw = sub.add_parser("gateway").add_subparsers(dest="verb",
                                                  required=True)
    for name in ("evaluate", "submit"):
        p = gw.add_parser(name)
        p.add_argument("--peer", required=True,
                       help="gateway peer addr (host:port)")
        p.add_argument("--channel", required=True)
        p.add_argument("--name", required=True, help="chaincode name")
        p.add_argument("--fn", required=True)
        p.add_argument("--arg", action="append", default=[],
                       help="chaincode argument (repeatable)")
        if name == "submit":
            p.add_argument("--timeout", default="30",
                           help="commit-status wait (seconds)")

    args = ap.parse_args(argv)
    signer = _load_client(args.client)
    msps = _load_msps(args.msp_config)

    if args.group == "channel" and args.verb == "join":
        with open(args.config, "rb") as f:
            cfg_bytes = f.read()
        conn = _connect(args.peer, signer, msps)
        try:
            out = conn.call("cscc.join", {
                "config": cfg_bytes, "config_height": args.height,
            }, timeout=30.0)
        finally:
            conn.close()
        print(json.dumps(out))
    elif args.group == "channel" and args.verb == "list":
        conn = _connect(args.peer, signer, msps)
        try:
            out = conn.call("cscc.channels", {}, timeout=10.0)
        finally:
            conn.close()
        print(json.dumps(out))
    elif args.group == "chaincode" and args.verb == "package":
        from fabric_tpu.chaincode.lifecycle import (package_chaincode,
                                                    package_id)
        with open(args.code_file, "rb") as f:
            code = f.read()
        pkg = package_chaincode(args.label, code)
        with open(args.out, "wb") as f:
            f.write(pkg)
        print(json.dumps({"package_id": package_id(pkg)}))
    elif args.group == "chaincode" and args.verb == "install":
        with open(args.package, "rb") as f:
            pkg = f.read()
        conn = _connect(args.peer, signer, msps)
        try:
            out = conn.call("lifecycle.install", {"package": pkg},
                            timeout=30.0)
        finally:
            conn.close()
        print(json.dumps(out))
    elif args.group == "chaincode" and args.verb == "installed":
        conn = _connect(args.peer, signer, msps)
        try:
            out = conn.call("lifecycle.installed", {}, timeout=10.0)
        finally:
            conn.close()
        print(json.dumps(out))
    elif args.group == "chaincode" and args.verb in ("approve", "commit"):
        fn = "approve_for_org" if args.verb == "approve" else "commit"
        fnargs = [args.name.encode(), args.version.encode(),
                  str(int(args.sequence)).encode(),
                  args.policy.encode()]
        txid = _lifecycle_tx(args, signer, msps, fn, fnargs)
        status = "approved" if args.verb == "approve" else "committed"
        print(json.dumps({"txid": txid, "status": status}))
    elif args.group == "chaincode" and args.verb == "querycommitted":
        from fabric_tpu.chaincode import LIFECYCLE_NS
        from fabric_tpu.endorser.proposal import signed_proposal
        sp = signed_proposal(args.channel, LIFECYCLE_NS,
                             "query_definition", [args.name.encode()],
                             signer)
        conn = _connect(args.peer[0], signer, msps)
        try:
            out = conn.call("endorse", {
                "channel": args.channel,
                "proposal": sp.proposal_bytes,
                "signature": sp.signature,
            }, timeout=30.0)
        finally:
            conn.close()
        if out["status"] != 200:
            raise SystemExit(f"query failed: {out['message']}")
        from fabric_tpu.utils import serde
        payload = serde.decode(out["payload"])
        defn = serde.decode(payload["action"]["response_payload"])
        defn = {k: (v.hex() if isinstance(v, bytes) else v)
                for k, v in defn.items()}
        print(json.dumps({"definition": defn}))
    elif args.group == "gateway":
        from fabric_tpu.gateway import GatewayClient, GatewayError
        from fabric_tpu.utils import serde
        gwc = GatewayClient(_addr(args.peer), signer, msps,
                            channel_id=args.channel)
        fnargs = [a.encode() for a in args.arg]
        try:
            if args.verb == "evaluate":
                payload = gwc.evaluate(args.name, args.fn, fnargs)
                resp = serde.decode(payload)["action"]["response_payload"]
                print(json.dumps({
                    "result": resp.decode("utf-8", "backslashreplace")}))
            else:
                code, block = gwc.submit_transaction(
                    args.name, args.fn, fnargs,
                    commit_timeout_s=float(args.timeout))
                print(json.dumps({"status": "committed", "code": code,
                                  "block": block}))
        except GatewayError as exc:
            raise SystemExit(f"gateway {args.verb} failed: {exc}")
        finally:
            gwc.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
