"""Dev-network provisioning: crypto material + node configs on disk.

The composition of the reference's cryptogen + configtxgen
(/root/reference/internal/cryptogen, internal/configtxgen): generates an
orderer org, per-node signing identities, the channel's genesis
ChannelConfig, and one JSON config file per orderer process, ready for
`python -m fabric_tpu.node.orderer <node.json>`.
"""

from __future__ import annotations

import json
import os
from typing import List, Tuple

from cryptography.hazmat.primitives import serialization

from fabric_tpu.config import BatchConfig, ChannelConfig, OrgConfig, default_policies
from fabric_tpu.msp.ca import DevOrg


def _key_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())


def _cert_pem(cert) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def provision_orderers(base_dir: str, n: int, channel_id: str = "ch",
                       base_port: int = 0,
                       batch: BatchConfig = None) -> List[str]:
    """Create material for an n-node orderer cluster; returns the list of
    node-config paths.  base_port=0 lets the OS pick ports (they are
    reserved by binding momentarily, then released)."""
    import socket

    org = DevOrg("OrdererOrg")
    mc = org.msp_config()

    ports = []
    socks = []
    for i in range(n):
        if base_port:
            ports.append(base_port + i)
        else:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
    for s in socks:
        s.close()

    cfg = ChannelConfig(
        channel_id=channel_id,
        sequence=0,
        orgs=(OrgConfig(mspid="OrdererOrg",
                        root_certs=tuple(mc.root_certs_pem),
                        admins=tuple(mc.admin_certs_pem)),),
        policies=default_policies(["OrdererOrg"]),
        batch=batch or BatchConfig(max_message_count=2, timeout_s=0.2),
        consenters=tuple(range(1, n + 1)),
    )
    cfg_hex = cfg.serialize().hex()

    # issue every consenter identity first so the shared cluster list can
    # bind raft ids to certificate fingerprints (not forgeable CN strings)
    from fabric_tpu.orderer.cluster import cert_fingerprint

    creds = [org.issuer.issue(f"orderer{i + 1}@OrdererOrg") for i in range(n)]
    cluster = [{"raft_id": i + 1, "host": "127.0.0.1", "port": ports[i],
                "mspid": "OrdererOrg",
                "cert_fp": cert_fingerprint(creds[i][0])}
               for i in range(n)]
    paths = []
    for i in range(n):
        node_dir = os.path.join(base_dir, f"orderer{i + 1}")
        os.makedirs(node_dir, exist_ok=True)
        cert, key = creds[i]
        node_cfg = {
            "mspid": "OrdererOrg",
            "raft_id": i + 1,
            "host": "127.0.0.1",
            "port": ports[i],
            "cert_pem": _cert_pem(cert).decode(),
            "key_pem": _key_pem(key).decode(),
            "channel_config_hex": cfg_hex,
            "cluster": cluster,
            "data_dir": node_dir,
        }
        path = os.path.join(base_dir, f"orderer{i + 1}.json")
        with open(path, "w") as f:
            json.dump(node_cfg, f)
        paths.append(path)

    # client material (for tests/tools): one member + the admin
    client_cert, client_key = org.issuer.issue("client@OrdererOrg")
    with open(os.path.join(base_dir, "client.json"), "w") as f:
        json.dump({
            "mspid": "OrdererOrg",
            "cert_pem": _cert_pem(client_cert).decode(),
            "key_pem": _key_pem(client_key).decode(),
            "channel_config_hex": cfg_hex,
            "cluster": cluster,
            "channel_id": channel_id,
        }, f)
    return paths
