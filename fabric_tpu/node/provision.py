"""Dev-network provisioning: crypto material + node configs on disk.

The composition of the reference's cryptogen + configtxgen
(/root/reference/internal/cryptogen, internal/configtxgen): generates an
orderer org, per-node signing identities, the channel's genesis
ChannelConfig, and one JSON config file per orderer process, ready for
`python -m fabric_tpu.node.orderer <node.json>`.
"""

from __future__ import annotations

import json
import os
from typing import List, Tuple

from fabric_tpu.crypto import serialization

from fabric_tpu.config import BatchConfig, ChannelConfig, OrgConfig, default_policies
from fabric_tpu.msp.ca import DevOrg


def _key_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())


def _cert_pem(cert) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def provision_orderers(base_dir: str, n: int, channel_id: str = "ch",
                       base_port: int = 0,
                       batch: BatchConfig = None) -> List[str]:
    """Create material for an n-node orderer cluster; returns the list of
    node-config paths.  base_port=0 lets the OS pick ports (they are
    reserved by binding momentarily, then released)."""
    import socket

    org = DevOrg("OrdererOrg")
    mc = org.msp_config()

    ports = []
    socks = []
    for i in range(n):
        if base_port:
            ports.append(base_port + i)
        else:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
    for s in socks:
        s.close()

    # issue every consenter identity first so both the channel config and
    # the shared cluster list can bind raft ids to certificate
    # fingerprints (not forgeable CN strings)
    from fabric_tpu.orderer.cluster import cert_fingerprint

    creds = [org.issuer.issue(f"orderer{i + 1}@OrdererOrg") for i in range(n)]
    cluster = [{"raft_id": i + 1, "host": "127.0.0.1", "port": ports[i],
                "mspid": "OrdererOrg",
                "cert_fp": cert_fingerprint(creds[i][0])}
               for i in range(n)]

    cfg = ChannelConfig(
        channel_id=channel_id,
        sequence=0,
        orgs=(OrgConfig(mspid="OrdererOrg",
                        root_certs=tuple(mc.root_certs_pem),
                        admins=tuple(mc.admin_certs_pem)),),
        policies=default_policies(["OrdererOrg"]),
        batch=batch or BatchConfig(max_message_count=2, timeout_s=0.2),
        consenters=tuple(cluster),
    )
    cfg_hex = cfg.serialize().hex()
    paths = []
    for i in range(n):
        node_dir = os.path.join(base_dir, f"orderer{i + 1}")
        os.makedirs(node_dir, exist_ok=True)
        cert, key = creds[i]
        node_cfg = {
            "mspid": "OrdererOrg",
            "raft_id": i + 1,
            "host": "127.0.0.1",
            "port": ports[i],
            "cert_pem": _cert_pem(cert).decode(),
            "key_pem": _key_pem(key).decode(),
            "channel_config_hex": cfg_hex,
            "cluster": cluster,
            "data_dir": node_dir,
        }
        path = os.path.join(base_dir, f"orderer{i + 1}.json")
        with open(path, "w") as f:
            json.dump(node_cfg, f)
        paths.append(path)

    # client material (for tests/tools): one member + the org admin
    client_cert, client_key = org.issuer.issue("client@OrdererOrg")
    with open(os.path.join(base_dir, "client.json"), "w") as f:
        json.dump({
            "mspid": "OrdererOrg",
            "cert_pem": _cert_pem(client_cert).decode(),
            "key_pem": _key_pem(client_key).decode(),
            "channel_config_hex": cfg_hex,
            "cluster": cluster,
            "channel_id": channel_id,
        }, f)
    with open(os.path.join(base_dir, "admin.json"), "w") as f:
        json.dump({
            "mspid": "OrdererOrg",
            "cert_pem": _cert_pem(org.admin.cert).decode(),
            "key_pem": _key_pem(org.admin._key.key).decode(),
            "channel_config_hex": cfg_hex,
            "cluster": cluster,
            "channel_id": channel_id,
        }, f)
    return paths


def _free_ports(n: int) -> List[int]:
    import socket
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def provision_network(base_dir: str, n_orderers: int = 3,
                      peer_orgs: List[str] = ("Org1", "Org2"),
                      peers_per_org: int = 1,
                      channel_id: str = "ch",
                      chaincodes: List[dict] = None,
                      collections: List[dict] = None,
                      batch: BatchConfig = None,
                      spare_orderers: int = 0) -> dict:
    """Full dev network: orderer cluster + peer-org peers on one channel.

    The nwo-style harness (reference: integration/nwo/network.go:173) —
    generates all crypto material and one JSON config per process.
    Returns {"orderers": [cfg paths], "peers": [cfg paths],
             "clients": {org: client cfg path}}.

    `spare_orderers`: additionally issues N orderer identities + node
    configs that are NOT in the genesis consenter set — provisioned but
    unjoined, the raw material for dynamic-membership drills (an
    add-consenter config entry carries the spare's binding to everyone).
    Their cfg paths land under "spare_orderers"; each cfg carries its
    own "cert_fp" so a drill can build the add_consenter request
    without re-deriving it.
    """
    from fabric_tpu.orderer.cluster import cert_fingerprint

    ord_org = DevOrg("OrdererOrg")
    p_orgs = {name: DevOrg(name) for name in peer_orgs}
    all_orgs = {"OrdererOrg": ord_org, **p_orgs}

    n_peers = len(p_orgs) * peers_per_org
    ports = _free_ports(n_orderers + n_peers + spare_orderers)
    ord_ports = ports[:n_orderers]
    peer_ports = ports[n_orderers:n_orderers + n_peers]
    spare_ports = ports[n_orderers + n_peers:]

    org_cfgs = []
    for name, org in all_orgs.items():
        mc = org.msp_config()
        org_cfgs.append(OrgConfig(mspid=name,
                                  root_certs=tuple(mc.root_certs_pem),
                                  admins=tuple(mc.admin_certs_pem)))
    # consenter identities first: the channel config itself carries the
    # rich consenter entries (raft id -> addr + mspid + cert fingerprint)
    creds = [ord_org.issuer.issue(f"orderer{i + 1}@OrdererOrg")
             for i in range(n_orderers)]
    cluster = [{"raft_id": i + 1, "host": "127.0.0.1", "port": ord_ports[i],
                "mspid": "OrdererOrg",
                "cert_fp": cert_fingerprint(creds[i][0])}
               for i in range(n_orderers)]

    cfg = ChannelConfig(
        channel_id=channel_id,
        sequence=0,
        orgs=tuple(org_cfgs),
        policies=default_policies(list(all_orgs)),
        batch=batch or BatchConfig(max_message_count=8, timeout_s=0.2),
        consenters=tuple(cluster),
    )
    cfg_hex = cfg.serialize().hex()

    chaincodes = chaincodes or [
        {"name": "assets", "version": "1.0", "contract": "asset_demo",
         "policy": "AND(%s)" % ", ".join(
             f"'{o}.member'" for o in peer_orgs)}]
    collections = collections or []

    # peer identities first: every peer hosts a gateway whose
    # handshake-verified transport identity the orderers pin as a
    # verdict-attestation attestor — trusting attestations is OFF by
    # node default, so the dev provisioner opts in EXPLICITLY with the
    # exact (mspid, cert sha256) bindings allowed to vouch
    peer_list = []
    idx = 0
    for org_name in peer_orgs:
        for j in range(peers_per_org):
            peer_list.append((org_name, j, peer_ports[idx]))
            idx += 1
    peer_creds = {(o, j): p_orgs[o].issuer.issue(f"peer{j}@{o}")
                  for o, j, _ in peer_list}
    attestors = [{"mspid": o, "cert_fp": cert_fingerprint(c)}
                 for (o, _), (c, _k) in peer_creds.items()]

    # orderers
    orderer_paths = []
    for i in range(n_orderers):
        node_dir = os.path.join(base_dir, f"orderer{i + 1}")
        os.makedirs(node_dir, exist_ok=True)
        cert, key = creds[i]
        path = os.path.join(base_dir, f"orderer{i + 1}.json")
        with open(path, "w") as f:
            json.dump({
                "mspid": "OrdererOrg", "raft_id": i + 1,
                "host": "127.0.0.1", "port": ord_ports[i],
                "cert_pem": _cert_pem(cert).decode(),
                "key_pem": _key_pem(key).decode(),
                "channel_config_hex": cfg_hex,
                "cluster": cluster, "data_dir": node_dir,
                "verify_once": {"trust_attestations": True,
                                "attestors": attestors,
                                "attest_deliver": True},
            }, f)
        orderer_paths.append(path)

    # spare orderers: identity + config on disk, EXCLUDED from the
    # genesis consenter tuple and every bootstrap cluster list.  A
    # spare that starts up is a silent learner (its raft node refuses
    # to campaign while outside the consenter set) until a committed
    # add-consenter config entry teaches the whole channel its binding.
    spare_paths = []
    spare_creds = [ord_org.issuer.issue(
        f"orderer{n_orderers + s + 1}@OrdererOrg")
        for s in range(spare_orderers)]
    for s in range(spare_orderers):
        rid = n_orderers + s + 1
        node_dir = os.path.join(base_dir, f"orderer{rid}")
        os.makedirs(node_dir, exist_ok=True)
        cert, key = spare_creds[s]
        path = os.path.join(base_dir, f"orderer{rid}.json")
        with open(path, "w") as f:
            json.dump({
                "mspid": "OrdererOrg", "raft_id": rid,
                "host": "127.0.0.1", "port": spare_ports[s],
                "cert_pem": _cert_pem(cert).decode(),
                "key_pem": _key_pem(key).decode(),
                "cert_fp": cert_fingerprint(cert),
                "channel_config_hex": cfg_hex,
                "cluster": cluster, "data_dir": node_dir,
                "verify_once": {"trust_attestations": True,
                                "attestors": attestors,
                                "attest_deliver": True},
            }, f)
        spare_paths.append(path)

    # the reverse direction: peers pin the orderer identities so the
    # admission-verdict digests riding deliver frames are honoured —
    # again an explicit dev-provisioner opt-in, off by node default.
    # Spares are pinned too: attestor trust is an identity allowlist,
    # not a membership statement, and a joined spare attests like any
    # other consenter.
    orderer_attestors = [{"mspid": "OrdererOrg",
                          "cert_fp": cert_fingerprint(c)}
                         for c, _k in creds + spare_creds]

    # peers: each knows every OTHER peer's endpoint + org (privdata push,
    # discovery membership)
    peer_paths = []
    for org_name, j, port in peer_list:
        org = p_orgs[org_name]
        node_dir = os.path.join(base_dir, f"peer{org_name}_{j}")
        os.makedirs(node_dir, exist_ok=True)
        cert, key = peer_creds[(org_name, j)]
        others = [["127.0.0.1", p, o] for (o, k, p) in peer_list
                  if (o, k) != (org_name, j)]
        path = os.path.join(base_dir, f"peer{org_name}_{j}.json")
        with open(path, "w") as f:
            json.dump({
                "mspid": org_name, "channel_id": channel_id,
                "host": "127.0.0.1", "port": port,
                "cert_pem": _cert_pem(cert).decode(),
                "key_pem": _key_pem(key).decode(),
                "channel_config_hex": cfg_hex,
                # the full ordering-service roster INCLUDING spares:
                # endpoint knowledge is fleet provisioning, not
                # membership — a spare that later joins (and may even
                # lead) must be dialable, an unstarted one just fails
                # dial and the broadcast/deliver failover walks on
                "orderers": [["127.0.0.1", p]
                             for p in ord_ports + spare_ports],
                "peers": others,
                "chaincodes": chaincodes,
                "collections": collections,
                "data_dir": node_dir,
                "verify_once": {"trust_attestations": True,
                                "attestors": orderer_attestors},
            }, f)
        peer_paths.append(path)

    # per-org clients: one per signature scheme the MSP accepts, so
    # mixed-identity workloads (workload/scenarios.py) can blend P-256
    # and ed25519 creators against the same channel
    from fabric_tpu.bccsp import SCHEME_ED25519
    clients = {}
    clients_ed25519 = {}
    for org_name, org in p_orgs.items():
        for scheme, book in (
                (None, clients), (SCHEME_ED25519, clients_ed25519)):
            ccert, ckey = org.issuer.issue(f"client@{org_name}",
                                           scheme=scheme)
            suffix = f"_{scheme}" if scheme else ""
            path = os.path.join(base_dir,
                                f"client_{org_name}{suffix}.json")
            with open(path, "w") as f:
                json.dump({
                    "mspid": org_name,
                    "cert_pem": _cert_pem(ccert).decode(),
                    "key_pem": _key_pem(ckey).decode(),
                    "channel_config_hex": cfg_hex,
                    "channel_id": channel_id,
                    "orderers": [["127.0.0.1", p]
                                 for p in ord_ports + spare_ports],
                    "peers": [["127.0.0.1", p, o]
                              for (o, k, p) in peer_list],
                }, f)
            book[org_name] = path
    # per-org ADMIN identities (channel-config admin certs): the admin
    # CLI's install/join verbs are Admins-gated
    admins = {}
    for org_name, org in p_orgs.items():
        path = os.path.join(base_dir, f"admin_{org_name}.json")
        with open(path, "w") as f:
            json.dump({
                "mspid": org_name,
                "cert_pem": _cert_pem(org.admin.cert).decode(),
                "key_pem": _key_pem(org.admin._key.key).decode(),
                "channel_config_hex": cfg_hex,
                "channel_id": channel_id,
            }, f)
        admins[org_name] = path
    return {"orderers": orderer_paths, "peers": peer_paths,
            "spare_orderers": spare_paths,
            "clients": clients, "clients_ed25519": clients_ed25519,
            "admins": admins}
