"""Runnable orderer node: Broadcast/Deliver + Raft cluster over sockets.

The reference's orderer server binary (VERDICT.md missing #9 / #3):
/root/reference/orderer/common/server/main.go wires localconfig, the
multichannel registrar, the cluster transport, and the AtomicBroadcast
gRPC service into one process.  This module is the same composition for
this framework: a JSON node config + MSP material on disk produce a
process serving `broadcast` (unary), `deliver` (stream), and `raft.step`
(cast) over the authenticated RPC plane.

Run:  python -m fabric_tpu.node.orderer <node.json>
Provision a dev network:  fabric_tpu.node.provision.provision_orderers().
"""

from __future__ import annotations

import json
import logging
import sys
import threading
from typing import Dict, Optional

from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
from fabric_tpu.comm.rpc import RpcServer
from fabric_tpu.config import Bundle, BundleSource, ChannelConfig
from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.msp.identity import SigningIdentity
from fabric_tpu.orderer import BroadcastHandler, DeliverHandler, Registrar
from fabric_tpu.orderer.blockcutter import BatchConfig
from fabric_tpu.orderer.cluster import ClusterService
from fabric_tpu.orderer.consensus import RaftChain
from fabric_tpu.orderer.deliver import SeekInfo
from fabric_tpu.orderer.raft import RaftNode
from fabric_tpu.policy import SignedData
from fabric_tpu.protocol import Envelope

logger = logging.getLogger("fabric_tpu.node.orderer")


def load_signing_identity(mspid: str, cert_pem: bytes, key_pem: bytes,
                          scheme: str = None) -> SigningIdentity:
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization
    from fabric_tpu.bccsp.sw import SigningKey

    from cryptography.hazmat.primitives.asymmetric import ec as _ec
    from fabric_tpu.bccsp import SCHEME_ED25519, SCHEME_P256

    cert = x509.load_pem_x509_certificate(cert_pem)
    key = serialization.load_pem_private_key(key_pem, password=None)
    if scheme is None:
        scheme = (SCHEME_P256 if isinstance(key, _ec.EllipticCurvePrivateKey)
                  else SCHEME_ED25519)
    return SigningIdentity(mspid, cert, SigningKey(scheme, key))


class OrdererNode:
    """One orderer process (library form; `main` wraps it)."""

    def __init__(self, cfg: dict, data_dir: str):
        self.cfg = cfg
        self.provider = init_factories(FactoryOpts(default="SW"))
        self.signer = load_signing_identity(
            cfg["mspid"], cfg["cert_pem"].encode(), cfg["key_pem"].encode())

        channel_cfg = ChannelConfig.deserialize(
            bytes.fromhex(cfg["channel_config_hex"]))
        self.bundle_source = BundleSource(Bundle(channel_cfg))
        msps = self.bundle_source.current().msps

        self.registrar = Registrar()
        self.raft_id = int(cfg["raft_id"])
        peer_ids = [int(p["raft_id"]) for p in cfg["cluster"]]
        node = RaftNode(self.raft_id, peer_ids,
                        wal_path=f"{data_dir}/wal.bin",
                        snap_path=f"{data_dir}/snap.bin")
        batch = channel_cfg.batch
        self.support = self.registrar.create_channel(
            channel_cfg.channel_id, msps, self.provider,
            writers_policy=None,
            signer=self.signer,
            batch_config=BatchConfig(
                max_message_count=batch.max_message_count,
                absolute_max_bytes=batch.absolute_max_bytes,
                preferred_max_bytes=batch.preferred_max_bytes,
                batch_timeout_s=batch.timeout_s),
            ledger=BlockStore(f"{data_dir}/ledger"),
            chain_factory=lambda cutter, writer, on_block: RaftChain(
                node, cutter, writer, on_block=on_block),
            bundle_source=self.bundle_source)

        self.broadcast = BroadcastHandler(self.registrar)
        self.deliver = DeliverHandler(self.registrar)
        self.rpc = RpcServer(cfg.get("host", "127.0.0.1"), int(cfg["port"]),
                             self.signer, msps)
        peers = {int(p["raft_id"]): (p.get("host", "127.0.0.1"), int(p["port"]))
                 for p in cfg["cluster"] if int(p["raft_id"]) != self.raft_id}
        # consenter auth is mandatory: every cluster entry must carry its
        # identity binding (mspid + cert sha256) or the node refuses to run
        consenters = {}
        for p in cfg["cluster"]:
            if not p.get("mspid") or not p.get("cert_fp"):
                raise ValueError(
                    f"cluster entry for raft_id {p.get('raft_id')} is "
                    "missing mspid/cert_fp — consenter identities must be "
                    "bound to certificate fingerprints (re-provision the "
                    "network; CN-based configs are no longer accepted)")
            consenters[int(p["raft_id"])] = (p["mspid"], p["cert_fp"])
        self.cluster = ClusterService(self.support.chain, self.rpc,
                                      self.signer, msps, peers,
                                      consenters=consenters)
        self.rpc.serve("broadcast", self._rpc_broadcast)
        self.rpc.serve("status", self._rpc_status)
        self.rpc.serve_stream("deliver", self._rpc_deliver)

        # ops plane: /metrics, /healthz (system.go:75-267 parity)
        self.ops = None
        if cfg.get("ops_port") is not None:
            from fabric_tpu.ops_plane import OperationsServer
            self.ops = OperationsServer(cfg.get("host", "127.0.0.1"),
                                        int(cfg["ops_port"]))
            self.ops.register_checker(
                "raft", lambda: self.support.chain.node.leader_id is not None)

    # -- rpc handlers --------------------------------------------------------

    def _rpc_broadcast(self, body: dict, peer_identity) -> dict:
        env = Envelope.deserialize(body["envelope"])
        resp = self.broadcast.handle(env)
        return {"status": resp.status, "info": resp.info or "",
                "leader": getattr(resp, "leader_hint", 0) or 0}

    def _rpc_deliver(self, body: dict, peer_identity):
        seek = SeekInfo(start=body.get("start", 0), stop=body.get("stop"),
                        behavior=body.get("behavior", "block_until_ready"))
        sd = None
        if body.get("signed_data"):
            s = body["signed_data"]
            sd = SignedData(s["data"], s["identity"], s["signature"])
        for block in self.deliver.deliver(body["channel"], seek, sd,
                                          timeout_s=body.get("timeout_s", 30)):
            yield {"block": block.serialize()}

    def _rpc_status(self, body: dict, peer_identity) -> dict:
        from fabric_tpu.orderer import raft as raftmod
        node = self.support.chain.node
        return {"raft_id": self.raft_id, "role": node.role,
                "leader": node.leader_id or 0, "term": node.term,
                "height": self.support.ledger.height}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "OrdererNode":
        self.rpc.start()
        self.cluster.start()
        if self.ops is not None:
            self.ops.start()
        logger.info("orderer %d serving on %s", self.raft_id, self.rpc.addr)
        return self

    def stop(self) -> None:
        self.cluster.stop()
        self.support.chain.halt()
        self.rpc.stop()
        if self.ops is not None:
            self.ops.stop()


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m fabric_tpu.node.orderer <node.json>",
              file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.INFO)
    with open(argv[0]) as f:
        cfg = json.load(f)
    node = OrdererNode(cfg, data_dir=cfg["data_dir"]).start()
    threading.Event().wait()   # serve until killed
    return 0


if __name__ == "__main__":
    sys.exit(main())
