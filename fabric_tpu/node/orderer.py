"""Runnable orderer node: Broadcast/Deliver + Raft cluster over sockets.

The reference's orderer server binary (VERDICT.md missing #9 / #3):
/root/reference/orderer/common/server/main.go wires localconfig, the
multichannel registrar, the cluster transport, and the AtomicBroadcast
gRPC service into one process.  This module is the same composition for
this framework: a JSON node config + MSP material on disk produce a
process serving `broadcast` (unary), `deliver` (stream), and `raft.step`
(cast) over the authenticated RPC plane.

Run:  python -m fabric_tpu.node.orderer <node.json>
Provision a dev network:  fabric_tpu.node.provision.provision_orderers().
"""

from __future__ import annotations

import json
import logging
import sys
import threading
from typing import Dict, Optional

from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
from fabric_tpu.comm.rpc import RpcServer
from fabric_tpu.config import Bundle, BundleSource, ChannelConfig
from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.msp.identity import SigningIdentity
from fabric_tpu.orderer import BroadcastHandler, DeliverHandler, Registrar
from fabric_tpu.orderer.blockcutter import BatchConfig
from fabric_tpu.orderer.cluster import ClusterService
from fabric_tpu.orderer.consensus import RaftChain
from fabric_tpu.orderer.deliver import SeekInfo
from fabric_tpu.orderer.raft import RaftNode
from fabric_tpu.policy import SignedData
from fabric_tpu.protocol import Envelope

logger = logging.getLogger("fabric_tpu.node.orderer")


def load_signing_identity(mspid: str, cert_pem: bytes, key_pem: bytes,
                          scheme: str = None) -> SigningIdentity:
    from fabric_tpu.crypto import x509
    from fabric_tpu.crypto import serialization
    from fabric_tpu.bccsp.sw import SigningKey

    from fabric_tpu.crypto import ec as _ec
    from fabric_tpu.bccsp import SCHEME_ED25519, SCHEME_P256

    cert = x509.load_pem_x509_certificate(cert_pem)
    key = serialization.load_pem_private_key(key_pem, password=None)
    if scheme is None:
        scheme = (SCHEME_P256 if isinstance(key, _ec.EllipticCurvePrivateKey)
                  else SCHEME_ED25519)
    return SigningIdentity(mspid, cert, SigningKey(scheme, key))


def attestation_trust(vcfg: dict):
    """(trust_attestations, attestors) from a `verify_once` config
    sub-dict.  Trusting gateway verdict attestations is a security
    decision, so it is OFF unless explicitly enabled — and useless
    without an attestor allowlist naming who may vouch."""
    return (bool(vcfg.get("trust_attestations", False)),
            list(vcfg.get("attestors", [])))


class _BlockStoreLedger:
    """Adapter giving an orderer-side BlockStore the `.height` +
    `.blockstore` shape the ByzantineMonitor judges against."""

    def __init__(self, store: BlockStore):
        self.blockstore = store

    @property
    def height(self) -> int:
        return self.blockstore.height


class OrdererNode:
    """One orderer process (library form; `main` wraps it)."""

    def __init__(self, cfg: dict, data_dir: str):
        self.cfg = cfg
        self.provider = init_factories(FactoryOpts(default="SW"))
        self.signer = load_signing_identity(
            cfg["mspid"], cfg["cert_pem"].encode(), cfg["key_pem"].encode())

        # verify-once plane (on by default; `verify_once: {"enabled":
        # false}` opts out): duplicate/retried submissions stop
        # re-verifying.  Attestation trust is a SECURITY decision and
        # is OFF by default: enabling it requires BOTH
        # `trust_attestations: true` AND an explicit `attestors` list
        # of {"mspid", "cert_fp"} bindings naming the gateway
        # identities allowed to vouch — only attestations arriving on
        # a transport handshake-authenticated as one of those
        # identities skip the SigFilter's device verify.
        vcfg = dict(cfg.get("verify_once", {}))
        self.verify_cache = None
        self._trust_attestations, self._attestors = attestation_trust(vcfg)
        # attest_deliver (opt-in): ride this orderer's own admission
        # verdicts back to committing peers on the deliver stream, so a
        # creator signature verified once at SigFilter need not be
        # re-dispatched at any peer's commit gate.  Emitting digests is
        # harmless by itself — whether a peer HONOURS them is the
        # peer's own trust_attestations + attestor-allowlist decision.
        self._attest_deliver = bool(vcfg.get("attest_deliver", False))
        if vcfg.get("enabled", True):
            from fabric_tpu.verify_plane import VerdictCache
            self.verify_cache = VerdictCache(
                capacity=int(vcfg.get("capacity", 65536)),
                owner="orderer%s" % cfg.get("raft_id", ""))

        channel_cfg = ChannelConfig.deserialize(
            bytes.fromhex(cfg["channel_config_hex"]))
        self.bundle_source = BundleSource(Bundle(channel_cfg))
        msps = self.bundle_source.current().msps
        self.data_dir = data_dir
        # per-gateway standing registry (verify_plane/trust.py): which
        # allowlisted attestors are still honoured.  Persisted under the
        # data dir so a digest-mismatch revocation survives restarts.
        self.attestor_trust = None
        if self._trust_attestations and self._attestors:
            import os
            from fabric_tpu.verify_plane import AttestorTrust
            self.attestor_trust = AttestorTrust(
                os.path.join(data_dir, "attestor_trust.json"))

        self.registrar = Registrar()
        self.raft_id = int(cfg["raft_id"])
        self.peer_ids = [int(p["raft_id"]) for p in cfg["cluster"]]
        self.channel_id = channel_cfg.channel_id
        # fleet lifecycle: serving -> draining -> drained.  A draining
        # orderer refuses new broadcasts (clients fail over), hands off
        # raft leadership, and fsyncs its WALs so the following stop()
        # is a clean point-in-time exit rather than a crash.
        self.lifecycle = "serving"
        # per-channel raft membership: raft_id -> rich consenter entry
        # ({raft_id, host, port, mspid, cert_fp}).  Seeded from the
        # channel config (or the bootstrap cluster list) and THEREAFTER
        # owned by committed membership config entries — persisted to
        # <channel>/membership.json so a restart mid-churn reloads the
        # post-reconfig set, not the genesis one.
        self._membership: Dict[str, Dict[int, dict]] = {}

        self.rpc = RpcServer(cfg.get("host", "127.0.0.1"), int(cfg["port"]),
                             self.signer, msps)
        peers = {int(p["raft_id"]): (p.get("host", "127.0.0.1"), int(p["port"]))
                 for p in cfg["cluster"] if int(p["raft_id"]) != self.raft_id}
        # consenter auth is mandatory: every cluster entry must carry its
        # identity binding (mspid + cert sha256) or the node refuses to run
        consenters = {}
        for p in cfg["cluster"]:
            if not p.get("mspid") or not p.get("cert_fp"):
                raise ValueError(
                    f"cluster entry for raft_id {p.get('raft_id')} is "
                    "missing mspid/cert_fp — consenter identities must be "
                    "bound to certificate fingerprints (re-provision the "
                    "network; CN-based configs are no longer accepted)")
            consenters[int(p["raft_id"])] = (p["mspid"], p["cert_fp"])
        self.cluster = ClusterService(self.rpc, self.signer, msps, peers,
                                      consenters=consenters)

        # byzantine containment plane, orderer side: ONE persistent
        # quarantine registry per process (same file layout as the peer,
        # so standings read identically across node kinds), per-channel
        # witness monitors built in _create_channel.  The cluster
        # transport's entry verifier reports into it: a mis-signed or
        # unsigned append scores the sending node, a raft-entry
        # equivocation convicts the proposing consenter and mints a
        # portable fraud proof AT THE ORDERER.
        import os as _byz_os
        byz_cfg = dict(cfg.get("byzantine", {}))
        self.byzantine = None
        self.byz_monitors: Dict[str, object] = {}
        # clean-observation window before offense-based quarantines are
        # pardoned; None = permanent (the r13 behaviour)
        self.byz_pardon_window = (
            float(byz_cfg["pardon_window_s"])
            if byz_cfg.get("pardon_window_s") is not None else None)
        if byz_cfg.get("enabled", True):
            from fabric_tpu.byzantine import QuarantineRegistry
            self.byzantine = QuarantineRegistry(
                _byz_os.path.join(data_dir, "byzantine_quarantine.json"),
                score_threshold=int(byz_cfg.get("score_threshold", 3)))
            self.cluster.on_entry_offense = self._on_entry_offense
            self.cluster.on_entry_crime = self._on_entry_crime

        # refuse to silently strand pre-multichannel node state (storage
        # moved from data_dir/wal.bin to data_dir/<channel>/wal.bin)
        import os as _os
        if _os.path.exists(_os.path.join(data_dir, "wal.bin")):
            raise ValueError(
                f"{data_dir} holds single-channel-era state (wal.bin at "
                "the data-dir root); move it into "
                f"{data_dir}/{channel_cfg.channel_id}/ or re-provision")

        # bootstrap channel (the registrar manages N chains; more join at
        # runtime via the participation API — registrar.go dynamic chains)
        self.support = self._create_channel(channel_cfg,
                                            self.bundle_source)

        # re-load channels joined at runtime in earlier lives of this
        # node: a restart must not silently drop them from the cluster
        for entry in sorted(_os.listdir(data_dir)):
            cfg_path = _os.path.join(data_dir, entry, "channel_config.bin")
            if entry == channel_cfg.channel_id or not _os.path.exists(
                    cfg_path):
                continue
            try:
                with open(cfg_path, "rb") as f:
                    joined_cfg = ChannelConfig.deserialize(f.read())
                self._create_channel(joined_cfg,
                                     BundleSource(Bundle(joined_cfg)))
                logger.info("restored joined channel %r", entry)
            except Exception:
                logger.exception("could not restore channel %r", entry)

        self.broadcast = BroadcastHandler(self.registrar)
        self.deliver = DeliverHandler(self.registrar)
        self.rpc.serve("broadcast", self._rpc_broadcast)
        self.rpc.serve("broadcast_batch", self._rpc_broadcast_batch)
        self.rpc.serve("status", self._rpc_status)
        self.rpc.serve_stream("deliver", self._rpc_deliver)
        self.rpc.serve("participation.join", self._rpc_join)
        self.rpc.serve("participation.list", self._rpc_list)
        self.rpc.serve("participation.remove", self._rpc_remove)
        # fleet lifecycle + dynamic membership (admin-gated)
        self.rpc.serve("admin.add_consenter", self._rpc_add_consenter)
        self.rpc.serve("admin.remove_consenter", self._rpc_remove_consenter)
        self.rpc.serve("admin.transfer_leadership",
                       self._rpc_transfer_leadership)
        self.rpc.serve("admin.drain", self._rpc_drain)

        # ops plane: /metrics, /healthz (system.go:75-267 parity) + the
        # channelparticipation REST API (channelparticipation/restapi.go)
        # tx tracing + flight recorder (sample rate / capacity via the
        # localconfig `tracing` sub-dict, FABRIC_TPU_ORDERER_TRACING__*)
        from fabric_tpu.ops_plane import tracing as _tracing
        _tracing.configure(cfg.get("tracing", {}))

        self.ops = None
        if cfg.get("ops_port") is not None:
            from fabric_tpu.ops_plane import OperationsServer
            self.ops = OperationsServer(cfg.get("host", "127.0.0.1"),
                                        int(cfg["ops_port"]))
            self.ops.register_checker(
                "raft", lambda: self.support.chain.node.leader_id is not None)
            self.ops.lifecycle_fn = lambda: self.lifecycle
            # POST /drain: plain-HTTP ops convenience (same trust
            # boundary caveat as the participation REST writes); the
            # authenticated admin.drain RPC is the production surface
            self.ops.register_route(
                "POST", "/drain",
                lambda path, body: (200, self.drain()))
            # profiling surface (orderer/common/server/main.go:408 slot)
            from fabric_tpu.ops_plane.profiling import register_routes
            register_routes(self.ops, enabled=bool(cfg.get("profiling")))
            # /traces, /traces/<id> (Chrome trace JSON), /spans/stats;
            # ?cluster=1 merges the trace across the `cluster_trace`
            # sub-dict's ops endpoints — same route shape as the peer's
            # so one client assembles from any node kind
            ct_cfg = dict(cfg.get("cluster_trace", {}))
            self.trace_peers = list(ct_cfg.get("peers", []))

            def _cluster_trace(tid, _cfg=ct_cfg):
                from fabric_tpu.node import tracecollect
                # the config's peer list may include this node's own
                # endpoint (one shared list for the whole cluster) —
                # serve self in-process, or the same spans would count
                # under two node identities
                own = "%s:%d" % self.ops.addr
                peers = [p for p in self.trace_peers if str(p) != own]
                out = tracecollect.collect_cluster_trace(
                    tid, peers, local_tracer=_tracing.tracer,
                    local_name=f"orderer:{self.raft_id}",
                    timeout_s=float(_cfg.get("timeout_s", 2.0)),
                    max_traces=int(_cfg.get("max_traces", 16)))
                if out is None:
                    return 404, {"error": "unknown trace", "trace_id": tid}
                return 200, out

            _tracing.register_routes(self.ops, cluster_fn=_cluster_trace)
            # GET /faults: active fault plan ({"active": false} outside
            # chaos drills)
            from fabric_tpu.comm import faults as _faults
            _faults.register_routes(self.ops)
            # GET /verify_plane: the verdict cache's live economics
            if self.verify_cache is not None:
                from fabric_tpu import verify_plane as _vp
                _vp.register_ops(
                    self.ops, self.verify_cache,
                    extra=lambda: {
                        "trust_attestations": self._trust_attestations,
                        "attestors": len(self._attestors),
                        "attestors_revoked": (
                            self.attestor_trust.revoked_count()
                            if self.attestor_trust is not None else 0),
                        "attestor_standing": (
                            self.attestor_trust.snapshot()
                            if self.attestor_trust is not None else {})})
            # GET /byzantine: quarantine standings + per-channel witness
            # stats — the SAME route shape as the peer's, so one ops
            # client reads standings across node kinds
            if self.byzantine is not None:
                from fabric_tpu.byzantine import register_ops as _byz_ops
                _byz_ops(self.ops, self.byzantine,
                         monitors_fn=lambda: dict(self.byz_monitors))
            self.ops.register_route("GET", "/participation/v1/channels",
                                    self._rest_channels)
            # the ops server is PLAIN HTTP with no client auth, so the
            # MUTATING participation routes are opt-in (dev/ops networks
            # behind a trusted boundary); the authenticated RPC verbs
            # (admin-gated) are the production surface
            if cfg.get("participation_rest_writes"):
                self.ops.register_route("POST",
                                        "/participation/v1/channels",
                                        self._rest_join)
                self.ops.register_route("DELETE",
                                        "/participation/v1/channels/",
                                        self._rest_remove)

        # SLO plane: GET /slo + /slo/alerts (burn-rate alerting over the
        # metrics registry), FABRIC_TPU_ORDERER_SLO__* env-overridable
        self.slo = None
        slo_cfg = cfg.get("slo", {})
        if self.ops is not None and slo_cfg.get("enabled", True):
            from fabric_tpu.ops_plane import slo as _slo
            self.slo = _slo.SloEvaluator(slo_cfg)
            _slo.register_routes(self.ops, self.slo)
            self.slo.start()

        # metric history + resource telemetry (same knobs as the peer:
        # `timeseries` / `resources` sub-dicts, OFF by default so the
        # disabled /metrics surface and runtime are byte-identical)
        self.timeseries = None
        ts_cfg = cfg.get("timeseries", {})
        if self.ops is not None and ts_cfg.get("enabled", False):
            from fabric_tpu.ops_plane import timeseries as _ts
            self.timeseries = _ts.TimeSeriesStore(ts_cfg)
            _ts.register_routes(self.ops, self.timeseries)
            self.timeseries.start()
        self.resources = None
        res_cfg = cfg.get("resources", {})
        if self.ops is not None and res_cfg.get("enabled", False):
            from fabric_tpu.ops_plane import resources as _res
            self.resources = _res.ResourceCollector(res_cfg)
            if self.verify_cache is not None:
                cache = self.verify_cache
                self.resources.add_source(
                    "verdict_cache_occupancy",
                    lambda: cache.snapshot()["size"])
            _res.register_routes(self.ops, self.resources)
            self.resources.start()

        # continuous sampling profiler + incident capture (same knobs
        # and zero-overhead guards as the peer: `profiler`/`incidents`
        # sub-dicts, OFF by default)
        self.profiler = None
        prof_cfg = cfg.get("profiler", {})
        if self.ops is not None and prof_cfg.get("enabled", False):
            from fabric_tpu.ops_plane import sampler as _sampler
            self.profiler = _sampler.SamplingProfiler(prof_cfg)
            _sampler.register_routes(self.ops, self.profiler)
            self.profiler.start()
        self.incidents = None
        inc_cfg = dict(cfg.get("incidents", {}))
        if self.ops is not None and inc_cfg.get("enabled", False):
            from fabric_tpu.ops_plane import incidents as _inc
            inc_cfg.setdefault(
                "dir", _os.path.join(data_dir, "incidents"))
            if "peers" not in inc_cfg:
                own = "%s:%d" % self.ops.addr
                inc_cfg["peers"] = [
                    p for p in getattr(self, "trace_peers", [])
                    if str(p) != own]
            self.incidents = _inc.IncidentRecorder(
                inc_cfg, node_name=f"orderer:{self.raft_id}",
                profiler=self.profiler, timeseries=self.timeseries)
            if getattr(self, "slo", None) is not None:
                self.incidents.attach_slo(self.slo)
            if self.resources is not None:
                self.incidents.add_source(
                    "resources", self.resources.collect)
            self.incidents.add_source(
                "lifecycle", lambda: {"lifecycle": self.lifecycle})
            _inc.register_routes(self.ops, self.incidents)

    # -- byzantine hooks (cluster entry verifier -> containment plane) -------

    def _on_entry_offense(self, channel_id: str, frm_node: int,
                          reason: str) -> None:
        """A dropped append (unsigned / bad proposer / bad signature)
        scores the SENDING node's consenter identity — repeat offenders
        cross the registry threshold into quarantine."""
        mon = self.byz_monitors.get(channel_id)
        key = self.cluster.consenter_binding(channel_id, frm_node)
        if mon is None or key is None:
            return
        mon.offense(key, "bad_sig" if reason != "unsigned_entry"
                    else "garbage")

    def _on_entry_crime(self, channel_id: str, binding: str,
                        evidence: dict) -> None:
        """Two different payloads validly signed for one (term, index)
        slot: provable equivocation by the PROPOSER — convict and mint
        the portable fraud proof here at the orderer."""
        mon = self.byz_monitors.get(channel_id)
        if mon is None:
            return
        mon.convict_external(binding, "equivocation", evidence)

    # -- channelparticipation REST (restapi.go) ------------------------------

    def _rest_channels(self, path: str, body: bytes):
        parts = path.rstrip("/").split("/")
        if parts[-1] != "channels":          # /channels/<id>
            cid = parts[-1]
            support = self.registrar.get(cid)
            if support is None:
                return 404, {"error": f"no such channel {cid!r}"}
            return 200, {"name": cid, "height": support.ledger.height,
                         "consensus": "raft"}
        return 200, {"channels": [
            {"name": cid, "height": s.ledger.height}
            for cid, s in sorted(self.registrar.channels().items())],
            "systemChannel": None}

    def _rest_join(self, path: str, body: bytes):
        import json as _json
        if path.rstrip("/").split("/")[-1] != "channels":
            return 404, {"error": "POST only on .../channels"}
        cfg_hex = _json.loads(body)["config_hex"]
        cfg = ChannelConfig.deserialize(bytes.fromhex(cfg_hex))
        if self.registrar.get(cfg.channel_id) is not None:
            return 409, {"error": f"channel {cfg.channel_id!r} exists"}
        self.join_channel(cfg)
        return 201, {"name": cfg.channel_id, "status": "joined"}

    def _rest_remove(self, path: str, body: bytes):
        cid = path.rstrip("/").split("/")[-1]
        support = self.registrar.get(cid)
        if support is None:
            return 404, {"error": f"no such channel {cid!r}"}
        self.cluster.remove_chain(cid)
        self.byz_monitors.pop(cid, None)
        support.chain.halt()
        self.registrar.remove(cid)
        return 200, {"name": cid, "status": "removed"}

    # -- channel lifecycle ---------------------------------------------------

    def _load_membership(self, ch_dir: str,
                         channel_cfg: ChannelConfig) -> Dict[int, dict]:
        """THIS channel's raft membership, newest source first: the
        persisted post-reconfig set (membership.json, written every time
        a membership config entry commits), else the channel config's
        rich consenter entries ({raft_id, host, port, mspid, cert_fp} —
        the reference authenticates cluster traffic against per-channel
        consenter sets, orderer/common/cluster/comm.go), else the
        bootstrap cluster list.  A node restarting mid-churn therefore
        comes back with the set as of its last committed conf entry —
        NOT the genesis set — and the raft WAL replay re-fires the same
        conf entries idempotently on top."""
        import os
        path = os.path.join(ch_dir, "membership.json")
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                return {int(e["raft_id"]): dict(e) for e in json.load(f)}
        rich = [c for c in channel_cfg.consenters if isinstance(c, dict)]
        if not rich:
            rich = list(self.cfg["cluster"])
        return {int(c["raft_id"]): dict(c) for c in rich}

    def _persist_membership(self, channel_id: str) -> None:
        import os
        members = self._membership.get(channel_id, {})
        ch_dir = os.path.join(self.data_dir, channel_id)
        path = os.path.join(ch_dir, "membership.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump([members[nid] for nid in sorted(members)], f,
                      sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _membership_maps(self, members: Dict[int, dict]):
        """(raft ids, consenter identity map, peer address map) from a
        membership set — the three views the raft node, the entry
        verifier, and the transport each need."""
        ids = sorted(members)
        consenters = {nid: (m["mspid"], m["cert_fp"])
                      for nid, m in members.items()}
        peers = {nid: (m.get("host", "127.0.0.1"), int(m["port"]))
                 for nid, m in members.items() if nid != self.raft_id}
        return ids, consenters, peers

    def _create_channel(self, channel_cfg: ChannelConfig, bundle_source):
        """One channel's chain: per-channel data dirs + raft instance,
        registered with the shared cluster transport.  The channel config
        is persisted alongside so runtime-joined channels survive
        restarts (participation state, registrar.go)."""
        import os
        cid = channel_cfg.channel_id
        ch_dir = os.path.join(self.data_dir, cid)
        os.makedirs(ch_dir, exist_ok=True)
        cfg_path = os.path.join(ch_dir, "channel_config.bin")
        if not os.path.exists(cfg_path):
            tmp = cfg_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(channel_cfg.serialize())
            os.replace(tmp, cfg_path)
        members = self._load_membership(ch_dir, channel_cfg)
        self._membership[cid] = members
        peer_ids, ch_consenters, ch_peers = self._membership_maps(members)
        # every proposed entry is signed with this consenter's identity;
        # followers verify the chain before applying (cluster.py
        # EntryVerifier) — enforcement keys on entry_signer being set
        from fabric_tpu.orderer.consensus import make_entry_signer
        node = RaftNode(self.raft_id, peer_ids,
                        wal_path=os.path.join(ch_dir, "wal.bin"),
                        snap_path=os.path.join(ch_dir, "snap.bin"),
                        entry_signer=make_entry_signer(self.signer))
        batch = channel_cfg.batch
        support = self.registrar.create_channel(
            cid, bundle_source.current().msps, self.provider,
            writers_policy=None,
            signer=self.signer,
            batch_config=BatchConfig(
                max_message_count=batch.max_message_count,
                absolute_max_bytes=batch.absolute_max_bytes,
                preferred_max_bytes=batch.preferred_max_bytes,
                batch_timeout_s=batch.timeout_s),
            ledger=BlockStore(os.path.join(ch_dir, "ledger")),
            chain_factory=lambda cutter, writer, on_block: RaftChain(
                node, cutter, writer, on_block=on_block,
                on_conf=lambda conf, _cid=cid: self._on_membership(
                    _cid, conf)),
            bundle_source=bundle_source)
        if self.verify_cache is not None:
            support.processor.verify_cache = self.verify_cache
            support.processor.trust_attestations = self._trust_attestations
            support.processor.attestors = \
                support.processor._normalize_attestors(self._attestors)
            support.processor.attestor_trust = self.attestor_trust
        self.cluster.add_chain(cid, support.chain,
                               consenters=ch_consenters, peers=ch_peers)
        if self.byzantine is not None:
            from fabric_tpu.byzantine import ByzantineMonitor, WitnessLog
            self.byz_monitors[cid] = ByzantineMonitor(
                cid,
                WitnessLog(os.path.join(ch_dir, "witness_log.json")),
                self.byzantine,
                ledger=_BlockStoreLedger(support.ledger),
                msps=bundle_source.current().msps, signer=self.signer,
                proof_dir=os.path.join(ch_dir, "fraud_proofs"),
                pardon_window_s=self.byz_pardon_window)
        return support

    def join_channel(self, channel_cfg: ChannelConfig):
        """Runtime channel join (channelparticipation Join): a NEW raft
        instance + ledger under this process's registrar."""
        src = BundleSource(Bundle(channel_cfg))
        return self._create_channel(channel_cfg, src)

    # -- dynamic raft membership (committed through the log itself) ----------

    def _on_membership(self, channel_id: str, conf: dict) -> None:
        """A membership config entry COMMITTED on this channel.  Runs on
        every replica (and re-runs on restart replay — conf entries do
        not advance the chain's applied index — so it must be
        idempotent): update the persisted membership set, then swap the
        transport's consenter identity + address maps and rebind the
        EntryVerifier in one atomic step.  From this instant a removed
        consenter's raft traffic and signed entries are rejected."""
        op = conf.get("op")
        nid = int(conf.get("node", 0))
        members = self._membership.setdefault(channel_id, {})
        if op == "add":
            entry = {"raft_id": nid,
                     "host": conf.get("host", "127.0.0.1"),
                     "port": int(conf.get("port", 0)),
                     "mspid": conf.get("mspid", ""),
                     "cert_fp": conf.get("cert_fp", "")}
            if members.get(nid) == entry:
                return                      # restart replay: already applied
            members[nid] = entry
        elif op == "remove":
            if nid not in members:
                return                      # restart replay: already applied
            members.pop(nid)
        else:
            logger.warning("[%s] unknown membership op %r ignored",
                           channel_id, op)
            return
        self._persist_membership(channel_id)
        _ids, consenters, peers = self._membership_maps(members)
        self.cluster.update_membership(channel_id, consenters, peers)
        logger.info("[%s] membership %s node %d -> consenters %s",
                    channel_id, op, nid, sorted(members))

    def _rpc_add_consenter(self, body: dict, peer_identity) -> dict:
        """Admin: propose an add-consenter config entry (leader only —
        callers retry against the leader hint on not_leader)."""
        self._require_admin(peer_identity)
        cid = body.get("channel", self.channel_id)
        support = self.registrar.get(cid)
        if support is None:
            raise ValueError(f"no such channel {cid!r}")
        for fld in ("raft_id", "port", "mspid", "cert_fp"):
            if not body.get(fld):
                raise ValueError(f"add_consenter requires {fld!r} — an "
                                 "unbound consenter could not be "
                                 "authenticated on the cluster plane")
        from fabric_tpu.orderer import raft as raftmod
        try:
            index = support.chain.propose_membership(
                "add", int(body["raft_id"]),
                host=body.get("host", "127.0.0.1"), port=int(body["port"]),
                mspid=body["mspid"], cert_fp=body["cert_fp"])
        except raftmod.NotLeaderError as exc:
            return {"status": "not_leader", "leader": exc.leader_id or 0}
        return {"status": "proposed", "channel": cid, "index": index}

    def _rpc_remove_consenter(self, body: dict, peer_identity) -> dict:
        """Admin: propose a remove-consenter config entry.  Removing the
        leader itself is legal — it self-evicts at commit and the rest
        of the cluster elects (callers wanting a gap-free handover
        transfer leadership first, as the drain path does)."""
        self._require_admin(peer_identity)
        cid = body.get("channel", self.channel_id)
        support = self.registrar.get(cid)
        if support is None:
            raise ValueError(f"no such channel {cid!r}")
        from fabric_tpu.orderer import raft as raftmod
        try:
            index = support.chain.propose_membership(
                "remove", int(body["raft_id"]))
        except raftmod.NotLeaderError as exc:
            return {"status": "not_leader", "leader": exc.leader_id or 0}
        return {"status": "proposed", "channel": cid, "index": index}

    def _rpc_transfer_leadership(self, body: dict, peer_identity) -> dict:
        self._require_admin(peer_identity)
        cid = body.get("channel", self.channel_id)
        support = self.registrar.get(cid)
        if support is None:
            raise ValueError(f"no such channel {cid!r}")
        sent = support.chain.transfer_leadership(int(body["to"]))
        return {"status": "sent" if sent else "refused",
                "leader": support.chain.node.leader_id or 0}

    def _rpc_drain(self, body: dict, peer_identity) -> dict:
        self._require_admin(peer_identity)
        return self.drain(timeout_s=float(body.get("timeout_s", 10.0)))

    # -- graceful drain ------------------------------------------------------

    def drain(self, timeout_s: float = 10.0) -> dict:
        """Orderly exit ramp: stop admitting broadcasts, hand raft
        leadership to the most caught-up follower, let every committed
        entry apply, then fsync the WALs.  After this returns the
        process can be stopped with nothing in flight — a rolling
        upgrade is drain -> stop -> restart -> rejoin-at-height instead
        of a crash-stop."""
        import time as _time
        from fabric_tpu.orderer import raft as raftmod
        self.lifecycle = "draining"
        deadline = _time.monotonic() + timeout_s
        leaders = {}
        for cid, support in self.registrar.channels().items():
            chain = support.chain
            node = chain.node
            # release leadership via explicit transfer: pick the most
            # caught-up follower; retry until deposed or out of time
            # (transfer_leadership nudges a lagging target's replication)
            while node.role == raftmod.LEADER \
                    and _time.monotonic() < deadline:
                with chain._lock:
                    targets = sorted(
                        (n for n in node.nodes if n != node.id),
                        key=lambda n: -node.match_index.get(n, 0))
                if not targets:
                    break               # single-node channel: nothing to do
                for to in targets:
                    if chain.transfer_leadership(to):
                        break
                _time.sleep(0.05)
            # finish in-flight blocks: everything raft committed must be
            # applied to the ledger before we call the WAL final
            while _time.monotonic() < deadline:
                with chain._lock:
                    if node.applied_index >= node.commit_index:
                        break
                _time.sleep(0.02)
            with chain._lock:
                node._wal.sync()
            leaders[cid] = node.leader_id or 0
        self.lifecycle = "drained"
        return {"lifecycle": self.lifecycle, "leaders": leaders}

    # -- rpc handlers --------------------------------------------------------

    def _require_admin(self, peer_identity) -> None:
        """Participation mutations are ADMIN operations: the caller's
        handshake-verified identity must hold the admin role in some org
        of the bootstrap channel (the reference gates this API behind
        client TLS auth; any-member access would let any org drop
        channels)."""
        from fabric_tpu.msp.msp import Principal
        msps = self.bundle_source.current().msps
        for mspid, msp in msps.items():
            try:
                ident = msp.deserialize_identity(peer_identity.serialize())
                if msp.satisfies_principal(ident, Principal.admin(mspid)):
                    return
            except Exception:
                continue
        raise PermissionError("channel participation requires an admin "
                              "identity")

    def _rpc_join(self, body: dict, peer_identity) -> dict:
        self._require_admin(peer_identity)
        cfg = ChannelConfig.deserialize(body["config"])
        if self.registrar.get(cfg.channel_id) is not None:
            raise ValueError(f"channel {cfg.channel_id!r} already exists")
        self.join_channel(cfg)
        return {"channel": cfg.channel_id, "status": "joined"}

    def _rpc_list(self, body: dict, peer_identity) -> dict:
        out = {}
        for cid, support in self.registrar.channels().items():
            out[cid] = {"height": support.ledger.height}
        return {"channels": out}

    def _rpc_remove(self, body: dict, peer_identity) -> dict:
        self._require_admin(peer_identity)
        cid = body["channel"]
        support = self.registrar.get(cid)
        if support is None:
            raise ValueError(f"no such channel {cid!r}")
        self.cluster.remove_chain(cid)
        self.byz_monitors.pop(cid, None)
        support.chain.halt()
        self.registrar.remove(cid)
        return {"channel": cid, "status": "removed"}

    def _rpc_broadcast(self, body: dict, peer_identity) -> dict:
        if self.lifecycle != "serving":
            # draining: refuse new work so clients fail over NOW; the
            # leader hint points them at whoever holds (or will hold)
            # leadership after our transfer
            return {"status": 503, "info": "draining",
                    "leader": self.support.chain.node.leader_id or 0}
        env = Envelope.deserialize(body["envelope"])
        resp = self.broadcast.handle(env)
        return {"status": resp.status, "info": resp.info or "",
                "leader": getattr(resp, "leader_hint", 0) or 0}

    def _rpc_broadcast_batch(self, body: dict, peer_identity) -> dict:
        """Gateway fan-in: many envelopes per RPC round trip.  Each is
        admitted independently; statuses/infos line up by index."""
        if self.lifecycle != "serving":
            n = len(body.get("envelopes", []))
            return {"statuses": [503] * n, "infos": ["draining"] * n,
                    "leader": self.support.chain.node.leader_id or 0}
        envs = [Envelope.deserialize(e) for e in body["envelopes"]]
        # verdict attestations carry no authority of their own: the
        # msgprocessor only honours them when the frame's handshake-
        # verified sender identity is in the channel's configured
        # attestor set, so the authenticated peer rides along as the
        # vouching party
        attests = body.get("attests") if peer_identity is not None else None
        resps = self.broadcast.handle_batch(envs, tps=body.get("tps"),
                                            attests=attests,
                                            attestor=peer_identity)
        leader = 0
        for r in resps:
            leader = getattr(r, "leader_hint", 0) or leader
        return {"statuses": [r.status for r in resps],
                "infos": [r.info or "" for r in resps],
                "leader": leader}

    def _rpc_deliver(self, body: dict, peer_identity):
        seek = SeekInfo(start=body.get("start", 0), stop=body.get("stop"),
                        behavior=body.get("behavior", "block_until_ready"))
        sd = None
        if body.get("signed_data"):
            s = body["signed_data"]
            sd = SignedData(s["data"], s["identity"], s["signature"])
        cid = body["channel"]
        attesting = (self._attest_deliver and self.verify_cache is not None)
        msps = None
        if attesting:
            support = self.registrar.get(cid)
            src = (getattr(support, "bundle_source", None)
                   or self.bundle_source) if support is not None \
                else self.bundle_source
            try:
                msps = src.current().msps
            except Exception:
                msps = None
        for block in self.deliver.deliver(cid, seek, sd,
                                          timeout_s=body.get("timeout_s", 30)):
            out = {"block": block.serialize()}
            if attesting and msps is not None:
                from fabric_tpu.verify_plane import attest_block
                try:
                    attests = attest_block(self.verify_cache, block, cid,
                                           msps)
                    if attests is not None:
                        out["attests"] = attests
                except Exception:
                    pass
            yield out

    def _rpc_status(self, body: dict, peer_identity) -> dict:
        from fabric_tpu.orderer import raft as raftmod
        node = self.support.chain.node
        return {"raft_id": self.raft_id, "role": node.role,
                "leader": node.leader_id or 0, "term": node.term,
                "height": self.support.ledger.height}

    # -- onboarding replication (cluster/replication.go) ---------------------

    def _replicate_once(self) -> int:
        """For every chain stuck behind a compacted raft log (snapshot
        install set catchup_target), pull the missing blocks from peer
        OSNs over their deliver stream, verify the orderer signatures,
        and hand them to the chain's catch_up — the reference's
        onboarding replication (orderer/common/cluster/replication.go).
        Returns how many blocks were replicated."""
        from fabric_tpu.comm.rpc import connect
        from fabric_tpu.orderer import block_signature_items
        from fabric_tpu.protocol.types import Block

        total = 0
        for cid, support in self.registrar.channels().items():
            target = getattr(support.chain, "catchup_target", None)
            if not target:
                continue
            # per-CHANNEL MSPs: a runtime-joined channel has its own
            # bundle (and its own config rotations)
            src = support.bundle_source or self.bundle_source
            msps = src.current().msps
            start = support.ledger.height
            stop = int(target.get("height", 0)) - 1
            if stop < start:
                continue
            payload = b"seek:%s" % cid.encode()
            sd = {"data": payload, "identity": self.signer.serialize(),
                  "signature": self.signer.sign(payload)}
            # pull from THIS channel's consenters (a runtime-joined
            # channel may have a different orderer set than bootstrap),
            # standing-aware: quarantined consenters sort last, so an
            # onboarding orderer prefers honest sources but can still
            # catch up from a convicted one as a last resort
            monitor = self.byz_monitors.get(cid)
            peer_map = self.cluster.peers_for(cid)
            def _standing(nid):
                key = self.cluster.consenter_binding(cid, nid)
                return 1 if (monitor is not None
                             and monitor.blocked_source(key)) else 0
            for nid in sorted(peer_map, key=lambda n: (_standing(n), n)):
                addr = peer_map[nid]
                src_key = self.cluster.consenter_binding(cid, nid)
                blocks = []
                try:
                    conn = connect(tuple(addr), self.signer, msps,
                                   timeout=3.0)
                    try:
                        for item in conn.call_stream("deliver", {
                                "channel": cid, "start": start,
                                "stop": stop, "timeout_s": 10,
                                "behavior": "fail_if_not_ready",
                                "signed_data": sd}):
                            block = Block.deserialize(item["block"])
                            items = block_signature_items(block, msps)
                            if not items or not bool(
                                    self.provider.batch_verify(items).all()):
                                raise ValueError(
                                    f"bad orderer signature on block "
                                    f"{block.header.number}")
                            if monitor is not None:
                                from fabric_tpu.byzantine.monitor import (
                                    VERDICT_ADMIT, VERDICT_STALE)
                                verdict = monitor.check_block(block, src_key)
                                if verdict == VERDICT_STALE:
                                    continue
                                if verdict != VERDICT_ADMIT:
                                    raise ValueError(
                                        f"block {block.header.number} "
                                        f"held/rejected by byzantine "
                                        f"monitor ({verdict})")
                            blocks.append(block)
                    finally:
                        conn.close()
                except Exception:
                    logger.debug("replication pull from OSN %s failed",
                                 nid, exc_info=True)
                    continue
                if blocks:
                    support.chain.catch_up(blocks)
                    total += len(blocks)
                    logger.info("[%s] onboarded %d blocks from OSN %s",
                                cid, len(blocks), nid)
                    break
        return total

    def _onboard_loop(self) -> None:
        while not self._stop_onboard.is_set():
            try:
                self._replicate_once()
            except Exception:
                logger.exception("onboarding replication failed")
            self._stop_onboard.wait(1.0)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "OrdererNode":
        self.rpc.start()
        self.cluster.start()
        self._stop_onboard = threading.Event()
        self._onboard_thread = threading.Thread(target=self._onboard_loop,
                                                daemon=True)
        self._onboard_thread.start()
        if self.ops is not None:
            self.ops.start()
        logger.info("orderer %d serving on %s", self.raft_id, self.rpc.addr)
        return self

    def stop(self) -> None:
        if getattr(self, "_stop_onboard", None) is not None:
            self._stop_onboard.set()
        self.cluster.stop()
        for support in self.registrar.channels().values():
            support.chain.halt()
        self.rpc.stop()
        if getattr(self, "slo", None) is not None:
            self.slo.stop()
        if getattr(self, "timeseries", None) is not None:
            self.timeseries.stop()
        if getattr(self, "resources", None) is not None:
            self.resources.stop()
        if getattr(self, "profiler", None) is not None:
            self.profiler.stop()
        if getattr(self, "incidents", None) is not None:
            self.incidents.stop()
        if self.ops is not None:
            self.ops.stop()


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m fabric_tpu.node.orderer <node.json>",
              file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.INFO)
    from fabric_tpu.config.localconfig import load_node_config
    cfg = load_node_config(argv[0], "orderer")
    node = OrdererNode(cfg, data_dir=cfg["data_dir"]).start()
    threading.Event().wait()   # serve until killed
    return 0


if __name__ == "__main__":
    sys.exit(main())
