"""Cross-node trace assembly: one Chrome trace spanning the cluster.

A transaction's spans are scattered: the gateway peer records the
request trace, the orderer records its `orderer.deliver` children, the
committing peers record block traces linked from the request's
`commit_wait` span.  Each node's `GET /traces/<id>` only exports what
its own flight recorder holds — this module fans out to every
configured ops endpoint, follows links TRANSITIVELY across nodes (node
A's spans can link a trace that only node B recorded), and merges the
results into one Perfetto-loadable export:

  * every node renders as its own process row (`pid` + process_name
    metadata), its threads as lanes under it;
  * span timestamps are already wall-anchored microseconds
    (`tracing._WALL_ANCHOR`), so cross-process ordering is as honest
    as the hosts' clocks — fine on one box, NTP-bounded across boxes;
  * the closure is bounded by `max_traces`, and like export_chrome the
    cut is never silent (`truncated: true` + the same counter).

Wired as `GET /traces/<id>?cluster=1` on peers and orderers via
`tracing.register_routes(..., cluster_fn=...)`; the peer list comes
from the node's `cluster_trace` config sub-dict
(`{"peers": ["127.0.0.1:9443", ...]}`) and may include the node's own
endpoint (self-fetches are served locally, not over HTTP).
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("fabric_tpu.node.tracecollect")

__all__ = ["collect_cluster_trace", "fetch_export"]

# per-node tid namespace: node i's thread k renders as i*_TID_STRIDE+k
_TID_STRIDE = 1000


def fetch_export(endpoint: str, trace_id: str,
                 timeout_s: float = 2.0) -> Optional[dict]:
    """One node's single-trace export (`follow=0` — the cluster walk
    follows links itself); None on any transport/HTTP failure (a dead
    peer must not sink the whole assembly)."""
    url = f"http://{endpoint}/traces/{trace_id}?follow=0"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read())
    except Exception:
        return None


def collect_cluster_trace(trace_id: str, endpoints: Sequence[str],
                          local_tracer=None, local_name: str = "local",
                          timeout_s: float = 2.0,
                          max_traces: int = 16) -> Optional[dict]:
    """Fan out, merge, follow links transitively; one Chrome export.

    `endpoints` are "host:port" ops addresses (peers AND orderers);
    `local_tracer` serves this node's own spans in-process so the list
    may freely include — or omit — the node itself.  Returns None only
    when NO node knows the root trace id.
    """
    from fabric_tpu.ops_plane.metrics import registry as _metrics_registry

    nodes: List[Tuple[str, object]] = []
    if local_tracer is not None:
        nodes.append((local_name,
                      lambda tid: local_tracer.export_chrome(
                          tid, follow_links=False)))
    for ep in endpoints:
        ep = str(ep)
        nodes.append((ep, lambda tid, _ep=ep: fetch_export(
            _ep, tid, timeout_s=timeout_s)))

    events: List[dict] = []
    seen_spans: set = set()
    node_spans: Dict[str, int] = {}
    pids: Dict[str, int] = {}
    fetched: set = set()
    pending: List[str] = [str(trace_id)]
    found_traces: set = set()
    truncated = False

    while pending:
        if len(fetched) >= max_traces:
            truncated = True
            break
        tid = pending.pop(0)
        fetched.add(tid)
        for name, fetch in nodes:
            exp = fetch(tid)
            if not exp:
                continue
            pid = pids.setdefault(name, len(pids) + 1)
            for ev in exp.get("traceEvents", ()):
                args = ev.get("args") or {}
                if ev.get("ph") == "M":
                    continue        # per-node thread names re-emitted below
                key = (name, args.get("trace_id"), args.get("span_id"))
                if args.get("span_id") is not None and key in seen_spans:
                    continue
                seen_spans.add(key)
                found_traces.add(args.get("trace_id") or tid)
                merged = dict(ev)
                merged["pid"] = pid
                merged["tid"] = (pid * _TID_STRIDE
                                 + int(ev.get("tid", 0)))
                merged.setdefault("args", {})
                merged["args"] = dict(args, node=name)
                events.append(merged)
                node_spans[name] = node_spans.get(name, 0) + 1
                for linked in args.get("links", ()) or ():
                    if linked not in fetched and linked not in pending:
                        pending.append(linked)
            # thread lanes, namespaced per node
            for ev in exp.get("traceEvents", ()):
                if ev.get("ph") == "M" and ev.get("name") == "thread_name":
                    events.append({
                        "name": "thread_name", "ph": "M", "pid": pid,
                        "tid": pid * _TID_STRIDE + int(ev.get("tid", 0)),
                        "args": dict(ev.get("args") or {})})
    if pending:
        truncated = True
    if truncated:
        _metrics_registry.counter(
            "tracing_export_links_truncated_total",
            "export_chrome link closures cut at max_traces").add()

    if not node_spans:
        return None
    # one process row per node; dedupe the metadata events
    meta_seen: set = set()
    deduped: List[dict] = []
    for ev in events:
        if ev.get("ph") == "M":
            key = (ev["pid"], ev.get("tid"), ev["name"],
                   tuple(sorted((ev.get("args") or {}).items())))
            if key in meta_seen:
                continue
            meta_seen.add(key)
        deduped.append(ev)
    for name, pid in pids.items():
        if name in node_spans:
            deduped.append({"name": "process_name", "ph": "M",
                            "pid": pid, "args": {"name": name}})
    deduped.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {
        "traceEvents": deduped,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": str(trace_id),
            "cluster": True,
            "nodes": node_spans,
            "n_nodes": len(node_spans),
            "n_traces_merged": len(found_traces),
            "truncated": truncated,
        },
    }
