"""Runnable node processes (server binaries) for the framework.

The reference ships peer/orderer binaries (/root/reference/cmd/); here
each node is `python -m fabric_tpu.node.<kind> <config.json>` composed
from the same library planes, with fabric_tpu.node.provision as the
cryptogen/configtxgen equivalent.
"""

from .provision import provision_orderers

__all__ = ["provision_orderers"]
