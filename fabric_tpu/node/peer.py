"""Runnable peer node: endorser + deliver client + validator/committer.

The reference's peer binary (the larger of its two server processes:
/root/reference/cmd/peer/main.go:29, internal/peer/node/start.go:110-860,
channel wiring core/peer/peer.go:207) composed for this framework: a JSON
node config + MSP material on disk produce ONE process that

  - serves the Endorser (`endorse`), qscc/cscc (`qscc.*`, `cscc.*`),
    discovery (`discovery.endorsers`), and the private-data pull/push
    plane (`privdata.fetch` / `privdata.push`) over the authenticated RPC
    plane (fabric_tpu/comm),
  - runs the deliver client against the orderer cluster with failover
    (internal/pkg/peer/blocksprovider semantics: seek from height, batch-
    verify orderer signatures, commit in order),
  - validates + commits through the verify-then-gate TxValidator and the
    privdata Coordinator (missing collections recorded and reconciled on
    a timer, gossip/privdata/reconcile.go),
  - exposes the ops plane (/healthz /metrics /logspec).

Run:  python -m fabric_tpu.node.peer <node.json>
Provision a dev network: fabric_tpu.node.provision.provision_network().
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
from fabric_tpu.chaincode import (
    ChaincodeDefinition,
    ChaincodeRegistry,
    LifecyclePolicyProvider,
    SimulationError,
)
from fabric_tpu.chaincode.runtime import FuncContract
from fabric_tpu.comm.rpc import RpcServer, connect
from fabric_tpu.committer import Committer, TxValidator
from fabric_tpu.committer.sbe import statedb_lookup
from fabric_tpu.config import Bundle, BundleSource, ChannelConfig
from fabric_tpu.endorser import Endorser
from fabric_tpu.endorser.proposal import SignedProposal
from fabric_tpu.ledger import KVLedger, LedgerConfig
from fabric_tpu.node.orderer import load_signing_identity
from fabric_tpu.orderer import block_signature_items
from fabric_tpu.policy import SignedData, parse_policy
from fabric_tpu.privdata import (
    CollectionConfig,
    CollectionRegistry,
    Coordinator,
    PvtDataStore,
    TransientStore,
)
from fabric_tpu.protocol.types import Block
from fabric_tpu.scc.cscc import Cscc
from fabric_tpu.scc.discovery import DiscoveryService
from fabric_tpu.scc.qscc import Qscc

logger = logging.getLogger("fabric_tpu.node.peer")


# -- built-in dev contracts (in-process dev mode; external chaincode is the
#    production path, fabric_tpu/chaincode/extcc.py) -------------------------

def _asset_contract():
    def create(stub, key, value):
        if stub.get_state(key.decode()) is not None:
            raise SimulationError("asset exists")
        stub.put_state(key.decode(), value)
        return b"created"

    def transfer(stub, key, owner):
        v = stub.get_state(key.decode())
        if v is None:
            raise SimulationError("no such asset")
        stub.put_state(key.decode(), owner)
        return b"transferred"

    def put_private(stub, collection, key, value):
        stub.put_state(key.decode() + ".marker", b"1")
        stub.put_private_data(collection.decode(), key.decode(), value)
        return b"ok"

    return FuncContract(create=create, transfer=transfer,
                        put_private=put_private)


DEV_CONTRACTS = {"asset_demo": _asset_contract}


class RemoteDeliver:
    """Deliver-handler facade over the orderer cluster's RPC deliver
    stream, with per-call failover across orderer endpoints."""

    def __init__(self, orderers: List[Tuple[str, int]], signer, msps):
        self.orderers = list(orderers)
        self.signer = signer
        self.msps = msps
        self._rr = 0

    def deliver(self, channel_id, seek, signed=None, timeout_s: int = 10):
        last = None
        payload = b"seek:%s" % channel_id.encode()
        sd = {"data": payload, "identity": self.signer.serialize(),
              "signature": self.signer.sign(payload)}
        for k in range(len(self.orderers)):
            addr = self.orderers[(self._rr + k) % len(self.orderers)]
            try:
                conn = connect(tuple(addr), self.signer, self.msps,
                               timeout=3.0)
                try:
                    for item in conn.call_stream("deliver", {
                            "channel": channel_id, "start": seek.start,
                            "stop": seek.stop, "behavior": seek.behavior,
                            "timeout_s": int(timeout_s),
                            "signed_data": sd}):
                        yield Block.deserialize(item["block"])
                    self._rr = (self._rr + k) % len(self.orderers)
                    return
                finally:
                    conn.close()
            except Exception as exc:
                last = exc
        if last is not None:
            raise last


class PeerNode:
    """One peer process (library form; `main` wraps it)."""

    def __init__(self, cfg: dict, data_dir: str):
        self.cfg = cfg
        self.channel_id = cfg.get("channel_id", "ch")
        self.provider = init_factories(
            FactoryOpts(default=cfg.get("bccsp", "SW")))
        self.signer = load_signing_identity(
            cfg["mspid"], cfg["cert_pem"].encode(), cfg["key_pem"].encode())
        self.mspid = cfg["mspid"]

        channel_cfg = ChannelConfig.deserialize(
            bytes.fromhex(cfg["channel_config_hex"]))
        # config_height: the block number the bootstrap config was taken
        # at (0 = genesis).  A peer bootstrapped at a later config MUST
        # carry it so catch-up replay of older config blocks is
        # recognized instead of being flagged INVALID (committer.py).
        self.bundle_source = BundleSource(
            Bundle(channel_cfg),
            config_height=int(cfg.get("config_height", 0)))
        self.msps = self.bundle_source.current().msps

        self.ledger = KVLedger(self.channel_id,
                               LedgerConfig(root=f"{data_dir}/ledger"))

        # chaincode runtime (dev mode: in-process contracts; external
        # chaincode processes are handled by chaincode/extcc.py)
        self.cc_registry = ChaincodeRegistry()
        self.policies = LifecyclePolicyProvider(self.ledger.statedb)
        self._cc_policies: Dict[str, object] = {}
        for cc in cfg.get("chaincodes", []):
            contract = self._make_contract(cc)
            self.cc_registry.install(
                ChaincodeDefinition(cc["name"], cc.get("version", "1.0")),
                contract)
            if cc.get("policy"):
                pol = parse_policy(cc["policy"])
                self.policies.set_policy(cc["name"], pol)
                self._cc_policies[cc["name"]] = pol

        self.validator = TxValidator(
            self.channel_id, None, self.provider, self.policies,
            bundle_source=self.bundle_source,
            sbe_lookup=statedb_lookup(self.ledger.statedb))
        self.committer = Committer(self.ledger, self.validator,
                                   bundle_source=self.bundle_source,
                                   provider=self.provider)

        # private data plane
        self.collections = CollectionRegistry()
        for col in cfg.get("collections", []):
            self.collections.define(col["ns"], CollectionConfig(
                col["name"], member_orgs=tuple(col["members"]),
                block_to_live=int(col.get("btl", 0))))
        self.transient = TransientStore()
        self.pvt_store = PvtDataStore()
        self.coordinator = Coordinator(
            self.committer, self.collections, self.transient,
            self.pvt_store, mspid=self.mspid,
            fetch=self._privdata_fetch_remote)

        self.endorser = Endorser(
            self.channel_id, self.ledger.statedb, self.cc_registry,
            self.msps, self.provider, self.signer,
            transient_store=self.transient, pvt_store=self.pvt_store,
            distribute=self._privdata_distribute,
            ledger_height=lambda: self.ledger.height)

        # system chaincodes + discovery
        self.qscc = Qscc(self.channel_id, self.ledger.blockstore)
        self.cscc = Cscc()
        self.cscc.register(self.channel_id, self)
        self.peers = [tuple(p) for p in cfg.get("peers", [])]
        self.peer_orgs = {tuple(p[:2]): p[2] if len(p) > 2 else None
                          for p in cfg.get("peers", [])}
        self.discovery = DiscoveryService(
            membership=self._membership,
            policy_for=self.policies.policy_for)

        self.orderers = [tuple(o) for o in cfg.get("orderers", [])]
        self.deliver_client = RemoteDeliver(self.orderers, self.signer,
                                            self.msps)

        # RPC surface
        self.rpc = RpcServer(cfg.get("host", "127.0.0.1"), int(cfg["port"]),
                             self.signer, self.msps)

        # gossip plane on the authenticated transport: membership,
        # epidemic block dissemination + ordered drain into the
        # coordinator, certstore pull, leader election
        from fabric_tpu.gossip.comm import SecureGossipTransport
        from fabric_tpu.gossip.mcs import MessageCryptoService
        from fabric_tpu.gossip.node import GossipNode

        self.mcs = MessageCryptoService(self.msps, self.provider)
        transport = SecureGossipTransport(self.rpc, self.signer, self.msps)

        def register(peer_id, handler):
            transport.start(handler)
            return transport

        bootstrap = [f"{p[0]}:{p[1]}" for p in self.peers]
        self.gossip = GossipNode(register, transport.id, self.coordinator,
                                 mcs=self.mcs, signer=self.signer,
                                 bootstrap=bootstrap, msps=self.msps)
        self.rpc.serve("endorse", self._rpc_endorse)
        self.rpc.serve("status", self._rpc_status)
        self.rpc.serve("qscc.chain_info", self._rpc_chain_info)
        self.rpc.serve("qscc.block_by_number", self._rpc_block_by_number)
        self.rpc.serve("qscc.tx_by_id", self._rpc_tx_by_id)
        self.rpc.serve("cscc.channels", lambda b, p:
                       {"channels": self.cscc.get_channels()})
        self.rpc.serve("discovery.endorsers", self._rpc_discovery)
        self.rpc.serve("privdata.fetch", self._rpc_privdata_fetch)
        self.rpc.serve_cast("privdata.push", self._rpc_privdata_push)

        self.ops = None
        if cfg.get("ops_port") is not None:
            from fabric_tpu.ops_plane import OperationsServer
            self.ops = OperationsServer(cfg.get("host", "127.0.0.1"),
                                        int(cfg["ops_port"]))
            self.ops.register_checker(
                "deliver", lambda: self._deliver_healthy)

        self._stop = threading.Event()
        self._deliver_healthy = True
        self._deliver_thread = threading.Thread(target=self._deliver_loop,
                                                daemon=True)

    # -- wiring helpers ------------------------------------------------------

    def _make_contract(self, cc_cfg: dict):
        kind = cc_cfg.get("contract", "asset_demo")
        if kind in DEV_CONTRACTS:
            return DEV_CONTRACTS[kind]()
        if kind.startswith("extern:"):
            # production mode: the contract runs as its own OS process
            # speaking the Register/Invoke stream FSM (chaincode/extcc.py)
            import shlex
            from fabric_tpu.chaincode.extcc import (
                ChaincodeSupport,
                ExtProcessContract,
            )
            if getattr(self, "cc_support", None) is None:
                self.cc_support = ChaincodeSupport(
                    f"{self.cfg['data_dir']}/cc")
            return ExtProcessContract(self.cc_support, cc_cfg["name"],
                                      shlex.split(kind[len("extern:"):]))
        raise ValueError(f"unknown contract {kind!r}")

    def _membership(self):
        """discovery membership: this peer + its configured neighbors
        (live gossip membership in the reference)."""
        me = f"{self.cfg.get('host', '127.0.0.1')}:{self.cfg['port']}"
        out = [{"id": me, "mspid": self.mspid, "roles": ["peer"]}]
        for p in self.cfg.get("peers", []):
            if len(p) > 2:
                out.append({"id": f"{p[0]}:{p[1]}", "mspid": p[2],
                            "roles": ["peer"]})
        return out

    # -- rpc handlers --------------------------------------------------------

    def _rpc_endorse(self, body: dict, peer_identity) -> dict:
        sp = SignedProposal(body["proposal"], body["signature"])
        resp = self.endorser.process_proposal(sp)
        out = {"status": resp.status, "message": resp.message,
               "payload": resp.payload}
        if resp.endorsement is not None:
            out["endorser"] = resp.endorsement.endorser
            out["endorsement_sig"] = resp.endorsement.signature
        return out

    def _rpc_status(self, body: dict, peer_identity) -> dict:
        return {"mspid": self.mspid, "channel": self.channel_id,
                "height": self.ledger.height,
                "commit_hash": (self.ledger.commit_hash or b"").hex()}

    def _rpc_chain_info(self, body: dict, peer_identity) -> dict:
        return self.qscc.get_chain_info()

    def _rpc_block_by_number(self, body: dict, peer_identity) -> dict:
        blk = self.qscc.get_block_by_number(int(body["number"]))
        return {"block": blk.serialize()}

    def _rpc_tx_by_id(self, body: dict, peer_identity) -> dict:
        env = self.qscc.get_transaction_by_id(body["txid"])
        return {"envelope": env.serialize()}

    def _rpc_discovery(self, body: dict, peer_identity) -> dict:
        out = self.discovery.endorsers(body["namespace"])
        out["layouts"] = [l.as_dict() for l in out["layouts"]]
        return out

    def _rpc_privdata_fetch(self, body: dict, peer_identity) -> dict:
        """Collection pull: ONLY collection-member orgs may read cleartext
        (gossip/privdata/pvtdataprovider.go membership check)."""
        ns, coll = body["namespace"], body["collection"]
        cfg = self.collections.get(ns, coll)
        if cfg is None or not cfg.is_member(
                getattr(peer_identity, "mspid", None)):
            return {"found": False, "denied": True}
        data = self.pvt_store.get_tx_set(ns, coll, body["txid"])
        if data is None:
            # also try the transient store (pre-commit staging)
            for sets in self.transient.get(body["txid"]):
                if (ns, coll) in sets:
                    data = sets[(ns, coll)]
                    break
        if data is None:
            return {"found": False}
        return {"found": True,
                "keys": list(data.keys()),
                "values": [v if v is not None else b"" for v in
                           data.values()],
                "deleted": [v is None for v in data.values()]}

    def _rpc_privdata_push(self, body: dict, peer_identity) -> None:
        """Endorsement-time distribution: a member peer pushes cleartext
        into our transient store (gossip/privdata/distributor.go)."""
        sets = {}
        for rec in body["sets"]:
            ns, coll = rec["namespace"], rec["collection"]
            cfg = self.collections.get(ns, coll)
            if cfg is None or not cfg.is_member(self.mspid):
                continue      # we are not a member: refuse cleartext
            sets[(ns, coll)] = {k: (None if d else v) for k, v, d in
                                zip(rec["keys"], rec["values"],
                                    rec["deleted"])}
        if sets:
            self.transient.persist(body["txid"], int(body["height"]), sets)

    # -- privdata client side ------------------------------------------------

    def _privdata_distribute(self, txid: str, pvt_sets: dict) -> None:
        """Push endorsement-time cleartext to collection member peers."""
        recs = []
        for (ns, coll), kv in pvt_sets.items():
            recs.append({"namespace": ns, "collection": coll,
                         "keys": list(kv.keys()),
                         "values": [v if v is not None else b""
                                    for v in kv.values()],
                         "deleted": [v is None for v in kv.values()]})
        if not recs:
            return
        body = {"txid": txid, "height": self.ledger.height, "sets": recs}
        for addr in self.peers:
            try:
                conn = connect(tuple(addr[:2]), self.signer, self.msps,
                               timeout=2.0)
                try:
                    conn.cast("privdata.push", body)
                finally:
                    conn.close()
            except Exception:
                logger.debug("privdata push to %s failed", addr,
                             exc_info=True)

    def _privdata_fetch_remote(self, txid: str, ns: str,
                               coll: str) -> Optional[dict]:
        """Reconciliation pull from member peers (reconcile.go)."""
        for addr in self.peers:
            try:
                conn = connect(tuple(addr[:2]), self.signer, self.msps,
                               timeout=2.0)
                try:
                    out = conn.call("privdata.fetch", {
                        "txid": txid, "namespace": ns, "collection": coll},
                        timeout=5.0)
                finally:
                    conn.close()
            except Exception:
                continue
            if out.get("found"):
                return {k: (None if d else v) for k, v, d in
                        zip(out["keys"], out["values"], out["deleted"])}
        return None

    # -- deliver / commit loop ----------------------------------------------

    def _deliver_loop(self) -> None:
        from fabric_tpu.orderer.deliver import SeekInfo
        backoff = 0.2
        reconcile_at = time.monotonic() + 5.0
        while not self._stop.is_set():
            height = self.ledger.height
            try:
                got = 0
                for block in self.deliver_client.deliver(
                        self.channel_id,
                        SeekInfo(start=height, stop=height + 31,
                                 behavior="block_until_ready"),
                        timeout_s=5):
                    items = block_signature_items(block, self.msps)
                    if not items or not bool(
                            self.provider.batch_verify(items).all()):
                        logger.warning("block %d failed orderer-signature "
                                       "verification; dropping window",
                                       block.header.number)
                        break
                    # through the gossip state plane: fans out to peers
                    # and drains strictly in block order
                    self.gossip.state.add_block(block)
                    got += 1
                self._deliver_healthy = True
                backoff = 0.2
                if not got:
                    time.sleep(0.1)
            except Exception:
                self._deliver_healthy = False
                logger.debug("deliver pull failed; retrying", exc_info=True)
                time.sleep(backoff)
                backoff = min(backoff * 2, 3.0)
            try:
                self.gossip.tick()
            except Exception:
                logger.exception("gossip tick failed")
            if time.monotonic() >= reconcile_at:
                try:
                    n = self.coordinator.reconcile()
                    if n:
                        logger.info("reconciled %d private collections", n)
                except Exception:
                    logger.exception("privdata reconcile failed")
                reconcile_at = time.monotonic() + 5.0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PeerNode":
        self.rpc.start()
        if self.ops is not None:
            self.ops.start()
        self._deliver_thread.start()
        logger.info("peer %s serving on %s", self.mspid, self.rpc.addr)
        return self

    def stop(self) -> None:
        self._stop.set()
        self.rpc.stop()
        if getattr(self, "cc_support", None) is not None:
            self.cc_support.stop()      # kills external chaincode processes
        if self.ops is not None:
            self.ops.stop()


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m fabric_tpu.node.peer <node.json>",
              file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.INFO)
    with open(argv[0]) as f:
        cfg = json.load(f)
    PeerNode(cfg, data_dir=cfg["data_dir"]).start()
    threading.Event().wait()   # serve until killed
    return 0


if __name__ == "__main__":
    sys.exit(main())
