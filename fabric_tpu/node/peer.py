"""Runnable peer node: endorser + deliver client + validator/committer.

The reference's peer binary (the larger of its two server processes:
/root/reference/cmd/peer/main.go:29, internal/peer/node/start.go:110-860,
channel wiring core/peer/peer.go:207) composed for this framework: a JSON
node config + MSP material on disk produce ONE process that

  - serves the Endorser (`endorse`), qscc/cscc (`qscc.*`, `cscc.*`),
    discovery (`discovery.endorsers`), and the private-data pull/push
    plane (`privdata.fetch` / `privdata.push`) over the authenticated RPC
    plane (fabric_tpu/comm),
  - runs the deliver client against the orderer cluster with failover
    (internal/pkg/peer/blocksprovider semantics: seek from height, batch-
    verify orderer signatures, commit in order),
  - validates + commits through the verify-then-gate TxValidator and the
    privdata Coordinator (missing collections recorded and reconciled on
    a timer, gossip/privdata/reconcile.go),
  - exposes the ops plane (/healthz /metrics /logspec).

Run:  python -m fabric_tpu.node.peer <node.json>
Provision a dev network: fabric_tpu.node.provision.provision_network().
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
from fabric_tpu.chaincode import (
    ChaincodeDefinition,
    ChaincodeRegistry,
    LifecyclePolicyProvider,
    SimulationError,
)
from fabric_tpu.chaincode.runtime import FuncContract
from fabric_tpu.comm.rpc import RpcServer, connect
from fabric_tpu.committer import Committer, TxValidator
from fabric_tpu.committer.sbe import statedb_lookup
from fabric_tpu.config import Bundle, BundleSource, ChannelConfig
from fabric_tpu.endorser import Endorser
from fabric_tpu.endorser.proposal import SignedProposal
from fabric_tpu.ledger import KVLedger, LedgerConfig
from fabric_tpu.node.orderer import load_signing_identity
from fabric_tpu.orderer import block_signature_items
from fabric_tpu.policy import SignedData, parse_policy
from fabric_tpu.privdata import (
    CollectionConfig,
    CollectionRegistry,
    Coordinator,
    PvtDataStore,
    TransientStore,
)
from fabric_tpu.protocol import wire
from fabric_tpu.protocol.types import Block
from fabric_tpu.scc.cscc import Cscc
from fabric_tpu.scc.discovery import DiscoveryService
from fabric_tpu.scc.qscc import Qscc

logger = logging.getLogger("fabric_tpu.node.peer")


# -- built-in dev contracts (in-process dev mode; external chaincode is the
#    production path, fabric_tpu/chaincode/extcc.py) -------------------------

def _asset_contract():
    def create(stub, key, value):
        if stub.get_state(key.decode()) is not None:
            raise SimulationError("asset exists")
        stub.put_state(key.decode(), value)
        return b"created"

    def read(stub, key):
        v = stub.get_state(key.decode())
        if v is None:
            raise SimulationError("no such asset")
        return v

    def transfer(stub, key, owner):
        v = stub.get_state(key.decode())
        if v is None:
            raise SimulationError("no such asset")
        stub.put_state(key.decode(), owner)
        return b"transferred"

    def put_private(stub, collection, key, value):
        stub.put_state(key.decode() + ".marker", b"1")
        stub.put_private_data(collection.decode(), key.decode(), value)
        return b"ok"

    def bump(stub, key):
        # read-modify-write upsert: records a read (version None when
        # absent) so two concurrent bumps of one key MVCC-conflict —
        # the workload plane's conflict dial rides on this
        cur = stub.get_state(key.decode())
        n = int(cur or b"0") + 1
        stub.put_state(key.decode(), str(n).encode())
        return str(n).encode()

    def scan(stub, start, end):
        # range read: stages a RangeQueryInfo, so a committed write
        # landing inside [start, end) invalidates this tx (phantoms)
        items = stub.get_state_by_range(start.decode(), end.decode())
        return str(len(items)).encode()

    return FuncContract(create=create, read=read, transfer=transfer,
                        put_private=put_private, bump=bump, scan=scan)


DEV_CONTRACTS = {"asset_demo": _asset_contract}


class RemoteDeliver:
    """Deliver-handler facade over the orderer cluster's RPC deliver
    stream, with per-call failover across orderer endpoints."""

    def __init__(self, orderers: List[Tuple[str, int]], signer, msps):
        self.orderers = list(orderers)
        self.signer = signer
        self.msps = msps
        self._rr = 0
        # optional containment hook: callable(sender_identity) -> bool;
        # a True verdict skips the endpoint (quarantined orderer)
        self.blocked = None

    def advance(self) -> None:
        """Rotate away from the current endpoint — called when the
        byzantine monitor convicts the stream's orderer so the next
        pull re-sources from a different consenter."""
        if self.orderers:
            self._rr = (self._rr + 1) % len(self.orderers)

    def deliver(self, channel_id, seek, signed=None, timeout_s: int = 10):
        """Yields (block, attests, sender) — `attests` is the orderer's
        optional per-envelope verdict-attestation list (verify_plane/
        attest.py) and `sender` the handshake-verified identity of the
        orderer connection it rode in on; both None when the orderer
        sends plain blocks.

        Standing-aware source selection is two-pass: quarantined
        endpoints are SKIPPED while any healthy endpoint remains
        (deferred, not refused), and retried as a last resort only once
        every healthy endpoint has failed — a convicted orderer degrades
        availability before it partitions the peer, and every block it
        serves is still re-judged by the byzantine monitor."""
        last = None
        payload = b"seek:%s" % channel_id.encode()
        sd = {"data": payload, "identity": self.signer.serialize(),
              "signature": self.signer.sign(payload)}
        deferred: List[int] = []
        for k in range(len(self.orderers)):
            idx = (self._rr + k) % len(self.orderers)
            addr = self.orderers[idx]
            try:
                # stream_views: block bytes arrive as memoryviews into
                # the received frame and go straight to the native span
                # parser — no frame->block copy, no per-tx objects
                conn = connect(tuple(addr), self.signer, self.msps,
                               timeout=3.0, stream_views=True)
                try:
                    sender = getattr(conn.channel, "peer_identity", None)
                    if self.blocked is not None and self.blocked(sender):
                        deferred.append(idx)
                        last = RuntimeError(
                            "orderer endpoint %s:%s is quarantined"
                            % tuple(addr[:2]))
                        continue
                    for item in conn.call_stream("deliver", {
                            "channel": channel_id, "start": seek.start,
                            "stop": seek.stop, "behavior": seek.behavior,
                            "timeout_s": int(timeout_s),
                            "signed_data": sd}):
                        yield (wire.parse_block(item["block"]),
                               item.get("attests"), sender)
                    self._rr = idx
                    return
                finally:
                    conn.close()
            except Exception as exc:
                last = exc
        for idx in deferred:
            addr = self.orderers[idx]
            try:
                conn = connect(tuple(addr), self.signer, self.msps,
                               timeout=3.0, stream_views=True)
                try:
                    sender = getattr(conn.channel, "peer_identity", None)
                    logger.warning(
                        "deliver: every healthy orderer failed; last-"
                        "resort pull from quarantined %s:%s",
                        *tuple(addr[:2]))
                    for item in conn.call_stream("deliver", {
                            "channel": channel_id, "start": seek.start,
                            "stop": seek.stop, "behavior": seek.behavior,
                            "timeout_s": int(timeout_s),
                            "signed_data": sd}):
                        yield (wire.parse_block(item["block"]),
                               item.get("attests"), sender)
                    # _rr stays put: the next pull tries healthy
                    # endpoints first again
                    return
                finally:
                    conn.close()
            except Exception as exc:
                last = exc
        if last is not None:
            raise last


def _app_org_ids(channel_cfg) -> List[str]:
    """The channel's APPLICATION org mspids: every config org that is
    not a consenter org (the reference scopes lifecycle endorsement /
    approvals to Application orgs, channelconfig/application.go)."""
    cons = {c.get("mspid")
            for c in (getattr(channel_cfg, "consenters", ()) or ())
            if isinstance(c, dict)}     # bare raft-id consenters: no org
    orgs = sorted(o.mspid for o in channel_cfg.orgs)
    app = [o for o in orgs if o not in cons]
    return app or orgs


class _LiveHandshakeMsps:
    """Mapping view of the peer's handshake MSPs, resolved through the
    live channel bundles on every access (union across joined channels,
    bootstrap bundle as the floor).  The transport layer authenticates
    against this instead of a one-time snapshot — see PeerNode wiring.
    """

    def __init__(self, node: "PeerNode", boot: dict):
        self._node = node
        self._boot = dict(boot)

    def _snap(self) -> dict:
        out = dict(self._boot)
        for ch in list(getattr(self._node, "channels", {}).values()):
            try:
                out.update(ch.bundle_source.current().msps)
            except Exception:       # a torn channel must not kill auth
                pass
        return out

    def get(self, key, default=None):
        return self._snap().get(key, default)

    def __getitem__(self, key):
        return self._snap()[key]

    def __contains__(self, key):
        return key in self._snap()

    def __iter__(self):
        return iter(self._snap())

    def __len__(self):
        return len(self._snap())

    def items(self):
        return self._snap().items()

    def values(self):
        return self._snap().values()

    def keys(self):
        return self._snap().keys()


class PeerChannel:
    """One channel's kernel inside a peer process: ledger + validator +
    committer + endorser + query/privdata/gossip planes + deliver loop.

    The slot of the reference's per-channel wiring in
    core/peer/peer.go:207-371 CreateChannel — the peer binary hosts N
    of these with independent ledgers, validators, and config bundles.
    """

    def __init__(self, node: "PeerNode", channel_cfg: ChannelConfig,
                 ch_dir: str, config_height: int = 0):
        self.node = node
        self.channel_id = channel_cfg.channel_id
        # Config persistence (core/ledger/confighistory/mgr.go role):
        # every applied config records (block_num, config) here, so a
        # restart resumes from the LATEST applied config — not the
        # join/bootstrap-time one — and config_height survives.  Without
        # this, runtime config updates were silently lost on restart and
        # catch-up replay of historical config blocks got flagged
        # INVALID, diverging from tip peers.
        from fabric_tpu.ledger.confighistory import ConfigHistory
        self.confighistory = ConfigHistory(root=ch_dir)
        entries = self.confighistory.entries()
        if entries:
            h, cfg_bytes = entries[-1]
            try:
                restored = ChannelConfig.deserialize(cfg_bytes)
                if restored.sequence > channel_cfg.sequence:
                    channel_cfg = restored
                config_height = max(config_height, h)
            except Exception:
                logger.exception("[%s] could not restore latest config",
                                 self.channel_id)
        elif config_height > 0 or channel_cfg.sequence > 0:
            # seed the history with the join/bootstrap config so the
            # committer's replay-covered check works after restart
            self.confighistory.record(config_height,
                                      channel_cfg.serialize())
        self.bundle_source = BundleSource(Bundle(channel_cfg),
                                          config_height=config_height)
        self.msps = self.bundle_source.current().msps
        # parallel MVCC commit plane (committer/parallel_commit).  The
        # early_abort sub-knob defaults to the plane's enabled state;
        # NOTE it must be uniform across a channel's peers — a doomed
        # tx's flag byte is MVCC_READ_CONFLICT even where the skipped
        # signature gate would have said otherwise, and flags feed the
        # commit hash (see parallel_commit/earlyabort.py).
        pc_cfg = dict(node.cfg.get("parallel_commit", {}))
        # fused device validation (committer/device_validate.py): gate
        # fold + MVCC as one XLA dispatch per block, prepared batch
        # consumed by the ledger.  Same uniformity note as early_abort
        # (demotions fall back bit-identically, so only timing differs,
        # but keep it uniform as an operational convention).
        dv_cfg = dict(node.cfg.get("device_validate", {}))
        dv_on = bool(dv_cfg.get("enabled", False))
        # sharded state plane knobs: `state: {shards, checkpoint_every}`
        st_cfg = dict(node.cfg.get("state", {}))
        ledger_root = f"{ch_dir}/ledger"
        # join-by-snapshot: `bootstrap_snapshot: {enabled, from:[[host,
        # port],...]}` — only attempted when this channel has no chain
        # yet; failure falls back to genesis replay via deliver
        snap_cfg = dict(node.cfg.get("bootstrap_snapshot", {}))
        self.snapshot_bootstrap = None   # install info (or None)
        if snap_cfg.get("enabled"):
            self._bootstrap_from_snapshot(ledger_root, snap_cfg)
        self.ledger = KVLedger(
            self.channel_id,
            LedgerConfig(root=ledger_root,
                         state_shards=int(st_cfg.get("shards", 8)),
                         snapshot_every=int(
                             st_cfg.get("checkpoint_every", 256)),
                         parallel_commit=bool(pc_cfg.get("enabled", False)),
                         commit_workers=int(pc_cfg.get("max_workers", 4)),
                         commit_adaptive=bool(pc_cfg.get("adaptive", True)),
                         commit_serial_fallback=bool(
                             pc_cfg.get("serial_fallback", True)),
                         # cross-block wavefront window (README
                         # "Cross-block wavefront"): W > 0 enables the
                         # pipelined commit_begin/commit_finish entry
                         # points used by PipelinedCommitter drivers
                         commit_window=int(pc_cfg.get("window", 0)),
                         device_validate=dv_on))
        early_abort = None
        if pc_cfg.get("early_abort", pc_cfg.get("enabled", False)):
            from fabric_tpu.committer.parallel_commit import (
                EarlyAbortAnalyzer,
            )
            # overlay_source keeps dooming sound while the pipelined
            # window holds uncommitted predecessors (savepoint lag)
            early_abort = EarlyAbortAnalyzer(
                self.ledger.statedb, self.channel_id,
                overlay_source=self.ledger.pending_overlay)
        device_validate = None
        if dv_on:
            from fabric_tpu.committer.device_validate import DeviceValidator
            device_validate = DeviceValidator(
                self.ledger.statedb, self.channel_id,
                window=int(dv_cfg.get("window", 4096)))
            self.ledger.set_prepared_source(device_validate.take_prepared)

        cfg = node.cfg
        self.policies = LifecyclePolicyProvider(self.ledger.statedb)
        # the `_lifecycle` namespace endorsement policy: majority of the
        # channel's orgs (the reference's default Application/
        # LifecycleEndorsement MAJORITY Endorsement rule)
        from fabric_tpu.chaincode import LIFECYCLE_NS
        _orgs = _app_org_ids(self.bundle_source.current().config)
        if _orgs:
            _maj = len(_orgs) // 2 + 1
            self.policies.set_policy(LIFECYCLE_NS, parse_policy(
                "OutOf(%d, %s)" % (_maj, ", ".join(
                    f"'{o}.member'" for o in _orgs))))
        self._cc_policies: Dict[str, object] = {}
        for cc in cfg.get("chaincodes", []):
            if cc.get("policy"):
                pol = parse_policy(cc["policy"])
                self.policies.set_policy(cc["name"], pol)
                self._cc_policies[cc["name"]] = pol
            # field indexes declared with the chaincode (the reference
            # ships CouchDB index definitions in the chaincode package's
            # META-INF/statedb/couchdb/indexes, created at deploy)
            for field in cc.get("indexes", []):
                self.ledger.statedb.create_index(cc["name"], field)

        # per-channel device placement: when the scheduler is live
        # (bccsp_placement) each channel verifies on its own carved
        # device span; provider_source lets every validator flush
        # re-resolve + report queue depth so spans track demand
        from fabric_tpu.bccsp import factory as bccsp_factory
        ch_provider = (bccsp_factory.provider_for_channel(self.channel_id)
                       or node.provider)
        provider_source = (bccsp_factory.provider_for_channel
                           if bccsp_factory.get_placement() is not None
                           else None)
        # device_validate needs the deep C collect tail, which key-level
        # endorsement (sbe_lookup) disables — enabling the fused path
        # trades away per-key validation-parameter overrides on this
        # peer (README "Device-resident validation")
        sbe = (None if device_validate is not None
               else statedb_lookup(self.ledger.statedb))
        self.validator = TxValidator(
            self.channel_id, None, ch_provider, self.policies,
            bundle_source=self.bundle_source,
            sbe_lookup=sbe,
            provider_source=provider_source,
            verify_cache=node.verify_cache,
            early_abort=early_abort,
            device_validate=device_validate)
        self.committer = Committer(self.ledger, self.validator,
                                   bundle_source=self.bundle_source,
                                   provider=ch_provider,
                                   confighistory=self.confighistory)

        # private data plane
        self.collections = CollectionRegistry()
        for col in cfg.get("collections", []):
            self.collections.define(col["ns"], CollectionConfig(
                col["name"], member_orgs=tuple(col["members"]),
                block_to_live=int(col.get("btl", 0))))
        self.transient = TransientStore()
        self.pvt_store = PvtDataStore()
        self.coordinator = Coordinator(
            self.committer, self.collections, self.transient,
            self.pvt_store, mspid=node.mspid,
            fetch=self._privdata_fetch_remote)

        # aclmgmt: resource-name -> channel-policy authorization, live
        # against the bundle so config-tx ACL changes take effect
        # (core/aclmgmt/aclmgmt.go:15 + resources.go)
        from fabric_tpu.policy import ACLProvider
        self.acl = ACLProvider(self.bundle_source, node.provider)

        self.endorser = Endorser(
            self.channel_id, self.ledger.statedb, node.cc_registry,
            self.msps, node.provider, node.signer,
            transient_store=self.transient, pvt_store=self.pvt_store,
            distribute=self._privdata_distribute,
            ledger_height=lambda: self.ledger.height,
            acl=self.acl)

        self.qscc = Qscc(self.channel_id, self.ledger.blockstore,
                         acl=self.acl)
        self.discovery = DiscoveryService(
            membership=node._membership,
            policy_for=self.policies.policy_for)
        self.deliver_client = RemoteDeliver(node.orderers, node.signer,
                                            self.msps)

        # per-channel gossip node on the SHARED authenticated transport
        # (gossip/comm.ChannelMux — the reference keys gossip state by
        # channel inside one instance, gossip_impl.go channel registry)
        from fabric_tpu.gossip.mcs import MessageCryptoService
        from fabric_tpu.gossip.node import GossipNode

        self.mcs = MessageCryptoService(self.msps, node.provider)
        bootstrap = [f"{p[0]}:{p[1]}" for p in node.peers]
        self.gossip = GossipNode(
            node.gossip_mux.register_for(self.channel_id),
            node.gossip_mux.transport.id, self.coordinator,
            mcs=self.mcs, signer=node.signer,
            bootstrap=bootstrap, msps=self.msps)

        # byzantine containment: per-channel witness log + monitor over
        # the node-scoped quarantine registry.  Judges every block at
        # deliver/gossip intake (after signature verification) and
        # guards the gossip drain so a contested header never commits.
        self.byz_monitor = None
        self.proof_gossip = None
        if node.byzantine is not None:
            from fabric_tpu.byzantine import (ByzantineMonitor, ProofGossip,
                                              WitnessLog)
            self.byz_monitor = ByzantineMonitor(
                self.channel_id,
                WitnessLog(f"{ch_dir}/witness_log.json"),
                node.byzantine, ledger=self.ledger, msps=self.msps,
                signer=node.signer,
                proof_dir=f"{ch_dir}/fraud_proofs",
                pardon_window_s=node.byz_pardon_window)
            self.gossip.state.monitor = self.byz_monitor
            self.deliver_client.blocked = (
                lambda s: self.byz_monitor.blocked_source(
                    self._byz_source(s)))
            # fraud-proof gossip: local convictions broadcast their
            # portable proof; received proofs are independently
            # re-verified (byzantine/proofgossip.py)
            self.proof_gossip = ProofGossip(
                self.gossip.endpoint, self.gossip.discovery,
                self.byz_monitor)
            self.gossip.state.proofs = self.proof_gossip
            self.byz_monitor.on_proof = self.proof_gossip.broadcast
            # proof-backed pardons ride the same plane: a NEW local
            # restoration gossips its signed record, receivers
            # re-verify independently (monitor.accept_remote_pardon)
            self.byz_monitor.on_pardon = self.proof_gossip.broadcast_pardon

        self.deliver_healthy = True
        self._thread = threading.Thread(target=self._deliver_loop,
                                        daemon=True)

    # -- snapshot bootstrap ---------------------------------------------

    def _bootstrap_from_snapshot(self, ledger_root: str,
                                 snap_cfg: dict) -> None:
        """Join-by-snapshot (the reference's `peer node
        join-by-snapshot`): when this channel has no chain yet, fetch +
        install a snapshot from a serving peer so recovery opens at the
        snapshot height and deliver only tail-replays to tip.  Never
        fatal — failure falls back to genesis replay."""
        from fabric_tpu.ledger import snapshot as snapmod
        try:
            if not snapmod.needs_bootstrap(ledger_root, self.channel_id):
                return
            sources = [tuple(a[:2]) for a in snap_cfg.get("from", [])]
            if not sources:
                sources = [tuple(p[:2]) for p in self.node.peers]
            if not sources:
                logger.warning("[%s] bootstrap_snapshot enabled but no "
                               "serving peers configured", self.channel_id)
                return
            info = snapmod.bootstrap_from_peers(
                ledger_root, self.channel_id, sources, self.node.signer,
                self.msps,
                chunk_timeout_s=float(snap_cfg.get("chunk_timeout_s", 2.0)),
                attempts=int(snap_cfg.get("attempts", 12)),
                source_blocked=self._source_blocked)
            self.snapshot_bootstrap = info
            logger.info("[%s] joined by snapshot: %s", self.channel_id,
                        info)
        except Exception:
            logger.exception("[%s] snapshot bootstrap failed; falling "
                             "back to genesis replay", self.channel_id)

    # -- privdata client side -------------------------------------------

    def _privdata_distribute(self, txid: str, pvt_sets: dict) -> None:
        """Push endorsement-time cleartext to collection member peers."""
        recs = []
        for (ns, coll), kv in pvt_sets.items():
            recs.append({"namespace": ns, "collection": coll,
                         "keys": list(kv.keys()),
                         "values": [v if v is not None else b""
                                    for v in kv.values()],
                         "deleted": [v is None for v in kv.values()]})
        if not recs:
            return
        body = {"txid": txid, "height": self.ledger.height, "sets": recs,
                "channel": self.channel_id}
        for addr in self.node.peers:
            try:
                conn = connect(tuple(addr[:2]), self.node.signer,
                               self.msps, timeout=2.0)
                try:
                    conn.cast("privdata.push", body)
                finally:
                    conn.close()
            except Exception:
                logger.debug("privdata push to %s failed", addr,
                             exc_info=True)

    def _privdata_fetch_remote(self, txid: str, ns: str,
                               coll: str) -> Optional[dict]:
        """Reconciliation pull from member peers (reconcile.go)."""
        for addr in self.node.peers:
            try:
                conn = connect(tuple(addr[:2]), self.node.signer,
                               self.msps, timeout=2.0)
                try:
                    out = conn.call("privdata.fetch", {
                        "txid": txid, "namespace": ns, "collection": coll,
                        "channel": self.channel_id}, timeout=5.0)
                finally:
                    conn.close()
            except Exception:
                continue
            if out.get("found"):
                return {k: (None if d else v) for k, v, d in
                        zip(out["keys"], out["values"], out["deleted"])}
        return None

    # -- deliver / commit loop ------------------------------------------

    @staticmethod
    def _byz_source(sender):
        """'mspid|cert-sha256' quarantine key for a transport-verified
        deliver sender, or None (never blocked) without a usable cert."""
        binding = PeerNode._attestor_binding(sender)
        if binding is None:
            return None
        return f"{binding[0]}|{binding[1]}"

    def _source_blocked(self, sender) -> bool:
        """Standing check against the node-scoped quarantine registry
        for transfer sources resolved BEFORE the channel monitor exists
        (snapshot bootstrap runs first in __init__) — the registry
        survives a ledger wipe, so a wiped-and-rejoining peer still
        refuses a convicted snapshot source."""
        if self.node.byzantine is None:
            return False
        key = self._byz_source(sender)
        return key is not None and self.node.byzantine.is_quarantined(key)

    def _seed_attestations(self, block, attests, sender) -> None:
        """Seed the node's verdict cache from an orderer's deliver-time
        admission attestations (verify_plane/attest.py).  A no-op
        unless this peer explicitly trusts attestations AND the
        deliver stream's handshake-verified sender is in the attestor
        allowlist; every digest is re-derived from our own envelope
        bytes before acceptance."""
        cache = self.node.verify_cache
        if cache is None or not self.node._attestor_authorized(sender):
            return
        from fabric_tpu.verify_plane import accept_block_attestations
        try:
            # mint under the channel's live config sequence — the same
            # epoch the commit-time validator will judge against
            cache.set_epoch(self.bundle_source.current().sequence,
                            scope=self.channel_id)
            binding = self.node._attestor_binding(sender)
            accept_block_attestations(
                cache, block, attests, self.channel_id, self.msps,
                trust=self.node.attestor_trust,
                attestor_binding=binding)
            # a digest mismatch just revoked the attestor (trust.py):
            # mirror that provable tamper into the byzantine plane so
            # /byzantine, the metric, and the BYZ column reflect it
            if (self.byz_monitor is not None and binding is not None
                    and self.node.attestor_trust is not None
                    and not self.node.attestor_trust.allowed(binding)):
                self.byz_monitor.convict_external(
                    f"{binding[0]}|{binding[1]}", "tampered_attestation",
                    {"block": int(block.header.number),
                     "channel": self.channel_id})
        except Exception:
            logger.debug("attestation seeding failed", exc_info=True)

    def _deliver_loop(self) -> None:
        from fabric_tpu.orderer.deliver import SeekInfo
        backoff = 0.2
        reconcile_at = time.monotonic() + 5.0
        while not self.node._stop.is_set():
            height = self.ledger.height
            try:
                got = 0
                for block, attests, sender in self.deliver_client.deliver(
                        self.channel_id,
                        SeekInfo(start=height, stop=height + 31,
                                 behavior="block_until_ready"),
                        timeout_s=5):
                    items = block_signature_items(block, self.msps)
                    if not items or not bool(
                            self.node.provider.batch_verify(items).all()):
                        logger.warning("block %d failed orderer-signature "
                                       "verification; dropping window",
                                       block.header.number)
                        # a KNOWN signer with an invalid signature is an
                        # offense (honest orderers cannot produce it —
                        # the authenticated transport rules out frame
                        # corruption); unknown signers may be config lag
                        # and are never scored
                        if self.byz_monitor is not None and items:
                            src = self._byz_source(sender)
                            if src is not None:
                                self.byz_monitor.offense(src, "bad_sig")
                        break
                    if self.byz_monitor is not None:
                        from fabric_tpu.byzantine.monitor import (
                            VERDICT_ADMIT, VERDICT_STALE)
                        verdict = self.byz_monitor.check_block(
                            block, self._byz_source(sender))
                        if verdict == VERDICT_STALE:
                            got += 1
                            continue
                        if verdict != VERDICT_ADMIT:
                            # hold: disputed height awaiting quorum;
                            # reject: this stream served crime evidence.
                            # Either way re-source from the next
                            # consenter — re-seek from committed height
                            # keeps exactly-once (replay guard dedups)
                            self.deliver_client.advance()
                            break
                    if attests:
                        self._seed_attestations(block, attests, sender)
                    # through the gossip state plane: fans out to peers
                    # and drains strictly in block order
                    self.gossip.state.add_block(block)
                    got += 1
                if got and self.byz_monitor is not None:
                    self.byz_monitor.on_committed(self.ledger.height)
                self.deliver_healthy = True
                backoff = 0.2
                if not got:
                    time.sleep(0.1)
            except Exception:
                self.deliver_healthy = False
                logger.debug("deliver pull failed; retrying", exc_info=True)
                time.sleep(backoff)
                backoff = min(backoff * 2, 3.0)
            try:
                self.gossip.tick()
            except Exception:
                logger.exception("gossip tick failed")
            if time.monotonic() >= reconcile_at:
                try:
                    n = self.coordinator.reconcile()
                    if n:
                        logger.info("[%s] reconciled %d private "
                                    "collections", self.channel_id, n)
                except Exception:
                    logger.exception("privdata reconcile failed")
                reconcile_at = time.monotonic() + 5.0

    def start(self) -> None:
        self._thread.start()


class PeerNode:
    """One peer process hosting N channels (library form; `main` wraps
    it).  Single-channel attribute surface (ledger/validator/...)
    delegates to the bootstrap channel."""

    def __init__(self, cfg: dict, data_dir: str):
        import os

        self.cfg = cfg
        self.data_dir = data_dir
        self.channel_id = cfg.get("channel_id", "ch")
        # `bccsp_degrade` unset -> None -> the factory's auto rule:
        # degrade ON for JAXTPU (a peer that loses its accelerator keeps
        # committing on SW, healthz flags it), OFF for SW.
        # `bccsp_degrade: false` is the fail-stop escape hatch.
        self.provider = init_factories(
            FactoryOpts(default=cfg.get("bccsp", "SW"),
                        degrade=cfg.get("bccsp_degrade"),
                        use_mesh=bool(cfg.get("bccsp_mesh", False)),
                        placement=bool(cfg.get("bccsp_placement", False)),
                        mesh_devices=cfg.get("bccsp_mesh_devices"),
                        compile_cache_dir=cfg.get("compile_cache_dir")))
        self.signer = load_signing_identity(
            cfg["mspid"], cfg["cert_pem"].encode(), cfg["key_pem"].encode())
        self.mspid = cfg["mspid"]

        # verify-once plane: ONE MAC'd verdict cache per peer process,
        # shared by the gateway's ingress stamping, the speculative
        # worker, and every channel's commit-time validator — so a
        # signature verified at submit time is never re-dispatched at
        # commit.  On by default; `verify_once: {"enabled": false}`
        # restores the classic always-verify pipeline.
        vcfg = dict(cfg.get("verify_once", {}))
        self.verify_cache = None
        self.speculative = None
        if vcfg.get("enabled", True):
            from fabric_tpu.verify_plane import VerdictCache
            self.verify_cache = VerdictCache(
                capacity=int(vcfg.get("capacity", 65536)),
                owner=self.mspid)
        # deliver-time attestation trust (the orderer->peer direction of
        # the gateway->orderer scheme in orderer/msgprocessor.py): OFF
        # unless `trust_attestations: true` AND an explicit `attestors`
        # allowlist of {"mspid", "cert_fp"} bindings names the orderer
        # identities allowed to vouch for creator-signature verdicts.
        from fabric_tpu.orderer.msgprocessor import StandardChannelProcessor
        self._trust_attestations = bool(
            vcfg.get("trust_attestations", False))
        self._attestors = StandardChannelProcessor._normalize_attestors(
            vcfg.get("attestors"))
        # per-orderer standing on top of the allowlist (verify_plane/
        # trust.py): a sender whose attested digest ever failed this
        # peer's own re-derivation is revoked, persistently.
        self.attestor_trust = None
        if self._trust_attestations and self._attestors:
            from fabric_tpu.verify_plane import AttestorTrust
            self.attestor_trust = AttestorTrust(
                os.path.join(data_dir, "attestor_trust.json"))

        # byzantine containment plane: ONE persistent quarantine
        # registry per peer process (identities are node-scoped — an
        # orderer convicted on any channel is distrusted on all), with
        # per-channel witness logs/monitors built in PeerChannel.  On by
        # default; `byzantine: {"enabled": false}` restores blind trust.
        byz_cfg = dict(cfg.get("byzantine", {}))
        self.byzantine = None
        # pardon window (seconds of clean observation before an
        # offense-based quarantine is restored); None keeps the r13
        # permanent-quarantine behaviour
        self.byz_pardon_window = (
            float(byz_cfg["pardon_window_s"])
            if byz_cfg.get("pardon_window_s") is not None else None)
        if byz_cfg.get("enabled", True):
            from fabric_tpu.byzantine import QuarantineRegistry
            self.byzantine = QuarantineRegistry(
                os.path.join(data_dir, "byzantine_quarantine.json"),
                score_threshold=int(byz_cfg.get("score_threshold", 3)))

        channel_cfg = ChannelConfig.deserialize(
            bytes.fromhex(cfg["channel_config_hex"]))

        self.peers = [tuple(p) for p in cfg.get("peers", [])]
        self.peer_orgs = {tuple(p[:2]): p[2] if len(p) > 2 else None
                          for p in cfg.get("peers", [])}
        self.orderers = [tuple(o) for o in cfg.get("orderers", [])]

        # chaincode runtime, shared across channels (installs are
        # peer-scoped in the reference too; per-channel policy state
        # lives in each PeerChannel)
        self.cc_registry = ChaincodeRegistry()
        for cc in cfg.get("chaincodes", []):
            contract = self._make_contract(cc)
            self.cc_registry.install(
                ChaincodeDefinition(cc["name"], cc.get("version", "1.0")),
                contract)
        # `_lifecycle` system contract + hash-addressed package store:
        # the admin CLI's install/approve/commit verbs ride these
        # (core/chaincode/lifecycle + persistence/chaincode_package.go)
        from fabric_tpu.chaincode import LIFECYCLE_NS, LifecycleContract
        from fabric_tpu.chaincode.lifecycle import ChaincodeInstaller
        self.installer = ChaincodeInstaller(
            os.path.join(data_dir, "chaincodes"))
        def _lifecycle_orgs(cid, _boot=channel_cfg):
            ch = self.channels.get(cid) if hasattr(self, "channels") \
                else None
            cfg_now = (ch.bundle_source.current().config
                       if ch is not None else _boot)
            return _app_org_ids(cfg_now)

        self.cc_registry.install(
            ChaincodeDefinition(LIFECYCLE_NS, "1.0"),
            LifecycleContract(_lifecycle_orgs))

        # RPC + shared gossip transport.  Handshake MSPs resolve through
        # the LIVE channel bundles (union across joined channels) at
        # every use, not a construction-time snapshot: orgs present only
        # on a runtime-joined channel can authenticate at the transport
        # layer, and MSP rotations committed via config tx reach the
        # handshake path immediately.
        boot_msps = Bundle(channel_cfg).msps
        live_msps = _LiveHandshakeMsps(self, boot_msps)
        self.rpc = RpcServer(cfg.get("host", "127.0.0.1"), int(cfg["port"]),
                             self.signer, live_msps)
        from fabric_tpu.gossip.comm import ChannelMux, SecureGossipTransport
        transport = SecureGossipTransport(self.rpc, self.signer, live_msps)
        self.gossip_mux = ChannelMux(transport, channel_cfg.channel_id)

        self._stop = threading.Event()
        # serving -> draining -> drained (fleet lifecycle: rolling
        # restarts drain a peer before killing it)
        self.lifecycle = "serving"
        self.channels: Dict[str, PeerChannel] = {}
        self.cscc = Cscc(create_channel=self._cscc_create)

        # bootstrap channel.  config_height: the block number the
        # bootstrap config was taken at (0 = genesis) — a peer
        # bootstrapped at a later config MUST carry it so catch-up
        # replay of older config blocks is recognized (committer.py).
        # Legacy layout detection keys on the OLD LEDGER ITSELF
        # (data_dir/ledger) — a stable marker; keying on the channels/
        # dir would silently relocate the bootstrap ledger after the
        # first runtime join created it.
        self._create_channel(channel_cfg,
                             config_height=int(cfg.get("config_height", 0)),
                             legacy_dir=os.path.isdir(
                                 os.path.join(data_dir, "ledger")))

        # restore channels joined at runtime in earlier lives
        ch_root = os.path.join(data_dir, "channels")
        if os.path.isdir(ch_root):
            for entry in sorted(os.listdir(ch_root)):
                cfg_path = os.path.join(ch_root, entry,
                                        "channel_config.bin")
                if entry in self.channels or not os.path.exists(cfg_path):
                    continue
                try:
                    with open(cfg_path, "rb") as f:
                        joined = ChannelConfig.deserialize(f.read())
                    self._create_channel(joined)
                    logger.info("restored joined channel %r", entry)
                except Exception:
                    logger.exception("could not restore channel %r", entry)

        self.rpc.serve("endorse", self._rpc_endorse)
        self.rpc.serve("status", self._rpc_status)
        self.rpc.serve("qscc.chain_info", self._rpc_chain_info)
        self.rpc.serve("qscc.block_by_number", self._rpc_block_by_number)
        self.rpc.serve("qscc.tx_by_id", self._rpc_tx_by_id)
        self.rpc.serve("cscc.channels", lambda b, p:
                       {"channels": self.cscc.get_channels()})
        self.rpc.serve("cscc.join", self._rpc_cscc_join)
        self.rpc.serve("discovery.endorsers", self._rpc_discovery)
        self.rpc.serve("discovery.peers", self._rpc_discovery_peers)
        self.rpc.serve("discovery.config", self._rpc_discovery_config)
        self.rpc.serve("lifecycle.install", self._rpc_cc_install)
        self.rpc.serve("lifecycle.installed", self._rpc_cc_installed)
        self.rpc.serve("privdata.fetch", self._rpc_privdata_fetch)
        self.rpc.serve_cast("privdata.push", self._rpc_privdata_push)
        # snapshot state-transfer (ledger/snapshot.py): meta + chunked
        # shard-file reads; the transport handshake already restricts
        # callers to channel MSP identities
        self.rpc.serve("state.snapshot_meta", self._rpc_snapshot_meta)
        self.rpc.serve("state.snapshot_chunk", self._rpc_snapshot_chunk)

        # gateway: the batched client front door (needs orderers to
        # broadcast to; a peer with no orderer list serves peers only)
        self.gateway = None
        if self.orderers and cfg.get("gateway_enabled", True):
            from fabric_tpu.gateway import GatewayService
            # `admission {enabled, shed_evaluate_burn, shed_hard_burn,
            # ...}` may live at the node top level (env-overridable as
            # FABRIC_TPU_PEER_ADMISSION__*) or nested under `gateway`;
            # top level wins so one flag flips shedding on a deployment
            gw_cfg = dict(cfg.get("gateway", {}))
            if cfg.get("admission") is not None:
                gw_cfg["admission"] = cfg.get("admission")
            self.gateway = GatewayService(self, gw_cfg)
            self.gateway.register(self.rpc)
        # speculative verifier: stamps creator verdicts at ingress and
        # verifies endorsement sets while the orderer cuts the block —
        # only a gateway-hosting peer sees transactions pre-ordering
        if self.gateway is not None and self.verify_cache is not None:
            from fabric_tpu.verify_plane import SpeculativeVerifier
            self.speculative = SpeculativeVerifier(
                self.verify_cache, lambda: self.provider,
                self._channel_msps, epoch_source=self._channel_epoch)

        # tx tracing + flight recorder: on by default for nodes (the
        # import-time default stays off so libraries/bench pay nothing);
        # sample rate and recorder capacity ride localconfig, e.g.
        # FABRIC_TPU_PEER_TRACING__SAMPLE_RATE=0.1
        from fabric_tpu.ops_plane import tracing as _tracing
        _tracing.configure(cfg.get("tracing", {}))

        self.ops = None
        if cfg.get("ops_port") is not None:
            from fabric_tpu.ops_plane import OperationsServer
            self.ops = OperationsServer(cfg.get("host", "127.0.0.1"),
                                        int(cfg["ops_port"]))
            self.ops.register_checker(
                "deliver", lambda: self._deliver_healthy)
            self.ops.register_checker("orderer_reachable",
                                      self._check_orderers)
            self.ops.register_checker("bccsp", self._check_bccsp)
            # lifecycle on /healthz (serving/draining/drained — an
            # ORDERLY state, not a failure) + POST /drain to enter it
            self.ops.lifecycle_fn = lambda: self.lifecycle
            self.ops.register_route(
                "POST", "/drain",
                lambda path, body: (200, self.drain()))
            # /debug/profile (jax.profiler) + /debug/pprof (host), the
            # peer.profile.enabled slot (internal/peer/node/start.go:813)
            from fabric_tpu.ops_plane.profiling import register_routes
            register_routes(self.ops, enabled=bool(cfg.get("profiling")))
            # /traces, /traces/<id> (Chrome trace JSON), /spans/stats;
            # ?cluster=1 assembles the trace across every ops endpoint
            # in the `cluster_trace` sub-dict's peer list (orderers
            # included) — one Perfetto export spanning gateway →
            # orderer → committer
            ct_cfg = dict(cfg.get("cluster_trace", {}))
            self.trace_peers = list(ct_cfg.get("peers", []))

            def _cluster_trace(tid, _cfg=ct_cfg):
                from fabric_tpu.node import tracecollect
                # the config's peer list may include this node's own
                # endpoint (one shared list for the whole cluster) —
                # serve self in-process, or the same spans would count
                # under two node identities
                own = "%s:%d" % self.ops.addr
                peers = [p for p in self.trace_peers if str(p) != own]
                out = tracecollect.collect_cluster_trace(
                    tid, peers, local_tracer=_tracing.tracer,
                    local_name=f"peer:{self.mspid}",
                    timeout_s=float(_cfg.get("timeout_s", 2.0)),
                    max_traces=int(_cfg.get("max_traces", 16)))
                if out is None:
                    return 404, {"error": "unknown trace", "trace_id": tid}
                return 200, out

            _tracing.register_routes(self.ops, cluster_fn=_cluster_trace)
            # GET /faults: the active fault plan ({"active": false} in
            # production — the plan only exists during chaos drills)
            from fabric_tpu.comm import faults as _faults
            _faults.register_routes(self.ops)
            # GET /state: per-channel shard sizes, checkpoint generation/
            # savepoint, and how much the last reopen had to replay
            self.ops.register_route("GET", "/state", self._state_route)
            # GET /byzantine: quarantine standings, per-channel witness
            # stats, fraud proofs
            if self.byzantine is not None:
                from fabric_tpu.byzantine import register_ops as _byz_ops
                _byz_ops(self.ops, self.byzantine,
                         monitors_fn=lambda: {
                             cid: ch.byz_monitor
                             for cid, ch in self.channels.items()
                             if ch.byz_monitor is not None})
            # GET /gateway: front-door queue + breaker snapshot (the
            # gateway shares the peer process and ops surface)
            if self.gateway is not None:
                self.gateway.register_ops(self.ops)
            # GET /verify_plane: verdict-cache economics + speculative
            # worker state
            if self.verify_cache is not None:
                from fabric_tpu import verify_plane as _vp
                _vp.register_ops(
                    self.ops, self.verify_cache, spec=self.speculative,
                    extra=lambda: {
                        "trust_attestations": self._trust_attestations,
                        "attestors": len(self._attestors),
                        "attestors_revoked": (
                            self.attestor_trust.revoked_count()
                            if self.attestor_trust is not None else 0)})

        # SLO plane: GET /slo + /slo/alerts, burn-rate alerting over the
        # metrics registry; config/env via the `slo` sub-dict
        # (FABRIC_TPU_PEER_SLO__SHORT_WINDOW_S=30 etc.)
        self.slo = None
        slo_cfg = cfg.get("slo", {})
        if self.ops is not None and slo_cfg.get("enabled", True):
            from fabric_tpu.ops_plane import slo as _slo
            self.slo = _slo.SloEvaluator(slo_cfg)
            _slo.register_routes(self.ops, self.slo)
            self.slo.start()

        # metric history + resource telemetry: GET /metrics/history
        # (ring store with raw→1m→10m downsampling) and a /proc-based
        # collector feeding RSS/fd/thread/GC/arena/verdict-cache gauges
        # into /metrics and the store.  Both OFF by default: disabled,
        # no thread runs, no gauge registers, /metrics is unchanged.
        # Config/env: FABRIC_TPU_PEER_TIMESERIES__ENABLED=true etc.
        self.timeseries = None
        ts_cfg = cfg.get("timeseries", {})
        if self.ops is not None and ts_cfg.get("enabled", False):
            from fabric_tpu.ops_plane import timeseries as _ts
            self.timeseries = _ts.TimeSeriesStore(ts_cfg)
            _ts.register_routes(self.ops, self.timeseries)
            self.timeseries.start()
        self.resources = None
        res_cfg = cfg.get("resources", {})
        if self.ops is not None and res_cfg.get("enabled", False):
            from fabric_tpu.ops_plane import resources as _res
            self.resources = _res.ResourceCollector(res_cfg)
            if self.verify_cache is not None:
                cache = self.verify_cache
                self.resources.add_source(
                    "verdict_cache_occupancy",
                    lambda: cache.snapshot()["size"])
            _res.register_routes(self.ops, self.resources)
            self.resources.start()

        # continuous sampling profiler: GET /profile/sampled, a daemon
        # thread folding sys._current_frames() into time-bucketed
        # windows.  OFF by default: disabled, no thread, no counter,
        # /metrics byte-identical.  FABRIC_TPU_PEER_PROFILER__ENABLED=true
        self.profiler = None
        prof_cfg = cfg.get("profiler", {})
        if self.ops is not None and prof_cfg.get("enabled", False):
            from fabric_tpu.ops_plane import sampler as _sampler
            self.profiler = _sampler.SamplingProfiler(prof_cfg)
            _sampler.register_routes(self.ops, self.profiler)
            self.profiler.start()

        # incident capture: on SLO alert fire, write a self-contained
        # incident_NNNN/ bundle (profile windows, slowest traces,
        # metric history, snapshots, peer fan-out) under data_dir.
        # OFF by default with the same zero-overhead guard.
        self.incidents = None
        inc_cfg = dict(cfg.get("incidents", {}))
        if self.ops is not None and inc_cfg.get("enabled", False):
            from fabric_tpu.ops_plane import incidents as _inc
            inc_cfg.setdefault(
                "dir", os.path.join(self.data_dir, "incidents"))
            if "peers" not in inc_cfg:
                own = "%s:%d" % self.ops.addr
                inc_cfg["peers"] = [
                    p for p in getattr(self, "trace_peers", [])
                    if str(p) != own]
            self.incidents = _inc.IncidentRecorder(
                inc_cfg, node_name=f"peer:{self.mspid}",
                profiler=self.profiler, timeseries=self.timeseries)
            if self.slo is not None:
                self.incidents.attach_slo(self.slo)
            if self.resources is not None:
                self.incidents.add_source(
                    "resources", self.resources.collect)
            if self.byzantine is not None:
                self.incidents.add_source(
                    "byzantine", self.byzantine.snapshot)
            if self.gateway is not None:
                gw = self.gateway

                def _gw_snapshot():
                    with gw._lock:
                        depth = len(gw._queue)
                        inflight = len(gw._inflight)
                    return {"queue_depth": depth,
                            "inflight": inflight,
                            "lifecycle": gw.lifecycle,
                            "healthy": gw.broadcaster.healthy(),
                            "admission": gw.admission.snapshot(),
                            "orderers": gw.broadcaster.states()}

                self.incidents.add_source("gateway", _gw_snapshot)
            self.incidents.add_source(
                "lifecycle", lambda: {"lifecycle": self.lifecycle})
            _inc.register_routes(self.ops, self.incidents)

    def _check_orderers(self):
        """healthz: at least one orderer breaker not OPEN (or no
        broadcast plane configured at all)."""
        if self.gateway is None:
            return True
        bc = getattr(self.gateway, "broadcaster", None)
        if bc is None or bc.healthy():
            return True
        raise RuntimeError("all orderer breakers open: %s" % [
            s["addr"] for s in bc.states()])

    def _check_bccsp(self):
        """healthz: which crypto backend is live; FAILs (with the
        backend named in the reason) while degraded to SW."""
        backend = getattr(self.provider, "backend", self.provider.name)
        if getattr(self.provider, "degraded", False):
            raise RuntimeError(f"bccsp backend = {backend}")
        return True

    # -- channel lifecycle ---------------------------------------------------

    def _channel_dir(self, channel_id: str, legacy: bool = False) -> str:
        import os
        if legacy:
            # pre-multichannel layout: the bootstrap channel's ledger
            # lived at data_dir/ledger
            return self.data_dir
        return os.path.join(self.data_dir, "channels", channel_id)

    def _create_channel(self, channel_cfg: ChannelConfig,
                        config_height: int = 0,
                        legacy_dir: bool = False) -> PeerChannel:
        import os
        cid = channel_cfg.channel_id
        ch_dir = self._channel_dir(cid, legacy=legacy_dir)
        os.makedirs(ch_dir, exist_ok=True)
        if not legacy_dir:
            cfg_path = os.path.join(ch_dir, "channel_config.bin")
            if not os.path.exists(cfg_path):
                tmp = cfg_path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(channel_cfg.serialize())
                os.replace(tmp, cfg_path)
        ch = PeerChannel(self, channel_cfg, ch_dir,
                         config_height=config_height)
        self.channels[cid] = ch
        self.cscc.register(cid, ch)
        if not self._stop.is_set() and getattr(self, "_started", False):
            ch.start()
        return ch

    def _cscc_create(self, channel_id: str, channel_config,
                     config_height: int = 0):
        if isinstance(channel_config, (bytes, bytearray)):
            channel_config = ChannelConfig.deserialize(bytes(channel_config))
        if channel_config.channel_id != channel_id:
            raise ValueError("channel id mismatch")
        return self._create_channel(channel_config,
                                    config_height=config_height)

    def join_channel(self, channel_cfg: ChannelConfig,
                     config_height: int = 0) -> PeerChannel:
        """Runtime channel join (cscc JoinChain,
        core/scc/cscc/configure.go) — a new per-channel kernel in this
        process.  config_height: the block number the join config was
        taken at (from a fetched config block), so catch-up replay of
        older config blocks is recognized as historical."""
        if channel_cfg.channel_id in self.channels:
            raise ValueError(
                f"already joined {channel_cfg.channel_id!r}")
        return self.cscc.join_chain(channel_cfg.channel_id, channel_cfg,
                                    config_height=config_height)

    def _chan(self, body: dict) -> PeerChannel:
        cid = body.get("channel") or self.channel_id
        ch = self.channels.get(cid)
        if ch is None:
            raise ValueError(f"peer has not joined channel {cid!r}")
        return ch

    # -- bootstrap-channel delegation (single-channel API compat) ------------

    @property
    def _bootstrap(self) -> PeerChannel:
        return self.channels[self.channel_id]

    @property
    def bundle_source(self):
        return self._bootstrap.bundle_source

    @property
    def msps(self):
        return self._bootstrap.msps

    @property
    def ledger(self):
        return self._bootstrap.ledger

    @property
    def policies(self):
        return self._bootstrap.policies

    @property
    def validator(self):
        return self._bootstrap.validator

    @property
    def committer(self):
        return self._bootstrap.committer

    @property
    def collections(self):
        return self._bootstrap.collections

    @property
    def transient(self):
        return self._bootstrap.transient

    @property
    def pvt_store(self):
        return self._bootstrap.pvt_store

    @property
    def coordinator(self):
        return self._bootstrap.coordinator

    @property
    def acl(self):
        return self._bootstrap.acl

    @property
    def endorser(self):
        return self._bootstrap.endorser

    @property
    def qscc(self):
        return self._bootstrap.qscc

    @property
    def discovery(self):
        return self._bootstrap.discovery

    @property
    def deliver_client(self):
        return self._bootstrap.deliver_client

    @property
    def gossip(self):
        return self._bootstrap.gossip

    @property
    def mcs(self):
        return self._bootstrap.mcs

    @property
    def _deliver_healthy(self):
        return all(ch.deliver_healthy for ch in self.channels.values())

    # -- wiring helpers ------------------------------------------------------

    def _channel_msps(self, channel_id: str):
        """Live MSP set for the speculative verifier's item derivation —
        resolved through the channel bundle at every use so MSP rotations
        reach speculation the same instant they reach the gate."""
        ch = self.channels.get(channel_id)
        if ch is None:
            return {}
        return ch.bundle_source.current().msps

    def _attestor_authorized(self, sender) -> bool:
        """Is this transport-authenticated orderer identity allowed to
        vouch for creator-signature verdicts?  Same rule as the
        orderer's gateway-attestation gate (msgprocessor.py): trust
        must be explicitly enabled, and the sender's (mspid, cert
        sha256) binding must be in the configured allowlist — no
        allowlist means nobody may vouch."""
        if (not self._trust_attestations or sender is None
                or not self._attestors):
            return False
        binding = self._attestor_binding(sender)
        if binding is None or binding not in self._attestors:
            return False
        # allowlisted but revoked (a past digest mismatch) = not honoured
        return (self.attestor_trust is None
                or self.attestor_trust.allowed(binding))

    @staticmethod
    def _attestor_binding(sender):
        """(mspid, cert sha256) of a transport-authenticated sender, or
        None when it carries no usable certificate."""
        try:
            from fabric_tpu.orderer.cluster import cert_fingerprint
            return (sender.mspid, cert_fingerprint(sender.cert))
        except Exception:
            return None

    def _channel_epoch(self, channel_id: str) -> int:
        """Config sequence for the speculative verifier's per-channel
        cache-epoch pin — the same value the commit-time validator will
        judge those entries against."""
        ch = self.channels.get(channel_id)
        if ch is None:
            return 0
        return ch.bundle_source.current().sequence

    def _make_contract(self, cc_cfg: dict):
        kind = cc_cfg.get("contract", "asset_demo")
        if kind in DEV_CONTRACTS:
            return DEV_CONTRACTS[kind]()
        if kind.startswith("extern:"):
            # production mode: the contract runs as its own OS process
            # speaking the Register/Invoke stream FSM (chaincode/extcc.py)
            import shlex
            from fabric_tpu.chaincode.extcc import (
                ChaincodeSupport,
                ExtProcessContract,
            )
            if getattr(self, "cc_support", None) is None:
                self.cc_support = ChaincodeSupport(
                    f"{self.cfg['data_dir']}/cc")
            return ExtProcessContract(self.cc_support, cc_cfg["name"],
                                      shlex.split(kind[len("extern:"):]))
        raise ValueError(f"unknown contract {kind!r}")

    def _membership(self):
        """discovery membership: this peer + its configured neighbors
        (live gossip membership in the reference)."""
        me = f"{self.cfg.get('host', '127.0.0.1')}:{self.cfg['port']}"
        out = [{"id": me, "mspid": self.mspid, "roles": ["peer"]}]
        for p in self.cfg.get("peers", []):
            if len(p) > 2:
                out.append({"id": f"{p[0]}:{p[1]}", "mspid": p[2],
                            "roles": ["peer"]})
        return out

    # -- rpc handlers --------------------------------------------------------

    def _rpc_endorse(self, body: dict, peer_identity) -> dict:
        sp = SignedProposal(body["proposal"], body["signature"])
        resp = self._chan(body).endorser.process_proposal(sp)
        out = {"status": resp.status, "message": resp.message,
               "payload": resp.payload}
        if resp.endorsement is not None:
            out["endorser"] = resp.endorsement.endorser
            out["endorsement_sig"] = resp.endorsement.signature
        return out

    def _rpc_status(self, body: dict, peer_identity) -> dict:
        ch = self._chan(body)
        return {"mspid": self.mspid, "channel": ch.channel_id,
                "channels": sorted(self.channels),
                "height": ch.ledger.height,
                "commit_hash": (ch.ledger.commit_hash or b"").hex()}

    def _rpc_snapshot_meta(self, body: dict, peer_identity) -> dict:
        """Serve a snapshot description: force-checkpoint the channel's
        derived DBs and return manifests + chain metadata at the
        checkpoint height (ledger/snapshot.py protocol)."""
        from fabric_tpu.ledger import snapshot as snapmod
        return snapmod.export_meta(self._chan(body).ledger)

    def _rpc_snapshot_chunk(self, body: dict, peer_identity) -> dict:
        from fabric_tpu.ledger import snapshot as snapmod
        return snapmod.serve_chunk(
            self._chan(body).ledger, str(body["db"]), int(body["gen"]),
            str(body["file"]), int(body["offset"]))

    def _state_route(self, path, body):
        from fabric_tpu.ops_plane import registry
        demotions = registry.counter(
            "validator_device_demotions_total",
            "device-validation demotions to the host path, by reason")
        out = {}
        for cid, ch in sorted(self.channels.items()):
            st = ch.ledger.state_status()
            by_reason = demotions.breakdown("reason", channel=cid)
            if by_reason:
                # policy_width called out: it is the k<=8 truth-table
                # cap's real-world demotion rate (README "Device-
                # resident validation")
                st["device_validate"] = {
                    "demotions": {r: int(n)
                                  for r, n in sorted(by_reason.items())},
                    "policy_width_demotions": int(
                        by_reason.get("policy_width", 0)),
                }
            out[cid] = st
        return 200, {"channels": out}

    def _rpc_chain_info(self, body: dict, peer_identity) -> dict:
        return self._chan(body).qscc.get_chain_info(peer_identity)

    def _rpc_block_by_number(self, body: dict, peer_identity) -> dict:
        blk = self._chan(body).qscc.get_block_by_number(
            int(body["number"]), peer_identity)
        return {"block": blk.serialize()}

    def _rpc_tx_by_id(self, body: dict, peer_identity) -> dict:
        env = self._chan(body).qscc.get_transaction_by_id(
            body["txid"], peer_identity)
        return {"envelope": env.serialize()}

    def _rpc_cscc_join(self, body: dict, peer_identity) -> dict:
        """Runtime channel join over RPC (cscc JoinChain,
        core/scc/cscc/configure.go) — gated by the PEER'S OWN
        cscc/JoinChain ACL (Admins of the bootstrap channel).  The
        incoming config must NEVER authorize its own join: it is
        attacker-supplied, and judging the caller against its MSPs
        would let anyone self-authorize with a crafted config (the
        reference checks JoinChain against the local MSP policy)."""
        self._bootstrap.acl.check("cscc/JoinChain", peer_identity)
        channel_cfg = ChannelConfig.deserialize(body["config"])
        ch = self.join_channel(channel_cfg,
                               config_height=int(body.get(
                                   "config_height", 0)))
        return {"channel": ch.channel_id, "status": "joined"}

    def _rpc_discovery(self, body: dict, peer_identity) -> dict:
        ch = self._chan(body)
        ch.acl.check("discovery/Discover", peer_identity)
        out = ch.discovery.endorsers(body["namespace"])
        out["layouts"] = [l.as_dict() for l in out["layouts"]]
        return out

    def _rpc_discovery_peers(self, body: dict, peer_identity) -> dict:
        """Live-membership peer query (the discover CLI's `peers` verb;
        discovery/client PeersOfChannel)."""
        ch = self._chan(body)
        ch.acl.check("discovery/Discover", peer_identity)
        return {"peers": self._membership()}

    def _rpc_discovery_config(self, body: dict, peer_identity) -> dict:
        """Channel-config summary (the discover CLI's `config` verb;
        discovery/client Config: msps + orderer endpoints)."""
        ch = self._chan(body)
        ch.acl.check("discovery/Discover", peer_identity)
        bundle = ch.bundle_source.current()
        return {"channel": ch.channel_id,
                "sequence": bundle.sequence,
                "msps": sorted(bundle.msps),
                "orderers": [f"{h}:{p}" for h, p in self.orderers]}

    def _check_local_admin(self, resource: str, peer_identity) -> None:
        """Peer-LOCAL admin gate: peer-scoped operations (chaincode
        install / query-installed) are authorized by an admin of the
        peer's OWN org — the reference evaluates these against the
        local MSP's admin policy, not a channel-wide majority
        (core/aclmgmt defaults for _lifecycle install)."""
        from fabric_tpu.msp import Principal, deserialize_from_msps
        from fabric_tpu.policy import ACLError, PolicyEvaluator, signed_by
        if peer_identity is None or not hasattr(peer_identity, "serialize"):
            raise ACLError(f"{resource}: unauthenticated caller")
        bundle = self._bootstrap.bundle_source.current()
        ident = deserialize_from_msps(bundle.msps,
                                      peer_identity.serialize(),
                                      validate=True)
        if ident is None or ident.mspid != self.mspid:
            raise ACLError(f"{resource}: caller is not a local-org "
                           "identity")
        evaluator = PolicyEvaluator(bundle.msps, self.provider)
        if not evaluator.evaluate(signed_by(Principal.admin(self.mspid)),
                                  [ident]):
            raise ACLError(f"{resource}: caller is not a local-org admin")

    def _rpc_cc_install(self, body: dict, peer_identity) -> dict:
        """Hash-addressed chaincode package install (lifecycle.go
        InstallChaincode), local-org-admin-gated."""
        self._check_local_admin("lifecycle/Install", peer_identity)
        pid = self.installer.install(body["package"])
        return {"package_id": pid}

    def _rpc_cc_installed(self, body: dict, peer_identity) -> dict:
        self._check_local_admin("lifecycle/QueryInstalled", peer_identity)
        return {"package_ids": self.installer.installed()}

    def _rpc_privdata_fetch(self, body: dict, peer_identity) -> dict:
        """Collection pull: ONLY collection-member orgs may read cleartext
        (gossip/privdata/pvtdataprovider.go membership check)."""
        ch = self._chan(body)
        ns, coll = body["namespace"], body["collection"]
        cfg = ch.collections.get(ns, coll)
        if cfg is None or not cfg.is_member(
                getattr(peer_identity, "mspid", None)):
            return {"found": False, "denied": True}
        data = ch.pvt_store.get_tx_set(ns, coll, body["txid"])
        if data is None:
            # also try the transient store (pre-commit staging)
            for sets in ch.transient.get(body["txid"]):
                if (ns, coll) in sets:
                    data = sets[(ns, coll)]
                    break
        if data is None:
            return {"found": False}
        return {"found": True,
                "keys": list(data.keys()),
                "values": [v if v is not None else b"" for v in
                           data.values()],
                "deleted": [v is None for v in data.values()]}

    def _rpc_privdata_push(self, body: dict, peer_identity) -> None:
        """Endorsement-time distribution: a member peer pushes cleartext
        into our transient store (gossip/privdata/distributor.go)."""
        ch = self._chan(body)
        sets = {}
        for rec in body["sets"]:
            ns, coll = rec["namespace"], rec["collection"]
            cfg = ch.collections.get(ns, coll)
            if cfg is None or not cfg.is_member(self.mspid):
                continue      # we are not a member: refuse cleartext
            sets[(ns, coll)] = {k: (None if d else v) for k, v, d in
                                zip(rec["keys"], rec["values"],
                                    rec["deleted"])}
        if sets:
            ch.transient.persist(body["txid"], int(body["height"]), sets)

    # -- lifecycle -----------------------------------------------------------

    def drain(self, timeout_s: float = 10.0) -> dict:
        """Graceful drain for rolling restarts: refuse NEW client work
        at the gateway while the batcher flushes everything already
        admitted, wait for in-flight block commits to go quiet, then
        force a checkpoint of every channel ledger (WAL truncated, the
        next recovery opens from the checkpoint instead of replaying).
        Idempotent; deliver/gossip reads keep serving throughout."""
        deadline = time.monotonic() + float(timeout_s)
        self.lifecycle = "draining"
        flushed = {}
        if self.gateway is not None:
            flushed = self.gateway.drain(
                max(0.0, deadline - time.monotonic()))
        heights = {}
        for cid, ch in list(self.channels.items()):
            # in-flight blocks: wait for the commit height to go quiet
            # (the deliver loop applies what it already pulled)
            last = ch.ledger.height
            quiet_at = time.monotonic() + 0.3
            while time.monotonic() < min(deadline, quiet_at):
                time.sleep(0.05)
                h = ch.ledger.height
                if h != last:
                    last, quiet_at = h, time.monotonic() + 0.3
            try:
                ch.ledger.snapshot_export()  # checkpoint + WAL truncate
            except Exception:
                logger.exception("[%s] drain checkpoint failed", cid)
            heights[cid] = ch.ledger.height
        self.lifecycle = "drained"
        return {"lifecycle": self.lifecycle, "gateway": flushed,
                "heights": heights}

    def start(self) -> "PeerNode":
        self.rpc.start()
        if self.ops is not None:
            self.ops.start()
        self._started = True
        if self.speculative is not None:
            self.speculative.start()
        if self.gateway is not None:
            self.gateway.start()
        for ch in self.channels.values():
            ch.start()
        logger.info("peer %s serving on %s (%d channels)", self.mspid,
                    self.rpc.addr, len(self.channels))
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.gateway is not None:
            self.gateway.stop()
        if self.speculative is not None:
            self.speculative.stop()
        self.rpc.stop()
        if getattr(self, "cc_support", None) is not None:
            self.cc_support.stop()      # kills external chaincode processes
        if getattr(self, "slo", None) is not None:
            self.slo.stop()
        if getattr(self, "timeseries", None) is not None:
            self.timeseries.stop()
        if getattr(self, "resources", None) is not None:
            self.resources.stop()
        if getattr(self, "profiler", None) is not None:
            self.profiler.stop()
        if getattr(self, "incidents", None) is not None:
            self.incidents.stop()
        if self.ops is not None:
            self.ops.stop()


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m fabric_tpu.node.peer <node.json>",
              file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.INFO)
    from fabric_tpu.config.localconfig import load_node_config
    cfg = load_node_config(argv[0], "peer")
    PeerNode(cfg, data_dir=cfg["data_dir"]).start()
    threading.Event().wait()   # serve until killed
    return 0


if __name__ == "__main__":
    sys.exit(main())
