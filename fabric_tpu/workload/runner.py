"""WorkloadRunner: open-loop phases -> per-phase offered/accepted/
committed accounting.

Glues the three generator pieces together: an arrival process says WHEN
(arrivals.py), a traffic mix says WHAT (keyspace.py), a client
population says WHO (clients.py).  Each phase runs one arrival schedule
open-loop — the scheduler thread fires every arrival at its wall-clock
instant and hands the op to a worker pool, so a saturated system under
test shows up as driver backlog + shed + sojourn blowup, never as a
quietly stretched schedule.

Accounting is per phase and three-tiered, the shape the overload
analysis needs:

  offered     arrivals the schedule generated (property of the world)
  accepted    submissions the gateway admitted (post-shed, post-
              backpressure); sojourn percentiles (p50/p99/p999) are
              measured on these, scheduler-arrival -> orderer ack
  committed   transactions the committer recorded VALID; MVCC and
              phantom losers are counted as conflicts (the conflict
              dial's empirical readout)

Two execution modes per op:

  inline      endorse -> assemble -> submit in the worker (the full
              client lifecycle; endorsement itself is sheddable)
  pool        envelopes pre-endorsed up front via `prepare(op)`; the
              open-loop phase then exercises ONLY the admission/order
              path — the mode overload probes use, since software P-256
              endorsement would otherwise rate-limit the driver itself
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from fabric_tpu.comm import RpcError
from fabric_tpu.endorser.proposal import assemble_transaction
from fabric_tpu.gateway.client import GatewayError, GatewayShedError
from fabric_tpu.protocol.txflags import ValidationCode
from fabric_tpu.workload.arrivals import OpenLoopScheduler, from_spec
from fabric_tpu.workload.clients import ClientPopulation, ThinkTimeModel
from fabric_tpu.workload.keyspace import Op, TrafficMix

logger = logging.getLogger("fabric_tpu.workload")

__all__ = ["WorkloadRunner", "PhaseStats", "pct"]

_CONFLICT_CODES = {int(ValidationCode.MVCC_READ_CONFLICT),
                   int(ValidationCode.PHANTOM_READ_CONFLICT)}


def pct(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def _lat_ms(xs: List[float]) -> Optional[dict]:
    if not xs:
        return None
    return {"p50": round(pct(xs, 0.50) * 1e3, 2),
            "p99": round(pct(xs, 0.99) * 1e3, 2),
            "p999": round(pct(xs, 0.999) * 1e3, 2),
            "max": round(max(xs) * 1e3, 2), "n": len(xs)}


class PhaseStats:
    """One phase's counters; workers update under the lock."""

    def __init__(self, name: str, duration_s: float, offered: int):
        self.name = name
        self.duration_s = float(duration_s)
        self.offered = int(offered)
        self.lock = threading.Lock()
        self.fired = 0
        self.accepted = 0
        self.shed = 0
        self.backpressure = 0
        self.dedup = 0
        self.errors = 0
        self.committed = 0
        self.conflicts = 0
        self.other_codes: Dict[str, int] = {}
        self.sojourns: List[float] = []      # arrival -> orderer ack
        self.commit_lat: List[float] = []    # arrival -> validation code
        self.evaluated = 0
        self.wall_s = 0.0
        self.max_skew_s = 0.0
        self.backlog_max = 0
        # per-client think-time shaping (phase key `think`): arrivals
        # pushed past the raw schedule by the owning client's delay
        self.think: Optional[dict] = None
        self.think_delayed = 0
        self.think_added_s = 0.0

    def report(self) -> dict:
        wall = max(self.wall_s, 1e-9)
        dur = max(self.duration_s, 1e-9)
        out = {
            "name": self.name, "duration_s": self.duration_s,
            "wall_s": round(self.wall_s, 3),
            "offered": self.offered,
            "offered_rate": round(self.offered / dur, 2),
            "fired": self.fired,
            "max_skew_s": round(self.max_skew_s, 4),
            "driver_backlog_max": self.backlog_max,
            "accepted": self.accepted,
            "accepted_rate": round(self.accepted / wall, 2),
            "evaluated": self.evaluated,
            "shed": self.shed,
            "shed_frac": round(self.shed / self.fired, 4)
            if self.fired else 0.0,
            "backpressure": self.backpressure,
            "dedup": self.dedup, "errors": self.errors,
            "committed": self.committed,
            "committed_rate": round(self.committed / wall, 2),
            "conflicts": self.conflicts,
            "conflict_frac": round(
                self.conflicts / (self.committed + self.conflicts), 4)
            if (self.committed + self.conflicts) else 0.0,
            "sojourn_ms": _lat_ms(self.sojourns),
            "commit_ms": _lat_ms(self.commit_lat),
        }
        if self.other_codes:
            out["other_codes"] = dict(self.other_codes)
        if self.think is not None:
            out["think"] = dict(self.think,
                                delayed=self.think_delayed,
                                added_s=round(self.think_added_s, 3))
        return out


class _Job:
    __slots__ = ("stats", "op", "env", "client_id", "t_arr", "track")

    def __init__(self, stats, op, env, client_id, t_arr, track):
        self.stats = stats
        self.op = op
        self.env = env
        self.client_id = client_id
        self.t_arr = t_arr
        self.track = track


class WorkloadRunner:
    """Run phases of open-loop load against one gateway peer.

    phases: [{"name": "ramp", "duration_s": 10,
              "arrivals": {"kind": "ramp", "end_rate": 80, ...}}, ...]
            a phase may carry an explicit "schedule": [offsets] instead
            of an arrivals spec (cold-start stampedes are hand-built).
    prepare: optional op -> Envelope hook; set -> pool mode (envelopes
            pre-endorsed before each phase starts firing).
    signer: needed for inline mode's assemble_transaction.
    """

    def __init__(self, clients: ClientPopulation, mix: TrafficMix,
                 phases: List[dict], *, signer=None,
                 prepare: Optional[Callable[[Op], object]] = None,
                 workers: int = 8, seed: int = 0,
                 submit_timeout_s: float = 15.0,
                 commit_timeout_s: float = 30.0,
                 track_commits: bool = True,
                 commit_every: int = 1,
                 drain_timeout_s: float = 45.0,
                 save_trace: Optional[str] = None):
        self.clients = clients
        self.mix = mix
        self.phases = list(phases)
        self.signer = signer
        self.prepare = prepare
        self.workers = int(workers)
        self.seed = int(seed)
        self.submit_timeout_s = float(submit_timeout_s)
        self.commit_timeout_s = float(commit_timeout_s)
        self.track_commits = bool(track_commits)
        # a commit_status wait parks a worker for the full commit
        # latency; tracking every k-th tx keeps the committed-rate
        # estimate honest without the tracker itself throttling the
        # open loop at overload rates
        self.commit_every = max(1, int(commit_every))
        self.drain_timeout_s = float(drain_timeout_s)
        # jsonl arrival trace: one {"phase", "i", "t"} line per fire
        # offset, replayable via {"kind": "trace", "path": ...}
        self.save_trace = save_trace
        self._jobs: "queue.Queue" = queue.Queue()
        self._outstanding = 0
        self._out_lock = threading.Lock()
        self._out_cv = threading.Condition(self._out_lock)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.phase_stats: List[PhaseStats] = []

    # -- op -> gateway call -------------------------------------------------

    @staticmethod
    def _call_shape(op: Op):
        """(fn, args) for an op against the built-in asset contract:
        writes are read-modify-write `bump`s (MVCC-conflictable), reads
        evaluate the same, ranges `scan` (phantom-conflictable)."""
        if op.kind == "range":
            return "scan", [op.key.encode(), (op.end_key
                                              or op.key).encode()]
        return "bump", [op.key.encode()]

    def _build_inline(self, gw, op: Op):
        fn, args = self._call_shape(op)
        sp, responses = gw.endorse(op.chaincode, fn, args,
                                   channel=op.channel)
        # the envelope signature must come from the proposal's creator:
        # mixed-identity populations carry per-connection signers
        return assemble_transaction(
            sp, responses, getattr(gw, "signer", None) or self.signer)

    def _execute(self, job: _Job) -> None:
        st = job.stats
        op = job.op
        gw = self.clients.conn_for(job.client_id)
        try:
            if op.kind == "read":
                # read path: evaluate only, nothing ordered
                fn, args = self._call_shape(op)
                gw.evaluate(op.chaincode, fn, args, channel=op.channel)
                now = time.monotonic()
                with st.lock:
                    st.evaluated += 1
                    st.sojourns.append(now - job.t_arr)
                self.clients.record(job.client_id)
                return
            env = job.env if job.env is not None \
                else self._build_inline(gw, op)
            out = gw.submit_envelope(env, timeout_s=self.submit_timeout_s)
            t_ack = time.monotonic()
            with st.lock:
                st.accepted += 1
                st.sojourns.append(t_ack - job.t_arr)
                if out.get("deduped"):
                    st.dedup += 1
            self.clients.record(job.client_id)
            if not job.track:
                return
            txid = env.header().channel_header.txid
            code, _ = gw.commit_status(txid, channel=op.channel,
                                       timeout_s=self.commit_timeout_s)
            t_commit = time.monotonic()
            with st.lock:
                st.commit_lat.append(t_commit - job.t_arr)
                if code == int(ValidationCode.VALID):
                    st.committed += 1
                elif code in _CONFLICT_CODES:
                    st.conflicts += 1
                else:
                    try:
                        name = ValidationCode(code).name
                    except ValueError:
                        name = str(code)
                    st.other_codes[name] = st.other_codes.get(name, 0) + 1
        except GatewayShedError:
            with st.lock:
                st.shed += 1
            self.clients.record(job.client_id, sheds=1)
        except GatewayError as exc:
            if exc.status == int(ValidationCode.MVCC_READ_CONFLICT) or \
                    exc.status in _CONFLICT_CODES:
                # submit_transaction-style conflict surfaced as an error
                with st.lock:
                    st.conflicts += 1
                self.clients.record(job.client_id)
            else:
                with st.lock:
                    st.errors += 1
                self.clients.record(job.client_id, error=True)
        except RpcError as exc:
            field = "backpressure" if "backpressure" in str(exc) \
                else "errors"
            with st.lock:
                setattr(st, field, getattr(st, field) + 1)
            self.clients.record(job.client_id,
                                error=(field == "errors"))
        except Exception:
            logger.exception("workload op failed")
            with st.lock:
                st.errors += 1
            self.clients.record(job.client_id, error=True)

    # -- worker pool --------------------------------------------------------

    def _worker(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            try:
                self._execute(job)
            finally:
                with self._out_cv:
                    self._outstanding -= 1
                    if self._outstanding == 0:
                        self._out_cv.notify_all()

    def _start_pool(self) -> None:
        self._threads = [
            threading.Thread(target=self._worker,
                             name=f"workload-{i}", daemon=True)
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    def _stop_pool(self) -> None:
        for _ in self._threads:
            self._jobs.put(None)
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []

    def _drain(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._out_cv:
            while self._outstanding > 0:
                left = deadline - time.monotonic()
                if left <= 0.0:
                    return False
                self._out_cv.wait(min(left, 0.25))
        return True

    # -- phases -------------------------------------------------------------

    def _run_phase(self, phase: dict, index: int) -> PhaseStats:
        name = str(phase.get("name", f"phase{index}"))
        if "schedule" in phase:
            schedule = [float(t) for t in phase["schedule"]]
            duration = float(phase.get(
                "duration_s", schedule[-1] if schedule else 0.0))
        else:
            duration = float(phase["duration_s"])
            proc = from_spec(phase["arrivals"],
                             seed=self.seed * 101 + index)
            schedule = proc.schedule(duration)
        stats = PhaseStats(name, duration, len(schedule))
        self.phase_stats.append(stats)
        if self.save_trace:
            import json as _json
            with open(self.save_trace, "a") as tf:
                for i, t in enumerate(schedule):
                    tf.write(_json.dumps(
                        {"phase": name, "i": i, "t": round(t, 6)}) + "\n")

        # pool mode: pre-endorse one envelope per scheduled arrival so
        # the open-loop phase pays ONLY admission+ordering per fire
        ops = [self.mix.next_op() for _ in schedule]
        envs: List[Optional[object]] = [None] * len(schedule)
        if self.prepare is not None:
            for i, op in enumerate(ops):
                if op.kind == "read":
                    continue
                while True:
                    try:
                        envs[i] = self.prepare(op)
                        break
                    except GatewayShedError as exc:
                        # pool building between phases rides out shed
                        # windows (endorsement sheds in every shed
                        # state): it is pre-load work, not part of the
                        # measured phase, so honoring the hint here
                        # never skews a phase's numbers
                        time.sleep(min(
                            max(exc.retry_after_ms, 50) / 1000.0, 1.0))

        # per-client open-loop think time (phase key `think`): pre-draw
        # the owning client per arrival, then push each client's ops at
        # least its think delay apart — the arrival process still sets
        # the AGGREGATE offered load, but each client's stream turns
        # bursty-with-pauses the way real submitters are.  The re-sort
        # keeps (offset, op, env, client) association intact.
        clients_for: Optional[List[int]] = None
        if phase.get("think"):
            model = ThinkTimeModel.from_spec(
                phase["think"], seed=self.seed * 211 + index)
            clients_for = [self.clients.next_client() for _ in schedule]
            last_at: Dict[int, float] = {}
            adjusted: List[float] = []
            for i, t in enumerate(schedule):
                c = clients_for[i]
                t2 = t
                prev = last_at.get(c)
                if prev is not None:
                    t2 = max(t, prev + model.delay(c))
                    if t2 > t:
                        stats.think_delayed += 1
                        stats.think_added_s += t2 - t
                last_at[c] = t2
                adjusted.append(t2)
            order = sorted(range(len(schedule)),
                           key=lambda i: (adjusted[i], i))
            schedule = [adjusted[i] for i in order]
            ops = [ops[i] for i in order]
            envs = [envs[i] for i in order]
            clients_for = [clients_for[i] for i in order]
            stats.think = model.describe()

        t_start = time.monotonic()

        def fire(i: int, offset: float) -> None:
            track = self.track_commits and i % self.commit_every == 0
            client = (clients_for[i] if clients_for is not None
                      else self.clients.next_client())
            job = _Job(stats, ops[i], envs[i],
                       client, time.monotonic(),
                       track)
            with self._out_cv:
                self._outstanding += 1
            backlog = self._jobs.qsize()
            if backlog > stats.backlog_max:
                stats.backlog_max = backlog
            self._jobs.put(job)
            stats.fired += 1

        sched = OpenLoopScheduler(schedule, fire)
        sched.run()                      # blocks for the phase duration
        if not self._drain(self.drain_timeout_s):
            logger.warning("phase %s: drain timed out with %d "
                           "outstanding ops", name, self._outstanding)
        stats.wall_s = time.monotonic() - t_start
        stats.max_skew_s = sched.max_skew_s
        return stats

    def run(self) -> dict:
        self._start_pool()
        try:
            for i, phase in enumerate(self.phases):
                self._run_phase(phase, i)
        finally:
            self._stop_pool()
        return self.report()

    # -- reporting ----------------------------------------------------------

    def report(self) -> dict:
        phases = [s.report() for s in self.phase_stats]
        tot = {k: sum(p[k] for p in phases) for k in
               ("offered", "fired", "accepted", "evaluated", "shed",
                "backpressure", "dedup", "errors", "committed",
                "conflicts")}
        wall = sum(p["wall_s"] for p in phases)
        all_sojourn = [x for s in self.phase_stats for x in s.sojourns]
        all_commit = [x for s in self.phase_stats for x in s.commit_lat]
        tot.update({
            "wall_s": round(wall, 3),
            "offered_rate": round(tot["offered"] / wall, 2)
            if wall else 0.0,
            "accepted_rate": round(tot["accepted"] / wall, 2)
            if wall else 0.0,
            "committed_rate": round(tot["committed"] / wall, 2)
            if wall else 0.0,
            "shed_frac": round(tot["shed"] / tot["fired"], 4)
            if tot["fired"] else 0.0,
            "conflict_frac": round(
                tot["conflicts"] / (tot["committed"] + tot["conflicts"]),
                4) if (tot["committed"] + tot["conflicts"]) else 0.0,
            "sojourn_ms": _lat_ms(all_sojourn),
            "commit_ms": _lat_ms(all_commit)})
        return {"seed": self.seed, "workers": self.workers,
                "commit_every": self.commit_every,
                "mode": "pool" if self.prepare is not None else "inline",
                "mix": self.mix.describe(),
                "clients": self.clients.totals(),
                "phases": phases, "totals": tot}
