"""Declarative adversarial scenario catalog: WAN shapes x Byzantine
actors x open-loop load, as plain dict specs.

Each scenario is ONE dict (no YAML, no DSL) composing the fault planes
this framework already owns:

  topology        ChaosNet shape: orderer count, peer orgs, peers/org
  links           per-link latency/loss matrix keyed "src->dst" (src =
                  dialing identity's mspid pattern, dst = "host:port"
                  pattern), compiled via FaultPlan.links — direction
                  matters, asymmetric WAN paths are two entries
  link_schedule   FaultSchedule kwargs enveloping every link rule
                  (windowed partitions, bursts riding the load burst)
  partition       {"org": ..., "window": [start_s, end_s]} — drop ALL
                  frames dialed by that org's identities inside the
                  window (a crash-stop org-level netsplit; heals and
                  must catch up via anti-entropy)
  adversaries     {"orderer1": crimes} -> testing.adversary actors that
                  LIE (equivocating deliver streams, tampered
                  attestation digests) behind real consenter keys
  poison          gossip-intake injection counts for a victim peer
                  (garbage / bad_sig / stale / one forged fork block)
  identity_blend  client creator mix over the signature schemes the MSP
                  accepts ({"p256": w, "ed25519": w}); idemix creators
                  are validated end-to-end by the idemix test lane —
                  channel-config idemix enrollment is a roadmap item
  fan_out         shard the ONE seeded arrival process across every
                  gateway peer (socket slot i -> peer i mod n) instead
                  of pinning the whole population to peer 0 — the load
                  shape fleet-lifecycle drills need, since a drill that
                  drains peers must see traffic ON the drained peer
  rolling_upgrade background drill: drain -> kill -> restart every node
                  one at a time under load (ChaosNet.rolling_restart),
                  recording pre/post heights for the no-regression gate
  membership_churn background drill: add a provisioned spare orderer
                  through an add-consenter config entry, start it,
                  transfer leadership onto it, then remove an original
                  consenter — all mid-traffic
  scale_out       background drill: N peers wiped + snapshot-bootstrapped
                  simultaneously from ONE source peer under load (the
                  elastic-join path; exercises concurrent chunk serving)
  gateway         gateway config override passed to every ChaosNet
                  peer (linger/max_batch/max_queue/admission) — how a
                  scenario throttles the drain rate STRUCTURALLY so
                  "overload" is a topology property, not a host-speed
                  measurement
  slo             scenario-owned SloEvaluator config (windows +
                  objective overrides; DEFAULT_OBJECTIVES merge in
                  unless disabled per-objective with enabled: False)
  incidents       IncidentRecorder config for `incidents` expect kinds
                  (cooldown_s, keep, profile_window_s, ...); bundles
                  land under <base_dir>/incidents and the report
                  carries their ids + MANIFEST verification verdicts
  profiler        SamplingProfiler config feeding incident bundles'
                  profile.json / profile_folded.txt
  phases          open-loop arrival phases (workload.runner format);
                  a phase's `think` key is a per-client think-time
                  spec ({"kind": "exponential", "mean_s": ...} or
                  {"kind": "lognormal", "median_s": ..., "sigma": ...},
                  workload.clients.ThinkTimeModel) delaying that
                  client's next arrival — seeded per client, so burst
                  clustering replays exactly
  expect          in-run SLO assertions, evaluated before the report is
                  written: convergence, quarantine counts BY REASON,
                  zero-quarantine guarantees for crash-stop-only runs,
                  shed/commit bounds, exactly-once (no duplicate txid
                  ever committed), incident-bundle presence/absence
                  (`incidents`: min/max count for an objective prefix +
                  MANIFEST verification)

Every run is seeded end to end (arrival schedules, fault draws, zipf
keys) and writes a JSON report artifact next to its data dir (or at
`report_path`), so a scenario is a reproducible experiment:

    env JAX_PLATFORMS=cpu python -m fabric_tpu.workload \
        --scenario equivocation --seed 7
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("fabric_tpu.workload.scenarios")

__all__ = ["SCENARIOS", "list_scenarios", "run_scenario",
           "ScenarioFailure"]


class ScenarioFailure(AssertionError):
    """Raised in strict mode when a scenario's `expect` block fails."""


# ---------------------------------------------------------------------------
# the catalog

SCENARIOS: Dict[str, dict] = {
    "geo-wan": {
        "description": "three regions on asymmetric WAN links (slow "
                       "trans-oceanic return paths, light loss); "
                       "diurnal load; everything honest — latency "
                       "reshapes tails, never safety",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1},
        "links": {
            "Org1->*": {"latency_s": 0.010, "loss": 0.005},
            "Org2->*": {"latency_s": 0.030, "loss": 0.01},
            "OrdererMSP->*": {"latency_s": 0.005, "loss": 0.0},
        },
        "phases": [
            # per-client lognormal think time rides the diurnal wave:
            # WAN users pause between submissions, so per-client
            # arrivals cluster instead of landing memorylessly
            {"name": "diurnal", "duration_s": 8.0,
             "arrivals": {"kind": "diurnal", "base_rate": 12.0,
                          "amplitude": 0.7, "period_s": 4.0},
             "think": {"kind": "lognormal", "median_s": 0.15,
                       "sigma": 0.8}},
        ],
        "expect": [
            {"kind": "converged", "min_height": 2},
            {"kind": "zero_quarantines"},
            {"kind": "min_committed", "value": 1},
            {"kind": "p99_ms", "objective": "commit_p99_s",
             "max_ms": 30000},
        ],
    },
    "equivocation": {
        "description": "orderer1 double-serves a forged, validly-signed "
                       "sibling at height 3 mid-ramp; every honest peer "
                       "must convict the signer from its witness, "
                       "persist a fraud proof, and converge exactly-once "
                       "on the honest chain",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1},
        "adversaries": {"orderer1": {"mode": "equivocate",
                                     "fork_height": 3, "count": 2}},
        "phases": [
            {"name": "ramp", "duration_s": 8.0,
             "arrivals": {"kind": "ramp", "start_rate": 4.0,
                          "end_rate": 20.0, "ramp_s": 6.0}},
        ],
        "expect": [
            {"kind": "converged", "min_height": 4},
            {"kind": "quarantine", "reasons": ["fork", "equivocation"],
             "min": 1, "on": "all_peers"},
            {"kind": "fraud_proofs", "min": 1, "on": "all_peers"},
            {"kind": "exactly_once"},
            {"kind": "min_committed", "value": 1},
        ],
    },
    "two-faced": {
        "description": "orderer1 keeps an honest raft face but "
                       "equivocates on deliver ONLY toward Org1's peer; "
                       "Org2's peer sees a spotless stream and must "
                       "still convict — via the victim's gossiped fraud "
                       "proof, independently re-verified — and every "
                       "peer demotes the convicted endpoints to last "
                       "resort while committing exactly-once",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1},
        "adversaries": {"orderer1": {"mode": "two_faced",
                                     "victims": ["Org1"],
                                     "fork_height": 3, "count": 2}},
        "phases": [
            {"name": "steady", "duration_s": 8.0,
             "arrivals": {"kind": "constant", "rate": 10.0}},
        ],
        "expect": [
            {"kind": "converged", "min_height": 4},
            {"kind": "quarantine", "reasons": ["fork", "equivocation"],
             "min": 1, "on": "all_peers"},
            {"kind": "fraud_proofs", "min": 1, "on": "all_peers"},
            {"kind": "exactly_once"},
            {"kind": "min_committed", "value": 1},
        ],
    },
    "gossip-poison": {
        "description": "a fake gossip endpoint floods one peer's intake "
                       "with garbage and tampered-signature payloads, "
                       "then injects a forged fork of a committed block; "
                       "the relay is score-quarantined, the forger "
                       "convicted, the ledger untouched",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1},
        "poison": {"victim": ["Org1", 0], "at_height": 2,
                   "garbage": 2, "bad_sig": 2, "stale": 3, "fork": True},
        "phases": [
            {"name": "steady", "duration_s": 8.0,
             "arrivals": {"kind": "constant", "rate": 10.0}},
        ],
        "expect": [
            {"kind": "converged", "min_height": 3},
            {"kind": "quarantine", "reasons": ["poison"], "min": 1,
             "on": "any_peer"},
            {"kind": "quarantine", "reasons": ["fork"], "min": 1,
             "on": "any_peer"},
            {"kind": "exactly_once"},
            {"kind": "min_committed", "value": 1},
        ],
    },
    "tampered-attestation": {
        "description": "orderer1 serves honest blocks but flips the "
                       "verdict-attestation digests riding its deliver "
                       "frames; the round-9 trust registry catches the "
                       "mismatch, the byzantine plane records the "
                       "conviction, peers re-verify and converge",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1},
        "adversaries": {"orderer1": {"mode": "tamper_attests",
                                     "fork_height": 2}},
        "phases": [
            {"name": "steady", "duration_s": 8.0,
             "arrivals": {"kind": "constant", "rate": 10.0}},
        ],
        "expect": [
            {"kind": "converged", "min_height": 3},
            {"kind": "quarantine", "reasons": ["tampered_attestation"],
             "min": 1, "on": "any_peer"},
            {"kind": "min_committed", "value": 1},
        ],
    },
    "snapshot-under-adversary": {
        "description": "the r12 wiped-peer snapshot rejoin with an "
                       "ACTIVE adversary: orderer1 equivocates mid-run, "
                       "then peerOrg2_0 is killed, its ledger wiped, and "
                       "it rejoins by snapshot with a quarantined source "
                       "listed first — the wipe-surviving registry must "
                       "steer the bootstrap to the honest source",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 2},
        "adversaries": {"orderer1": {"mode": "equivocate",
                                     "fork_height": 3, "count": 2}},
        "snapshot_rejoin": {"victim": "peerOrg2_0",
                            "quarantined_source": "peerOrg1_0",
                            "honest_source": "peerOrg1_1"},
        "phases": [
            {"name": "steady", "duration_s": 8.0,
             "arrivals": {"kind": "constant", "rate": 10.0}},
        ],
        "expect": [
            {"kind": "snapshot_rejoin"},
            {"kind": "converged", "min_height": 4, "timeout_s": 45.0},
            {"kind": "quarantine", "reasons": ["fork", "equivocation"],
             "min": 1, "on": "any_peer"},
            {"kind": "exactly_once"},
            {"kind": "min_committed", "value": 1},
            {"kind": "p99_ms", "objective": "commit_p99_s",
             "max_ms": 30000},
        ],
    },
    "mixed-identity": {
        "description": "P-256 and ed25519 creators blended through one "
                       "gateway under bursty load — the MSP's multi-"
                       "scheme acceptance exercised at traffic level, "
                       "zero quarantines expected",
        "topology": {"n_orderers": 1, "peer_orgs": ["Org1"],
                     "peers_per_org": 1},
        "identity_blend": {"p256": 0.5, "ed25519": 0.5},
        "mode": "inline",
        "phases": [
            {"name": "bursts", "duration_s": 8.0,
             "arrivals": {"kind": "burst", "low_rate": 3.0,
                          "high_rate": 12.0, "period_s": 3.0,
                          "duty": 0.4},
             "think": {"kind": "exponential", "mean_s": 0.1}},
        ],
        "expect": [
            {"kind": "converged", "min_height": 2},
            {"kind": "zero_quarantines"},
            {"kind": "exactly_once"},
            {"kind": "min_committed", "value": 1},
        ],
    },
    "soak-compressed": {
        "description": "2-org compressed soak under steady open-loop "
                       "load with the resource collector sampling "
                       "RSS/fd/thread/GC into the timeseries ring; the "
                       "leak gate runs Theil-Sen over the soak window "
                       "and must find every gated series FLAT (slope "
                       "CI spanning zero or immaterial growth) — the "
                       "ROADMAP #4 leak/regression gate at smoke "
                       "length",
        "topology": {"n_orderers": 1, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1},
        # observe: scenario-owned TimeSeriesStore + ResourceCollector
        # over the process-global registry (ChaosNet nodes share the
        # process, so one collector sees the whole cluster's resources).
        # warmup_s must outlast link establishment: the client pool is
        # warm-dialed up front, but gossip/state-transfer links dial
        # lazily on their first round ~3-6 s into the load — a one-time
        # step the gate should never even see
        "observe": {"interval_s": 0.25, "warmup_s": 6.0},
        # a clean soak must also capture ZERO incident bundles: the
        # recorder arms on the shed-rate objective only (the default
        # objectives are host-timing-sensitive and would make "zero
        # bundles" a flaky claim), and an unshedding soak never burns it
        "slo": {
            "sample_interval_s": 0.5, "short_window_s": 5.0,
            "long_window_s": 15.0,
            "objectives": {
                "shed_rate": {"kind": "max", "source": "counter_rate",
                              "metric": "gateway_shed_total",
                              "threshold": 1.0,
                              "help": "gateway sheds per second"},
                "commit_p99_s": {"enabled": False},
                "verify_throughput_floor": {"enabled": False},
                "breaker_open_frac": {"enabled": False},
                "overlap_floor": {"enabled": False},
            }},
        "phases": [
            {"name": "soak", "duration_s": 15.0,
             "arrivals": {"kind": "constant", "rate": 10.0},
             "think": {"kind": "exponential", "mean_s": 0.2}},
        ],
        "expect": [
            {"kind": "converged", "min_height": 2},
            {"kind": "min_committed", "value": 1},
            {"kind": "zero_quarantines"},
            {"kind": "incidents", "max": 0},
            # fd/thread counts must be dead flat at steady state; RSS
            # and allocator blocks grow legitimately with committed
            # ledger state under a 90%-write mix, so their thresholds
            # gate the RATE of growth, not its existence — an injected
            # leak (a steady retain of fds/objects) still blows
            # through, a one-time step never fires (slope CI hits 0)
            {"kind": "leak_free", "series": {
                "process_open_fds": {"max_growth_frac": 0.10},
                "process_threads": {"max_growth_frac": 0.10},
                "process_resident_memory_bytes":
                    {"max_growth_frac": 0.30},
                "process_allocated_blocks": {"max_growth_frac": 0.40},
            }},
        ],
    },
    "overload-incident": {
        "description": "structurally throttled gateway flooded at ~5x "
                       "its drain ceiling: the admission plane sheds, "
                       "the shed-rate SLO burns, and the flight data "
                       "recorder must capture EXACTLY ONE verifiable "
                       "incident bundle naming that objective — the "
                       "self-diagnosing-overload drill",
        "topology": {"n_orderers": 1, "peer_orgs": ["Org1"],
                     "peers_per_org": 1},
        # max_batch 2 + 250ms linger caps the drain rate structurally
        # (~8 tx/s), so "overload" is a topology property, not a host-
        # speed measurement; the short queue forces shedding within the
        # first burn window
        "gateway": {"linger_s": 0.25, "max_batch": 2, "max_queue": 16,
                    "broadcast_deadline_s": 20.0,
                    "admission": {"enabled": True,
                                  "queue_high_frac": 0.25,
                                  "latency_slo_s": 0.4, "dwell_s": 0.5,
                                  "recover_ratio": 0.6,
                                  "eval_interval_s": 0.05,
                                  "retry_after_base_ms": 50}},
        # only the shed-rate objective is armed (defaults disabled):
        # the drill must prove the bundle names the RIGHT objective,
        # so no other objective may fire first.  cooldown outlasts the
        # run -> "exactly one" is deterministic, not a race
        "slo": {
            "sample_interval_s": 0.25, "short_window_s": 2.0,
            "long_window_s": 6.0,
            "objectives": {
                "shed_rate": {"kind": "max", "source": "counter_rate",
                              "metric": "gateway_shed_total",
                              "threshold": 1.0,
                              "help": "gateway sheds per second"},
                "commit_p99_s": {"enabled": False},
                "verify_throughput_floor": {"enabled": False},
                "breaker_open_frac": {"enabled": False},
                "overlap_floor": {"enabled": False},
            }},
        "incidents": {"cooldown_s": 600.0, "keep": 4,
                      "profile_window_s": 30.0},
        "profiler": {"hz": 19.0, "window_s": 2.0},
        "mode": "pool",
        "phases": [
            {"name": "flood", "duration_s": 8.0,
             "arrivals": {"kind": "constant", "rate": 40.0}},
            # the cool-down lets in-flight batches drain so the
            # converged gate sees a quiesced ledger
            {"name": "cool", "duration_s": 4.0,
             "arrivals": {"kind": "constant", "rate": 1.0}},
        ],
        "expect": [
            {"kind": "incidents", "min": 1, "max": 1,
             "objective": "shed_rate"},
            {"kind": "min_committed", "value": 1},
            {"kind": "converged", "min_height": 1},
        ],
    },
    "rolling-upgrade": {
        "description": "drain -> restart every node one at a time while "
                       "the open loop keeps firing across all gateway "
                       "peers: each node must hand off cleanly (orderers "
                       "transfer leadership, peers checkpoint), come "
                       "back from disk without losing committed height, "
                       "and the fleet must end converged with every "
                       "txid committed exactly once and ZERO "
                       "quarantines — an upgrade is not a crime",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1},
        "fan_out": True,
        # inline mode: endorsement happens at fire time, so the drill
        # overlaps real traffic (pool mode would pre-endorse everything
        # against a peer the drill is about to drain)
        "mode": "inline",
        "rolling_upgrade": {"after_s": 2.0, "drain_timeout_s": 6.0,
                            "settle_s": 60.0},
        "phases": [
            {"name": "steady", "duration_s": 14.0,
             "arrivals": {"kind": "constant", "rate": 12.0}},
        ],
        "expect": [
            {"kind": "rolling_upgrade"},
            {"kind": "no_height_regression"},
            {"kind": "converged", "min_height": 2, "timeout_s": 90.0},
            {"kind": "exactly_once"},
            {"kind": "zero_quarantines"},
            {"kind": "min_committed", "value": 1},
            {"kind": "sojourn_p99_ms", "max_ms": 30000},
        ],
    },
    "membership-churn": {
        "description": "dynamic consenter set under load: a provisioned "
                       "spare orderer is added through an add-consenter "
                       "config entry riding the raft log itself, "
                       "started, handed leadership, then an original "
                       "consenter is removed by a second config entry — "
                       "the removed node self-evicts, every remaining "
                       "node drops it from the signed-entry verifier, "
                       "and throughput/exactly-once hold throughout "
                       "with zero false-positive quarantines",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1, "spare_orderers": 1},
        "membership_churn": {"after_s": 2.0, "remove": "orderer1"},
        "phases": [
            {"name": "steady", "duration_s": 14.0,
             "arrivals": {"kind": "constant", "rate": 10.0}},
        ],
        "expect": [
            {"kind": "membership_churn"},
            {"kind": "converged", "min_height": 2, "timeout_s": 90.0},
            {"kind": "exactly_once"},
            {"kind": "zero_quarantines"},
            {"kind": "min_committed", "value": 1},
        ],
    },
    "elastic-scale-out": {
        "description": "elastic join: two peers are wiped and snapshot-"
                       "bootstrap SIMULTANEOUSLY from one serving peer "
                       "while that peer is also carrying the client "
                       "load — both must install the same checkpoint "
                       "generation (the chunk server lease-pins it "
                       "against concurrent checkpoint GC), join deliver "
                       "at snapshot height, and converge with the fleet",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 2},
        "scale_out": {"source": "peerOrg1_0",
                      "joiners": ["peerOrg2_0", "peerOrg2_1"],
                      "after_s": 3.0},
        "phases": [
            {"name": "steady", "duration_s": 12.0,
             "arrivals": {"kind": "constant", "rate": 10.0}},
        ],
        "expect": [
            {"kind": "scale_out"},
            {"kind": "converged", "min_height": 3, "timeout_s": 90.0},
            {"kind": "exactly_once"},
            {"kind": "zero_quarantines"},
            {"kind": "min_committed", "value": 1},
        ],
    },
    "burst-partition": {
        "description": "square-wave bursts while Org2's outbound links "
                       "black-hole for a mid-run window (crash-stop "
                       "netsplit, nobody lies): the partitioned peer "
                       "falls behind, heals, anti-entropy catches it up "
                       "— and the byzantine plane must stay SILENT",
        "topology": {"n_orderers": 3, "peer_orgs": ["Org1", "Org2"],
                     "peers_per_org": 1},
        "partition": {"org": "Org2", "window": [2.0, 5.0]},
        "phases": [
            {"name": "bursts", "duration_s": 8.0,
             "arrivals": {"kind": "burst", "low_rate": 4.0,
                          "high_rate": 16.0, "period_s": 4.0,
                          "duty": 0.35}},
        ],
        "expect": [
            {"kind": "converged", "min_height": 2,
             "timeout_s": 45.0},
            {"kind": "zero_quarantines"},
            {"kind": "min_committed", "value": 1},
        ],
    },
}


def list_scenarios() -> List[str]:
    return sorted(SCENARIOS)


# ---------------------------------------------------------------------------
# spec -> fault plan

def build_plan(spec: dict, seed: int):
    """Compile a scenario's links/partition into one installed-ready
    FaultPlan (or None when the spec declares neither)."""
    from fabric_tpu.comm.faults import FaultPlan, FaultSchedule
    links = spec.get("links")
    part = spec.get("partition")
    if not links and not part:
        return None
    plan = FaultPlan(seed=seed * 977 + 5)
    if links:
        matrix = {}
        for key, props in links.items():
            src, _, dst = key.partition("->")
            matrix[(src, dst or "*")] = props
        plan.links(matrix, schedule=spec.get("link_schedule"))
    if part:
        lo, hi = part.get("window", [0.0, 3.0])
        plan.rule(src=str(part.get("org", "*")), drop=1.0,
                  schedule=FaultSchedule(kind="window", start_s=float(lo),
                                         end_s=float(hi)))
    return plan


# ---------------------------------------------------------------------------
# gossip poisoning injection

def _poison_thread(net, spec: dict, sent: dict) -> threading.Thread:
    """Background injector: waits for the victim to commit past
    `at_height`, then lands the configured offenses + one forged fork
    block (signed with a real consenter key pulled from an orderer)."""
    pcfg = dict(spec.get("poison") or {})
    org, idx = pcfg.get("victim", ["Org1", 0])

    def _run() -> None:
        from fabric_tpu.testing.adversary import (
            GossipPoisoner, forge_fork_block)
        deadline = time.time() + 30.0
        victim = None
        at = int(pcfg.get("at_height", 2))
        while time.time() < deadline:
            peers = [p for n, p in net.nodes.items()
                     if net._specs[n][0] == "peer"
                     and n.startswith(f"peer{org}")]
            if peers and idx < len(peers):
                ch = peers[idx].channels[net.channel_id]
                if ch.ledger.height > at:
                    victim = ch
                    break
            time.sleep(0.1)
        if victim is None:
            logger.warning("poison: victim never reached height %d", at)
            return
        poisoner = GossipPoisoner(victim)
        # fork first: once the offense flood quarantines the relay,
        # its frames are pre-dropped at intake and never reach the
        # witness — the forger must be convicted while the relay is
        # still being heard
        if pcfg.get("fork"):
            orderer = net.orderers()[0]
            forged = forge_fork_block(
                victim.ledger.blockstore, at, orderer.signer)
            poisoner.inject(forged)
        poisoner.garbage(int(pcfg.get("garbage", 0)))
        poisoner.bad_sig(int(pcfg.get("bad_sig", 0)))
        poisoner.stale(int(pcfg.get("stale", 0)))
        sent.update(poisoner.sent)

    t = threading.Thread(target=_run, name="scenario-poison", daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# wiped-peer snapshot rejoin under an active adversary

def _snapshot_rejoin(net, spec: dict) -> dict:
    """The r12 wiped-peer rejoin drill with standing: kill + wipe the
    victim peer's ledger, seed its quarantine registry (which SURVIVES
    the wipe — it is node-scoped, not ledger-scoped) with the first
    snapshot source's identity — the conviction a gossiped fraud proof
    left in the victim's previous life — then restart it with the
    convicted source listed FIRST.  The rejoining peer must refuse that
    source and bootstrap from the honest one behind it."""
    import shutil
    from fabric_tpu.byzantine import QuarantineRegistry
    from fabric_tpu.orderer.cluster import cert_fingerprint

    cfg = dict(spec["snapshot_rejoin"])
    victim = str(cfg.get("victim", "peerOrg2_0"))
    evil = str(cfg.get("quarantined_source", "peerOrg1_0"))
    honest = str(cfg.get("honest_source", "peerOrg1_1"))
    out: dict = {"victim": victim, "quarantined_source": evil,
                 "honest_source": honest}

    def _load_cfg(name):
        with open(net._specs[name][1]) as f:
            c = json.load(f)
        return c

    evil_cfg, honest_cfg, vcfg = (_load_cfg(evil), _load_cfg(honest),
                                  _load_cfg(victim))
    evil_addr = [evil_cfg.get("host", "127.0.0.1"), int(evil_cfg["port"])]
    honest_addr = [honest_cfg.get("host", "127.0.0.1"),
                   int(honest_cfg["port"])]
    evil_node = net.nodes[evil]
    evil_key = (f"{evil_node.signer.mspid}|"
                f"{cert_fingerprint(evil_node.signer.cert)}")

    net.kill(victim)
    ledger_root = os.path.join(vcfg["data_dir"], "channels",
                               net.channel_id, "ledger")
    if not os.path.isdir(ledger_root):
        ledger_root = os.path.join(vcfg["data_dir"], "ledger")
    shutil.rmtree(ledger_root, ignore_errors=True)
    QuarantineRegistry(
        os.path.join(vcfg["data_dir"], "byzantine_quarantine.json")
    ).quarantine(evil_key, "equivocation")
    vcfg["bootstrap_snapshot"] = {
        "enabled": True, "from": [evil_addr, honest_addr],
        "chunk_timeout_s": 2.0, "attempts": 4}
    with open(net._specs[victim][1], "w") as f:
        json.dump(vcfg, f)
    node = net.restart(victim)
    ch = node.channels[net.channel_id]
    info = getattr(ch, "snapshot_bootstrap", None)
    out["bootstrap"] = info
    out["base"] = int(ch.ledger.blockstore.base)
    src = list(info.get("from", [])) if info else None
    out["from_honest"] = src == list(honest_addr)
    out["refused_quarantined"] = src != list(evil_addr)
    return out


# ---------------------------------------------------------------------------
# fleet lifecycle drills (background threads riding the load phases)

def _admin_call(net, admin, msps, method: str, body: dict,
                timeout_s: float = 30.0,
                retry_on=("not_leader",)):
    """Issue one admin RPC against whichever running orderer currently
    leads: walk the consenters, follow not_leader verdicts (and any
    other status named in `retry_on`), retry until something terminal
    comes back.  Returns (orderer-name, response) — (None, last-error)
    when the deadline lapses."""
    from fabric_tpu.comm.rpc import connect
    deadline = time.time() + timeout_s
    last = None
    while time.time() < deadline:
        for oname, (kind, _) in list(net._specs.items()):
            if kind != "orderer" or oname not in net.nodes:
                continue
            try:
                conn = connect(net.orderer_addr(oname), admin, msps,
                               timeout=5.0)
                try:
                    out = conn.call(method, body, timeout=10.0)
                finally:
                    conn.close()
                if out.get("status") not in retry_on:
                    return oname, out
                last = out
            except Exception as exc:       # dial/refusal: try the next
                last = {"error": str(exc)}
        time.sleep(0.2)
    return None, last


def _load_admin(net):
    """(admin-signer, msps) for the first peer org — membership RPCs
    are Admins-gated, and a peer-org admin of the bootstrap channel
    satisfies the orderer's participation gate."""
    from fabric_tpu.node.orderer import load_signing_identity
    org = sorted(net.paths["admins"])[0]
    with open(net.paths["admins"][org]) as f:
        ac = json.load(f)
    admin = load_signing_identity(
        ac["mspid"], ac["cert_pem"].encode(), ac["key_pem"].encode())
    return admin, net.peers()[0].msps


def _rolling_upgrade_thread(net, spec: dict, out: dict) -> threading.Thread:
    """Background rolling restart: after `after_s`, drain -> kill ->
    restart every running node one at a time while the open loop keeps
    firing.  Pre/post heights land in `out` for the no-regression gate."""
    rcfg = dict(spec.get("rolling_upgrade") or {})

    def _run() -> None:
        time.sleep(float(rcfg.get("after_s", 2.0)))
        out["pre_heights"] = net.heights()
        try:
            out["drains"] = net.rolling_restart(
                drain_timeout_s=float(rcfg.get("drain_timeout_s", 6.0)),
                settle_s=float(rcfg.get("settle_s", 60.0)))
        except Exception as exc:
            logger.exception("rolling upgrade drill failed")
            out["error"] = str(exc)
        out["post_heights"] = net.heights()
        out["regressed"] = sorted(
            n for n, h in out["pre_heights"].items()
            if out["post_heights"].get(n, 0) < h)
        out["done"] = True

    t = threading.Thread(target=_run, name="scenario-roll", daemon=True)
    t.start()
    return t


def _membership_churn_thread(net, spec: dict, out: dict) -> threading.Thread:
    """Background membership churn: add the provisioned spare consenter
    through the log, start it, transfer leadership onto it, remove an
    original consenter, then prove the removed node is out — every
    remaining consenter's raft node set excludes it, the removed node
    self-evicted, and (once killed) the fleet keeps committing without
    it."""
    mcfg = dict(spec.get("membership_churn") or {})

    def _wait(pred, timeout_s: float) -> bool:
        deadline = time.time() + float(timeout_s)
        while time.time() < deadline:
            try:
                if pred():
                    return True
            except Exception:
                pass
            time.sleep(0.1)
        return False

    def _run() -> None:
        time.sleep(float(mcfg.get("after_s", 2.0)))
        try:
            admin, msps = _load_admin(net)
            spare = net.spare_names()[0]
            scfg = net.spare_cfg(spare)
            spare_rid = int(scfg["raft_id"])
            out["spare"] = spare

            # 1. add-consenter config entry THROUGH the raft log
            who, resp = _admin_call(net, admin, msps, "admin.add_consenter",
                                    {"raft_id": spare_rid,
                                     "host": scfg.get("host", "127.0.0.1"),
                                     "port": int(scfg["port"]),
                                     "mspid": scfg["mspid"],
                                     "cert_fp": scfg["cert_fp"]})
            out["add"] = {"via": who, "resp": resp}
            if who is None or resp.get("status") != "proposed":
                out["error"] = f"add_consenter failed: {resp}"
                return

            # 2. start the spare; it replicates the log (including its
            # own add entry) from the leader and becomes a voter
            spare_node = net.restart(spare)
            out["added_joined"] = _wait(
                lambda: spare_rid in spare_node.support.chain.node.nodes
                and spare_node.support.chain.node.applied_index
                >= int(resp.get("index", 1)), 30.0)

            # 3. leadership onto the NEW consenter (the gap-free
            # handover the drain path uses; retried — a transfer is a
            # request, the target still has to win its election)
            def _spare_leads():
                return spare_node.support.chain.node.role == "leader"
            deadline = time.time() + 30.0
            while not _spare_leads() and time.time() < deadline:
                _admin_call(net, admin, msps, "admin.transfer_leadership",
                            {"to": spare_rid}, timeout_s=5.0,
                            retry_on=("not_leader", "refused"))
                _wait(_spare_leads, 2.0)
            out["leader_transferred"] = _spare_leads()

            # 4. remove an ORIGINAL consenter by a second config entry
            victim = str(mcfg.get("remove", "orderer1"))
            with open(net._specs[victim][1]) as f:
                victim_rid = int(json.load(f)["raft_id"])
            victim_node = net.nodes[victim]
            who, resp = _admin_call(net, admin, msps,
                                    "admin.remove_consenter",
                                    {"raft_id": victim_rid})
            out["remove"] = {"via": who, "resp": resp, "node": victim}
            if who is None or resp.get("status") != "proposed":
                out["error"] = f"remove_consenter failed: {resp}"
                return

            # 5. the removal must take everywhere: remaining consenters
            # drop the victim from their raft node sets (its entries are
            # rejected at the consenter-authorization gate from the
            # commit point forward) and the victim self-evicts
            remaining = [net.nodes[n] for n, (k, _) in net._specs.items()
                         if k == "orderer" and n in net.nodes
                         and n != victim]
            out["removed_isolated"] = _wait(
                lambda: all(victim_rid not in o.support.chain.node.nodes
                            for o in remaining), 30.0)
            out["removed_self_evicted"] = _wait(
                lambda: victim_rid
                not in victim_node.support.chain.node.nodes, 30.0)
            # decommission the now-external process; deliver clients
            # fail over and the fleet must keep committing without it
            net.kill(victim)
        except Exception as exc:
            logger.exception("membership churn drill failed")
            out["error"] = str(exc)
        finally:
            out["done"] = True

    t = threading.Thread(target=_run, name="scenario-churn", daemon=True)
    t.start()
    return t


def _scale_out_thread(net, spec: dict, out: dict) -> threading.Thread:
    """Background elastic scale-out: wipe N peers and snapshot-bootstrap
    them SIMULTANEOUSLY from one source peer that is still serving the
    client load — the concurrent-fetch path the chunk server's
    generation leases exist for."""
    scfg = dict(spec.get("scale_out") or {})

    def _run() -> None:
        import shutil
        time.sleep(float(scfg.get("after_s", 3.0)))
        try:
            source = str(scfg.get("source", "peerOrg1_0"))
            joiners = [str(j) for j in (scfg.get("joiners") or [])]
            with open(net._specs[source][1]) as f:
                src_cfg = json.load(f)
            src_addr = [src_cfg.get("host", "127.0.0.1"),
                        int(src_cfg["port"])]
            # the source needs a snapshotable history first (pool-mode
            # pre-endorsement can hold the load back for a while, so
            # this wait is generous)
            deadline = time.time() + 120.0
            while time.time() < deadline:
                src = net.nodes.get(source)
                if src is not None and \
                        src.channels[net.channel_id].ledger.height >= 2:
                    break
                time.sleep(0.1)
            out["source"] = source
            results: Dict[str, dict] = {}

            def _join(name: str) -> None:
                try:
                    with open(net._specs[name][1]) as f:
                        vcfg = json.load(f)
                    net.kill(name)
                    root = os.path.join(vcfg["data_dir"], "channels",
                                        net.channel_id, "ledger")
                    if not os.path.isdir(root):
                        root = os.path.join(vcfg["data_dir"], "ledger")
                    shutil.rmtree(root, ignore_errors=True)
                    vcfg["bootstrap_snapshot"] = {
                        "enabled": True, "from": [src_addr],
                        "chunk_timeout_s": 5.0, "attempts": 6}
                    with open(net._specs[name][1], "w") as f:
                        json.dump(vcfg, f)
                    node = net.restart(name)
                    ch = node.channels[net.channel_id]
                    results[name] = {
                        "bootstrap": getattr(ch, "snapshot_bootstrap",
                                             None),
                        "base": int(ch.ledger.blockstore.base),
                        "height": int(ch.ledger.height)}
                except Exception as exc:
                    logger.exception("scale-out join of %s failed", name)
                    results[name] = {"error": str(exc)}

            threads = [threading.Thread(target=_join, args=(n,),
                                        name=f"scale-out-{n}",
                                        daemon=True) for n in joiners]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=120.0)
            out["joiners"] = results
        except Exception as exc:
            logger.exception("scale-out drill failed")
            out["error"] = str(exc)
        finally:
            out["done"] = True

    t = threading.Thread(target=_run, name="scenario-scale", daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# byzantine state collection + SLO evaluation

def _byz_state(net) -> dict:
    out = {}
    for name, node in net.nodes.items():
        byz = getattr(node, "byzantine", None)
        if byz is None:
            continue
        kind = net._specs[name][0]
        chans = {}
        if kind == "peer":
            for cid, ch in getattr(node, "channels", {}).items():
                mon = getattr(ch, "byz_monitor", None)
                if mon is not None:
                    chans[cid] = mon.snapshot()
                pg = getattr(ch, "proof_gossip", None)
                if pg is not None:
                    chans.setdefault(cid, {})["proof_gossip"] = \
                        pg.snapshot()
        else:
            # orderers carry per-channel monitors too (r14); their
            # registry reads identically but quarantine expectations
            # are judged against PEERS, so kind rides along
            for cid, mon in getattr(node, "byz_monitors", {}).items():
                chans[cid] = mon.snapshot()
        out[name] = {"kind": kind,
                     "quarantined": byz.count(),
                     "reasons": byz.reasons(),
                     "identities": sorted(byz.snapshot()),
                     "channels": chans}
    return out


def _committed_txids(peer, channel_id: str) -> List[str]:
    """Every txid committed on one peer, in block order — the raw
    material of the exactly-once assertion."""
    from fabric_tpu.protocol.types import Envelope
    store = peer.channels[channel_id].ledger.blockstore
    txids: List[str] = []
    # a snapshot-rejoined peer has no blocks below its snapshot base;
    # exactly-once is judged over what the store actually holds
    base = int(getattr(store, "base", 0) or 0)
    for num in range(base, store.height):
        for raw in store.get_by_number(num).data:
            try:
                hdr = Envelope.deserialize(bytes(raw)).header()
                txid = hdr.channel_header.txid
            except Exception:
                continue
            if txid:
                txids.append(txid)
    return txids


def _check_expectations(spec: dict, net, report: dict,
                        slo_eval=None, ts_store=None) -> List[str]:
    """Evaluate the `expect` block; returns human-readable violations
    (empty = all SLOs held)."""
    violations: List[str] = []
    byz = report["byzantine"]
    # quarantine/fraud-proof expectations are judged against PEERS:
    # orderer nodes carry registries of their own (r14) and "all_peers"
    # must not demand a conviction from the adversary's own process
    peers = {n: s for n, s in byz.items()
             if s.get("kind", "peer") == "peer"}
    tot = report.get("totals", {})
    for check in spec.get("expect", []):
        kind = check["kind"]
        if kind == "converged":
            ok = net.wait_converged(
                timeout_s=float(check.get("timeout_s", 30.0)),
                min_height=check.get("min_height"))
            report["converged"] = ok
            report["heights"] = net.heights()
            if not ok:
                violations.append(
                    f"converged: peers diverged or stalled "
                    f"(heights={net.heights()})")
        elif kind == "zero_quarantines":
            # every node kind: crash-stop faults must be silent on
            # orderer registries too
            noisy = {n: s["reasons"] for n, s in byz.items()
                     if s["quarantined"]}
            if noisy:
                violations.append(
                    f"zero_quarantines: false positives {noisy}")
            loud = {n: ch["proof_gossip"]["broadcasts"]
                    for n, s in byz.items()
                    for ch in s["channels"].values()
                    if ch.get("proof_gossip", {}).get("broadcasts")}
            if loud:
                violations.append(
                    f"zero_quarantines: fraud proofs broadcast with "
                    f"nothing to prove {loud}")
        elif kind == "quarantine":
            reasons = check.get("reasons", [])
            need = int(check.get("min", 1))
            hits = {n: sum(s["reasons"].get(r, 0) for r in reasons)
                    for n, s in peers.items()}
            quorum = (all if check.get("on", "any_peer") == "all_peers"
                      else any)
            if not peers or not quorum(v >= need for v in hits.values()):
                violations.append(
                    f"quarantine[{','.join(reasons)}]: wanted >={need} "
                    f"on {check.get('on', 'any_peer')}, got {hits}")
        elif kind == "fraud_proofs":
            need = int(check.get("min", 1))
            hits = {n: sum(c.get("fraud_proofs", 0)
                           for c in s["channels"].values())
                    for n, s in peers.items()}
            quorum = (all if check.get("on", "any_peer") == "all_peers"
                      else any)
            if not peers or not quorum(v >= need for v in hits.values()):
                violations.append(
                    f"fraud_proofs: wanted >={need}, got {hits}")
        elif kind == "min_committed":
            if tot.get("committed", 0) < int(check["value"]):
                violations.append(
                    f"min_committed: {tot.get('committed', 0)} < "
                    f"{check['value']}")
        elif kind == "max_shed_frac":
            if tot.get("shed_frac", 0.0) > float(check["value"]):
                violations.append(
                    f"max_shed_frac: {tot.get('shed_frac')} > "
                    f"{check['value']}")
        elif kind == "p99_ms":
            # latency-percentile assertion fed from the SLO evaluator's
            # WINDOWED quantiles (ops_plane/slo.py) — the same numbers
            # /slo serves in production, not a whole-run average
            obj_name = check.get("objective", "commit_p99_s")
            limit = float(check["max_ms"])
            value_ms = None
            if slo_eval is not None:
                try:
                    slo_eval.step()      # force one final sample+eval
                except Exception:
                    logger.exception("slo evaluator step failed")
                for obj in slo_eval.status().get("objectives", []):
                    if obj.get("name") != obj_name:
                        continue
                    v = obj.get("value_short")
                    if v is None:
                        v = obj.get("value_long")
                    if v is not None:
                        value_ms = round(float(v) * 1000.0, 3)
            report.setdefault("latency_p99_ms", {})[obj_name] = value_ms
            if value_ms is None:
                violations.append(
                    f"p99_ms[{obj_name}]: no windowed quantile observed")
            elif value_ms > limit:
                violations.append(
                    f"p99_ms[{obj_name}]: {value_ms}ms > {limit}ms")
        elif kind == "incidents":
            # the flight-data-recorder assertion: overload-shaped runs
            # must capture a bundle NAMING the burning objective
            # (min>=1); clean runs must capture none (max=0) — a bundle
            # on a healthy run is itself a regression
            inc = report.get("incidents") or {}
            bundles = inc.get("bundles") or []
            obj = check.get("objective")
            if obj is not None:
                bundles = [b for b in bundles
                           if str(b.get("objective", "")).startswith(obj)]
            need = int(check.get("min", 0))
            cap = check.get("max")
            tag = f"incidents[{obj or '*'}]"
            got = [(b["id"], b.get("objective")) for b in bundles]
            if len(bundles) < need:
                violations.append(
                    f"{tag}: wanted >={need} bundle(s), got {got}")
            if cap is not None and len(bundles) > int(cap):
                violations.append(
                    f"{tag}: wanted <={cap} bundle(s), got {got}")
            bad = [b["id"] for b in bundles if not b.get("verified")]
            if bad:
                violations.append(
                    f"{tag}: MANIFEST verification failed for {bad}")
        elif kind == "snapshot_rejoin":
            sr = report.get("snapshot_rejoin") or {}
            if sr.get("base", 0) <= 0:
                violations.append(
                    f"snapshot_rejoin: no snapshot installed ({sr})")
            elif not sr.get("refused_quarantined"):
                violations.append(
                    f"snapshot_rejoin: bootstrapped from the "
                    f"quarantined source ({sr})")
            elif not sr.get("from_honest"):
                violations.append(
                    f"snapshot_rejoin: honest source not used ({sr})")
        elif kind == "leak_free":
            # Theil-Sen slope gate over the scenario's timeseries ring
            # (ops_plane/timeseries.py): each gated series must stay
            # flat over the soak — slope CI spanning zero, or growth an
            # immaterial fraction of the level.  The verdicts (slope +
            # CI per series) land in the report either way, so an
            # honest run documents its flatness evidence.
            if ts_store is None:
                violations.append(
                    "leak_free: no timeseries store (spec needs an "
                    "`observe` block)")
                continue
            from fabric_tpu.ops_plane import timeseries as _ts
            obs = dict(spec.get("observe", {}))
            gate = _ts.evaluate_leak_gate(
                ts_store, dict(check.get("series", {})),
                window_s=float(check.get("window_s", 1e9)),
                warmup_s=float(obs.get("warmup_s", 0.0)))
            report["leak_gate"] = gate
            for name in gate["leaking"]:
                v = gate["series"][name]
                violations.append(
                    f"leak_free[{name}]: slope "
                    f"{v['slope_per_s']:.4g}/s (95% CI "
                    f"[{v['ci_lo']:.4g}, {v['ci_hi']:.4g}]), "
                    f"+{v['growth_frac']:.1%} over {v['span_s']:.1f}s "
                    f"soak (limit {v['max_growth_frac']:.0%})")
            missing = [n for n, v in gate["series"].items()
                       if v.get("verdict") == "insufficient_data"]
            if missing:
                violations.append(
                    f"leak_free: insufficient samples for {missing}")
        elif kind == "rolling_upgrade":
            ru = report.get("rolling_upgrade") or {}
            if not ru.get("done"):
                violations.append("rolling_upgrade: drill never finished")
            elif ru.get("error"):
                violations.append(f"rolling_upgrade: {ru['error']}")
            else:
                stuck = {n: r for n, r in (ru.get("drains") or {}).items()
                         if r.get("lifecycle") != "drained"}
                if stuck:
                    violations.append(
                        f"rolling_upgrade: nodes never drained {stuck}")
        elif kind == "no_height_regression":
            ru = report.get("rolling_upgrade") or {}
            if ru.get("regressed"):
                violations.append(
                    f"no_height_regression: committed height lost on "
                    f"{ru['regressed']} (pre={ru.get('pre_heights')}, "
                    f"post={ru.get('post_heights')})")
        elif kind == "membership_churn":
            mc = report.get("membership_churn") or {}
            if not mc.get("done"):
                violations.append("membership_churn: drill never finished")
            elif mc.get("error"):
                violations.append(f"membership_churn: {mc['error']}")
            else:
                for flag in ("added_joined", "leader_transferred",
                             "removed_isolated", "removed_self_evicted"):
                    if not mc.get(flag):
                        violations.append(
                            f"membership_churn: {flag} is false ({mc})")
        elif kind == "scale_out":
            so = report.get("scale_out") or {}
            joiners = so.get("joiners") or {}
            if not so.get("done") or not joiners:
                violations.append(
                    f"scale_out: drill incomplete ({so})")
            elif so.get("error"):
                violations.append(f"scale_out: {so['error']}")
            else:
                for name, r in joiners.items():
                    if r.get("error"):
                        violations.append(
                            f"scale_out[{name}]: {r['error']}")
                    elif int(r.get("base", 0) or 0) <= 0:
                        violations.append(
                            f"scale_out[{name}]: no snapshot installed "
                            f"(joined from genesis, base="
                            f"{r.get('base')})")
        elif kind == "sojourn_p99_ms":
            # accepted-path tail straight off the runner's totals:
            # arrival -> orderer ack for every ADMITTED submission
            v = (tot.get("sojourn_ms") or {}).get("p99")
            limit = float(check["max_ms"])
            if v is None:
                violations.append("sojourn_p99_ms: nothing accepted")
            elif float(v) > limit:
                violations.append(
                    f"sojourn_p99_ms: {v}ms > {limit}ms")
        elif kind == "exactly_once":
            dup_peers = {}
            for name, node in net.nodes.items():
                if net._specs[name][0] != "peer":
                    continue
                txids = _committed_txids(node, net.channel_id)
                if len(txids) != len(set(txids)):
                    dup_peers[name] = len(txids) - len(set(txids))
            report["exactly_once"] = not dup_peers
            if dup_peers:
                violations.append(
                    f"exactly_once: duplicate commits {dup_peers}")
        else:
            violations.append(f"unknown expect kind {kind!r}")
    return violations


# ---------------------------------------------------------------------------
# the runner

def run_scenario(name: str, seed: int = 7,
                 base_dir: Optional[str] = None,
                 report_path: Optional[str] = None,
                 strict: bool = False) -> dict:
    """Provision, attack, load, assert, report.

    Returns the report dict (also written as a JSON artifact).  With
    `strict=True` a failed `expect` block raises ScenarioFailure AFTER
    the artifact is written — the evidence survives the assertion.
    """
    spec = SCENARIOS.get(name)
    if spec is None:
        raise KeyError(f"unknown scenario {name!r} "
                       f"(one of {list_scenarios()})")
    from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
    from fabric_tpu.comm import faults
    from fabric_tpu.gateway import GatewayClient
    from fabric_tpu.node.orderer import load_signing_identity
    from fabric_tpu.testing.chaos import ChaosNet
    from fabric_tpu.workload.clients import ClientPopulation
    from fabric_tpu.workload.keyspace import TrafficMix
    from fabric_tpu.workload.runner import WorkloadRunner

    init_factories(FactoryOpts(default="SW"))
    own_tmp = None
    if base_dir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix=f"scenario_{name}_")
        base_dir = own_tmp.name
        if report_path is None:
            # the artifact must outlive the scratch network dir
            report_path = os.path.join(
                tempfile.gettempdir(), f"scenario_{name}_report.json")
    report: dict = {"scenario": name, "seed": seed,
                    "description": spec.get("description", ""),
                    "spec": {k: v for k, v in spec.items()
                             if k != "description"}}

    factory = None
    adversaries = spec.get("adversaries")
    if adversaries:
        from fabric_tpu.testing.adversary import adversary_factory
        factory = adversary_factory(adversaries)
    topo = dict(spec.get("topology", {}))
    net = ChaosNet(base_dir,
                   n_orderers=int(topo.get("n_orderers", 3)),
                   peer_orgs=tuple(topo.get("peer_orgs", ["Org1"])),
                   peers_per_org=int(topo.get("peers_per_org", 1)),
                   node_factory=factory,
                   spare_orderers=int(topo.get("spare_orderers", 0)),
                   gateway_cfg=(dict(spec["gateway"])
                                if spec.get("gateway") else None))
    plan = build_plan(spec, seed)
    poison_sent: dict = {}
    clients = None
    # scenario-owned SLO evaluator over the process-global metrics
    # registry: ChaosNet nodes run without ops servers, so p99_ms
    # expectations sample here — tight windows sized to drill length
    slo_eval = None
    if any(c.get("kind") in ("p99_ms", "incidents")
           for c in spec.get("expect", [])):
        from fabric_tpu.ops_plane import slo as _slo
        slo_cfg = {"sample_interval_s": 0.5,
                   "short_window_s": 10.0,
                   "long_window_s": 60.0}
        slo_cfg.update(spec.get("slo", {}))
        slo_eval = _slo.SloEvaluator(slo_cfg)
        slo_eval.start()
    # scenario-owned incident recorder (+ sampling profiler feeding its
    # bundles): `incidents` expect kinds assert that overload-shaped
    # runs capture a bundle naming the burning objective — and that
    # clean runs capture none
    incident_rec = None
    profiler = None
    if any(c.get("kind") == "incidents" for c in spec.get("expect", [])):
        from fabric_tpu.ops_plane import incidents as _inc
        from fabric_tpu.ops_plane import sampler as _sampler
        profiler = _sampler.SamplingProfiler(
            dict(spec.get("profiler", {})))
        profiler.start()
        inc_cfg = dict(spec.get("incidents", {}))
        inc_cfg.setdefault("dir", os.path.join(base_dir, "incidents"))
        incident_rec = _inc.IncidentRecorder(
            inc_cfg, node_name=f"scenario:{name}", profiler=profiler)
        incident_rec.attach_slo(slo_eval)
    # scenario-owned timeseries ring + resource collector (the leak
    # gate's evidence): ChaosNet nodes share this process, so one
    # collector watching the process-global registry sees the whole
    # cluster's RSS/fd/thread/GC/cache series
    ts_store = None
    ts_collector = None
    drills: List[threading.Thread] = []
    drill_out: Dict[str, dict] = {}
    if spec.get("observe") or any(c.get("kind") == "leak_free"
                                  for c in spec.get("expect", [])):
        from fabric_tpu.ops_plane import resources as _res
        from fabric_tpu.ops_plane import timeseries as _tsm
        obs = dict(spec.get("observe", {}))
        interval = float(obs.get("interval_s", 0.25))
        ts_store = _tsm.TimeSeriesStore({"interval_s": interval})
        ts_collector = _res.ResourceCollector({"interval_s": interval})
    if incident_rec is not None and ts_store is not None:
        incident_rec.timeseries = ts_store
    try:
        net.start()
        if plan is not None:
            faults.install(plan)
        poison = (None if not spec.get("poison")
                  else _poison_thread(net, spec, poison_sent))

        # -- fleet lifecycle drills (ride the load in the background) --
        for key, launch in (("rolling_upgrade", _rolling_upgrade_thread),
                            ("membership_churn", _membership_churn_thread),
                            ("scale_out", _scale_out_thread)):
            if spec.get(key):
                drill_out[key] = {}
                drills.append(launch(net, spec, drill_out[key]))

        # -- client population (identity blend over schemes) ----------
        org = list(topo.get("peer_orgs", ["Org1"]))[0]
        blend = dict(spec.get("identity_blend") or {"p256": 1.0})
        signers = {}
        with open(net.paths["clients"][org]) as f:
            cc = json.load(f)
        signers["p256"] = load_signing_identity(
            cc["mspid"], cc["cert_pem"].encode(), cc["key_pem"].encode())
        if blend.get("ed25519"):
            with open(net.paths["clients_ed25519"][org]) as f:
                ce = json.load(f)
            signers["ed25519"] = load_signing_identity(
                ce["mspid"], ce["cert_pem"].encode(),
                ce["key_pem"].encode())
        sockets = 6
        total_w = sum(blend.values()) or 1.0
        ed_slots = int(round(sockets * blend.get("ed25519", 0.0)
                             / total_w))
        peer = net.peers()[0]
        # fan-out: ONE seeded arrival process sharded across every
        # gateway peer (slot i -> peer i mod n) — lifecycle drills need
        # traffic ON the node being drained, not a spectator fleet
        gw_peers = (list(net.peers()) if spec.get("fan_out")
                    else [peer])
        gw_addrs = [p.rpc.addr for p in gw_peers]

        def _factory(slot: int):
            scheme = "ed25519" if slot < ed_slots else "p256"
            return GatewayClient(gw_addrs[slot % len(gw_addrs)],
                                 signers[scheme],
                                 peer.msps, channel_id=net.channel_id,
                                 seed=seed * 1000 + slot,
                                 call_timeout=30.0)

        clients = ClientPopulation(512, sockets, factory=_factory,
                                   seed=seed)
        clients.warm()

        traffic = dict(spec.get("traffic", {}))
        mix = TrafficMix([{
            "channel": net.channel_id, "chaincode": "assets",
            "weight": 1.0, "keys": int(traffic.get("keys", 64)),
            "zipf_s": float(traffic.get("zipf_s", 1.0)),
            "blend": traffic.get("blend", {"read": 0.1, "write": 0.9}),
        }], seed=seed)

        prepare = None
        prep_gw = None
        if spec.get("mode", "pool") == "pool":
            from fabric_tpu.endorser.proposal import assemble_transaction
            prep_gw = GatewayClient(peer.rpc.addr, signers["p256"],
                                    peer.msps, channel_id=net.channel_id,
                                    shed_retry_max=0)

            def prepare(op):
                fn, args = WorkloadRunner._call_shape(op)
                sp, responses = prep_gw.endorse(op.chaincode, fn, args,
                                                channel=op.channel)
                return assemble_transaction(sp, responses,
                                            signers["p256"])

        runner = WorkloadRunner(clients, mix, list(spec["phases"]),
                                signer=signers["p256"], prepare=prepare,
                                workers=8, seed=seed)
        if ts_store is not None:
            # sampling starts at load start, not at provisioning: the
            # startup ramp (node boot, client warm) is not soak
            # evidence; the observe block's warmup_s still trims the
            # worker spin-up at the window head
            ts_collector.start()
            ts_store.start()
        report.update(runner.run())
        if prep_gw is not None:
            prep_gw.close()
        if poison is not None:
            poison.join(timeout=30.0)
            report["poison_sent"] = dict(poison_sent)
        if plan is not None:
            faults.uninstall()
            plan = None

        # -- post-run drills ------------------------------------------
        if spec.get("snapshot_rejoin"):
            report["snapshot_rejoin"] = _snapshot_rejoin(net, spec)
        for d in drills:
            d.join(timeout=300.0)
        for key, out_d in drill_out.items():
            report[key] = dict(out_d)

        # -- post-run evidence + SLO evaluation ------------------------
        report["byzantine"] = _byz_state(net)
        crimes = {}
        for n, node in net.nodes.items():
            cc_list = getattr(node, "crimes_committed", None)
            if cc_list:
                crimes[n] = list(cc_list)
        if crimes:
            report["crimes"] = crimes
        for p in net.peers():
            if getattr(p, "slo", None) is not None:
                report.setdefault("slo_alerts", {})[
                    p.name if hasattr(p, "name") else "peer"] = \
                    p.slo.alerts_snapshot()
                break
        if ts_store is not None:
            # one final sweep so the gate's window reaches run end
            if ts_collector is not None:
                ts_collector.collect()
            ts_store.step()
            ts_store.stop()
            ts_collector.stop()
        if incident_rec is not None:
            # the alert's capture thread may still be writing; the
            # expectation must see the landed bundle, not the race
            if slo_eval is not None:
                slo_eval.step()
            incident_rec.drain(30.0)
            from fabric_tpu.ops_plane.incidents import verify_bundle
            bundles = []
            for meta in incident_rec.list():
                bpath = os.path.join(incident_rec.dir, meta["id"])
                bundles.append(dict(
                    meta, path=bpath,
                    verified=verify_bundle(bpath)["ok"]))
            report["incidents"] = {"dir": incident_rec.dir,
                                   "bundles": bundles}
        violations = _check_expectations(spec, net, report,
                                         slo_eval=slo_eval,
                                         ts_store=ts_store)
        report["slo"] = {"pass": not violations,
                         "checks": len(spec.get("expect", [])),
                         "violations": violations}
    finally:
        # lifecycle drills drive kill/restart on their own threads: let
        # them finish before the net (and its tmpdir) is torn down, or
        # teardown races a mid-restart node
        for d in drills:
            d.join(timeout=300.0)
        if slo_eval is not None:
            slo_eval.stop()
        if incident_rec is not None:
            incident_rec.stop()
        if profiler is not None:
            profiler.stop()
        if ts_collector is not None:
            ts_collector.stop()
        if ts_store is not None:
            ts_store.stop()
        if plan is not None:
            faults.uninstall()
        if clients is not None:
            clients.close()
        net.stop_all()
        out = report_path or os.path.join(
            base_dir, f"scenario_{name}_report.json")
        try:
            with open(out, "w") as f:
                json.dump(report, f, indent=2, default=str, sort_keys=True)
            report["report_path"] = out
        except OSError:
            logger.exception("scenario report not written: %s", out)
        if own_tmp is not None:
            own_tmp.cleanup()
    violations = report.get("slo", {}).get("violations")
    if strict and violations:
        raise ScenarioFailure(f"{name}: " + "; ".join(violations))
    return report
