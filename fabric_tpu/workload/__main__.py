"""CLI: boot an in-process network, run a named open-loop scenario,
print the JSON report.

    env JAX_PLATFORMS=cpu python -m fabric_tpu.workload \
        --scenario ramp --rate 40 --duration 12 --zipf-s 1.1

Scenario catalog (all seeded; --rate R is the nominal offered rate):

  poisson          constant-rate Poisson at R for the whole run
  diurnal          sinusoid day/night swing around R
  burst            square-wave: R/5 baseline, 2R bursts
  ramp             ramp 0 -> 2R, hold at 2R, then recover at R/5 —
                   the saturation probe (watch shed states + hysteresis)
  stampede         cold-start: half the run's arrivals crammed into the
                   first second, then steady R
  reconnect-storm  steady R with every pooled socket cut mid-run

The booted peer runs with admission ENABLED (aggressive thresholds so
short runs exhibit shedding) and a tight SLO evaluator.  The report
carries the runner's per-phase offered/accepted/committed rates and
sojourn percentiles plus the gateway's admission snapshot (state
transitions included) and client-perceived shed counters.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time
from typing import Optional

from fabric_tpu.bccsp.factory import FactoryOpts, init_factories
from fabric_tpu.config import BatchConfig
from fabric_tpu.endorser.proposal import assemble_transaction
from fabric_tpu.gateway import GatewayClient
from fabric_tpu.node.orderer import OrdererNode, load_signing_identity
from fabric_tpu.node.peer import PeerNode
from fabric_tpu.node.provision import provision_network
from fabric_tpu.workload.clients import ClientPopulation
from fabric_tpu.workload.keyspace import TrafficMix
from fabric_tpu.workload.runner import WorkloadRunner


def build_phases(scenario: str, rate: float, duration: float,
                 seed: int) -> list:
    """Scenario name -> phase list for the WorkloadRunner."""
    r = float(rate)
    d = float(duration)
    if scenario == "poisson":
        return [{"name": "steady", "duration_s": d,
                 "arrivals": {"kind": "constant", "rate": r}}]
    if scenario == "diurnal":
        return [{"name": "diurnal", "duration_s": d,
                 "arrivals": {"kind": "diurnal", "base_rate": r,
                              "amplitude": 0.8, "period_s": d / 2.0}}]
    if scenario == "burst":
        return [{"name": "bursts", "duration_s": d,
                 "arrivals": {"kind": "burst", "low_rate": r / 5.0,
                              "high_rate": 2.0 * r,
                              "period_s": max(d / 3.0, 2.0),
                              "duty": 0.3}}]
    if scenario == "ramp":
        ramp_d = d * 0.5
        hold_d = d * 0.25
        rec_d = d * 0.25
        return [
            {"name": "ramp", "duration_s": ramp_d,
             "arrivals": {"kind": "ramp", "start_rate": max(r / 10.0, 1.0),
                          "end_rate": 2.0 * r, "ramp_s": ramp_d}},
            {"name": "hold_2x", "duration_s": hold_d,
             "arrivals": {"kind": "constant", "rate": 2.0 * r}},
            {"name": "recover", "duration_s": rec_d,
             "arrivals": {"kind": "constant", "rate": r / 5.0}},
        ]
    if scenario == "stampede":
        import random as _random
        n = max(4, int(r * d / 2.0))
        rnd = _random.Random(seed * 53 + 1)
        front = sorted(rnd.uniform(0.0, 1.0) for _ in range(n))
        return [
            {"name": "stampede", "duration_s": 1.0, "schedule": front},
            {"name": "tail", "duration_s": max(d - 1.0, 1.0),
             "arrivals": {"kind": "constant", "rate": r}},
        ]
    if scenario == "reconnect-storm":
        return [{"name": "steady+storm", "duration_s": d,
                 "arrivals": {"kind": "constant", "rate": r}}]
    raise SystemExit(f"unknown scenario {scenario!r}")


def boot(base: str, n_orderers: int, admission: dict, slo: dict,
         max_queue: int, gateway: Optional[dict] = None):
    paths = provision_network(
        base, n_orderers=n_orderers, peer_orgs=["Org1"], peers_per_org=1,
        batch=BatchConfig(max_message_count=32, timeout_s=0.05))
    orderers, peers = [], []
    for p in paths["orderers"]:
        with open(p) as f:
            cfg = json.load(f)
        cfg["ops_port"] = 0
        orderers.append(OrdererNode(cfg, data_dir=cfg["data_dir"]).start())
    for p in paths["peers"]:
        with open(p) as f:
            cfg = json.load(f)
        gw_cfg = {"linger_s": 0.005, "max_batch": 64,
                  "max_queue": max_queue,
                  "admission": admission}
        gw_cfg.update(gateway or {})
        cfg["gateway"] = gw_cfg
        cfg["slo"] = slo
        cfg["ops_port"] = 0
        peers.append(PeerNode(cfg, data_dir=cfg["data_dir"]).start())
    deadline = time.time() + 60
    while time.time() < deadline:
        if any(o.support.chain.node.role == "leader" for o in orderers):
            return paths, orderers, peers
        time.sleep(0.2)
    raise SystemExit("no raft leader elected")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fabric_tpu.workload",
        description="open-loop workload scenarios against an in-process "
                    "network")
    from fabric_tpu.workload import scenarios as _scenarios
    ap.add_argument("--scenario", default="ramp",
                    choices=["poisson", "diurnal", "burst", "ramp",
                             "stampede", "reconnect-storm"]
                    + _scenarios.list_scenarios(),
                    help="load-shape scenarios run a single peer under "
                         "admission pressure; catalog scenarios "
                         f"({', '.join(_scenarios.list_scenarios())}) "
                         "run full adversarial topologies with in-run "
                         "SLO assertions")
    ap.add_argument("--rate", type=float, default=30.0,
                    help="nominal offered rate (tx/s)")
    ap.add_argument("--duration", type=float, default=12.0,
                    help="total run seconds")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--keys", type=int, default=256,
                    help="keyspace size per channel")
    ap.add_argument("--zipf-s", type=float, default=1.1,
                    help="key skew (0 = uniform)")
    ap.add_argument("--reads", type=float, default=0.2,
                    help="read fraction of the op blend")
    ap.add_argument("--ranges", type=float, default=0.05,
                    help="range-scan fraction of the op blend")
    ap.add_argument("--population", type=int, default=10000,
                    help="simulated client identities")
    ap.add_argument("--sockets", type=int, default=8,
                    help="pooled gateway connections")
    ap.add_argument("--workers", type=int, default=16,
                    help="driver worker threads")
    ap.add_argument("--orderers", type=int, default=1)
    ap.add_argument("--max-queue", type=int, default=128,
                    help="gateway admission queue bound")
    ap.add_argument("--inline", action="store_true",
                    help="endorse per arrival instead of pre-endorsing "
                         "an envelope pool")
    ap.add_argument("--no-commits", action="store_true",
                    help="skip per-tx commit tracking")
    ap.add_argument("--commit-every", type=int, default=1,
                    help="track commit status for every k-th tx only "
                         "(keeps the driver open-loop at high rates)")
    ap.add_argument("--json-out", help="write the report here too")
    ap.add_argument("--save-trace",
                    help="append every fired arrival offset to this "
                         "jsonl file (replay later with a "
                         '{"kind": "trace", "path": ...} arrival spec)')
    ap.add_argument("--strict", action="store_true",
                    help="catalog scenarios: exit non-zero when an "
                         "in-run SLO assertion fails")
    args = ap.parse_args(argv)

    if args.scenario in _scenarios.SCENARIOS:
        try:
            report = _scenarios.run_scenario(
                args.scenario, seed=args.seed,
                report_path=args.json_out, strict=args.strict)
        except _scenarios.ScenarioFailure as exc:
            print(f"SLO FAILED: {exc}", file=sys.stderr)
            return 1
        print(json.dumps(report, indent=2, default=str))
        slo = report.get("slo", {})
        print(f"slo: {'PASS' if slo.get('pass') else 'FAIL'} "
              f"({slo.get('checks', 0)} checks)", file=sys.stderr)
        return 0

    init_factories(FactoryOpts(default="SW"))
    # aggressive admission thresholds: a dozen-second run must traverse
    # the shed ladder, so queue pressure maps steeply into severity
    admission = {"enabled": True, "queue_high_frac": 0.5,
                 "latency_slo_s": 1.5, "dwell_s": 1.0,
                 "recover_ratio": 0.7, "eval_interval_s": 0.05,
                 "seed": args.seed}
    slo = {"sample_interval_s": 1.0, "short_window_s": 5.0,
           "long_window_s": 15.0}
    report: dict = {"scenario": args.scenario, "rate": args.rate,
                    "duration_s": args.duration, "seed": args.seed}
    with tempfile.TemporaryDirectory() as base:
        print(f"booting {args.orderers} orderer(s) + 1 peer ...",
              file=sys.stderr)
        paths, orderers, peers = boot(base, args.orderers, admission, slo,
                                      args.max_queue)
        peer = peers[0]
        with open(paths["clients"]["Org1"]) as f:
            cc = json.load(f)
        signer = load_signing_identity(
            cc["mspid"], cc["cert_pem"].encode(), cc["key_pem"].encode())

        mix = TrafficMix([{
            "channel": "ch", "chaincode": "assets", "weight": 1.0,
            "keys": args.keys, "zipf_s": args.zipf_s,
            "blend": {"read": args.reads,
                      "write": max(0.0, 1.0 - args.reads - args.ranges),
                      "range": args.ranges}}], seed=args.seed)
        clients = ClientPopulation(
            args.population, args.sockets,
            factory=lambda slot: GatewayClient(
                peer.rpc.addr, signer, peer.msps, channel_id="ch",
                seed=args.seed * 1000 + slot),
            seed=args.seed)
        clients.warm()

        prepare = None
        if not args.inline:
            # pre-endorse through a dedicated client with shed retries
            # OFF so pool building never races the load it precedes
            prep_gw = GatewayClient(peer.rpc.addr, signer, peer.msps,
                                    channel_id="ch", shed_retry_max=0)

            def prepare(op):
                fn, call_args = WorkloadRunner._call_shape(op)
                sp, responses = prep_gw.endorse(
                    op.chaincode, fn, call_args, channel=op.channel)
                return assemble_transaction(sp, responses, signer)

        phases = build_phases(args.scenario, args.rate, args.duration,
                              args.seed)
        runner = WorkloadRunner(
            clients, mix, phases, signer=signer, prepare=prepare,
            workers=args.workers, seed=args.seed,
            track_commits=not args.no_commits,
            commit_every=args.commit_every,
            save_trace=args.save_trace)

        storm = None
        if args.scenario == "reconnect-storm":
            storm = threading.Timer(
                args.duration / 2.0,
                lambda: print(f"reconnect storm: cut "
                              f"{clients.reconnect_storm(1.0)} sockets",
                              file=sys.stderr))
            storm.daemon = True
            storm.start()

        print(f"running {args.scenario} "
              f"(~{args.rate:.0f} tx/s x {args.duration:.0f}s, "
              f"zipf_s={args.zipf_s}) ...", file=sys.stderr)
        try:
            report.update(runner.run())
        finally:
            if storm is not None:
                storm.cancel()
            gw = peer.gateway
            if gw is not None:
                report["admission"] = gw.admission.snapshot()
            clients.close()
            if prepare is not None:
                prep_gw.close()
            for n in peers + orderers:
                try:
                    n.stop()
                except Exception:
                    pass
    out = json.dumps(report, indent=2, default=str)
    print(out)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
