"""Client population: millions of client IDENTITIES over few SOCKETS.

A million-user deployment does not mean a million TCP connections —
SDKs pool and multiplex — but it does mean a million independent
client *behaviours*: distinct submitter ids, skewed per-client issue
rates, and correlated pathologies (everyone reconnecting at once after
a load balancer blip, everyone arriving cold at market open).  This
module models exactly that split:

  ClientPopulation(population, sockets)  maps a Zipf-skewed draw over
      `population` client ids onto `sockets` pooled GatewayClient
      connections (client_id % sockets), so per-client bookkeeping
      scales with the population while the OS fd table scales with the
      pool.

Scenarios (both seeded, both composable with any arrival process):

  reconnect_storm(fraction)   close that fraction of pooled sockets at
      once; the next op on each redials, modelling the post-blip dial
      stampede that turns a hiccup into an outage.
  stampede_schedule(n, window_s)  a cold-start burst: n arrivals
      crammed into the first window_s (uniform, seeded) — prepend to
      any schedule for the market-open profile.
  ThinkTimeModel              per-client open-loop think time: each
      client id gets its own seeded exponential/lognormal delay stream,
      so a client's successive ops are spaced like a human's (bursts
      and pauses), not like a Poisson process's — the per-client burst
      structure is what cross-block conflict drills need to look real.
"""

from __future__ import annotations

import math
import random
import threading
from typing import Callable, Dict, List, Optional

from fabric_tpu.workload.keyspace import ZipfSampler

__all__ = ["ClientPopulation", "ThinkTimeModel"]


class ThinkTimeModel:
    """Seeded per-client think-time delays.

    Scenario-dict spec (WorkloadRunner phase key `think`):
        {"kind": "exponential", "mean_s": 0.5}
        {"kind": "lognormal", "median_s": 0.3, "sigma": 1.0}

    Each client id owns an independent `random.Random` stream derived
    from (seed, client_id), so the k-th think delay of client c is a
    pure function of (spec, seed, c, k): re-running a scenario replays
    the exact same per-client burst pattern regardless of how other
    clients' draws interleave."""

    KINDS = ("exponential", "lognormal")

    def __init__(self, kind: str = "exponential", mean_s: float = 0.5,
                 median_s: float = 0.3, sigma: float = 1.0,
                 seed: int = 0):
        if kind not in self.KINDS:
            raise ValueError(f"unknown think-time kind {kind!r} "
                             f"(one of {self.KINDS})")
        self.kind = kind
        self.mean_s = float(mean_s)
        self.median_s = float(median_s)
        self.sigma = float(sigma)
        self.seed = int(seed)
        self._streams: Dict[int, random.Random] = {}

    @classmethod
    def from_spec(cls, spec: dict, seed: int = 0) -> "ThinkTimeModel":
        kind = str(spec.get("kind", "exponential"))
        return cls(kind=kind,
                   mean_s=float(spec.get("mean_s", 0.5)),
                   median_s=float(spec.get("median_s", 0.3)),
                   sigma=float(spec.get("sigma", 1.0)),
                   seed=seed)

    def _stream(self, client_id: int) -> random.Random:
        rng = self._streams.get(client_id)
        if rng is None:
            rng = self._streams[client_id] = random.Random(
                (self.seed * 1_000_003) ^ (int(client_id) * 2_654_435_761))
        return rng

    def delay(self, client_id: int) -> float:
        """The client's next think delay (seconds, >= 0)."""
        rng = self._stream(client_id)
        if self.kind == "exponential":
            return rng.expovariate(1.0 / self.mean_s) \
                if self.mean_s > 0 else 0.0
        # lognormal parameterized by its median: exp(mu) = median_s
        mu = math.log(self.median_s) if self.median_s > 0 else 0.0
        return rng.lognormvariate(mu, self.sigma)

    def describe(self) -> dict:
        d = {"kind": self.kind, "seed": self.seed}
        if self.kind == "exponential":
            d["mean_s"] = self.mean_s
        else:
            d.update(median_s=self.median_s, sigma=self.sigma)
        return d


class _ClientStats:
    __slots__ = ("ops", "sheds", "retries", "errors")

    def __init__(self):
        self.ops = 0
        self.sheds = 0
        self.retries = 0
        self.errors = 0


class ClientPopulation:
    """A seeded population of client ids multiplexed over a socket pool.

    `factory(slot)` builds one pooled connection (a GatewayClient, or
    any object with .close()); slots dial lazily on first use unless
    `warm()` is called (the cold-start stampede dials them all at
    once).  Thread-safe: the arrival scheduler's pool workers draw
    client ids and resolve sockets concurrently.
    """

    def __init__(self, population: int, sockets: int,
                 factory: Callable[[int], object],
                 skew_s: float = 1.0, seed: int = 0):
        if population < 1 or sockets < 1:
            raise ValueError("population and sockets must be >= 1")
        self.population = int(population)
        self.sockets = int(sockets)
        self.factory = factory
        # per-client issue-rate skew: heavy users exist in every real
        # population, and they are the ones whose dedup/shed behaviour
        # matters (same identity retrying through the same socket)
        self._sampler = ZipfSampler(self.population, skew_s, seed=seed)
        self._rand = random.Random(seed * 31 + 7)
        self._lock = threading.Lock()
        self._conns: Dict[int, object] = {}
        self.stats: Dict[int, _ClientStats] = {}
        self.dials = 0
        self.reconnects = 0

    # -- id / socket resolution -------------------------------------------

    def next_client(self) -> int:
        """Draw a client id (1-based rank; 1 = heaviest user)."""
        return self._sampler.rank()

    def slot_of(self, client_id: int) -> int:
        return (client_id - 1) % self.sockets

    def conn_for(self, client_id: int):
        """The pooled connection this client id multiplexes over,
        dialing the slot on first use."""
        slot = self.slot_of(client_id)
        with self._lock:
            conn = self._conns.get(slot)
            if conn is None:
                conn = self.factory(slot)
                self._conns[slot] = conn
                self.dials += 1
            return conn

    def warm(self) -> int:
        """Dial every slot NOW — the cold-start stampede's opening move
        (and the fixture step for latency runs that should not charge
        the first arrivals for dials)."""
        for slot in range(self.sockets):
            with self._lock:
                if slot in self._conns:
                    continue
                self._conns[slot] = self.factory(slot)
                self.dials += 1
            # actually establish the socket (a GatewayClient dials
            # lazily on first call — "warm" must mean connected)
            warm = getattr(self._conns[slot], "warm", None)
            if warm is not None:
                warm()
        return self.sockets

    # -- per-client bookkeeping -------------------------------------------

    def record(self, client_id: int, *, sheds: int = 0, retries: int = 0,
               error: bool = False) -> None:
        with self._lock:
            st = self.stats.get(client_id)
            if st is None:
                st = self.stats[client_id] = _ClientStats()
            st.ops += 1
            st.sheds += sheds
            st.retries += retries
            if error:
                st.errors += 1

    def totals(self) -> dict:
        with self._lock:
            ops = sum(s.ops for s in self.stats.values())
            sheds = sum(s.sheds for s in self.stats.values())
            retries = sum(s.retries for s in self.stats.values())
            errors = sum(s.errors for s in self.stats.values())
            shed_clients = sum(1 for s in self.stats.values() if s.sheds)
            return {"population": self.population,
                    "sockets": self.sockets,
                    "active_clients": len(self.stats),
                    "ops": ops, "sheds": sheds, "retries": retries,
                    "errors": errors,
                    "clients_shed": shed_clients,
                    "client_shed_frac": (shed_clients / len(self.stats)
                                         if self.stats else 0.0),
                    "dials": self.dials, "reconnects": self.reconnects}

    # -- scenarios ---------------------------------------------------------

    def reconnect_storm(self, fraction: float = 1.0) -> int:
        """Close `fraction` of the live pooled sockets simultaneously
        (seeded choice).  The next op on each slot redials — so a storm
        at time T turns into a dial burst riding on top of whatever the
        arrival process is already offering."""
        with self._lock:
            live = sorted(self._conns)
            n = max(1, int(len(live) * min(max(fraction, 0.0), 1.0))) \
                if live else 0
            victims = self._rand.sample(live, n) if n else []
            conns = [self._conns.pop(s) for s in victims]
            self.reconnects += len(conns)
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
        return len(conns)

    def stampede_schedule(self, n: int, window_s: float = 1.0) -> List[float]:
        """n cold-start arrivals crammed uniformly into the first
        window_s — prepend to an arrival schedule for the market-open /
        post-outage reconnect profile."""
        return sorted(self._rand.uniform(0.0, window_s) for _ in range(n))

    def close(self) -> None:
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
