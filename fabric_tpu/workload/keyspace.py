"""Zipf-skewed keyspace + traffic mix: WHAT each arrival touches.

Key access in real permissioned-ledger deployments is heavily skewed —
a few hot assets absorb most writes — and skew is exactly what turns
load into MVCC conflict storms: two in-flight txs that endorsed the
same hot key's version race, and every loser burns a full
endorse/order/validate round just to be flagged MVCC_READ_CONFLICT.
This module makes that a dial, not an accident:

  ZipfSampler(n, s, seed)   rank-frequency key draw, p(k) ~ 1/k^s.
                            s=0 is uniform (conflicts ~ birthday
                            bound), s>=1.2 hammers a handful of keys.
  TrafficMix                channel/chaincode weights + a read/write/
                            range op blend, one seeded PRNG, so a
                            multi-tenant workload is reproducible
                            draw-for-draw.

`expected_collision_p(n, s)` is the analytic conflict dial — the
probability two independent draws pick the same key (sum p_i^2) —
monotone in s, which the tests pin so "turn s up, get more conflicts"
stays true as samplers evolve.
"""

from __future__ import annotations

import bisect
import random
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["ZipfSampler", "Op", "TrafficMix", "expected_collision_p"]

OP_KINDS = ("read", "write", "range")


def _zipf_weights(n: int, s: float) -> List[float]:
    return [1.0 / (k ** s) for k in range(1, n + 1)]


def expected_collision_p(n: int, s: float) -> float:
    """P(two independent Zipf(s) draws over n keys collide) = sum p_i^2.

    The analytic form of the MVCC conflict dial: strictly increasing in
    s for n > 1 (mass concentrates on low ranks), so a workload's
    conflict rate is tunable by skew alone at a fixed offered rate."""
    w = _zipf_weights(n, s)
    total = sum(w)
    return sum((x / total) ** 2 for x in w)


class ZipfSampler:
    """Seeded Zipf(s) rank sampler over n keys via inverse-CDF bisect.

    Rank 1 is the hottest key.  `key(rank)` maps ranks to stable key
    strings so independent samplers over the same n collide on the
    same hot set (what a multi-client conflict storm needs)."""

    def __init__(self, n: int, s: float = 1.0, seed: int = 0,
                 prefix: str = "k"):
        if n < 1:
            raise ValueError("ZipfSampler needs n >= 1")
        self.n = int(n)
        self.s = float(s)
        self.seed = int(seed)
        self.prefix = prefix
        self._rand = random.Random(self.seed)
        w = _zipf_weights(self.n, self.s)
        total = sum(w)
        self._cdf: List[float] = []
        acc = 0.0
        for x in w:
            acc += x / total
            self._cdf.append(acc)
        self._cdf[-1] = 1.0           # guard float drift at the tail

    def rank(self) -> int:
        """One draw -> rank in [1, n] (1 = hottest)."""
        return bisect.bisect_left(self._cdf, self._rand.random()) + 1

    def key(self, rank: Optional[int] = None) -> str:
        r = self.rank() if rank is None else rank
        return f"{self.prefix}{r:06d}"

    def pmf(self, rank: int) -> float:
        lo = self._cdf[rank - 2] if rank >= 2 else 0.0
        return self._cdf[rank - 1] - lo


class Op:
    """One generated operation: where it goes and what it touches."""

    __slots__ = ("channel", "chaincode", "kind", "key", "end_key",
                 "client_id")

    def __init__(self, channel: str, chaincode: str, kind: str, key: str,
                 end_key: Optional[str] = None,
                 client_id: Optional[int] = None):
        self.channel = channel
        self.chaincode = chaincode
        self.kind = kind
        self.key = key
        self.end_key = end_key
        self.client_id = client_id

    def as_dict(self) -> dict:
        return {"channel": self.channel, "chaincode": self.chaincode,
                "kind": self.kind, "key": self.key,
                "end_key": self.end_key, "client_id": self.client_id}

    def __repr__(self) -> str:
        return (f"Op({self.kind} {self.channel}/{self.chaincode} "
                f"{self.key})")


class TrafficMix:
    """Weighted multi-channel traffic with a read/write/range blend.

    channels: [{"channel": "ch", "chaincode": "assets", "weight": 1.0,
                "keys": 1000, "zipf_s": 1.0,
                "blend": {"read": .3, "write": .6, "range": .1}}]

    One seeded PRNG drives channel choice, op-kind choice, and every
    per-channel key draw (each channel's ZipfSampler is sub-seeded from
    the mix seed + channel index), so a mix is reproducible end-to-end
    from a single integer.
    """

    def __init__(self, channels: Sequence[dict], seed: int = 0):
        if not channels:
            raise ValueError("TrafficMix needs at least one channel")
        self.seed = int(seed)
        self._rand = random.Random(self.seed)
        self.entries: List[dict] = []
        self._samplers: List[ZipfSampler] = []
        weights: List[float] = []
        for i, c in enumerate(channels):
            ent = {"channel": str(c.get("channel", "ch")),
                   "chaincode": str(c.get("chaincode", "assets")),
                   "weight": float(c.get("weight", 1.0)),
                   "keys": int(c.get("keys", 1024)),
                   "zipf_s": float(c.get("zipf_s", 1.0)),
                   "blend": dict(c.get("blend")
                                 or {"read": 0.2, "write": 0.8,
                                     "range": 0.0})}
            bad = set(ent["blend"]) - set(OP_KINDS)
            if bad:
                raise ValueError(f"unknown op kinds {sorted(bad)} "
                                 f"(one of {OP_KINDS})")
            self.entries.append(ent)
            weights.append(ent["weight"])
            self._samplers.append(ZipfSampler(
                ent["keys"], ent["zipf_s"], seed=self.seed * 7919 + i,
                prefix=f"{ent['channel']}-"))
        total = sum(weights)
        if total <= 0.0:
            raise ValueError("channel weights sum to zero")
        self._chan_cdf: List[float] = []
        acc = 0.0
        for w in weights:
            acc += w / total
            self._chan_cdf.append(acc)
        self._chan_cdf[-1] = 1.0

    def _pick_kind(self, blend: Dict[str, float]) -> str:
        total = sum(blend.values())
        if total <= 0.0:
            return "write"
        r = self._rand.random() * total
        acc = 0.0
        for kind in OP_KINDS:
            acc += blend.get(kind, 0.0)
            if r < acc:
                return kind
        return "write"

    def next_op(self) -> Op:
        i = bisect.bisect_left(self._chan_cdf, self._rand.random())
        ent = self.entries[i]
        sampler = self._samplers[i]
        kind = self._pick_kind(ent["blend"])
        rank = sampler.rank()
        key = sampler.key(rank)
        end_key = None
        if kind == "range":
            # a short scan window starting at the drawn rank: ranges
            # collide with writes landing anywhere inside the window,
            # which is what drives phantom-read conflicts
            end = min(ent["keys"], rank + 8)
            end_key = sampler.key(end)
        return Op(ent["channel"], ent["chaincode"], kind, key,
                  end_key=end_key)

    def ops(self, n: int) -> List[Op]:
        return [self.next_op() for _ in range(n)]

    def conflict_dial(self) -> float:
        """Weighted expected same-key collision probability across the
        mix — the single-number conflict dial for reports."""
        total_w = sum(e["weight"] for e in self.entries)
        return sum(
            (e["weight"] / total_w)
            * expected_collision_p(e["keys"], e["zipf_s"])
            for e in self.entries)

    def describe(self) -> dict:
        return {"seed": self.seed, "channels": [dict(e)
                                                for e in self.entries],
                "conflict_dial": round(self.conflict_dial(), 6)}
