"""Workload plane: open-loop, trace-driven load generation.

The measurement counterpart of the gateway's admission plane.  Closed-
loop drivers (examples/gateway_load.py) self-throttle under saturation
— offered load adapts to what the system sustains, so overload regimes
are unreachable by construction.  This package generates load the way
the world does: seeded arrival processes fire on a wall-clock schedule
regardless of completions (arrivals.py), a Zipf-skewed multi-channel
traffic mix makes MVCC conflict rate a dial (keyspace.py), and a large
client population multiplexes over a small socket pool with reconnect-
storm / cold-start scenarios (clients.py).  The WorkloadRunner
(runner.py) phases them into offered-vs-accepted-vs-committed reports
with sojourn percentiles; `python -m fabric_tpu.workload` boots an
in-process network and runs a named scenario end to end.
"""

from fabric_tpu.workload.arrivals import (
    ArrivalProcess,
    ConstantArrivals,
    DiurnalArrivals,
    OpenLoopScheduler,
    RampArrivals,
    SquareWaveArrivals,
    from_spec,
)
from fabric_tpu.workload.clients import ClientPopulation, ThinkTimeModel
from fabric_tpu.workload.keyspace import (
    Op,
    TrafficMix,
    ZipfSampler,
    expected_collision_p,
)
from fabric_tpu.workload.runner import PhaseStats, WorkloadRunner, pct

__all__ = [
    "ArrivalProcess", "ClientPopulation", "ConstantArrivals",
    "DiurnalArrivals", "Op", "OpenLoopScheduler", "PhaseStats",
    "RampArrivals", "SquareWaveArrivals", "ThinkTimeModel", "TrafficMix",
    "WorkloadRunner", "ZipfSampler", "expected_collision_p", "from_spec",
    "pct",
]
