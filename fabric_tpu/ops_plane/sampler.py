"""Always-on wall-clock sampling profiler (`GET /profile/sampled`).

The on-demand `/debug/pprof` route (profiling.py) answers "where is
time going RIGHT NOW, for the next N seconds" — which is almost always
too late for an incident: by the time a human notices the burn and
posts the capture request, the bad window is over.  This plane inverts
the capture direction: a daemon thread walks `sys._current_frames()`
at a low fixed rate (default ~19 Hz — deliberately co-prime with the
common 10/20/50/100 ms periodic loops in this codebase, so the sampler
never phase-locks onto them) and aggregates folded call stacks per
THREAD ROLE into a bounded ring of time-bucketed windows.  The profile
covering any incident interval therefore *already exists* when an SLO
alert fires; incidents.py just copies the overlapping windows into the
bundle.

Aggregation shape (the r15 raw→coarse tier idea, applied to stacks):

  open window     [bucket_start, bucket_start + window_s): folded-stack
                  counts accumulate in place (the "open bucket")
  fine ring       sealed windows, bounded deque — recent history at
                  window_s resolution (default 10 s × 30 = 5 min)
  coarse ring     fine windows evicted off the ring MERGE into
                  coarse_window_s buckets (default 60 s × 30 = 30 min)
                  — counts are carried, never dropped, until the coarse
                  ring itself rolls

A "folded stack" is the flamegraph interchange format: semicolon-
joined frames, root first, prefixed with the sampled thread's role
(`workload;runner.fire;gateway.submit_envelope 31`).  Roles collapse
pool-numbered thread names (`workload-7` → `workload`) so a 128-worker
pool aggregates into one flame instead of 128 singletons.

Zero-overhead guard: nothing in this module runs at import; a node
that leaves the `profiler` sub-dict disabled constructs no sampler,
registers no counter, starts no thread, and serves a byte-identical
/metrics surface (asserted in tests/test_sampler.py).

Render a flamegraph from the folded output with Brendan Gregg's
flamegraph.pl, or paste into https://www.speedscope.app:

    curl -s 'http://127.0.0.1:9443/profile/sampled?window=120&fmt=folded' \
        | flamegraph.pl > profile.svg
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .metrics import MetricsRegistry, registry as default_registry

__all__ = ["SamplingProfiler", "register_routes"]

_OWN_THREAD_NAME = "profile-sampler"


def role_of(thread_name: str) -> str:
    """Collapse pool-numbered thread names into one role: `workload-17`
    → `workload`, `Thread-3` → `Thread`, `slo-evaluator` stays put."""
    base = thread_name.rstrip("0123456789")
    base = base.rstrip("-_")
    return base or thread_name


def _frame_label(frame) -> str:
    code = frame.f_code
    mod = os.path.basename(code.co_filename)
    if mod.endswith(".py"):
        mod = mod[:-3]
    return f"{mod}.{code.co_name}"


class _Window:
    """One time bucket of folded-stack counts."""

    __slots__ = ("start", "end", "samples", "folded")

    def __init__(self, start: float, end: float):
        self.start = start
        self.end = end
        self.samples = 0                    # sampler ticks in this bucket
        self.folded: Dict[str, int] = {}    # folded stack -> count

    def add(self, stacks: List[str]) -> None:
        self.samples += 1
        for s in stacks:
            self.folded[s] = self.folded.get(s, 0) + 1

    def merge_from(self, other: "_Window") -> None:
        self.samples += other.samples
        self.start = min(self.start, other.start)
        self.end = max(self.end, other.end)
        for s, c in other.folded.items():
            self.folded[s] = self.folded.get(s, 0) + c

    def summary(self) -> dict:
        return {"start": self.start, "end": self.end,
                "samples": self.samples, "stacks": len(self.folded)}


class SamplingProfiler:
    """Continuous `sys._current_frames()` sampler with a bounded
    fine/coarse window ring.

    Config (the node's `profiler` sub-dict):
        enabled            gate read by the NODE, not here (disabled ->
                           never constructed; the zero-overhead guard)
        hz                 sampling rate (default 19.0)
        window_s           fine bucket width (default 10.0)
        windows            fine ring length (default 30)
        coarse_window_s    coarse bucket width (default 60.0)
        coarse_windows     coarse ring length (default 30)
        max_depth          frames kept per stack, leaf-up (default 64)
        top_n              default rows in the self-time table
    """

    def __init__(self, cfg: Optional[dict] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None):
        cfg = dict(cfg or {})
        self.hz = max(0.1, float(cfg.get("hz", 19.0)))
        self.window_s = max(0.1, float(cfg.get("window_s", 10.0)))
        self.windows = max(1, int(cfg.get("windows", 30)))
        self.coarse_window_s = max(self.window_s, float(
            cfg.get("coarse_window_s", 60.0)))
        self.coarse_windows = max(1, int(cfg.get("coarse_windows", 30)))
        self.max_depth = max(2, int(cfg.get("max_depth", 64)))
        self.top_n = max(1, int(cfg.get("top_n", 25)))
        self.registry = registry or default_registry
        self._clock = clock or time.time
        self._lock = threading.Lock()
        self._open: Optional[_Window] = None
        self._fine: deque = deque()
        self._coarse: deque = deque()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters register at CONSTRUCTION: a disabled plane never
        # constructs, so the disabled /metrics stays byte-identical
        self._samples_c = self.registry.counter(
            "profiler_samples_total",
            "sampler ticks taken by the wall-clock profiler")
        self._threads_c = self.registry.counter(
            "profiler_thread_samples_total",
            "thread stacks folded by the profiler")
        # walk-time counter = the profiler's own duty cycle; the smoke
        # overhead gate reads this instead of flaky A/B throughput runs
        self._walk_c = self.registry.counter(
            "profiler_walk_seconds_total",
            "wall seconds the profiler spent walking frames")

    # -- sampling ------------------------------------------------------------

    def _collect_stacks(self) -> List[str]:
        """One walk over every live thread -> folded stacks (role-
        prefixed, root-first).  Overridable/injectable for tests."""
        names = {t.ident: t.name for t in threading.enumerate()}
        own = threading.get_ident()
        out: List[str] = []
        for tid, frame in sys._current_frames().items():
            if tid == own or names.get(tid) == _OWN_THREAD_NAME:
                continue
            entries: List[str] = []
            f = frame
            while f is not None and len(entries) < self.max_depth:
                entries.append(_frame_label(f))
                f = f.f_back
            entries.reverse()               # root first (folded format)
            role = role_of(names.get(tid, f"tid{tid}"))
            out.append(role + ";" + ";".join(entries))
        return out

    def sample_once(self, now: Optional[float] = None) -> int:
        """Take one sample tick; returns the number of threads folded.
        `now` is injectable so tests drive the window ring directly."""
        t0 = time.perf_counter()
        now = self._clock() if now is None else float(now)
        stacks = self._collect_stacks()
        start = (now // self.window_s) * self.window_s
        with self._lock:
            if self._open is None or self._open.start != start:
                self._roll(start)
            self._open.add(stacks)
        try:
            self._samples_c.add(1)
            self._threads_c.add(len(stacks))
            self._walk_c.add(time.perf_counter() - t0)
        except Exception:
            pass
        return len(stacks)

    def _roll(self, new_start: float) -> None:
        """Seal the open window and open the bucket at `new_start`;
        fine overflow merges into the coarse tier (counts are CARRIED,
        not dropped — the r15 tier idea).  Caller holds the lock."""
        if self._open is not None and self._open.samples:
            self._fine.append(self._open)
        while len(self._fine) > self.windows:
            w = self._fine.popleft()
            cstart = (w.start // self.coarse_window_s) \
                * self.coarse_window_s
            if self._coarse and self._coarse[-1].start == cstart:
                self._coarse[-1].merge_from(w)
            else:
                cw = _Window(cstart, cstart + self.coarse_window_s)
                cw.merge_from(w)
                self._coarse.append(cw)
            while len(self._coarse) > self.coarse_windows:
                self._coarse.popleft()
        self._open = _Window(new_start, new_start + self.window_s)

    # -- reading -------------------------------------------------------------

    def _windows_locked(self) -> List[_Window]:
        out = list(self._coarse) + list(self._fine)
        if self._open is not None and self._open.samples:
            out.append(self._open)
        return out

    def profile(self, window_s: Optional[float] = None,
                now: Optional[float] = None,
                top_n: Optional[int] = None) -> dict:
        """Merged folded profile over the trailing `window_s` seconds
        (coarse + fine + open buckets overlapping the interval)."""
        now = self._clock() if now is None else float(now)
        window_s = float(window_s if window_s is not None
                         else 6 * self.window_s)
        t0 = now - window_s
        merged: Dict[str, int] = {}
        samples = 0
        summaries: List[dict] = []
        with self._lock:
            for w in self._windows_locked():
                if w.end <= t0 or w.start > now:
                    continue
                samples += w.samples
                summaries.append(w.summary())
                for s, c in w.folded.items():
                    merged[s] = merged.get(s, 0) + c
        return {"now": now, "window_s": window_s, "hz": self.hz,
                "samples": samples, "stacks": len(merged),
                "folded": merged, "windows": summaries,
                "top": self.top_table(merged, top_n or self.top_n)}

    def windows_overlapping(self, t0: float, t1: float) -> List[dict]:
        """Summaries of the buckets intersecting [t0, t1] — the
        incident bundle's 'profile covers the burn' evidence."""
        with self._lock:
            return [w.summary() for w in self._windows_locked()
                    if w.end > t0 and w.start <= t1]

    @staticmethod
    def folded_text(folded: Dict[str, int]) -> str:
        """Flamegraph interchange: one `stack count` line, hottest
        first (order is cosmetic; flamegraph.pl re-sorts)."""
        lines = sorted(folded.items(), key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(f"{s} {c}" for s, c in lines)

    @staticmethod
    def top_table(folded: Dict[str, int], n: int) -> List[dict]:
        """Self-time table: a frame's `self` counts samples where it
        was the leaf; `total` counts samples where it appears anywhere
        on the stack (each stack counted once per frame)."""
        self_c: Dict[str, int] = {}
        total_c: Dict[str, int] = {}
        grand = 0
        for stack, c in folded.items():
            frames = stack.split(";")[1:]   # drop the role prefix
            if not frames:
                continue
            grand += c
            self_c[frames[-1]] = self_c.get(frames[-1], 0) + c
            for fr in set(frames):
                total_c[fr] = total_c.get(fr, 0) + c
        rows = sorted(self_c.items(), key=lambda kv: (-kv[1], kv[0]))[:n]
        return [{"frame": fr, "self": c,
                 "self_frac": round(c / grand, 4) if grand else 0.0,
                 "total": total_c.get(fr, c)} for fr, c in rows]

    # -- background thread ---------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        interval = 1.0 / self.hz

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.sample_once()
                except Exception:
                    pass                    # never take the node down

        self._thread = threading.Thread(
            target=loop, name=_OWN_THREAD_NAME, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=2.0)


def register_routes(ops, profiler: SamplingProfiler) -> None:
    """Mount GET /profile/sampled?window=&top=&fmt=folded|json.
    `fmt=folded` answers text/plain folded stacks (pipe straight into
    flamegraph.pl); the default JSON carries the folded text as a
    string field plus the top-N self-time table."""
    from urllib.parse import parse_qs, urlparse

    def _route(path: str, body: bytes) -> Tuple[int, object]:
        q = parse_qs(urlparse(path).query)
        try:
            window = float(q.get("window", [6 * profiler.window_s])[0])
            top = int(q.get("top", [profiler.top_n])[0])
        except ValueError as exc:
            return 400, {"error": str(exc)}
        prof = profiler.profile(window_s=window, top_n=top)
        if q.get("fmt", ["json"])[0] == "folded":
            return 200, profiler.folded_text(prof["folded"])
        prof["folded"] = profiler.folded_text(prof["folded"])
        return 200, prof

    ops.register_route("GET", "/profile/sampled", _route)
