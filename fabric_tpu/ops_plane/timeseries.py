"""Metric time-series history: bounded ring store + leak-slope gate.

The registry (`ops_plane.metrics`) is cumulative and instantaneous —
`/metrics` answers "what is the value now", never "how did it get
here".  This module adds the missing axis: a per-node ring store that
samples every registered Counter/Gauge/Histogram on a configurable
cadence and keeps three tiers of history with increasing reach and
decreasing resolution:

  raw   one point per sample            (default 10 min @ 1 s)
  1m    min/mean/max per 60 s bucket    (default 2 h)
  10m   min/mean/max per 600 s bucket   (default 24 h)

Raw points are appended on every sample; a coarse bucket is flushed the
first time a sample lands past its end, so downsampling is O(1) per
sample and the store's footprint is fixed by config, not uptime.

Served as `GET /metrics/history?name=<series>&window=<seconds>` on the
ops server (tier auto-selected from the window, or forced with
`&tier=raw|1m|10m`) and consumed by `node.top --spark` sparklines.

The same history feeds the long-soak leak gate (ROADMAP direction #4):
`theil_sen` is a median-of-pairwise-slopes estimator — robust to the
sawtooth a GC or ring eviction leaves in RSS — with Sen's
normal-approximation confidence interval, and `assess_leak` turns a
series into a verdict: leaking only when the slope CI excludes zero
AND the projected growth over the window is a material fraction of the
series' level (a one-time step or allocator jitter never fires, a
steady climb does).  `workload/scenarios.py` wires this as the
`leak_free` expect kind.

Everything here is off the hot path: the sampler thread reads the same
cumulative snapshots the SLO evaluator reads (`Counter.total`,
`Gauge.values`, `Histogram.state`), so observing code pays nothing new.
Nodes construct the store only when the `timeseries` config sub-dict
enables it — disabled, there is no thread, no ring, and no route.
"""

from __future__ import annotations

import math
import statistics
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .metrics import registry as default_registry

__all__ = ["TimeSeriesStore", "theil_sen", "assess_leak",
           "evaluate_leak_gate", "register_routes"]

# (tier name, bucket width seconds); raw is width 0 (no bucketing)
_COARSE_TIERS: Tuple[Tuple[str, float], ...] = (("1m", 60.0),
                                                ("10m", 600.0))


class _Series:
    """One metric's rings: raw points + per-tier bucket accumulators."""

    __slots__ = ("raw", "coarse", "_acc")

    def __init__(self, raw_len: int, coarse_lens: Dict[str, int]):
        self.raw: deque = deque(maxlen=raw_len)
        self.coarse: Dict[str, deque] = {
            tier: deque(maxlen=n) for tier, n in coarse_lens.items()}
        # tier -> [bucket_start, min, max, sum, n] (open bucket)
        self._acc: Dict[str, Optional[list]] = {
            tier: None for tier in coarse_lens}

    def record(self, now: float, value: float) -> None:
        self.raw.append((now, value))
        for tier, width in _COARSE_TIERS:
            if tier not in self.coarse:
                continue
            bucket = math.floor(now / width) * width
            acc = self._acc[tier]
            if acc is not None and acc[0] != bucket:
                self.coarse[tier].append(
                    (acc[0], acc[3] / acc[4], acc[1], acc[2]))
                acc = None
            if acc is None:
                self._acc[tier] = [bucket, value, value, value, 1]
            else:
                acc[1] = min(acc[1], value)
                acc[2] = max(acc[2], value)
                acc[3] += value
                acc[4] += 1


class TimeSeriesStore:
    """Bounded ring store over a MetricsRegistry, with tiered history.

    Config keys (the node's `timeseries` sub-dict):
      enabled        node-level gate (read by the node, not here)
      interval_s     sampling cadence            (default 1.0)
      raw_window_s   raw-tier retention          (default 600)
      m1_window_s    1m-tier retention           (default 7200)
      m10_window_s   10m-tier retention          (default 86400)
    """

    def __init__(self, cfg: Optional[dict] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None):
        cfg = dict(cfg or {})
        self.registry = registry or default_registry
        self._clock = clock or time.monotonic
        self.interval_s = max(0.05, float(cfg.get("interval_s", 1.0)))
        self.raw_window_s = float(cfg.get("raw_window_s", 600.0))
        self.m1_window_s = float(cfg.get("m1_window_s", 7200.0))
        self.m10_window_s = float(cfg.get("m10_window_s", 86400.0))
        self._raw_len = max(
            8, int(math.ceil(self.raw_window_s / self.interval_s)) + 2)
        self._coarse_lens = {
            "1m": max(4, int(math.ceil(self.m1_window_s / 60.0)) + 2),
            "10m": max(4, int(math.ceil(self.m10_window_s / 600.0)) + 2),
        }
        self._series: Dict[str, _Series] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- writing -------------------------------------------------------------

    def record(self, name: str, value: float,
               now: Optional[float] = None) -> None:
        """Append one point (extra series beyond the registry sweep)."""
        now = self._clock() if now is None else now
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = _Series(self._raw_len, self._coarse_lens)
                self._series[name] = s
            s.record(now, float(value))

    def sample(self, now: Optional[float] = None) -> None:
        """One sweep over every registered metric.

        Counters record their cross-label total, gauges the mean over
        label sets (a single unlabelled gauge records itself), and a
        histogram contributes `<name>_count` + `<name>_sum` — enough to
        derive windowed rates and means client-side.
        """
        now = self._clock() if now is None else now
        for name, m in self.registry.metrics().items():
            if isinstance(m, Counter):
                self.record(name, m.total(), now)
            elif isinstance(m, Gauge):
                vals = m.values()
                if vals:
                    self.record(name, sum(vals.values()) / len(vals), now)
            elif isinstance(m, Histogram):
                _, total, n = m.state()
                self.record(name + "_count", n, now)
                self.record(name + "_sum", total, now)

    # -- reading -------------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def _pick_tier(self, window_s: float) -> str:
        if window_s <= self.raw_window_s:
            return "raw"
        if window_s <= self.m1_window_s:
            return "1m"
        return "10m"

    def history(self, name: str, window_s: Optional[float] = None,
                tier: Optional[str] = None,
                now: Optional[float] = None) -> dict:
        """Points for one series: raw tier as [t, v], coarse tiers as
        [bucket_start, mean, min, max]; only points inside the window
        (ending at `now`) are returned."""
        now = self._clock() if now is None else now
        window_s = self.raw_window_s if window_s is None else float(window_s)
        tier = tier or self._pick_tier(window_s)
        if tier not in ("raw", "1m", "10m"):
            raise ValueError(f"unknown tier {tier!r}")
        lo = now - window_s
        with self._lock:
            s = self._series.get(name)
            if s is None:
                pts: List[list] = []
            elif tier == "raw":
                pts = [[t, v] for t, v in s.raw if t >= lo]
            else:
                pts = [[t, mean, mn, mx]
                       for t, mean, mn, mx in s.coarse[tier] if t >= lo]
                acc = s._acc.get(tier)
                if acc is not None and acc[0] >= lo:
                    # the open bucket: partial, but the freshest data
                    pts.append([acc[0], acc[3] / acc[4], acc[1], acc[2]])
        return {"name": name, "tier": tier, "window_s": window_s,
                "interval_s": self.interval_s, "now": now, "points": pts}

    # -- lifecycle -----------------------------------------------------------

    def step(self) -> None:
        self.sample()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:       # keep the sampler alive
                import logging
                logging.getLogger(__name__).exception(
                    "timeseries sample failed")

    def start(self) -> "TimeSeriesStore":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="timeseries-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# leak-slope estimation (Theil–Sen + Sen's CI)

def theil_sen(points) -> Optional[dict]:
    """Median of all pairwise slopes with Sen's 95% confidence interval
    (normal approximation of Kendall's S).  `points` is a sequence of
    (t, v); returns None with fewer than 2 distinct timestamps.

    O(n^2) in the point count — callers feed windowed history (a few
    hundred points), never the unbounded series.
    """
    pts = sorted((float(t), float(v)) for t, v in points)
    n = len(pts)
    slopes: List[float] = []
    for i in range(n):
        ti, vi = pts[i]
        for j in range(i + 1, n):
            dt = pts[j][0] - ti
            if dt > 0:
                slopes.append((pts[j][1] - vi) / dt)
    if not slopes:
        return None
    slopes.sort()
    big_n = len(slopes)
    slope = statistics.median(slopes)
    sigma = math.sqrt(n * (n - 1) * (2 * n + 5) / 18.0)
    c = 1.96 * sigma
    lo_i = max(0, min(big_n - 1, int(math.floor((big_n - c) / 2.0))))
    hi_i = max(0, min(big_n - 1, int(math.ceil((big_n + c) / 2.0))))
    return {"slope": slope, "ci_lo": slopes[lo_i], "ci_hi": slopes[hi_i],
            "n_points": n, "n_slopes": big_n}


def assess_leak(points, *, max_growth_frac: float = 0.05,
                min_points: int = 8, warmup_s: float = 0.0) -> dict:
    """Leak verdict for one series' windowed points.

    Leaking iff the Theil–Sen slope CI excludes zero from below AND the
    slope projected over the observed span grows the series by more
    than `max_growth_frac` of its mean level — so a one-time step, GC
    sawtooth, or allocator jitter never fires, while a steady climb
    does.  `warmup_s` drops the head of the window (startup ramps are
    not leaks).
    """
    pts = sorted((float(t), float(v)) for t, v in points)
    if warmup_s > 0.0 and pts:
        t0 = pts[0][0]
        pts = [(t, v) for t, v in pts if t >= t0 + warmup_s]
    if len(pts) < min_points:
        return {"leaking": False, "verdict": "insufficient_data",
                "n_points": len(pts), "min_points": min_points}
    est = theil_sen(pts)
    if est is None:
        return {"leaking": False, "verdict": "insufficient_data",
                "n_points": len(pts), "min_points": min_points}
    span_s = pts[-1][0] - pts[0][0]
    mean_level = sum(v for _, v in pts) / len(pts)
    projected = est["slope"] * span_s
    growth_frac = projected / max(abs(mean_level), 1e-9)
    leaking = bool(est["ci_lo"] > 0.0 and growth_frac > max_growth_frac)
    return {
        "leaking": leaking,
        "verdict": "leaking" if leaking else "flat",
        "slope_per_s": est["slope"],
        "ci_lo": est["ci_lo"], "ci_hi": est["ci_hi"],
        "span_s": span_s, "n_points": est["n_points"],
        "mean_level": mean_level,
        "projected_growth": projected,
        "growth_frac": growth_frac,
        "max_growth_frac": max_growth_frac,
    }


def evaluate_leak_gate(store: TimeSeriesStore, series: Dict[str, dict],
                       window_s: Optional[float] = None,
                       now: Optional[float] = None,
                       warmup_s: float = 0.0) -> dict:
    """Run `assess_leak` over named series from one store.

    `series` maps series name -> per-series overrides
    ({max_growth_frac, min_points, warmup_s}); returns
    {"series": {name: verdict}, "leaking": [names]}.
    """
    out: dict = {"series": {}, "leaking": []}
    for name, overrides in series.items():
        o = dict(overrides or {})
        hist = store.history(name, window_s=window_s, tier="raw", now=now)
        verdict = assess_leak(
            [(p[0], p[1]) for p in hist["points"]],
            max_growth_frac=float(o.get("max_growth_frac", 0.05)),
            min_points=int(o.get("min_points", 8)),
            warmup_s=float(o.get("warmup_s", warmup_s)))
        out["series"][name] = verdict
        if verdict["leaking"]:
            out["leaking"].append(name)
    out["pass"] = not out["leaking"]
    return out


# ---------------------------------------------------------------------------
# ops route

def register_routes(ops, store: TimeSeriesStore) -> None:
    """Mount GET /metrics/history on an OperationsServer.

    No `name` lists the available series; with a name, `window` (s) and
    `tier` shape the reply.  The built-in /metrics handler matches the
    exact path only, so this prefix route never shadows it.
    """
    from urllib.parse import parse_qs, urlparse

    def _history(path: str, body: bytes):
        q = parse_qs(urlparse(path).query)
        name = (q.get("name") or [None])[0]
        if not name:
            return 200, {"series": store.names(),
                         "interval_s": store.interval_s,
                         "windows_s": {"raw": store.raw_window_s,
                                       "1m": store.m1_window_s,
                                       "10m": store.m10_window_s}}
        window = (q.get("window") or q.get("window_s") or [None])[0]
        tier = (q.get("tier") or [None])[0]
        try:
            out = store.history(
                name, window_s=float(window) if window else None, tier=tier)
        except ValueError as exc:
            return 400, {"error": str(exc)}
        if not out["points"] and name not in store.names():
            return 404, {"error": "unknown series", "name": name,
                         "series": store.names()}
        return 200, out

    ops.register_route("GET", "/metrics/history", _history)
