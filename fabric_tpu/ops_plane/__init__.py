"""Operations plane: metrics registry, Prometheus exposition, health checks.

Re-design of /root/reference/common/metrics (provider.go) +
core/operations/system.go:75-267 (VERDICT.md missing #6): a process-local
metrics registry with counters/gauges/histograms, Prometheus text-format
exposition, pluggable health checkers, and a tiny ops HTTP server
(`/metrics`, `/healthz`, `/logspec`, `/version`).

Named ops_plane (not "operations") to avoid clashing with fabric_tpu.ops,
the TPU kernel package.
"""

from .metrics import Counter, Gauge, Histogram, MetricsRegistry, registry
from .server import OperationsServer
from .tracing import (FlightRecorder, Span, SpanContext, Tracer, tracer,
                      configure as configure_tracing,
                      register_routes as register_trace_routes)
from .logging import jlog
from .slo import (SloEvaluator,
                  register_routes as register_slo_routes)
from .timeseries import (TimeSeriesStore, theil_sen, assess_leak,
                         evaluate_leak_gate,
                         register_routes as register_history_routes)
from .resources import (ResourceCollector, provenance,
                        register_routes as register_resource_routes)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
           "OperationsServer", "FlightRecorder", "Span", "SpanContext",
           "Tracer", "tracer", "configure_tracing", "register_trace_routes",
           "jlog", "SloEvaluator", "register_slo_routes",
           "TimeSeriesStore", "theil_sen", "assess_leak",
           "evaluate_leak_gate", "register_history_routes",
           "ResourceCollector", "provenance", "register_resource_routes"]
