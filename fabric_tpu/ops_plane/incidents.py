"""SLO-triggered incident capture: the cluster's flight data recorder.

The r5 burn-rate evaluator (slo.py) can say THAT an objective is
burning; diagnosing WHY needs evidence from the burn window — a
profile covering it, the slowest traces, peer-node state — and until
now a human had to capture all of that by hand, after the fact.  This
plane closes the loop: an `IncidentRecorder` hooks the evaluator's
alert transitions and, on fire, atomically writes a self-contained
`incident_NNNN/` bundle:

    incident.json        alert attrs, node identity, peer roll call
    metric_history.json  the timeseries ring's trailing window for the
                         burning objective's metric + every input series
    traces.json          the FlightRecorder's K slowest traces (full
                         span records, not just summaries)
    profile.json         merged sampled profile over the burn window
    profile_folded.txt   same, flamegraph-ready folded stacks
    snapshots.json       admission / breaker / byzantine / lifecycle /
                         resources snapshots (whatever the node wired)
    jlog_tail.txt        the last N structured log lines
    peers/<endpoint>.json  each peer's /incidents/snapshot at the burn
                         instant (dead peers recorded as errors and the
                         bundle marked `partial` — same fail-open rule
                         as node/tracecollect.py)
    MANIFEST.json        sha256 of every file above; `verify_bundle`
                         re-hashes and names any tamper/missing file

Rate-limiting is per OBJECTIVE (cooldown_s): a flapping alert cannot
fill the disk.  Retention is bounded (keep last N bundles, gc the
oldest).  Everything is served on the ops surface: GET /incidents
(index), GET /incidents/<id> (manifest + verification), and GET
/incidents/snapshot (the self-view peers fetch during fan-out).

Zero-overhead guard: a node that leaves the `incidents` sub-dict
disabled constructs no recorder, registers no counter or route, and
serves a byte-identical /metrics surface (tests/test_incidents.py).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .logging import jlog
from .metrics import MetricsRegistry, registry as default_registry

logger = logging.getLogger("fabric_tpu.ops_plane.incidents")

__all__ = ["IncidentRecorder", "verify_bundle", "register_routes"]

MANIFEST = "MANIFEST.json"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(65536), b""):
            h.update(chunk)
    return h.hexdigest()


def verify_bundle(bundle_dir: str) -> dict:
    """Re-hash a bundle against its MANIFEST.  Returns
    {"ok": bool, "files": n, "mismatched": [...], "missing": [...],
     "extra": [...]}  — any tamper, truncation, or deletion is named."""
    mpath = os.path.join(bundle_dir, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        return {"ok": False, "files": 0, "mismatched": [],
                "missing": [MANIFEST], "extra": [],
                "error": str(exc)[:200]}
    want: Dict[str, str] = dict(manifest.get("files", {}))
    mismatched, missing = [], []
    for rel, digest in sorted(want.items()):
        p = os.path.join(bundle_dir, rel)
        try:
            got = _sha256_file(p)
        except OSError:
            missing.append(rel)
            continue
        if got != digest:
            mismatched.append(rel)
    have = set()
    for root, _dirs, files in os.walk(bundle_dir):
        for fn in files:
            rel = os.path.relpath(os.path.join(root, fn), bundle_dir)
            if rel != MANIFEST:
                have.add(rel)
    extra = sorted(have - set(want))
    return {"ok": not (mismatched or missing or extra),
            "files": len(want), "mismatched": mismatched,
            "missing": missing, "extra": extra}


class _JlogTail(logging.Handler):
    """Bounded in-memory tail of the structured log stream — the
    bundle's `jlog_tail.txt` evidence."""

    def __init__(self, maxlen: int):
        super().__init__()
        self.buf: deque = deque(maxlen=maxlen)
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s %(message)s"))

    def emit(self, record):
        try:
            self.buf.append(self.format(record))
        except Exception:
            pass


class IncidentRecorder:
    """Captures incident bundles when an attached SloEvaluator fires.

    Config (the node's `incidents` sub-dict):
        enabled            gate read by the NODE (disabled -> never
                           constructed; the zero-overhead guard)
        dir                bundle directory (node default: <data_dir>/
                           incidents)
        cooldown_s         per-objective re-capture suppression (120)
        keep               retained bundles; oldest gc'd first (8)
        slow_traces        K slowest FlightRecorder traces bundled (5)
        profile_window_s   sampled-profile span copied per bundle (120)
        history_window_s   timeseries window copied per bundle (300)
        jlog_tail          log lines retained for the tail file (200)
        peers              ops endpoints ("host:port") fanned out to
        peer_timeout_s     per-peer snapshot fetch budget (2.0)
        sync               capture on the alert thread instead of a
                           one-shot capture thread (tests)
    """

    def __init__(self, cfg: Optional[dict] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None, node_name: str = "node",
                 profiler=None, timeseries=None):
        cfg = dict(cfg or {})
        self.dir = str(cfg.get("dir") or os.path.join(
            os.getcwd(), "incidents"))
        self.cooldown_s = float(cfg.get("cooldown_s", 120.0))
        self.keep = max(1, int(cfg.get("keep", 8)))
        self.slow_traces = max(0, int(cfg.get("slow_traces", 5)))
        self.profile_window_s = float(cfg.get("profile_window_s", 120.0))
        self.history_window_s = float(cfg.get("history_window_s", 300.0))
        self.peers: List[str] = [str(p) for p in cfg.get("peers", [])]
        self.peer_timeout_s = float(cfg.get("peer_timeout_s", 2.0))
        self.sync = bool(cfg.get("sync", False))
        self.node_name = str(node_name)
        self.registry = registry or default_registry
        self._clock = clock or time.time
        self.profiler = profiler
        self.timeseries = timeseries
        self._sources: Dict[str, Callable[[], object]] = {}
        self._slo = None
        self._lock = threading.Lock()
        self._last_fire: Dict[str, float] = {}
        self._suppressed: deque = deque(maxlen=32)
        self._threads: List[threading.Thread] = []
        os.makedirs(self.dir, exist_ok=True)
        self._seq = self._scan_seq()
        self._captured_c = self.registry.counter(
            "incidents_captured_total", "incident bundles written")
        self._suppressed_c = self.registry.counter(
            "incidents_suppressed_total",
            "alert fires suppressed by per-objective cooldown")
        self._tail = _JlogTail(max(8, int(cfg.get("jlog_tail", 200))))
        logging.getLogger("fabric_tpu").addHandler(self._tail)

    # -- wiring --------------------------------------------------------------

    def add_source(self, name: str, fn: Callable[[], object]) -> None:
        """Register a snapshot source (admission, byzantine, resources,
        lifecycle...); called at capture time, failures recorded inline."""
        self._sources[str(name)] = fn

    def attach_slo(self, evaluator) -> None:
        """Hook the evaluator's alert transitions (slo.py on_fire /
        on_clear callbacks)."""
        self._slo = evaluator
        evaluator.on_fire = self.on_alert_fired
        evaluator.on_clear = self.on_alert_cleared

    # -- alert hooks ---------------------------------------------------------

    def on_alert_fired(self, name: str, alert: dict) -> Optional[str]:
        """Fire hook: cooldown-gate, then capture (async by default).
        Returns the bundle id when captured synchronously."""
        now = self._clock()
        with self._lock:
            last = self._last_fire.get(name)
            if last is not None and now - last < self.cooldown_s:
                self._suppressed.append(
                    {"objective": name, "at": now,
                     "cooldown_left_s": round(
                         self.cooldown_s - (now - last), 3)})
                try:
                    self._suppressed_c.add(1)
                except Exception:
                    pass
                return None
            self._last_fire[name] = now
        alert = dict(alert or {}, objective=alert.get("objective", name))
        if self.sync:
            return self.capture(alert)
        th = threading.Thread(target=self.capture, args=(alert,),
                              name="incident-capture", daemon=True)
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            self._threads.append(th)
        th.start()
        return None

    def on_alert_cleared(self, name: str, alert: dict) -> None:
        """Clears never capture — the evidence window was the burn —
        but they land in the log tail for the NEXT bundle's timeline."""
        jlog(logger, "incidents.alert_cleared", objective=name)

    # -- capture -------------------------------------------------------------

    def _scan_seq(self) -> int:
        seq = 0
        try:
            for d in os.listdir(self.dir):
                if d.startswith("incident_"):
                    try:
                        seq = max(seq, int(d.split("_", 1)[1]))
                    except ValueError:
                        pass
        except OSError:
            pass
        return seq

    def capture(self, alert: dict) -> Optional[str]:
        """Write one bundle atomically (tmp dir -> rename); never
        raises — an incident capture must not become an incident."""
        try:
            return self._capture(alert)
        except Exception:
            logger.exception("incident capture failed")
            return None

    def _capture(self, alert: dict) -> str:
        now = self._clock()
        with self._lock:
            self._seq += 1
            seq = self._seq
        inc_id = f"incident_{seq:04d}"
        tmp = os.path.join(self.dir, f".tmp_{seq:04d}_{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)
        files: Dict[str, str] = {}

        def put(rel: str, payload) -> None:
            path = os.path.join(tmp, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            if isinstance(payload, str):
                data = payload.encode()
            else:
                data = json.dumps(payload, indent=2, default=str,
                                  sort_keys=True).encode()
            with open(path, "wb") as f:
                f.write(data)
            files[rel] = hashlib.sha256(data).hexdigest()

        # -- snapshots from the wired sources (fail-open per source) --
        snaps: Dict[str, object] = {}
        for sname, fn in sorted(self._sources.items()):
            try:
                snaps[sname] = fn()
            except Exception as exc:
                snaps[sname] = {"error": repr(exc)[:200]}
        if self._slo is not None:
            try:
                snaps["slo"] = self._slo.status()
            except Exception as exc:
                snaps["slo"] = {"error": repr(exc)[:200]}
        put("snapshots.json", snaps)

        # -- metric history: the burning metric + every input series --
        if self.timeseries is not None:
            hist: Dict[str, object] = {}
            try:
                for name in self.timeseries.names():
                    hist[name] = self.timeseries.history(
                        name, window_s=self.history_window_s)
            except Exception as exc:
                hist["error"] = repr(exc)[:200]
            put("metric_history.json",
                {"metric": alert.get("metric"), "series": hist})

        # -- the K slowest traces, full span records ------------------
        if self.slow_traces:
            traces: List[dict] = []
            try:
                from . import tracing
                rec = tracing.tracer.recorder
                for s in rec.list()["slowest"][:self.slow_traces]:
                    full = rec.get(s["trace_id"])
                    traces.append(full if full is not None else s)
            except Exception as exc:
                traces = [{"error": repr(exc)[:200]}]
            put("traces.json", {"slowest": traces})

        # -- the sampled-profile windows overlapping the burn ---------
        fired_at = float(alert.get("fired_at", now))
        if self.profiler is not None:
            try:
                prof = self.profiler.profile(
                    window_s=self.profile_window_s, now=now)
                folded = prof.pop("folded")
                prof["overlapping"] = self.profiler.windows_overlapping(
                    fired_at - self.profile_window_s, now)
                put("profile.json", prof)
                put("profile_folded.txt",
                    self.profiler.folded_text(folded))
            except Exception as exc:
                put("profile.json", {"error": repr(exc)[:200]})

        # -- jlog tail ------------------------------------------------
        put("jlog_tail.txt", "\n".join(self._tail.buf))

        # -- cluster fan-out: every peer's state at the burn instant --
        partial = False
        peer_status: Dict[str, str] = {}
        for ep in self.peers:
            snap = self._fetch_peer(ep)
            safe = ep.replace(":", "_").replace("/", "_")
            if snap is None:
                partial = True
                peer_status[ep] = "unreachable"
                put(f"peers/{safe}.json",
                    {"endpoint": ep, "error": "unreachable"})
            else:
                peer_status[ep] = "ok"
                put(f"peers/{safe}.json", snap)

        put("incident.json", {
            "schema": 1, "id": inc_id, "node": self.node_name,
            "objective": alert.get("objective"),
            "alert": alert, "captured_at": now,
            "cooldown_s": self.cooldown_s, "partial": partial,
            "peers": peer_status})
        put(MANIFEST, {"id": inc_id, "created_at": now,
                       "algo": "sha256", "files": files})

        final = os.path.join(self.dir, inc_id)
        os.replace(tmp, final)
        try:
            self._captured_c.add(1)
        except Exception:
            pass
        jlog(logger, "incidents.captured", level=logging.WARNING,
             id=inc_id, objective=alert.get("objective"),
             partial=partial, dir=final)
        self._gc()
        return inc_id

    def _fetch_peer(self, endpoint: str) -> Optional[dict]:
        """One peer's /incidents/snapshot; None on ANY failure — a dead
        peer must not sink the bundle (it gets marked partial instead)."""
        url = f"http://{endpoint}/incidents/snapshot"
        try:
            with urllib.request.urlopen(
                    url, timeout=self.peer_timeout_s) as resp:
                return json.loads(resp.read())
        except Exception:
            logger.warning("incident fan-out: %s unreachable", endpoint)
            return None

    def _gc(self) -> None:
        """Bounded retention: keep the newest `keep` bundles."""
        try:
            bundles = sorted(d for d in os.listdir(self.dir)
                             if d.startswith("incident_"))
        except OSError:
            return
        for d in bundles[:max(0, len(bundles) - self.keep)]:
            shutil.rmtree(os.path.join(self.dir, d), ignore_errors=True)

    # -- reading -------------------------------------------------------------

    def list(self) -> List[dict]:
        out: List[dict] = []
        try:
            bundles = sorted(d for d in os.listdir(self.dir)
                             if d.startswith("incident_"))
        except OSError:
            return out
        for d in bundles:
            meta = {"id": d}
            try:
                with open(os.path.join(self.dir, d,
                                       "incident.json")) as f:
                    inc = json.load(f)
                meta.update(objective=inc.get("objective"),
                            captured_at=inc.get("captured_at"),
                            partial=inc.get("partial", False),
                            node=inc.get("node"))
            except (OSError, ValueError) as exc:
                meta["error"] = str(exc)[:200]
            out.append(meta)
        return out

    def index(self) -> dict:
        with self._lock:
            suppressed = list(self._suppressed)
        incidents = self.list()
        return {"dir": self.dir, "count": len(incidents),
                "keep": self.keep, "cooldown_s": self.cooldown_s,
                "peers": list(self.peers),
                "suppressed": suppressed, "incidents": incidents}

    def get(self, inc_id: str) -> Optional[dict]:
        """One bundle's manifest + fresh verification + file sizes."""
        bundle = os.path.join(self.dir, inc_id)
        if not (inc_id.startswith("incident_")
                and os.path.isdir(bundle)):
            return None
        out: dict = {"id": inc_id, "dir": bundle}
        try:
            with open(os.path.join(bundle, "incident.json")) as f:
                out["incident"] = json.load(f)
        except (OSError, ValueError) as exc:
            out["incident"] = {"error": str(exc)[:200]}
        try:
            with open(os.path.join(bundle, MANIFEST)) as f:
                manifest = json.load(f)
            out["files"] = {
                rel: os.path.getsize(os.path.join(bundle, rel))
                for rel in manifest.get("files", {})
                if os.path.exists(os.path.join(bundle, rel))}
        except (OSError, ValueError):
            out["files"] = {}
        out["verify"] = verify_bundle(bundle)
        return out

    def self_snapshot(self) -> dict:
        """What THIS node serves to a firing peer's fan-out: sources,
        SLO status, and the profile windows covering the recent past —
        everything except the heavyweight folded stacks."""
        snaps: Dict[str, object] = {}
        for sname, fn in sorted(self._sources.items()):
            try:
                snaps[sname] = fn()
            except Exception as exc:
                snaps[sname] = {"error": repr(exc)[:200]}
        out = {"node": self.node_name, "time": self._clock(),
               "snapshots": snaps}
        if self._slo is not None:
            try:
                out["slo"] = self._slo.status()
            except Exception as exc:
                out["slo"] = {"error": repr(exc)[:200]}
        if self.profiler is not None:
            try:
                prof = self.profiler.profile(
                    window_s=self.profile_window_s)
                prof.pop("folded", None)
                out["profile"] = prof
            except Exception as exc:
                out["profile"] = {"error": repr(exc)[:200]}
        return out

    # -- lifecycle -----------------------------------------------------------

    def drain(self, timeout_s: float = 30.0) -> None:
        """Wait for in-flight async captures (scenario/test teardown)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            threads = list(self._threads)
        for th in threads:
            th.join(timeout=max(0.0, deadline - time.monotonic()))

    def stop(self) -> None:
        self.drain(timeout_s=5.0)
        if self._slo is not None:
            # == not `is`: bound methods are re-created per attribute
            # access, but compare equal for the same (func, instance)
            if getattr(self._slo, "on_fire", None) == self.on_alert_fired:
                self._slo.on_fire = None
            if getattr(self._slo, "on_clear", None) \
                    == self.on_alert_cleared:
                self._slo.on_clear = None
        logging.getLogger("fabric_tpu").removeHandler(self._tail)


def register_routes(ops, recorder: IncidentRecorder) -> None:
    """Mount GET /incidents, /incidents/<id>, /incidents/snapshot.
    Specific prefixes FIRST: the ops server matches registered prefixes
    in insertion order."""
    ops.register_route(
        "GET", "/incidents/snapshot",
        lambda path, body: (200, recorder.self_snapshot()))

    def _one(path: str, body: bytes) -> Tuple[int, dict]:
        inc_id = path.split("?", 1)[0].rstrip("/").rsplit("/", 1)[-1]
        out = recorder.get(inc_id)
        if out is None:
            return 404, {"error": "unknown incident", "id": inc_id}
        return 200, out

    ops.register_route("GET", "/incidents/", _one)
    ops.register_route("GET", "/incidents",
                       lambda path, body: (200, recorder.index()))
