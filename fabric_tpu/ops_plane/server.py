"""Ops HTTP server: /metrics, /healthz, /logspec, /version.

Reference parity: /root/reference/core/operations/system.go:75-267 —
Prometheus exposition, health checks with per-checker status, runtime
log-level administration (the flogging /logspec admin), and a version
endpoint.  Plain http.server (stdlib): the ops surface is control-plane
only and stays off the data path.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from .metrics import MetricsRegistry, registry as default_registry

VERSION = "fabric-tpu/0.2"


class OperationsServer:
    """healthz checkers: name -> callable() (raise or return falsy = FAIL,
    mirroring the healthz.StatusOK / failed-checks JSON of system.go:203)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 metrics: Optional[MetricsRegistry] = None):
        self.metrics = metrics or default_registry
        self._checkers: Dict[str, Callable] = {}
        # fleet lifecycle: a provider returning "serving" | "draining" |
        # "drained", surfaced in the /healthz body so rollout tooling
        # (chaos rolling_restart, node.top LIFECYCLE column) can watch a
        # drain complete without a separate endpoint.  A draining node
        # still answers 200 when its checkers pass — drain is an
        # ORDERLY state, not a failure.
        self.lifecycle_fn: Optional[Callable] = None
        # extension routes: (method, path-prefix) -> fn(path, body) ->
        # (code, json-able) — e.g. the orderer's channelparticipation REST
        self._routes: Dict[tuple, Callable] = {}
        ops = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "text/plain; charset=utf-8"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, ops.metrics.expose_text().encode())
                elif self.path == "/healthz":
                    ok, failed = ops.run_checks()
                    out = {"status": "OK" if ok else "Service Unavailable",
                           "failed_checks": failed}
                    if ops.lifecycle_fn is not None:
                        try:
                            out["lifecycle"] = str(ops.lifecycle_fn())
                        except Exception:
                            pass
                    body = json.dumps(out).encode()
                    self._send(200 if ok else 503, body, "application/json")
                elif self.path == "/version":
                    self._send(200, json.dumps({"version": VERSION}).encode(),
                               "application/json")
                elif self.path == "/logspec":
                    level = logging.getLevelName(
                        logging.getLogger().getEffectiveLevel())
                    self._send(200, json.dumps({"spec": level}).encode(),
                               "application/json")
                else:
                    self._route("GET") or self._send(404, b"not found")

            def _route(self, method: str) -> bool:
                for (m, prefix), fn in ops._routes.items():
                    if m == method and self.path.startswith(prefix):
                        try:
                            ln = int(self.headers.get("Content-Length", "0"))
                            body = self.rfile.read(ln) if ln else b""
                            code, out = fn(self.path, body)
                            if isinstance(out, str):
                                # routes may return plain text (folded
                                # profile stacks) instead of a jsonable
                                self._send(code, out.encode())
                            else:
                                self._send(code, json.dumps(out).encode(),
                                           "application/json")
                        except Exception as exc:
                            self._send(400, str(exc).encode())
                        return True
                return False

            def do_POST(self):
                self._route("POST") or self._send(404, b"not found")

            def do_DELETE(self):
                self._route("DELETE") or self._send(404, b"not found")

            def do_PUT(self):
                if self.path == "/logspec":
                    # runtime log-level admin (flogging/httpadmin parity)
                    try:
                        ln = int(self.headers.get("Content-Length", "0"))
                        spec = json.loads(self.rfile.read(ln))["spec"]
                        logging.getLogger().setLevel(spec.upper())
                        self._send(204, b"")
                    except Exception as exc:
                        self._send(400, str(exc).encode())
                else:
                    self._send(404, b"not found")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._httpd.server_address
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    def register_checker(self, name: str, check: Callable) -> None:
        self._checkers[name] = check

    def register_route(self, method: str, path_prefix: str,
                       fn: Callable) -> None:
        """fn(path, body_bytes) -> (status_code, json-able body)."""
        self._routes[(method.upper(), path_prefix)] = fn

    def run_checks(self):
        failed = []
        for name, check in self._checkers.items():
            try:
                result = check()
                if result is not None and not result:
                    failed.append({"component": name, "reason": "unhealthy"})
            except Exception as exc:
                failed.append({"component": name, "reason": str(exc)[:200]})
        return not failed, failed

    def start(self) -> "OperationsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
