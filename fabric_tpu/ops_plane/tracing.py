"""Span tracer + in-memory flight recorder for the tx pipeline.

Dependency-free (stdlib only) tracing in the OpenTelemetry shape —
trace_id/span_id/parent, monotonic timestamps, attributes — with W3C
`traceparent`-style context propagation carried inside the RPC plane's
req/cast frames (comm/rpc.py adds a "tp" field when an ambient span is
active).  Two trace families exist:

  * request traces — rooted at an opted-in client (GatewayClient /
    examples/gateway_load.py) and continued across processes by the
    RPC server, covering gateway admission, endorsement and ordering;
  * block traces — rooted at `committer.store_block`, covering VSCC
    batch verify (device time), MVCC, ledger append and commit
    notification.

The two are stitched by **links**: the commit notifier remembers each
block's trace id, and the gateway's commit_status span links to it, so
`GET /traces/<request-id>` exports the request's spans *and* the linked
block's spans in one Chrome trace-event JSON (Perfetto-loadable).

The flight recorder is bounded: last N complete traces + K slowest.
Everything is off by default — `tracer` starts disabled and every
instrumentation site gets the shared no-op span, keeping the hot path
at one attribute load — and is switched on per-node via the `tracing`
sub-dict of localconfig (`FABRIC_TPU_PEER_TRACING__SAMPLE_RATE=0.1`
etc.), mirroring how Fabric gates its operations surface.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional

from .metrics import registry as default_registry

# one wall-clock anchor so exported timestamps are perf_counter-precise
# relative to each other yet land on real epoch time in Perfetto
_WALL_ANCHOR = time.time() - time.perf_counter()

_SPAN_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, float("inf"))


class SpanContext(NamedTuple):
    """Propagatable identity of a span (the traceparent payload)."""
    trace_id: str            # 32 lowercase hex chars
    span_id: str             # 16 lowercase hex chars
    sampled: bool
    remote: bool = False     # True when parsed off the wire


def format_traceparent(ctx: SpanContext) -> str:
    return "00-%s-%s-%s" % (ctx.trace_id, ctx.span_id,
                            "01" if ctx.sampled else "00")


def parse_traceparent(value) -> Optional[SpanContext]:
    """Parse `00-<32hex>-<16hex>-<2hex>`; returns None on any malformation."""
    if not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
        flags = int(parts[3], 16)
    except ValueError:
        return None
    return SpanContext(parts[1], parts[2], bool(flags & 1), remote=True)


class _NoopSpan:
    """Shared do-nothing span: returned whenever tracing is off."""
    __slots__ = ()
    recording = False
    context = None

    def set_attribute(self, key, value):
        return self

    def add_link(self, trace_id):
        return self

    def add_event(self, name, **attributes):
        return self

    def end(self, status: str = "OK", end_time: Optional[float] = None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NOOP_SPAN = _NoopSpan()


class Span:
    """A live span.  Use as a context manager (activates its context on
    the current thread) or keep the object and call .end() from another
    thread — cross-thread handoff is how the gateway's admission-queue
    wait span is closed by the batcher."""

    __slots__ = ("_tracer", "name", "context", "parent_id", "start",
                 "attributes", "status", "thread", "_ended", "_prev",
                 "_entered")

    recording = True

    def __init__(self, tracer: "Tracer", name: str, context: SpanContext,
                 parent_id: Optional[str], attributes: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.start = time.perf_counter()
        self.attributes = dict(attributes) if attributes else {}
        self.status = "OK"
        self.thread = threading.current_thread().name
        self._ended = False
        self._prev = None
        self._entered = False

    def set_attribute(self, key, value):
        self.attributes[key] = value
        return self

    def add_link(self, trace_id: Optional[str]):
        """Record a pointer to another trace (request <-> block stitch)."""
        if trace_id:
            self.attributes.setdefault("links", []).append(trace_id)
        return self

    def add_event(self, name: str, **attributes):
        """Timestamped annotation INSIDE this span — what happened at
        +Nms into a long operation (a fault fired, a breaker tripped).
        Exported with the span under attributes["events"]."""
        ev = {"name": name,
              "t_offset_ms": round(
                  (time.perf_counter() - self.start) * 1e3, 3)}
        if attributes:
            ev.update(attributes)
        self.attributes.setdefault("events", []).append(ev)
        return self

    def end(self, status: str = "OK", end_time: Optional[float] = None):
        if self._ended:
            return
        self._ended = True
        if status != "OK":
            self.status = status
        self._tracer._on_span_end(
            self, end_time if end_time is not None else time.perf_counter())

    def __enter__(self):
        tls = self._tracer._tls
        self._prev = getattr(tls, "ctx", None)
        tls.ctx = self.context
        self._entered = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._entered:
            self._tracer._tls.ctx = self._prev
            self._entered = False
        if exc_type is not None:
            self.set_attribute("error", repr(exc))
            self.end(status="ERROR")
        else:
            self.end()
        return False


class _Activation:
    __slots__ = ("_tls", "_ctx", "_prev")

    def __init__(self, tls, ctx):
        self._tls = tls
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(self._tls, "ctx", None)
        if self._ctx is not None:
            self._tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        if self._ctx is not None:
            self._tls.ctx = self._prev
        return False


class FlightRecorder:
    """Bounded store of finished traces: last `max_traces` complete ones
    plus the `max_slow` slowest ever seen (so a tail-latency outlier
    survives long after ring eviction — the flight-recorder property).

    `retention` adds a per-root-span-name cap on top of the global ring:
    ``{"gossip.pull_window": 8}`` keeps only the newest 8 pull-window
    traces, so a high-frequency poller can't flush the rarer (and more
    interesting) request/block traces out of the recorder.  Configured
    via the tracing localconfig sub-dict, e.g.
    ``FABRIC_TPU_PEER_TRACING__RETENTION='{"gossip.pull_window": 8}'``."""

    def __init__(self, max_traces: int = 256, max_slow: int = 32,
                 retention: Optional[Dict[str, int]] = None):
        self.max_traces = int(max_traces)
        self.max_slow = int(max_slow)
        self.retention = dict(retention or {})   # root span name -> max kept
        self._lock = threading.Lock()
        self._recent: "OrderedDict[str, dict]" = OrderedDict()
        self._slow: List[dict] = []          # sorted by duration desc

    def add(self, record: dict) -> None:
        with self._lock:
            tid = record["trace_id"]
            old = self._recent.pop(tid, None)
            if old is not None:              # late fragment: merge spans
                old["spans"].extend(record["spans"])
                old["duration_s"] = max(old["duration_s"],
                                        record["duration_s"])
                record = old
            self._recent[tid] = record
            root = record.get("root_name")
            cap = self.retention.get(root) if self.retention else None
            if cap is not None:
                # oldest-first: OrderedDict keeps insertion order
                same = [k for k, r in self._recent.items()
                        if r.get("root_name") == root]
                for k in same[:max(0, len(same) - int(cap))]:
                    self._maybe_keep_slow(self._recent.pop(k))
            while len(self._recent) > self.max_traces:
                evicted_id, evicted = self._recent.popitem(last=False)
                self._maybe_keep_slow(evicted)
            self._maybe_keep_slow(record)

    def _maybe_keep_slow(self, record: dict) -> None:
        if self.max_slow <= 0:
            return
        for r in self._slow:
            if r["trace_id"] == record["trace_id"]:
                return
        self._slow.append(record)
        self._slow.sort(key=lambda r: -r["duration_s"])
        del self._slow[self.max_slow:]

    def append_span(self, trace_id: str, span: dict) -> bool:
        """Attach a late span to an already-finished trace, if retained."""
        with self._lock:
            rec = self._recent.get(trace_id)
            if rec is None:
                for r in self._slow:
                    if r["trace_id"] == trace_id:
                        rec = r
                        break
            if rec is None:
                return False
            rec["spans"].append(span)
            return True

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            rec = self._recent.get(trace_id)
            if rec is None:
                for r in self._slow:
                    if r["trace_id"] == trace_id:
                        rec = r
                        break
            return rec

    def list(self) -> dict:
        def summary(rec):
            return {"trace_id": rec["trace_id"],
                    "root": rec.get("root_name"),
                    "start": rec.get("start_wall"),
                    "duration_ms": round(rec["duration_s"] * 1e3, 3),
                    "n_spans": len(rec["spans"])}
        with self._lock:
            recent = [summary(r) for r in reversed(self._recent.values())]
            slow = [summary(r) for r in self._slow]
        return {"recent": recent, "slowest": slow}

    def clear(self) -> None:
        with self._lock:
            self._recent.clear()
            self._slow.clear()


class Tracer:
    """Process-wide tracer.  Sampling is decided once at root-span
    creation and rides the context flags everywhere downstream."""

    def __init__(self, recorder: Optional[FlightRecorder] = None):
        self.enabled = False
        self.sample_rate = 1.0
        self.recorder = recorder or FlightRecorder()
        self._tls = threading.local()
        self._lock = threading.Lock()
        # trace_id -> {"spans": [dict], "open_roots": set, "t0": perf,
        #              "root_name": str, "start_wall": float}
        self._active: Dict[str, dict] = {}
        self._stats: Dict[str, list] = {}    # name -> [n, sum, max, buckets]
        self._registry = default_registry
        self._rand = random.Random(os.urandom(8))

    # -- configuration ------------------------------------------------------

    def configure(self, cfg: Optional[dict] = None, *,
                  default_enabled: bool = True) -> "Tracer":
        """Apply a localconfig `tracing` sub-dict.  Called by node
        constructors, so env overrides like
        FABRIC_TPU_PEER_TRACING__SAMPLE_RATE work out of the box."""
        cfg = cfg or {}
        self.enabled = bool(cfg.get("enabled", default_enabled))
        self.sample_rate = max(0.0, min(1.0, float(
            cfg.get("sample_rate", self.sample_rate))))
        self.recorder.max_traces = int(
            cfg.get("max_traces", self.recorder.max_traces))
        self.recorder.max_slow = int(
            cfg.get("max_slow", self.recorder.max_slow))
        retention = cfg.get("retention")
        if retention is not None:
            self.recorder.retention = {str(k): int(v)
                                       for k, v in dict(retention).items()}
        return self

    # -- context ------------------------------------------------------------

    def current_context(self) -> Optional[SpanContext]:
        return getattr(self._tls, "ctx", None)

    def current_trace_id(self) -> Optional[str]:
        ctx = getattr(self._tls, "ctx", None)
        return ctx.trace_id if ctx is not None else None

    def traceparent(self) -> Optional[str]:
        """Wire form of the ambient context, or None (fast when idle)."""
        ctx = getattr(self._tls, "ctx", None)
        return format_traceparent(ctx) if ctx is not None else None

    def context_from(self, traceparent) -> Optional[SpanContext]:
        if not self.enabled:
            return None
        return parse_traceparent(traceparent)

    def activate(self, ctx: Optional[SpanContext]):
        """Context manager making `ctx` the ambient context on this
        thread without opening a span (per-item context switching in
        batched handlers)."""
        return _Activation(self._tls, ctx)

    # -- span creation ------------------------------------------------------

    def start_span(self, name: str, parent="ambient",
                   attributes: Optional[dict] = None,
                   require_parent: bool = False):
        """Create a span.  parent: "ambient" (default, thread-local),
        a SpanContext, or None to force a new root.  require_parent=True
        yields a no-op when there is no ambient/explicit parent — used by
        mid-pipeline stages so untraced traffic records nothing."""
        if not self.enabled:
            return NOOP_SPAN
        if parent == "ambient":
            parent = getattr(self._tls, "ctx", None)
        if parent is None:
            if require_parent:
                return NOOP_SPAN
            sampled = self.sample_rate >= 1.0 or \
                self._rand.random() < self.sample_rate
            ctx = SpanContext(os.urandom(16).hex(), os.urandom(8).hex(),
                              sampled)
            span = Span(self, name, ctx, None, attributes)
            if sampled:
                self._register_root(span)
            return span
        ctx = SpanContext(parent.trace_id, os.urandom(8).hex(),
                          parent.sampled)
        span = Span(self, name, ctx, parent.span_id, attributes)
        if parent.sampled and parent.remote:
            # continuing a trace whose root lives in another process:
            # this span anchors the local fragment
            self._register_root(span)
        return span

    def record_span(self, name: str, start: float, end: float,
                    attributes: Optional[dict] = None,
                    parent: Optional[SpanContext] = None) -> None:
        """Retroactive span from explicit perf_counter() endpoints —
        used for phases timed by existing code (CommitStats et al.)."""
        if not self.enabled:
            return
        if parent is None:
            parent = getattr(self._tls, "ctx", None)
        if parent is None or not parent.sampled:
            return
        ctx = SpanContext(parent.trace_id, os.urandom(8).hex(),
                          True)
        span = Span(self, name, ctx, parent.span_id, attributes)
        span.start = start
        span.end(end_time=end)

    def event(self, name: str, **attributes) -> None:
        """Instant annotation on the AMBIENT trace: a zero-duration
        child span of whatever is active on this thread.  For code that
        has no span object in hand (the fault plane firing deep inside
        the transport) but should still show up on /traces/<id>.
        No ambient sampled context => free no-op."""
        if not self.enabled:
            return
        ctx = getattr(self._tls, "ctx", None)
        if ctx is None or not ctx.sampled:
            return
        now = time.perf_counter()
        self.record_span(name, now, now,
                         attributes=attributes or None, parent=ctx)

    # -- lifecycle plumbing -------------------------------------------------

    def _register_root(self, span: Span) -> None:
        with self._lock:
            entry = self._active.get(span.context.trace_id)
            if entry is None:
                entry = {"spans": [], "open_roots": set(),
                         "t0": span.start, "root_name": span.name,
                         "start_wall": span.start + _WALL_ANCHOR}
                self._active[span.context.trace_id] = entry
                # backstop against leaked fragments (e.g. a remote caller
                # that dies before its server span ends)
                if len(self._active) > max(64, 2 * self.recorder.max_traces):
                    tid, stale = next(iter(self._active.items()))
                    del self._active[tid]
                    self._finalize_locked(tid, stale)
            entry["open_roots"].add(span.context.span_id)

    def _on_span_end(self, span: Span, end: float) -> None:
        dur = max(0.0, end - span.start)
        self._observe(span.name, dur)
        if not span.context.sampled:
            return
        d = {"name": span.name, "trace_id": span.context.trace_id,
             "span_id": span.context.span_id, "parent_id": span.parent_id,
             "start": span.start, "duration_s": dur,
             "thread": span.thread, "status": span.status,
             "attributes": span.attributes}
        tid = span.context.trace_id
        with self._lock:
            entry = self._active.get(tid)
            if entry is not None:
                entry["spans"].append(d)
                entry["open_roots"].discard(span.context.span_id)
                if not entry["open_roots"]:
                    del self._active[tid]
                    self._finalize_locked(tid, entry)
                return
        # trace already finalized (late child, e.g. a lagging listener):
        # try to attach to the retained record, else drop
        self.recorder.append_span(tid, d)

    def _finalize_locked(self, trace_id: str, entry: dict) -> None:
        spans = entry["spans"]
        if not spans:
            return
        t0 = min(s["start"] for s in spans)
        t1 = max(s["start"] + s["duration_s"] for s in spans)
        self.recorder.add({"trace_id": trace_id,
                           "root_name": entry["root_name"],
                           "start_wall": entry["start_wall"],
                           "duration_s": t1 - t0,
                           "spans": spans})

    # -- per-stage stats ----------------------------------------------------

    def _observe(self, name: str, dur: float) -> None:
        try:
            with self._lock:
                st = self._stats.get(name)
                if st is None:
                    st = [0, 0.0, 0.0, [0] * len(_SPAN_BUCKETS)]
                    self._stats[name] = st
                st[0] += 1
                st[1] += dur
                st[2] = max(st[2], dur)
                for i, ub in enumerate(_SPAN_BUCKETS):
                    if dur <= ub:
                        st[3][i] += 1
                        break
            self._registry.histogram(
                "span_duration_seconds",
                "Duration of tracer spans by span name",
                buckets=_SPAN_BUCKETS).observe(dur, span=name)
        except Exception:
            pass                 # stats must never break the traced path

    def span_stats(self) -> dict:
        with self._lock:
            out = {}
            for name, (n, total, mx, buckets) in sorted(self._stats.items()):
                out[name] = {
                    "count": n,
                    "total_s": round(total, 6),
                    "mean_ms": round(total / n * 1e3, 3) if n else 0.0,
                    "max_ms": round(mx * 1e3, 3),
                    "buckets": {("+Inf" if ub == float("inf") else repr(ub)): c
                                for ub, c in zip(_SPAN_BUCKETS, buckets)},
                }
        return out

    # -- export -------------------------------------------------------------

    def export_chrome(self, trace_id: str,
                      follow_links: bool = True,
                      max_traces: int = 16) -> Optional[dict]:
        """Chrome trace-event JSON for one trace plus the transitive
        closure of its linked traces (bounded by `max_traces`),
        loadable in Perfetto / chrome://tracing.  Transitive: a client
        request links its block trace, which links the speculative
        verify traces that pre-verified its signatures — all of them
        belong in one picture."""
        rec = self.recorder.get(trace_id)
        if rec is None:
            return None
        records = [rec]
        truncated = False
        if follow_links:
            seen = {trace_id}
            frontier = [rec]
            while frontier:
                nxt = []
                for r in frontier:
                    for span in r["spans"]:
                        for linked in span["attributes"].get("links", ()):
                            if linked in seen:
                                continue
                            if len(records) >= max_traces:
                                # bounded on purpose, but never silently:
                                # the export says so and telemetry counts
                                truncated = True
                                continue
                            seen.add(linked)
                            lrec = self.recorder.get(linked)
                            if lrec is not None:
                                records.append(lrec)
                                nxt.append(lrec)
                frontier = nxt
        if truncated:
            default_registry.counter(
                "tracing_export_links_truncated_total",
                "export_chrome link closures cut at max_traces").add()
        events = []
        tids: Dict[str, int] = {}
        for r in records:
            for s in r["spans"]:
                tid = tids.setdefault(s["thread"], len(tids) + 1)
                args = dict(s["attributes"])
                args.update({"trace_id": s["trace_id"],
                             "span_id": s["span_id"],
                             "parent_id": s["parent_id"],
                             "status": s["status"]})
                events.append({
                    "name": s["name"], "cat": "fabric_tpu", "ph": "X",
                    "ts": round((s["start"] + _WALL_ANCHOR) * 1e6, 3),
                    "dur": round(s["duration_s"] * 1e6, 3),
                    "pid": 1, "tid": tid, "args": args,
                })
        for thread, tid in tids.items():
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tid, "args": {"name": thread}})
        events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0)))
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {"trace_id": trace_id,
                              "root": rec.get("root_name"),
                              "n_traces_merged": len(records),
                              "truncated": truncated}}

    def reset(self) -> None:
        """Drop all state (tests)."""
        with self._lock:
            self._active.clear()
            self._stats.clear()
        self.recorder.clear()


tracer = Tracer()                # the process default


def configure(cfg: Optional[dict] = None, *,
              default_enabled: bool = True) -> Tracer:
    return tracer.configure(cfg, default_enabled=default_enabled)


def event(name: str, **attributes) -> None:
    """Module-level shorthand for `tracer.event` (ambient annotation)."""
    tracer.event(name, **attributes)


def register_routes(ops, t: Optional[Tracer] = None,
                    cluster_fn=None) -> None:
    """Mount GET /traces, /traces/<id>, /spans/stats on an
    OperationsServer.

    Query params on /traces/<id>: `follow=0` exports the one trace
    without its link closure (node/tracecollect.py follows links
    cluster-wide itself), and `cluster=1` delegates to `cluster_fn`
    (trace_id -> (code, payload)) — the node-wired cross-node assembly
    — when one was registered.
    """
    from urllib.parse import parse_qs, urlparse

    t = t or tracer

    def _traces(path: str, body: bytes):
        u = urlparse(path)
        q = parse_qs(u.query)
        tail = u.path[len("/traces"):].strip("/")
        if not tail:
            return 200, t.recorder.list()
        if cluster_fn is not None and \
                (q.get("cluster") or ["0"])[0] not in ("", "0", "false"):
            return cluster_fn(tail)
        follow = (q.get("follow") or ["1"])[0] not in ("0", "false")
        out = t.export_chrome(tail, follow_links=follow)
        if out is None:
            return 404, {"error": "unknown trace", "trace_id": tail}
        return 200, out

    def _stats(path: str, body: bytes):
        return 200, {"enabled": t.enabled,
                     "sample_rate": t.sample_rate,
                     "spans": t.span_stats()}

    ops.register_route("GET", "/traces", _traces)
    ops.register_route("GET", "/spans/stats", _stats)
