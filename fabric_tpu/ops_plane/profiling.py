"""Profiling surface: host+device trace capture behind the ops plane.

Reference parity: the peer serves Go pprof when peer.profile.enabled
(/root/reference/internal/peer/node/start.go:813-825); the orderer
likewise (orderer/common/server/main.go:408).  The TPU-native analogue
captures BOTH planes:

  * device: jax.profiler traces (XLA/TPU timeline, one .trace per
    capture) — POST /debug/profile?seconds=N writes a trace directory
    and returns its path;
  * host: cProfile over the same window — POST /debug/pprof?seconds=N
    returns pstats text for the capture window;
  * per-phase device timings: the provider's dispatch/resolve spans are
    recorded as histogram metrics (fabric_tpu/ops_plane/metrics.py) and
    appear on /metrics alongside the commit-phase timings.

Wire-up: node/peer.py and node/orderer.py register these routes on
their OperationsServer when `profiling: true` is configured.
"""

from __future__ import annotations

import cProfile
import io
import os
import pstats
import tempfile
import threading
import time

_lock = threading.Lock()


def capture_device_trace(seconds: float, out_dir: str = None) -> dict:
    """Capture a jax.profiler trace for `seconds`; returns metadata.

    The trace is written under out_dir (default: a fresh directory in
    the system tmpdir) in TensorBoard/xplane format — load with
    `tensorboard --logdir` or xprof.  Device work happening in other
    threads during the window is captured too (the point: profile a
    serving node under live block traffic)."""
    import jax

    out_dir = out_dir or tempfile.mkdtemp(prefix="fabric_tpu_trace_")
    if not _lock.acquire(blocking=False):
        return {"error": "a capture is already in progress"}
    try:
        jax.profiler.start_trace(out_dir)
        time.sleep(seconds)
        jax.profiler.stop_trace()
    finally:
        _lock.release()
    files = []
    for root, _dirs, names in os.walk(out_dir):
        files.extend(os.path.join(root, n) for n in names)
    return {"trace_dir": out_dir, "seconds": seconds,
            "files": sorted(files)[:50]}


def capture_host_profile(seconds: float, top: int = 40) -> dict:
    """cProfile the whole process for `seconds`; returns pstats text.

    Captures all Python work in the window (the Go pprof CPU-profile
    shape).  Note: profiles only Python frames — device time shows as
    blocking calls into jax."""
    if not _lock.acquire(blocking=False):
        return {"error": "a capture is already in progress"}
    prof = cProfile.Profile()
    try:
        prof.enable()
        time.sleep(seconds)
    finally:
        prof.disable()
        _lock.release()
    buf = io.StringIO()
    stats = pstats.Stats(prof, stream=buf)
    stats.sort_stats("cumulative").print_stats(top)
    return {"seconds": seconds, "pstats": buf.getvalue()}


def register_routes(ops, enabled: bool = True) -> None:
    """Install /debug/profile (device) and /debug/pprof (host) on an
    OperationsServer.  Gated by config like the reference's
    peer.profile.enabled — profiling endpoints stall the serving
    process and must be opt-in."""
    if not enabled:
        return

    def _seconds(path: str, default: float = 3.0) -> float:
        if "?" in path:
            for kv in path.split("?", 1)[1].split("&"):
                if kv.startswith("seconds="):
                    try:
                        return min(60.0, max(0.1, float(kv[8:])))
                    except ValueError:
                        pass
        return default

    def device(path: str, body: bytes):
        return 200, capture_device_trace(_seconds(path))

    def host(path: str, body: bytes):
        return 200, capture_host_profile(_seconds(path))

    ops.register_route("POST", "/debug/profile", device)
    ops.register_route("POST", "/debug/pprof", host)
