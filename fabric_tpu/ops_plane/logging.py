"""Structured one-line JSON logging, correlated with traces.

`jlog(log, "gateway.broadcast_failed", level=logging.ERROR, txid=...,
channel=...)` emits a single-line JSON record carrying the event name,
wall time, the ambient trace_id (when a span is active on the calling
thread) and any keyword fields.  One line per event keeps the records
grep-able and ingestible without a log-parsing stack, and the trace_id
field makes a failure log line jump straight to its flight-recorder
trace (`GET /traces/<trace_id>`).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Optional

from . import tracing


def jlog(log: logging.Logger, event: str, *, level: int = logging.INFO,
         exc: Optional[BaseException] = None, **fields) -> None:
    """Emit one structured JSON log line; never raises."""
    try:
        rec = {"event": event, "ts": round(time.time(), 6)}
        trace_id = tracing.tracer.current_trace_id()
        if trace_id:
            rec["trace_id"] = trace_id
        if exc is not None:
            rec["error"] = repr(exc)
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        log.log(level, json.dumps(rec, default=str, sort_keys=True))
    except Exception:
        pass
