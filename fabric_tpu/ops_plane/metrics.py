"""Metrics registry with Prometheus text exposition.

Reference parity: common/metrics/provider.go's Counter/Gauge/Histogram
abstraction + the prometheus provider.  Label support follows the same
With("name", value, ...) pairing convention.
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Dict, List, Optional, Tuple

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0, float("inf"))

# prometheus data-model name rules (common/expfmt); metric names may
# carry colons (recording-rule convention), label names may not
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# label names validated once, then cached — _label_key sits on the
# dispatch/commit hot paths
_validated_labels: set = set()


def _check_metric_name(name: str) -> str:
    if not _METRIC_NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _label_key(pairs) -> Tuple:
    for k in pairs:
        if k not in _validated_labels:
            if not _LABEL_NAME_RE.match(k):
                raise ValueError(f"invalid label name {k!r}")
            _validated_labels.add(k)
    return tuple(sorted(pairs.items()))


def _escape_label_value(v) -> str:
    s = str(v)
    if "\\" in s or '"' in s or "\n" in s:
        s = (s.replace("\\", "\\\\").replace('"', '\\"')
             .replace("\n", "\\n"))
    return s


def _fmt_labels(key: Tuple, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = _check_metric_name(name)
        self.help = help_
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = {}

    def add(self, delta: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + delta

    def value(self, **labels) -> float:
        k = _label_key(labels)
        with self._lock:
            return self._values.get(k, 0.0)

    def total(self) -> float:
        """Sum across every label set (SLO-window rate source)."""
        with self._lock:
            return sum(self._values.values())

    def total_by(self, label: str) -> Dict[str, float]:
        """Totals grouped by one label's value (per-channel SLO source);
        label sets without `label` are skipped — they can't be
        attributed to any group."""
        out: Dict[str, float] = {}
        with self._lock:
            for k, v in self._values.items():
                lv = dict(k).get(label)
                if lv is None:
                    continue
                out[lv] = out.get(lv, 0.0) + v
        return out

    def breakdown(self, group: str, **fixed) -> Dict[str, float]:
        """Totals grouped by `group`'s label value, restricted to label
        sets carrying every `fixed` label at the given value (e.g. one
        channel's demotion counts by reason)."""
        out: Dict[str, float] = {}
        with self._lock:
            for k, v in self._values.items():
                d = dict(k)
                if any(d.get(fk) != fv for fk, fv in fixed.items()):
                    continue
                gv = d.get(group)
                if gv is None:
                    continue
                out[gv] = out.get(gv, 0.0) + v
        return out

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name = _check_metric_name(name)
        self.help = help_
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def add(self, delta: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + delta

    def value(self, **labels) -> float:
        k = _label_key(labels)
        with self._lock:
            return self._values.get(k, 0.0)

    def values(self) -> Dict[Tuple, float]:
        """Snapshot of every label set (SLO breaker-fraction source)."""
        with self._lock:
            return dict(self._values)

    def mean_by(self, label: str) -> Dict[str, float]:
        """Per-label-value means (per-channel SLO source); label sets
        without `label` are skipped."""
        acc: Dict[str, List[float]] = {}
        with self._lock:
            for k, v in self._values.items():
                lv = dict(k).get(label)
                if lv is None:
                    continue
                a = acc.setdefault(lv, [0.0, 0.0])
                a[0] += v
                a[1] += 1.0
        return {lv: s / n for lv, (s, n) in acc.items()}

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: Tuple[float, ...] = _DEFAULT_BUCKETS):
        self.name = _check_metric_name(name)
        self.help = help_
        self.buckets = tuple(buckets)
        self._lock = threading.Lock()
        self._counts: Dict[Tuple, List[int]] = {}
        self._sum: Dict[Tuple, float] = {}
        self._n: Dict[Tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(k, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
            self._sum[k] = self._sum.get(k, 0.0) + value
            self._n[k] = self._n.get(k, 0) + 1

    def state(self) -> Tuple[List[int], float, int]:
        """Aggregate (bucket counts, sum, n) across every label set.

        Cumulative snapshots of this feed the SLO evaluator's windowed
        quantiles (delta between two snapshots = the window's
        distribution).
        """
        with self._lock:
            counts = [0] * len(self.buckets)
            for per_key in self._counts.values():
                for i, c in enumerate(per_key):
                    counts[i] += c
            return counts, sum(self._sum.values()), sum(self._n.values())

    def state_by(self, label: str) -> Dict[str, Tuple[List[int], float, int]]:
        """Per-label-value (bucket counts, sum, n) — the `state()` shape
        grouped by one label (per-channel SLO quantiles); label sets
        without `label` are skipped."""
        acc: Dict[str, list] = {}
        with self._lock:
            for k, counts in self._counts.items():
                lv = dict(k).get(label)
                if lv is None:
                    continue
                a = acc.setdefault(lv, [[0] * len(self.buckets), 0.0, 0])
                for i, c in enumerate(counts):
                    a[0][i] += c
                a[1] += self._sum[k]
                a[2] += self._n[k]
        return {lv: (c, s, n) for lv, (c, s, n) in acc.items()}

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            for k, counts in sorted(self._counts.items()):
                cum = 0
                for ub, c in zip(self.buckets, counts):
                    cum += c
                    le = "+Inf" if ub == float("inf") else repr(ub)
                    le_label = 'le="%s"' % le
                    out.append(f"{self.name}_bucket"
                               f"{_fmt_labels(k, le_label)} {cum}")
                out.append(f"{self.name}_sum{_fmt_labels(k)} {self._sum[k]}")
                out.append(f"{self.name}_count{_fmt_labels(k)} {self._n[k]}")
        return out


class MetricsRegistry:
    """Process metrics registry (the metrics.Provider role)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Tuple[float, ...] = _DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, buckets),
                         Histogram)

    def get(self, name: str):
        """Registered metric by name, or None (read-only lookup)."""
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> Dict[str, object]:
        """Snapshot of the registered set, name -> metric (the
        timeseries sampler's sweep source — read-only)."""
        with self._lock:
            return dict(self._metrics)

    def _get(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered "
                                f"as {type(m).__name__}")
            return m

    def expose_text(self) -> str:
        """Prometheus text exposition format (system.go:183 /metrics)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


registry = MetricsRegistry()     # the process default, like prometheus's
