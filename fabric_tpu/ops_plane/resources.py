"""Process resource telemetry: stdlib /proc collector feeding /metrics.

The leak gate (ROADMAP direction #4) needs RSS, fd count, thread
count, GC pressure, native arena-pool occupancy and verdict-cache
occupancy AS TIME SERIES — none of which the registry records today.
This collector samples them on an interval into plain gauges, so the
existing exposition (`/metrics`), the SLO evaluator and the timeseries
ring store all pick them up with zero extra wiring:

  process_resident_memory_bytes   /proc/self/status VmRSS
  process_open_fds                len(/proc/self/fd)
  process_threads                 threading.active_count()
  process_allocated_blocks        sys.getallocatedblocks() — the
                                  crispest pure-Python ref-leak proxy
  process_gc_collections_total    gc.get_stats(), {generation=} label
  process_gc_uncollectable_total  gc.get_stats(), {generation=} label
  native_arena_pool_free          _fastparse.stats() pool gauges
  native_arena_pool_hit_total     (arena reuse economics; absent when
  native_arena_pool_miss_total     the native parser isn't built)
  native_arena_pool_drop_total
  jax_live_buffer_bytes           sum of live jax array nbytes — only
                                  when jax is ALREADY imported (the
                                  collector never initializes a device)

Extra per-node series (verdict-cache occupancy, queue depths...) ride
`add_source(name, fn)`: fn() -> float, sampled with the same cadence
and surfaced as a gauge of the same name.

Zero-overhead guarantee: gauges register at construction time, so a
node that leaves the `resources` config sub-dict disabled constructs
nothing and its /metrics output is byte-identical to before this
module existed.  All reads are stdlib (/proc, gc, sys, threading) and
every probe degrades to "metric absent" off-Linux or when a source is
missing, never to an exception on the sampling thread.

`provenance()` also lives here: the {platform, device_kind, n_devices,
hostname} stamp bench.py records in every BENCH/MULTICHIP JSON, making
the ROADMAP's "cpu-virtual caveat" machine-readable.
"""

from __future__ import annotations

import gc
import logging
import os
import socket
import sys
import threading
import time
from typing import Callable, Dict, Optional

from .metrics import MetricsRegistry
from .metrics import registry as default_registry

logger = logging.getLogger("fabric_tpu.ops_plane.resources")

__all__ = ["ResourceCollector", "read_rss_bytes", "count_open_fds",
           "provenance", "register_routes"]


def read_rss_bytes() -> Optional[float]:
    """VmRSS from /proc/self/status, bytes; None off-Linux."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) * 1024.0
    except OSError:
        pass
    return None


def count_open_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def provenance() -> dict:
    """Where a measurement ran: {platform, device_kind, n_devices,
    hostname}.  `platform` is "tpu" only on real TPU devices —
    everything else (host-platform virtual meshes included) is
    "cpu-virtual", so a bench JSON carries the ROADMAP's wall-clock
    caveat in-band.  Never initializes jax itself: callers that bench
    devices have already imported it."""
    out = {"platform": "cpu-virtual", "device_kind": "unknown",
           "n_devices": 0, "hostname": socket.gethostname()}
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            devs = jax.devices()
            out["n_devices"] = len(devs)
            out["device_kind"] = str(
                getattr(devs[0], "device_kind", devs[0]))
            if getattr(devs[0], "platform", "cpu") == "tpu":
                out["platform"] = "tpu"
        except Exception:
            pass
    return out


class ResourceCollector:
    """Samples process/runtime resources into registry gauges.

    Config keys (the node's `resources` sub-dict):
      enabled       node-level gate (read by the node, not here)
      interval_s    sampling cadence (default 5.0)
      jax_buffers   include jax_live_buffer_bytes (default True; only
                    ever read when jax is already imported)
    """

    def __init__(self, cfg: Optional[dict] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None):
        cfg = dict(cfg or {})
        self.registry = registry or default_registry
        self._clock = clock or time.monotonic
        self.interval_s = max(0.05, float(cfg.get("interval_s", 5.0)))
        self.jax_buffers = bool(cfg.get("jax_buffers", True))
        self._sources: Dict[str, Callable[[], float]] = {}
        self._g_rss = self.registry.gauge(
            "process_resident_memory_bytes", "VmRSS of this process")
        self._g_fds = self.registry.gauge(
            "process_open_fds", "open file descriptors")
        self._g_threads = self.registry.gauge(
            "process_threads", "live Python threads")
        self._g_blocks = self.registry.gauge(
            "process_allocated_blocks",
            "sys.getallocatedblocks() — live interpreter allocations")
        self._g_gc_coll = self.registry.gauge(
            "process_gc_collections_total", "GC runs per generation")
        self._g_gc_unc = self.registry.gauge(
            "process_gc_uncollectable_total",
            "uncollectable objects per generation")
        self._g_jax = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_source(self, name: str, fn: Callable[[], float]) -> None:
        """Register an extra series: fn() -> float, sampled each
        collect into a gauge named `name` (exceptions skip the tick)."""
        self.registry.gauge(name, "resource collector source")
        self._sources[name] = fn

    # -- one sweep -----------------------------------------------------------

    def collect(self) -> dict:
        """Sample every source into its gauge; returns the snapshot."""
        snap: dict = {}
        rss = read_rss_bytes()
        if rss is not None:
            self._g_rss.set(rss)
            snap["process_resident_memory_bytes"] = rss
        fds = count_open_fds()
        if fds is not None:
            self._g_fds.set(float(fds))
            snap["process_open_fds"] = fds
        nthreads = float(threading.active_count())
        self._g_threads.set(nthreads)
        snap["process_threads"] = nthreads
        try:
            blocks = float(sys.getallocatedblocks())
            self._g_blocks.set(blocks)
            snap["process_allocated_blocks"] = blocks
        except Exception:
            pass
        try:
            for gen, st in enumerate(gc.get_stats()):
                self._g_gc_coll.set(float(st.get("collections", 0)),
                                    generation=str(gen))
                self._g_gc_unc.set(float(st.get("uncollectable", 0)),
                                   generation=str(gen))
            snap["process_gc_collections_total"] = sum(
                st.get("collections", 0) for st in gc.get_stats())
        except Exception:
            pass
        self._collect_native(snap)
        if self.jax_buffers:
            self._collect_jax(snap)
        for name, fn in self._sources.items():
            try:
                v = float(fn())
            except Exception:
                continue
            self.registry.gauge(name).set(v)
            snap[name] = v
        return snap

    def _collect_native(self, snap: dict) -> None:
        """Arena-pool occupancy from the native parser's counters —
        the parse-path's reuse economics, absent when _fastparse isn't
        built (the gauges simply never register)."""
        try:
            from fabric_tpu.native import _fastparse
            stats = _fastparse.stats()
        except Exception:
            return
        for key, metric in (("pool_free", "native_arena_pool_free"),
                            ("pool_hit", "native_arena_pool_hit_total"),
                            ("pool_miss", "native_arena_pool_miss_total"),
                            ("pool_drop", "native_arena_pool_drop_total")):
            if key in stats:
                self.registry.gauge(metric).set(float(stats[key]))
                snap[metric] = float(stats[key])

    def _collect_jax(self, snap: dict) -> None:
        """Live device-buffer bytes — only when jax is ALREADY loaded
        (sampling must never initialize a backend)."""
        jax = sys.modules.get("jax")
        if jax is None:
            return
        try:
            total = float(sum(getattr(a, "nbytes", 0)
                              for a in jax.live_arrays()))
        except Exception:
            return
        if self._g_jax is None:
            self._g_jax = self.registry.gauge(
                "jax_live_buffer_bytes", "bytes held by live jax arrays")
        self._g_jax.set(total)
        snap["jax_live_buffer_bytes"] = total

    # -- lifecycle -----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.collect()
            except Exception:       # keep the collector alive
                logger.exception("resource collect failed")

    def start(self) -> "ResourceCollector":
        if self._thread is None:
            self._stop.clear()
            self.collect()          # first point lands immediately
            self._thread = threading.Thread(
                target=self._loop, name="resource-collector", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def register_routes(ops, collector: ResourceCollector) -> None:
    """Mount GET /resources: one fresh snapshot as JSON (the same
    numbers the gauges carry, without parsing exposition text)."""

    def _resources(path: str, body: bytes):
        return 200, collector.collect()

    ops.register_route("GET", "/resources", _resources)
