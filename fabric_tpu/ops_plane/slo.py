"""SLO evaluator: multi-window burn-rate alerting over the metrics plane.

Implements the SRE-workbook multi-window pattern on top of the local
ops_plane registry: each objective is measured over a SHORT and a LONG
rolling window, burn rate = measured value relative to the objective's
threshold, and an alert fires only when BOTH windows burn — the short
window proves the problem is happening *now*, the long window proves it
is sustained (one slow block never pages, a stuck pipeline does).
Alerts are deduplicated by a per-objective state machine with hysteresis
(clear only when the short window drops below clear_ratio * threshold)
and land in three places: a jlog record, a `slo.alert` root span in the
trace stream, and the `/slo` + `/slo/alerts` ops routes.

Everything here is sampling/aggregation off the hot path: the evaluator
thread reads cumulative snapshots (Histogram.state / Counter.total /
Gauge.values) on an interval and derives windowed deltas, so observing
code never pays more than it already does for the registry.

Node wiring: the `slo` sub-dict of the local config (peer and orderer),
env-overridable as FABRIC_TPU_<ROLE>_SLO__<KEY> (localconfig tiering).

Per-channel objectives: `slo: {per_channel: ["commit_p99_s"]}` expands
the named objective into a channel-grouped template — one independent
instance (own windows, own burn state, own alert) per observed
`channel` label value, named `commit_p99_s_by_channel[<ch>]`, so one
slow channel pages without being averaged away by its quiet neighbours.
The aggregated original keeps running unchanged.  An objective may also
carry `per: <label>` directly to group by any other label.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .logging import jlog
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .metrics import registry as default_registry

logger = logging.getLogger("fabric_tpu.ops_plane.slo")

# the four pipeline-economics objectives every node watches out of the
# box; node config merges overrides (or adds new ones) by name
DEFAULT_OBJECTIVES: Dict[str, dict] = {
    "commit_p99_s": {
        "kind": "max", "source": "histogram_quantile",
        "metric": "validation_duration_seconds", "q": 0.99,
        "threshold": 5.0,
        "help": "per-block validate wall time p99 (seconds)"},
    "verify_throughput_floor": {
        "kind": "min", "source": "counter_rate",
        "metric": "provider_device_sigs_total", "threshold": 0.0,
        "help": "device-verified signatures per second"},
    "breaker_open_frac": {
        "kind": "max", "source": "gauge_mean",
        "metric": "gateway_orderer_breaker_open", "threshold": 0.5,
        "help": "fraction of orderer circuit breakers open"},
    "overlap_floor": {
        "kind": "min", "source": "gauge_mean",
        "metric": "pipeline_collect_under_verify_frac", "threshold": 0.0,
        "help": "live collect-under-verify overlap fraction"},
}

_BURN_CAP = 1e6          # keep /slo JSON strict (no Infinity literals)


def _burn(kind: str, value: Optional[float],
          threshold: float) -> Optional[float]:
    """Burn rate: 1.0 = consuming budget exactly at the threshold.

    max-objectives (value must stay <= threshold): value/threshold.
    min-objectives (value must stay >= threshold): threshold/value.
    """
    if value is None:
        return None
    if kind == "max":
        if threshold <= 0.0:
            return 0.0 if value <= 0.0 else _BURN_CAP
        return min(_BURN_CAP, value / threshold)
    if threshold <= 0.0:
        return 0.0
    if value <= 0.0:
        return _BURN_CAP
    return min(_BURN_CAP, threshold / value)


class SloEvaluator:
    """Samples the registry on an interval, evaluates objectives over
    short/long windows, and runs the multi-window alert state machine."""

    def __init__(self, cfg: Optional[dict] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None):
        cfg = dict(cfg or {})
        self.registry = registry or default_registry
        self._clock = clock or time.monotonic
        self.sample_interval_s = float(cfg.get("sample_interval_s", 5.0))
        self.short_window_s = float(cfg.get("short_window_s", 60.0))
        self.long_window_s = float(cfg.get("long_window_s", 300.0))
        self.burn_threshold = float(cfg.get("burn_threshold", 1.0))
        self.clear_ratio = float(cfg.get("clear_ratio", 0.9))
        # delta sources need this much of the window covered by samples
        self.min_coverage = float(cfg.get("min_coverage", 0.5))

        self.objectives: Dict[str, dict] = {}
        merged = {k: dict(v) for k, v in DEFAULT_OBJECTIVES.items()}
        for name, o in (cfg.get("objectives") or {}).items():
            merged.setdefault(name, {}).update(o or {})
        # `per_channel: [names]` templates: each named objective (after
        # the merge above) also gets a channel-expanded variant that
        # evaluates — and alerts — once per observed `channel` label
        # value, so one slow channel pages as `commit_p99_s_by_channel
        # [ch]` without drowning in the aggregate.  The aggregated
        # original keeps running unchanged.  An objective may also
        # carry `per: <label>` directly.
        for name in cfg.get("per_channel") or ():
            base = merged.get(name)
            if base is None:
                raise ValueError(
                    f"slo per_channel names unknown objective {name!r}")
            merged[f"{name}_by_channel"] = dict(base, per="channel")
        for name, o in merged.items():
            if o.get("enabled", True) is False:
                continue
            o.setdefault("kind", "max")
            o.setdefault("source", "gauge_mean")
            o.setdefault("threshold", 0.0)
            if "metric" not in o:
                raise ValueError(f"slo objective {name!r} needs a metric")
            self.objectives[name] = o

        maxlen = max(16, int(self.long_window_s /
                             max(self.sample_interval_s, 1e-3)) * 2 + 4)
        self._samples: deque = deque(maxlen=min(maxlen, 4096))
        self._lock = threading.RLock()
        self._states: Dict[str, dict] = {
            n: {"state": "no_data", "since": time.time()}
            for n in self.objectives}
        self._active: Dict[str, dict] = {}
        self._history: deque = deque(maxlen=64)
        self._last_status: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # alert-transition hooks (incidents.py attaches here): called
        # as hook(name, alert_dict) AFTER the transition is recorded.
        # Invoked under the evaluator lock — hooks must not block
        # (the incident recorder only spawns a capture thread).
        self.on_fire = None
        self.on_clear = None

    # -- sampling ------------------------------------------------------------

    @staticmethod
    def _snap_key(o: dict) -> str:
        """Snapshot key: the metric name, suffixed with the grouping
        label for `per` objectives so an aggregated and a per-channel
        objective over the SAME metric coexist in one sample."""
        per = o.get("per")
        return f"{o['metric']}|{per}" if per else o["metric"]

    def _capture(self) -> dict:
        snap: dict = {}
        for o in self.objectives.values():
            key = self._snap_key(o)
            if key in snap:
                continue
            m = self.registry.get(o["metric"])
            per = o.get("per")
            if per:
                # grouped snapshot: {label value -> classic-shape state}
                if isinstance(m, Histogram):
                    snap[key] = ("h*", m.buckets, m.state_by(per))
                elif isinstance(m, Counter):
                    snap[key] = ("c*", m.total_by(per))
                elif isinstance(m, Gauge):
                    snap[key] = ("g*", m.mean_by(per))
                continue
            if isinstance(m, Histogram):
                snap[key] = ("h", m.buckets, m.state())
            elif isinstance(m, Counter):
                snap[key] = ("c", m.total())
            elif isinstance(m, Gauge):
                vals = m.values()
                snap[key] = ("g", (sum(vals.values()) / len(vals))
                             if vals else None)
        return snap

    def sample(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        snap = self._capture()
        with self._lock:
            self._samples.append((now, snap))

    # -- windowed values -----------------------------------------------------

    def _select(self, o: dict, group: Optional[str]):
        """Entry accessor for one sample dict: classic objectives read
        the metric's aggregate tuple; `per` instances project their
        group's slice out of the grouped snapshot into the same
        ("h"/"c"/"g", ...) shape so the windowing below is shared."""
        key = self._snap_key(o)
        if group is None:
            return lambda p: p.get(key)

        def sel(p):
            ent = p.get(key)
            if ent is None:
                return None
            if ent[0] == "h*":
                st = ent[2].get(group)
                return None if st is None else ("h", ent[1], st)
            if ent[0] == "c*":
                v = ent[1].get(group)
                return None if v is None else ("c", v)
            if ent[0] == "g*":
                v = ent[1].get(group)
                return None if v is None else ("g", v)
            return None
        return sel

    def _window_value(self, o: dict, samples: list, now: float,
                      window_s: float,
                      group: Optional[str] = None) -> Optional[float]:
        src = o["source"]
        sel = self._select(o, group)
        if src == "gauge_mean":
            vals = [sel(p)[1] for t, p in samples
                    if now - window_s < t <= now and sel(p) is not None
                    and sel(p)[0] == "g" and sel(p)[1] is not None]
            return (sum(vals) / len(vals)) if vals else None
        # delta sources: newest sample vs the newest sample at/before
        # the window start (falling back to the oldest we have)
        present = [(t, sel(p)) for t, p in samples if sel(p) is not None]
        if len(present) < 2:
            return None
        t1, e1 = present[-1]
        base = None
        for t, e in present:
            if t <= now - window_s:
                base = (t, e)
            else:
                break
        t0, e0 = base if base is not None else present[0]
        span = t1 - t0
        if span <= 0.0 or span < self.min_coverage * window_s:
            return None
        if src == "counter_rate":
            if e0[0] != "c" or e1[0] != "c":
                return None
            return max(0.0, e1[1] - e0[1]) / span
        if src == "histogram_quantile":
            if e0[0] != "h" or e1[0] != "h":
                return None
            buckets = e1[1]
            c0, _, n0 = e0[2]
            c1, _, n1 = e1[2]
            n = n1 - n0
            if n <= 0:
                return None
            target = float(o.get("q", 0.99)) * n
            cum = 0
            last_finite = 0.0
            for ub, a, b in zip(buckets, c1, c0):
                cum += a - b
                if ub != float("inf"):
                    last_finite = ub
                if cum >= target:
                    return ub if ub != float("inf") else last_finite
            return last_finite
        return None

    # -- evaluation + alert state machine ------------------------------------

    def _observed_groups(self, o: dict, samples: list) -> List[str]:
        """Every label value a `per` objective's metric was seen with in
        the current sample set (union across samples, so a group that
        just went quiet still evaluates its long window)."""
        key = self._snap_key(o)
        groups: set = set()
        for _, p in samples:
            ent = p.get(key)
            if ent is None:
                continue
            groups.update(ent[2] if ent[0] == "h*" else ent[1])
        return sorted(groups)

    def _eval_one(self, name: str, o: dict, samples: list, now: float,
                  group: Optional[str] = None) -> dict:
        short_s = float(o.get("short_window_s", self.short_window_s))
        long_s = float(o.get("long_window_s", self.long_window_s))
        bt = float(o.get("burn_threshold", self.burn_threshold))
        kind = o["kind"]
        thr = float(o["threshold"])
        vs = self._window_value(o, samples, now, short_s, group=group)
        vl = self._window_value(o, samples, now, long_s, group=group)
        bs = _burn(kind, vs, thr)
        bl = _burn(kind, vl, thr)
        with self._lock:
            st = self._states.setdefault(
                name, {"state": "no_data", "since": time.time()})
            prev = st["state"]
            if prev == "alerting":
                # hysteresis: only a clearly-healthy SHORT window
                # clears; no-data holds the alert (absence of
                # evidence is not recovery)
                if bs is not None and bs < bt * self.clear_ratio:
                    st["state"] = "ok"
                    st["since"] = time.time()
                    self._clear_alert(name, o, vs, bs, bl)
            else:
                if bs is not None and bl is not None \
                        and bs >= bt and bl >= bt:
                    st["state"] = "alerting"
                    st["since"] = time.time()
                    self._fire_alert(name, o, vs, bs, bl)
                elif bs is None and bl is None:
                    if prev != "no_data":
                        st["state"] = "no_data"
                        st["since"] = time.time()
                elif prev != "ok":
                    st["state"] = "ok"
                    st["since"] = time.time()
            state = st["state"]
            since = st["since"]
        status = {
            "name": name, "kind": kind, "source": o["source"],
            "metric": o["metric"], "threshold": thr,
            "help": o.get("help", ""),
            "windows": {"short_s": short_s, "long_s": long_s},
            "burn_threshold": bt,
            "value_short": vs, "value_long": vl,
            "burn_short": bs, "burn_long": bl,
            "state": state, "since": since}
        if o.get("per"):
            status["per"] = o["per"]
            status["group"] = group
        return status

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        now = self._clock() if now is None else now
        with self._lock:
            samples = list(self._samples)
        statuses: List[dict] = []
        for name, o in self.objectives.items():
            if not o.get("per"):
                statuses.append(self._eval_one(name, o, samples, now))
                continue
            # per-label objective: one independent instance (own windows,
            # own alert state, own /slo row) per observed label value
            groups = self._observed_groups(o, samples)
            if not groups:
                statuses.append(self._eval_one(name, o, samples, now))
                continue
            for g in groups:
                statuses.append(self._eval_one(
                    f"{name}[{g}]", o, samples, now, group=g))
        with self._lock:
            self._last_status = statuses
        return statuses

    def _alert_attrs(self, name, o, value, bs, bl) -> dict:
        # `o` is passed in (not looked up) because per-label instance
        # names like "commit_p99_s_by_channel[ch1]" are not objective
        # keys — they share their template's config
        return {"objective": name, "metric": o["metric"],
                "kind": o["kind"], "threshold": float(o["threshold"]),
                "value": value, "burn_short": bs, "burn_long": bl}

    def _fire_alert(self, name, o, value, bs, bl) -> None:
        rec = dict(self._alert_attrs(name, o, value, bs, bl),
                   state="firing", fired_at=time.time())
        self._active[name] = rec
        self._history.append(rec)
        try:
            self.registry.counter(
                "slo_alerts_total", "SLO alerts fired").add(
                    1, objective=name)
            self.registry.gauge(
                "slo_alerting", "1 while the objective is alerting").set(
                    1.0, objective=name)
        except Exception:
            pass
        jlog(logger, "slo.alert_fired", level=logging.WARNING,
             **self._alert_attrs(name, o, value, bs, bl))
        self._trace_alert("slo.alert_fired", name, o, value, bs, bl)
        hook = self.on_fire
        if hook is not None:
            try:
                hook(name, dict(rec))
            except Exception:
                logger.exception("slo on_fire hook failed")

    def _clear_alert(self, name, o, value, bs, bl) -> None:
        rec = self._active.pop(name, None)
        if rec is not None:
            rec["state"] = "resolved"
            rec["cleared_at"] = time.time()
        try:
            self.registry.gauge(
                "slo_alerting", "1 while the objective is alerting").set(
                    0.0, objective=name)
        except Exception:
            pass
        jlog(logger, "slo.alert_cleared",
             **self._alert_attrs(name, o, value, bs, bl))
        self._trace_alert("slo.alert_cleared", name, o, value, bs, bl)
        hook = self.on_clear
        if hook is not None:
            try:
                hook(name, dict(rec) if rec is not None
                     else self._alert_attrs(name, o, value, bs, bl))
            except Exception:
                logger.exception("slo on_clear hook failed")

    def _trace_alert(self, event, name, o, value, bs, bl) -> None:
        """Alert transitions land in the trace stream as a `slo.alert`
        root span carrying an event annotation — the evaluator thread
        has no ambient request context, so it roots its own trace."""
        try:
            from . import tracing
            attrs = self._alert_attrs(name, o, value, bs, bl)
            with tracing.tracer.start_span("slo.alert", attributes=attrs):
                tracing.event(event, **attrs)
        except Exception:
            pass

    # -- public surface ------------------------------------------------------

    def step(self, now: Optional[float] = None) -> None:
        self.sample(now)
        self.evaluate(now)

    def status(self) -> dict:
        with self._lock:
            statuses = list(self._last_status)
            n_samples = len(self._samples)
            active = sorted(self._active)
        if not statuses:           # no step yet: evaluate on demand
            statuses = self.evaluate()
            with self._lock:
                active = sorted(self._active)
        return {"enabled": True, "sampled_at": time.time(),
                "sample_count": n_samples,
                "sample_interval_s": self.sample_interval_s,
                "windows": {"short_s": self.short_window_s,
                            "long_s": self.long_window_s},
                "burn_threshold": self.burn_threshold,
                "clear_ratio": self.clear_ratio,
                "alerting": active,
                "objectives": statuses}

    def alerts_snapshot(self) -> dict:
        with self._lock:
            return {"active": [dict(r) for r in self._active.values()],
                    "history": [dict(r) for r in self._history]}

    def burn_state(self) -> dict:
        """Consumer view for load-control planes (gateway admission):
        the max short-window burn across objectives plus the per-
        objective burns.  Reads the LAST evaluation only — never
        re-samples — so callers may poll it on a hot path."""
        with self._lock:
            statuses = list(self._last_status)
            active = sorted(self._active)
        burns = {s["name"]: s["burn_short"] for s in statuses
                 if s.get("burn_short") is not None}
        return {"max_burn_short": max(burns.values()) if burns else None,
                "alerting": active, "burns": burns}

    # -- background thread ---------------------------------------------------

    def start(self) -> "SloEvaluator":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.sample_interval_s):
                try:
                    self.step()
                except Exception:      # never take the node down
                    logger.exception("slo evaluator step failed")

        self._thread = threading.Thread(
            target=loop, name="slo-evaluator", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=2.0)


def register_routes(ops, evaluator: SloEvaluator) -> None:
    """Mount GET /slo and GET /slo/alerts.  /slo/alerts first: the ops
    server matches registered prefixes in insertion order."""
    ops.register_route("GET", "/slo/alerts",
                       lambda path, body: (200,
                                           evaluator.alerts_snapshot()))
    ops.register_route("GET", "/slo",
                       lambda path, body: (200, evaluator.status()))
