"""Chaos harness: a live in-process topology with kill/restart and
fault-plan drills.

`ChaosNet` provisions a dev network (provision.provision_network),
starts every node in-process, and keeps each node's JSON config so any
component can be **crash-stopped** (`kill`) and **restarted**
(`restart`) against its on-disk state — the orderer replays its raft
WAL, the peer re-runs ledger recovery (`BlockStore._recover`,
`KVLedger._recover`).  The kill is the crash-stop model: listeners
close immediately, in-flight work is abandoned, and the only surviving
state is what was already durable on disk.

Combined with `fabric_tpu.comm.faults` this is the robustness test
rig: install a seeded `FaultPlan`, drive traffic, kill/restart nodes,
then assert the convergence invariants with `heights()` /
`commit_hashes()` / `wait_converged()` — every peer at the same height
with the same chained commit hash, which is exactly the state-machine-
replication promise the pipeline must keep under faults.

    net = ChaosNet(base_dir, n_orderers=3)
    net.start()
    plan = faults.install(FaultPlan(seed=7).rule(drop=0.05, dup=0.05))
    ...drive traffic...
    net.kill("orderer1"); net.restart("orderer1")
    faults.uninstall()
    assert net.wait_converged(timeout_s=30)
"""

from __future__ import annotations

import errno
import json
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("fabric_tpu.testing.chaos")


class ChaosNet:
    """One in-process dev network with lifecycle control per node."""

    def __init__(self, base_dir: str, n_orderers: int = 3,
                 peer_orgs=("Org1", "Org2"), peers_per_org: int = 1,
                 channel_id: str = "ch", batch=None,
                 gateway_cfg: Optional[dict] = None,
                 peer_overrides: Optional[dict] = None,
                 orderer_overrides: Optional[dict] = None,
                 node_factory=None, spare_orderers: int = 0):
        from fabric_tpu.node.provision import provision_network
        self.base_dir = str(base_dir)
        self.channel_id = channel_id
        self.paths = provision_network(
            self.base_dir, n_orderers=n_orderers,
            peer_orgs=list(peer_orgs), peers_per_org=peers_per_org,
            channel_id=channel_id, batch=batch,
            spare_orderers=spare_orderers)
        self.gateway_cfg = gateway_cfg or {
            "linger_s": 0.002, "max_batch": 8,
            "broadcast_deadline_s": 20.0}
        self.peer_overrides = dict(peer_overrides or {})
        self.orderer_overrides = dict(orderer_overrides or {})
        # optional hook: callable(name, kind, cfg) -> node | None.  A
        # non-None return replaces the stock node — how adversarial
        # actors (testing/adversary.py) join a drill topology.
        self.node_factory = node_factory
        # name -> (kind, cfg-path); insertion order = start order
        self._specs: Dict[str, Tuple[str, str]] = {}
        for p in self.paths["orderers"]:
            self._specs[self._name_of(p)] = ("orderer", p)
        for p in self.paths["peers"]:
            self._specs[self._name_of(p)] = ("peer", p)
        # spare orderers: provisioned (identity + cfg on disk) but NOT
        # auto-started — a membership drill starts one with restart()
        # after committing its add-consenter config entry
        self._spares: set = set()
        for p in self.paths.get("spare_orderers", []):
            name = self._name_of(p)
            self._specs[name] = ("orderer", p)
            self._spares.add(name)
        self.nodes: Dict[str, object] = {}      # name -> live node

    @staticmethod
    def _name_of(cfg_path: str) -> str:
        import os
        return os.path.splitext(os.path.basename(cfg_path))[0]

    # -- lifecycle -------------------------------------------------------

    def _build(self, name: str):
        kind, path = self._specs[name]
        with open(path) as f:
            cfg = json.load(f)
        if kind == "orderer":
            cfg.update(self.orderer_overrides)
        else:
            cfg["gateway"] = dict(self.gateway_cfg)
            cfg.update(self.peer_overrides)
        if self.node_factory is not None:
            node = self.node_factory(name, kind, cfg)
            if node is not None:
                return node
        if kind == "orderer":
            from fabric_tpu.node.orderer import OrdererNode
            return OrdererNode(cfg, data_dir=cfg["data_dir"])
        from fabric_tpu.node.peer import PeerNode
        return PeerNode(cfg, data_dir=cfg["data_dir"])

    def start(self, leader_timeout_s: float = 60.0) -> "ChaosNet":
        for name, (kind, _) in self._specs.items():
            if kind == "orderer" and name not in self._spares:
                self.nodes[name] = self._build(name).start()
        self.wait_for_leader(leader_timeout_s)
        for name, (kind, _) in self._specs.items():
            if kind == "peer":
                self.nodes[name] = self._build(name).start()
        return self

    def spare_names(self) -> List[str]:
        """Provisioned-but-unjoined orderers, in raft-id order."""
        return sorted(self._spares)

    def spare_cfg(self, name: str) -> dict:
        """The spare's node config (raft_id, port, cert_fp, ...) — the
        material an add-consenter proposal needs."""
        _, path = self._specs[name]
        with open(path) as f:
            return json.load(f)

    def kill(self, name: str) -> None:
        """Crash-stop one node: close its listeners and abandon it.
        On-disk state stays exactly as fsync left it."""
        node = self.nodes.pop(name, None)
        if node is None:
            raise KeyError(f"{name!r} is not running")
        logger.warning("chaos: killing %s", name)
        node.stop()

    def restart(self, name: str, wait_s: float = 30.0):
        """Bring a killed node back from its on-disk state (raft WAL
        replay / ledger recovery happen in the constructor)."""
        if name in self.nodes:
            raise KeyError(f"{name!r} is already running")
        logger.warning("chaos: restarting %s", name)
        # the fixed port can transiently be claimed by an outbound
        # ephemeral connection (chaos retries dial constantly) or a
        # not-yet-drained socket from the killed node — retry the bind
        deadline = time.time() + 15.0
        while True:
            try:
                node = self._build(name).start()
                break
            except OSError as exc:
                if exc.errno != errno.EADDRINUSE or time.time() > deadline:
                    raise
                time.sleep(0.25)
        self.nodes[name] = node
        kind, _ = self._specs[name]
        if kind == "orderer":
            self.wait_for_leader(wait_s)
        return node

    def drain(self, name: str, timeout_s: float = 10.0) -> dict:
        """Graceful drain of one running node (peer or orderer): stop
        admitting, flush in-flight work, checkpoint/fsync, release
        leadership — the opposite of kill()'s crash-stop."""
        node = self.nodes.get(name)
        if node is None:
            raise KeyError(f"{name!r} is not running")
        logger.warning("chaos: draining %s", name)
        return node.drain(timeout_s=timeout_s)

    def rolling_restart(self, names: Optional[List[str]] = None,
                        drain_timeout_s: float = 10.0,
                        settle_s: float = 60.0) -> Dict[str, dict]:
        """The rolling-upgrade primitive: drain -> kill -> restart each
        named node in turn (default: every running node, orderers
        first), waiting for peer convergence after each peer restart so
        at most one node is ever down.  Returns per-node drain reports
        (a failed drain is recorded, the roll continues — an upgrade
        must not wedge on one stuck node)."""
        if names is None:
            names = [n for n, (k, _) in self._specs.items()
                     if n in self.nodes]
        reports: Dict[str, dict] = {}
        for name in names:
            if name not in self.nodes:
                continue
            try:
                reports[name] = self.drain(name,
                                           timeout_s=drain_timeout_s)
            except Exception as exc:
                logger.exception("chaos: drain of %s failed", name)
                reports[name] = {"error": str(exc)}
            self.kill(name)
            self.restart(name)
            if self._specs[name][0] == "peer":
                self.wait_converged(timeout_s=settle_s)
        return reports

    def stop_all(self) -> None:
        # peers first so their deliver loops stop hammering dead orderers
        for name in [n for n, (k, _) in self._specs.items() if k == "peer"]:
            node = self.nodes.pop(name, None)
            if node is not None:
                try:
                    node.stop()
                except Exception:
                    pass
        for name in list(self.nodes):
            node = self.nodes.pop(name)
            try:
                node.stop()
            except Exception:
                pass

    # -- topology views --------------------------------------------------

    def orderers(self) -> List:
        return [self.nodes[n] for n, (k, _) in self._specs.items()
                if k == "orderer" and n in self.nodes]

    def peers(self) -> List:
        return [self.nodes[n] for n, (k, _) in self._specs.items()
                if k == "peer" and n in self.nodes]

    def orderer_addr(self, name: str) -> Tuple[str, int]:
        _, path = self._specs[name]
        with open(path) as f:
            cfg = json.load(f)
        return (cfg["host"], int(cfg["port"]))

    def wait_for_leader(self, timeout_s: float = 60.0) -> None:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if any(o.support.chain.node.role == "leader"
                   for o in self.orderers()):
                return
            time.sleep(0.1)
        raise AssertionError("no raft leader within %.0fs" % timeout_s)

    def client(self, org: str = "Org1", peer_idx: int = 0,
               timeout: float = 5.0, call_timeout: float = 30.0):
        """GatewayClient bound to one running peer.  Raise the timeouts
        when the peers verify on a slow provider (e.g. the JAXTPU eager
        CPU path, seconds per handshake/endorse on a 1-core host)."""
        from fabric_tpu.gateway import GatewayClient
        from fabric_tpu.node.orderer import load_signing_identity
        with open(self.paths["clients"][org]) as f:
            cc = json.load(f)
        signer = load_signing_identity(
            cc["mspid"], cc["cert_pem"].encode(), cc["key_pem"].encode())
        peer = self.peers()[peer_idx]
        return GatewayClient(peer.rpc.addr, signer, peer.msps,
                             channel_id=self.channel_id,
                             timeout=timeout, call_timeout=call_timeout)

    # -- convergence invariants ------------------------------------------

    def heights(self) -> Dict[str, int]:
        return {n: p.channels[self.channel_id].ledger.height
                for n, p in self.nodes.items()
                if self._specs[n][0] == "peer"}

    def commit_hashes(self, height: Optional[int] = None) -> Dict[str, str]:
        """Each peer's chained commit hash; with `height`, the hash of
        the block at height-1 so peers ahead of the slowest still
        compare equal prefixes."""
        out = {}
        for n, p in self.nodes.items():
            if self._specs[n][0] != "peer":
                continue
            ledger = p.channels[self.channel_id].ledger
            if height is None:
                out[n] = ledger.commit_hash.hex()
            else:
                from fabric_tpu.protocol import block_header_hash
                blk = ledger.blockstore.get_by_number(height - 1)
                out[n] = block_header_hash(blk.header).hex()
        return out

    def wait_converged(self, timeout_s: float = 30.0,
                       min_height: Optional[int] = None) -> bool:
        """Block until every running peer reports the same height (>=
        min_height when given) AND identical commit hashes."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            hs = self.heights()
            if hs and len(set(hs.values())) == 1 and (
                    min_height is None
                    or next(iter(hs.values())) >= min_height):
                if len(set(self.commit_hashes().values())) == 1:
                    return True
            time.sleep(0.1)
        logger.error("chaos: convergence timed out: heights=%s hashes=%s",
                     self.heights(), self.commit_hashes())
        return False
