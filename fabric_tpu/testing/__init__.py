"""Test-support plane: the chaos harness (node lifecycle + fault-plan
drills).  Lives in the package, not tests/, so operators can drive
drills from scripts and the smoke gate."""

from .chaos import ChaosNet

__all__ = ["ChaosNet"]
