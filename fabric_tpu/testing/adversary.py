"""Adversarial actors: in-process nodes that commit Byzantine crimes
deterministically.

Crash-stop chaos (chaos.py + comm/faults.py) models components that
die; this module models components that LIE, so the byzantine plane's
detection/containment paths can be exercised as ordinary seeded tests:

  EquivocatingOrderer   a real OrdererNode (it orders, raft-replicates,
                        and serves honestly) whose deliver stream also
                        commits crimes on configured heights: it serves
                        the honest block AND a forged, validly-SIGNED
                        sibling at the same height (equivocation /
                        double-serve), or tampers the attestation
                        digests riding its deliver frames.
  forge_fork_block      build the history-rewrite weapon: a forged
                        sibling of an already-committed block, signed
                        with a consenter key — inject it via gossip and
                        every honest peer convicts the signer from its
                        blockstore witness ("fork"), with zero effect
                        on the committed chain.
  GossipPoisoner        injects garbage / badly-signed / stale payloads
                        (and forged blocks) straight into a victim
                        channel's gossip intake — the same entrypoint
                        transport casts land on, minus the transport,
                        so every injection is deterministic.

All forgeries are signed with REAL consenter keys (the adversary owns
an orderer identity), so they pass signature verification and reach the
witness/judgment layer — exactly the threat the byzantine plane exists
for.  Nothing here weakens honest nodes: adversaries are built only by
tests and scenarios, via ChaosNet's `node_factory` hook.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from fabric_tpu.node.orderer import OrdererNode

logger = logging.getLogger("fabric_tpu.testing.adversary")


def forge_sibling(block, signer) -> "Block":
    """A forged sibling of `block`: same height, same previous_hash,
    different data (one duplicated envelope) — so a DIFFERENT header
    hash — carrying a fully VALID orderer signature by `signer`.  This
    is the provable-misbehavior artifact: two validly-signed headers at
    one height."""
    from fabric_tpu.orderer.blockwriter import block_signed_bytes
    from fabric_tpu.protocol.build import new_nonce
    from fabric_tpu.protocol.types import (
        META_LAST_CONFIG,
        META_SIGNATURES,
        Block,
        BlockHeader,
        BlockMetadata,
        block_data_hash,
    )
    data = [bytes(d) for d in block.data]
    data.append(data[-1] if data else b"\x00")
    header = BlockHeader(block.header.number, block.header.previous_hash,
                         block_data_hash(data))
    last_config = int(block.metadata.items.get(META_LAST_CONFIG, 0))
    forged = Block(header, data, BlockMetadata({
        META_LAST_CONFIG: last_config}))
    sig_header = {"creator": signer.serialize(), "nonce": new_nonce()}
    forged.metadata.items[META_SIGNATURES] = [{
        "sig_header": sig_header,
        "signature": signer.sign(
            block_signed_bytes(forged, sig_header, last_config)),
    }]
    return forged


def forge_fork_block(blockstore, height: int, signer):
    """History rewrite: a validly-signed forged sibling of the COMMITTED
    block at `height` (the fork-at-height crime)."""
    return forge_sibling(blockstore.get_by_number(int(height)), signer)


def break_signature(block):
    """A copy of `block` whose header no longer matches its orderer
    signature (data_hash flipped, signature kept): parses fine, fails
    MCS verification — the `bad_sig` gossip offense."""
    from fabric_tpu.protocol.types import Block, BlockHeader
    bad_hash = bytes(b ^ 0xFF for b in block.header.data_hash)
    return Block(
        BlockHeader(block.header.number, block.header.previous_hash,
                    bad_hash),
        [bytes(d) for d in block.data], block.metadata)


class EquivocatingOrderer(OrdererNode):
    """An OrdererNode that commits deliver-plane crimes on demand.

    `crimes` keys:
      mode         "equivocate" (default): serve honest block then a
                   forged sibling at each crime height.
                   "two_faced": equivocate ONLY toward the peers named
                   in `victims` — every other caller gets a spotless
                   honest stream.  Without fraud-proof gossip, only the
                   victims ever hold conviction evidence; with it, one
                   victim's conviction must spread network-wide.
                   "tamper_attests": flip the attestation digests on
                   every deliver frame from `fork_height` on (requires
                   attest_deliver on this orderer + trust_attestations
                   on the peer).
      victims      ("two_faced" only) list of peer mspids and/or full
                   "mspid|cert-sha256" bindings the crimes target
      fork_height  first height the crime fires at (default 2 — past
                   genesis/config so the honest chain has traction)
      count        how many consecutive heights to hit (default 1)
      channel      restrict crimes to one channel (default: all)

    Honest-THEN-forged order is deliberate: the honest header reaches
    the victim first, so detection happens against a committed (or
    witnessed) honest hash and the drill's convergence assertions stay
    deterministic.  The forged sibling is still a complete, validly
    signed equivocation — exactly what a real double-serving orderer
    would emit."""

    def __init__(self, cfg: dict, data_dir: str,
                 crimes: Optional[dict] = None):
        super().__init__(cfg, data_dir)
        self.crimes = dict(crimes or {})
        self.crimes_committed: List[dict] = []

    def _crime_heights(self) -> range:
        start = int(self.crimes.get("fork_height", 2))
        return range(start, start + int(self.crimes.get("count", 1)))

    def _is_victim(self, peer_identity) -> bool:
        """two_faced target check: match the caller's mspid or its full
        mspid|cert-sha256 binding against crimes["victims"]."""
        victims = set(self.crimes.get("victims") or [])
        if peer_identity is None or not victims:
            return False
        labels = {getattr(peer_identity, "mspid", None)}
        try:
            from fabric_tpu.orderer.cluster import cert_fingerprint
            labels.add(f"{peer_identity.mspid}|"
                       f"{cert_fingerprint(peer_identity.cert)}")
        except Exception:
            pass
        return bool(victims & labels)

    def _rpc_deliver(self, body: dict, peer_identity):
        from fabric_tpu.protocol.types import Block
        mode = self.crimes.get("mode", "equivocate")
        only = self.crimes.get("channel")
        cid = body.get("channel")
        armed = only is None or cid == only
        if mode == "two_faced":
            # honest face for everyone but the configured victims; the
            # crime itself is the plain double-serve below
            armed = armed and self._is_victim(peer_identity)
            mode = "equivocate"
        heights = self._crime_heights()
        for out in super()._rpc_deliver(body, peer_identity):
            if not armed:
                yield out
                continue
            block = Block.deserialize(bytes(out["block"]))
            num = int(block.header.number)
            if mode == "tamper_attests" and num >= heights.start \
                    and block.data:
                out = dict(out)
                if out.get("attests"):
                    # flip real attestation digests riding the frame
                    out["attests"] = [
                        None if a is None else
                        "".join("%02x" % (int(c, 16) ^ 0xF) for c in a)
                        for a in out["attests"]]
                else:
                    # no cached verdicts to vouch for: fabricate a
                    # digest per envelope — re-derivation on the peer
                    # mismatches and revokes this attestor just the same
                    out["attests"] = ["5a" * 32] * len(block.data)
                self.crimes_committed.append(
                    {"kind": "tamper_attests", "height": num})
                yield out
                continue
            yield out
            if mode == "equivocate" and num in heights:
                forged = forge_sibling(block, self.signer)
                self.crimes_committed.append(
                    {"kind": "equivocate", "height": num,
                     "forged_hash": forged.hash().hex()})
                logger.warning("adversary: equivocating at height %d "
                               "on %r", num, cid)
                yield {"block": forged.serialize()}


class GossipPoisoner:
    """Deterministic gossip-intake attacker for one victim channel.

    Injections land on `GossipState.handle` — the exact entrypoint the
    gossip transport dispatches casts to — under a fixed fake transport
    endpoint, so offense scoring and quarantine hit a stable identity
    (`gossip|<endpoint>`)."""

    def __init__(self, victim_channel, endpoint: str = "evil:0"):
        self.state = victim_channel.gossip.state
        self.endpoint = endpoint
        self.sent: Dict[str, int] = {}

    def _note(self, kind: str, n: int = 1) -> None:
        self.sent[kind] = self.sent.get(kind, 0) + n

    def garbage(self, n: int = 1) -> None:
        """Unparseable payloads: each scores a `garbage` offense."""
        from fabric_tpu.gossip.state import MSG_BLOCK
        for i in range(int(n)):
            self.state.handle(MSG_BLOCK, self.endpoint,
                              {"block": b"\xde\xad\xbe\xef" + bytes([i])})
        self._note("garbage", n)

    def bad_sig(self, n: int = 1) -> None:
        """Blocks whose header was tampered after signing: parse fine,
        fail MCS verification, score `bad_sig` offenses."""
        from fabric_tpu.gossip.state import MSG_BLOCK
        store = self.state.committer.ledger.blockstore
        if store.height == 0:
            raise RuntimeError("victim has no committed block to tamper")
        raw = break_signature(
            store.get_by_number(store.height - 1)).serialize()
        for _ in range(int(n)):
            self.state.handle(MSG_BLOCK, self.endpoint, {"block": raw})
        self._note("bad_sig", n)

    def stale(self, n: int = 1) -> None:
        """Replay the victim's own genesis block: tolerated (dropped as
        an idempotent dup), never an offense — anti-entropy replays
        stale blocks all the time."""
        from fabric_tpu.gossip.state import MSG_BLOCK
        store = self.state.committer.ledger.blockstore
        raw = store.get_by_number(0).serialize()
        for _ in range(int(n)):
            self.state.handle(MSG_BLOCK, self.endpoint, {"block": raw})
        self._note("stale", n)

    def inject(self, block) -> None:
        """Deliver an arbitrary (e.g. forged) block as a gossip frame."""
        from fabric_tpu.gossip.state import MSG_BLOCK
        self.state.handle(MSG_BLOCK, self.endpoint,
                          {"block": block.serialize()})
        self._note("inject")


def adversary_factory(crimes_by_name: Dict[str, dict]):
    """A ChaosNet `node_factory` that builds EquivocatingOrderer for the
    named orderers (e.g. {"orderer1": {"fork_height": 4}})."""

    def _factory(name: str, kind: str, cfg: dict):
        crimes = crimes_by_name.get(name)
        if crimes is None or kind != "orderer":
            return None
        return EquivocatingOrderer(cfg, data_dir=cfg["data_dir"],
                                   crimes=crimes)

    return _factory
