"""Anonymous credentials over BN254 — the Idemix capability pillar.

Reference parity (host-side scope per VERDICT.md next-round #9):
/root/reference/idemix/{issuerkey,credential,signature}.go implement a
CL/BBS+-family anonymous credential scheme over BN254 (via fabric-amcl):
an issuer signs an attribute vector; the holder later proves possession
in zero knowledge, selectively disclosing attributes, unlinkably across
presentations.  This module implements the same BBS+ structure
(A = (g1 h0^s prod hi^mi)^(1/(e+x))) with the standard presentation
protocol (randomized signature + two Fiat-Shamir Schnorr proofs), on the
from-scratch pairing of fabric_tpu/idemix/bn254.py.

Wire/test-vector compatibility with fabric-amcl is NOT claimed (different
generator derivation and hash-to-group); the scheme, proof obligations,
and verification equations are the reference's.  The batched TPU pairing
kernel (BASELINE config 4) is a later-round target; this is the host
oracle it will be differentially tested against.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import bn254 as bn


def _rand_zr() -> int:
    return secrets.randbelow(bn.R - 1) + 1


def _hash_zr(*parts) -> int:
    h = hashlib.sha256()
    for p in parts:
        if isinstance(p, int):
            p = p.to_bytes(32, "big")
        elif isinstance(p, tuple):
            p = repr(p).encode()
        h.update(p)
        h.update(b"|")
    return int.from_bytes(h.digest(), "big") % bn.R


def attr_to_zr(value: bytes) -> int:
    return int.from_bytes(hashlib.sha256(value).digest(), "big") % bn.R


@dataclass
class IssuerKey:
    """isk = x; ipk = (w = g2^x, bases h0..hL) (issuerkey.go)."""
    x: int
    w: bn.G2Point
    h: List[Tuple[int, int]]     # h[0] = HRand; h[1..L] = attribute bases
    n_attrs: int

    @staticmethod
    def generate(n_attrs: int) -> "IssuerKey":
        x = _rand_zr()
        w = bn.g2_mul(x, bn.G2_GEN)
        h = [bn.hash_to_g1(b"fabric-tpu-idemix-h%d" % i)
             for i in range(n_attrs + 1)]
        return IssuerKey(x, w, h, n_attrs)

    def public(self) -> "IssuerPublicKey":
        return IssuerPublicKey(self.w, self.h, self.n_attrs)


@dataclass
class IssuerPublicKey:
    w: bn.G2Point
    h: List[Tuple[int, int]]
    n_attrs: int


@dataclass
class Credential:
    """(A, e, s) on attributes m1..mL (credential.go)."""
    A: Tuple[int, int]
    e: int
    s: int
    attrs: List[int]

    def B(self, ipk: IssuerPublicKey):
        b = bn.g1_add(bn.G1_GEN, bn.g1_mul(self.s, ipk.h[0]))
        for i, m in enumerate(self.attrs):
            b = bn.g1_add(b, bn.g1_mul(m, ipk.h[i + 1]))
        return b


def issue(isk: IssuerKey, attrs: Sequence[int]) -> Credential:
    if len(attrs) != isk.n_attrs:
        raise ValueError("attribute count mismatch")
    e = _rand_zr()
    s = _rand_zr()
    cred = Credential(None, e, s, list(attrs))
    b = cred.B(isk.public())
    inv = pow((e + isk.x) % bn.R, -1, bn.R)
    cred.A = bn.g1_mul(inv, b)
    return cred


def verify_credential(ipk: IssuerPublicKey, cred: Credential) -> bool:
    """e(A, w * g2^e) == e(B, g2) (signature.go credential check)."""
    if cred.A is None or not bn.g1_on_curve(cred.A):
        return False
    lhs = bn.pairing(cred.A, bn.g2_add(ipk.w, bn.g2_mul(cred.e, bn.G2_GEN)))
    rhs = bn.pairing(cred.B(ipk), bn.G2_GEN)
    return lhs == rhs


# ---------------------------------------------------------------------------
# Presentation: selective disclosure, unlinkable (signature.go NewSignature /
# Ver — the BBS+ SPK with Fiat-Shamir)
# ---------------------------------------------------------------------------

@dataclass
class Presentation:
    A_prime: Tuple[int, int]
    A_bar: Tuple[int, int]
    d: Tuple[int, int]
    c: int
    z_e: int
    z_r2: int
    z_r3: int
    z_sprime: int
    z_hidden: Dict[int, int]          # attr index -> response
    disclosed: Dict[int, int]         # attr index -> attribute value
    nonrev: Optional[dict] = None     # joint non-revocation proof fields


def present(ipk: IssuerPublicKey, cred: Credential,
            disclose: Sequence[int], nonce: bytes,
            nonrev=None, rh_index: Optional[int] = None) -> Presentation:
    """Randomize (A, e, s) and prove possession, disclosing attrs in
    `disclose` (indices).

    nonrev: optional revocation.NonRevProver — its weak-BB proof shares
    the hidden rh attribute's Schnorr response through the JOINT
    Fiat-Shamir challenge, binding "some unrevoked handle" to "THIS
    credential's handle" (nonrevocation-prover.go).  rh_index selects
    the handle attribute (must be hidden).
    """
    D = set(disclose)
    if nonrev is not None and (rh_index is None or rh_index in D):
        raise ValueError("non-revocation needs a HIDDEN rh attribute")
    r1 = _rand_zr()
    r2 = _rand_zr()
    r3 = pow(r1, -1, bn.R)
    B = cred.B(ipk)
    A_prime = bn.g1_mul(r1, cred.A)
    A_bar = bn.g1_add(bn.g1_mul((-cred.e) % bn.R, A_prime), bn.g1_mul(r1, B))
    d = bn.g1_add(bn.g1_mul(r1, B), bn.g1_mul((-r2) % bn.R, ipk.h[0]))
    s_prime = (cred.s - r2 * r3) % bn.R

    # pi1: A_bar - d = -e * A' + r2 * h0      (knowledge of e, r2)
    re_, rr2 = _rand_zr(), _rand_zr()
    t1 = bn.g1_add(bn.g1_mul((-re_) % bn.R, A_prime), bn.g1_mul(rr2, ipk.h[0]))
    # pi2: g1 + sum_D mi*hi = r3*d - s'*h0 - sum_{!D} mi*hi
    rr3, rs = _rand_zr(), _rand_zr()
    rm = {i: _rand_zr() for i in range(len(cred.attrs)) if i not in D}
    t2 = bn.g1_add(bn.g1_mul(rr3, d), bn.g1_mul((-rs) % bn.R, ipk.h[0]))
    for i, r in rm.items():
        t2 = bn.g1_add(t2, bn.g1_mul((-r) % bn.R, ipk.h[i + 1]))

    disclosed = {i: cred.attrs[i] for i in D}
    extra = ()
    if nonrev is not None:
        extra = nonrev.commit(rm[rh_index])
    c = _hash_zr(A_prime, A_bar, d, t1, t2, *extra, nonce,
                 repr(sorted(disclosed.items())).encode())

    return Presentation(
        A_prime=A_prime, A_bar=A_bar, d=d, c=c,
        z_e=(re_ + c * cred.e) % bn.R,
        z_r2=(rr2 + c * r2) % bn.R,
        z_r3=(rr3 + c * r3) % bn.R,
        z_sprime=(rs + c * s_prime) % bn.R,
        z_hidden={i: (rm[i] + c * cred.attrs[i]) % bn.R for i in rm},
        disclosed=disclosed,
        nonrev=nonrev.respond(c) if nonrev is not None else None,
    )


def verify_presentation(ipk: IssuerPublicKey, pres: Presentation,
                        nonce: bytes, epoch_pk=None,
                        rh_index: Optional[int] = None) -> bool:
    ok, pair = verify_presentation_parts(ipk, pres, nonce,
                                         epoch_pk=epoch_pk,
                                         rh_index=rh_index)
    if not ok:
        return False
    a_prime, a_bar = pair
    # (1) pairing check: e(A', w) == e(A_bar, g2) — host path; the TPU
    # provider batches this equation instead (ops/bn254_batch.py
    # pairing_check_batch)
    return bn.pairing(a_prime, ipk.w) == bn.pairing(a_bar, bn.G2_GEN)


def verify_presentation_parts(ipk: IssuerPublicKey, pres: Presentation,
                              nonce: bytes, epoch_pk=None,
                              rh_index: Optional[int] = None):
    """Everything in verify_presentation EXCEPT the pairing equation.

    Returns (ok, (A_prime, A_bar)): when ok, the presentation is valid
    iff e(A_prime, w) == e(A_bar, g2) — the caller either checks it on
    host or collects it into the TPU pairing batch (BASELINE config 4).
    """
    # reject (never crash on) degenerate attacker-supplied points
    if any(p is None for p in (pres.A_prime, pres.A_bar, pres.d)):
        return False, None
    # invalid-curve gate: the group ops and the pairing operate blindly
    # on off-curve coordinates; soundness requires membership
    if not all(bn.g1_on_curve(p)
               for p in (pres.A_prime, pres.A_bar, pres.d)):
        return False, None
    # (2) recompute t1: t1 = -z_e*A' + z_r2*h0 - c*(A_bar - d)
    abar_minus_d = bn.g1_add(pres.A_bar, bn.g1_neg(pres.d))
    t1 = bn.g1_add(
        bn.g1_add(bn.g1_mul((-pres.z_e) % bn.R, pres.A_prime),
                  bn.g1_mul(pres.z_r2, ipk.h[0])),
        bn.g1_mul((-pres.c) % bn.R, abar_minus_d))
    # (3) recompute t2: t2 = z_r3*d - z_s'*h0 - sum z_mi*hi
    #                        - c*(g1 + sum_D mi*hi)
    t2 = bn.g1_add(bn.g1_mul(pres.z_r3, pres.d),
                   bn.g1_mul((-pres.z_sprime) % bn.R, ipk.h[0]))
    for i, z in pres.z_hidden.items():
        if i in pres.disclosed or not 0 <= i < ipk.n_attrs:
            return False, None
        t2 = bn.g1_add(t2, bn.g1_mul((-z) % bn.R, ipk.h[i + 1]))
    if set(pres.z_hidden) | set(pres.disclosed) != set(range(ipk.n_attrs)):
        return False, None
    pub = bn.G1_GEN
    for i, m in pres.disclosed.items():
        pub = bn.g1_add(pub, bn.g1_mul(m, ipk.h[i + 1]))
    t2 = bn.g1_add(t2, bn.g1_mul((-pres.c) % bn.R, pub))

    if t1 is None or t2 is None:
        return False, None
    # (4) non-revocation (when the channel requires an epoch_pk):
    # recompute the weak-BB commitment from the shared rh response —
    # the joint challenge below then binds it to THIS credential
    extra = ()
    if epoch_pk is not None:
        from . import revocation as rev
        if epoch_pk.alg == rev.ALG_NO_REVOCATION:
            pass                         # empty revocation set attested
        else:
            if (not isinstance(pres.nonrev, dict) or rh_index is None
                    or rh_index not in pres.z_hidden
                    or pres.nonrev.get("epoch") != epoch_pk.epoch):
                return False, None
            extra = rev.nonrev_commitment_parts(
                epoch_pk, pres.nonrev, pres.c, pres.z_hidden[rh_index])
            if extra is None:
                return False, None
    c = _hash_zr(pres.A_prime, pres.A_bar, pres.d, t1, t2, *extra, nonce,
                 repr(sorted(pres.disclosed.items())).encode())
    if c != pres.c:
        return False, None
    return True, (pres.A_prime, pres.A_bar)
