"""idemixgen: generate issuer material and signer configs on disk.

Reference parity: /root/reference/cmd/idemixgen/main.go — `ca-keygen`
writes the issuer key pair + revocation authority material, and
`signerconfig` enrolls users and writes their credentials.

Usage:
  python -m fabric_tpu.idemix.gen <outdir> --mspid IdemixOrg \
      --user alice:engineering:member --user boss:hq:admin

Outputs (serde files):
  <outdir>/issuer.key        issuer secret (x + bases)          KEEP SECRET
  <outdir>/ipk.bin           issuer public key
  <outdir>/ra.pem            revocation authority public key
  <outdir>/msp_config.bin    {mspid, ipk, ra_pk, epoch record}
  <outdir>/<user>.signer     {credential, ou, role, rh, handle_sig}
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from fabric_tpu.utils import serde

from . import credential as cred
from . import revocation as rev
from .msp import (
    ATTR_RH,
    N_ATTRS,
    ROLE_ADMIN,
    ROLE_MEMBER,
    IdemixMSPConfig,
    IdemixSigningIdentity,
    enroll,
    serialize_credential,
    deserialize_credential,
    serialize_ipk,
)


def generate(outdir: str, mspid: str, users: List[str],
             epoch: int = 1, alg: int = rev.ALG_PLAIN_SIGNATURE) -> dict:
    os.makedirs(outdir, exist_ok=True)
    isk = cred.IssuerKey.generate(N_ATTRS)
    ra = rev.RevocationAuthority()
    epk = ra.epoch_pk(epoch, alg=alg)
    ipk_bytes = serialize_ipk(isk.public())
    config = IdemixMSPConfig(mspid, ipk_bytes, ra.public_key_pem(), epk)

    with open(os.path.join(outdir, "issuer.key"), "wb") as f:
        f.write(serde.encode({"x": isk.x, "ipk": ipk_bytes}))
    with open(os.path.join(outdir, "ipk.bin"), "wb") as f:
        f.write(ipk_bytes)
    with open(os.path.join(outdir, "ra.pem"), "wb") as f:
        f.write(ra.public_key_pem())
    with open(os.path.join(outdir, "msp_config.bin"), "wb") as f:
        f.write(serde.encode({
            "mspid": mspid, "ipk": ipk_bytes, "ra": ra.public_key_pem(),
            "epoch": epk.epoch, "alg": epk.alg, "w": epk.w_e,
            "sig": epk.signature}))

    written = {}
    for spec in users:
        name, ou, role_s = (spec.split(":") + ["", "member"])[:3]
        role = ROLE_ADMIN if role_s == "admin" else ROLE_MEMBER
        signer = enroll(isk, config, ou, role, name, ra=ra)
        path = os.path.join(outdir, f"{name}.signer")
        with open(path, "wb") as f:
            f.write(serde.encode({
                "mspid": mspid, "ou": ou, "role": role,
                "credential": serialize_credential(signer._cred),
                "handle_sig": (list(signer._handle_sig)
                               if signer._handle_sig else []),
            }))
        written[name] = path
    return {"config": config, "ra": ra, "isk": isk, "signers": written}


def load_msp_config(path: str) -> IdemixMSPConfig:
    with open(path, "rb") as f:
        d = serde.decode(f.read())
    epk = None
    if d.get("w") or d.get("sig"):
        epk = rev.EpochPK(int(d["epoch"]), int(d["alg"]), d["w"], d["sig"])
    return IdemixMSPConfig(d["mspid"], d["ipk"], d["ra"], epk)


def load_signer(signer_path: str, msp_config_path: str) -> IdemixSigningIdentity:
    config = load_msp_config(msp_config_path)
    with open(signer_path, "rb") as f:
        d = serde.decode(f.read())
    credential = deserialize_credential(d["credential"])
    hs = tuple(int(v) for v in d["handle_sig"]) if d["handle_sig"] else None
    return IdemixSigningIdentity(d["mspid"], config, credential,
                                 str(d["ou"]), int(d["role"]),
                                 handle_sig=hs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="idemixgen")
    ap.add_argument("outdir")
    ap.add_argument("--mspid", default="IdemixOrg")
    ap.add_argument("--user", action="append", default=[],
                    help="name:ou:role (role: member|admin)")
    ap.add_argument("--epoch", type=int, default=1)
    args = ap.parse_args(argv)
    out = generate(args.outdir, args.mspid, args.user, epoch=args.epoch)
    print(f"issuer material + {len(out['signers'])} signer configs "
          f"written to {args.outdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
