"""BN254 pairing-friendly curve: host-side reference implementation.

The math plane for the Idemix capability (VERDICT.md missing #4 /
next-round #9): the reference implements anonymous credentials over the
BN254 curve via the vendored pure-Go AMCL library
(/root/reference/idemix/, vendor/github.com/hyperledger/fabric-amcl).
This module is the from-scratch Python-int equivalent: the BN curve
family with the AMCL BN254 parameter x = -(2^62 + 2^55 + 1), G1 over Fp,
G2 on the sextic twist over Fp2, and the Tate pairing into Fp12.

Design choices (correctness-first host oracle; the TPU batch kernel is a
later-round target, BASELINE config 4):
  - Tate pairing with the full Miller loop over r and a conjugate-based
    easy part + generic hard part final exponentiation — textbook-shaped
    and self-checking (bilinearity tests in tests/test_idemix.py), no
    hand-derived Frobenius constants to get subtly wrong.
  - G2 points are handled on the twist E'(Fp2) for group operations and
    untwisted into E(Fp12) only for pairing evaluation.
  - The twist cofactor is derived numerically from the BN trace (both
    sextic twist orders are computed and the one divisible by r is
    selected at import, asserted).
"""

from __future__ import annotations

import hashlib
import math
from typing import Optional, Tuple

# -- BN254 parameters (AMCL BN254: x = -(2^62 + 2^55 + 1)) -------------------

X_BN = -(2**62 + 2**55 + 1)


def _bn_p(x: int) -> int:
    return 36 * x**4 + 36 * x**3 + 24 * x**2 + 6 * x + 1


def _bn_r(x: int) -> int:
    return 36 * x**4 + 36 * x**3 + 18 * x**2 + 6 * x + 1


P = _bn_p(X_BN)
R = _bn_r(X_BN)
T_TRACE = 6 * X_BN**2 + 1          # Frobenius trace: #E(Fp) = p + 1 - t
B_COEFF = 2                        # E: y^2 = x^3 + 2 (AMCL BN254)

assert P + 1 - T_TRACE == R, "BN sanity: #E(Fp) == r"
assert pow(2, P - 1, P) == 1


# -- Fp2 = Fp[i]/(i^2 + 1)  (p % 4 == 3 for BN254) ---------------------------

assert P % 4 == 3

Fp2 = Tuple[int, int]   # a + b*i


def f2_add(a, b): return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)
def f2_sub(a, b): return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)
def f2_neg(a): return ((-a[0]) % P, (-a[1]) % P)


def f2_mul(a, b):
    t0 = a[0] * b[0] % P
    t1 = a[1] * b[1] % P
    t2 = (a[0] + a[1]) * (b[0] + b[1]) % P
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def f2_sqr(a): return f2_mul(a, a)


def f2_inv(a):
    d = pow(a[0] * a[0] + a[1] * a[1], P - 2, P)
    return (a[0] * d % P, (-a[1]) * d % P)


def f2_mul_scalar(a, k): return (a[0] * k % P, a[1] * k % P)


F2_ZERO = (0, 0)
F2_ONE = (1, 0)

# the sextic non-residue used to build Fp12 = Fp2[w]/(w^6 - XI)
XI: Fp2 = (1, 1)                   # 1 + i (standard for BN254-style towers)


# -- Fp12 as degree-6 extension of Fp2: sum c_k w^k, w^6 = XI ----------------

Fp12 = Tuple[Fp2, ...]             # 6 Fp2 coefficients

F12_ZERO = (F2_ZERO,) * 6
F12_ONE = (F2_ONE,) + (F2_ZERO,) * 5


def f12_add(a, b): return tuple(f2_add(x, y) for x, y in zip(a, b))
def f12_sub(a, b): return tuple(f2_sub(x, y) for x, y in zip(a, b))
def f12_neg(a): return tuple(f2_neg(x) for x in a)


def f12_mul(a, b):
    out = [F2_ZERO] * 11
    for i in range(6):
        if a[i] == F2_ZERO:
            continue
        for j in range(6):
            if b[j] == F2_ZERO:
                continue
            out[i + j] = f2_add(out[i + j], f2_mul(a[i], b[j]))
    # reduce w^(6+k) = XI * w^k
    for k in range(5):
        out[k] = f2_add(out[k], f2_mul(out[6 + k], XI))
    return tuple(out[:6])


def f12_sqr(a): return f12_mul(a, a)


def f12_conj(a):
    """Conjugate over Fp6 (negate odd w-coefficients): a^(p^6)."""
    return tuple(x if k % 2 == 0 else f2_neg(x) for k, x in enumerate(a))


def f2_conj(a: Fp2) -> Fp2:
    """p-Frobenius on Fp2 (i^2 = -1): complex conjugation."""
    return (a[0], (-a[1]) % P)


def f2_pow(a: Fp2, e: int) -> Fp2:
    result = F2_ONE
    base = a
    while e:
        if e & 1:
            result = f2_mul(result, base)
        base = f2_sqr(base)
        e >>= 1
    return result


# -- Fp6 = Fp2[v]/(v^3 - XI) and Fp12 = Fp6[w]/(w^2 - v) views ---------------
# The degree-6-over-Fp2 coefficients (c0..c5 over w, w^6 = XI) regroup as
# a = (c0, c2, c4) + w * (c1, c3, c5): even coefficients are the Fp6
# element over v = w^2, odd ones the w-part.  Tower inversion then costs
# one Fp2 inversion instead of a ~3000-squaring Fermat chain.

def f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(t0, f2_mul(XI, f2_sub(
        f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2))))
    c1 = f2_add(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)),
                       f2_add(t0, t1)), f2_mul(XI, t2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)),
                       f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_mul_by_v(a):
    """v * (a0 + a1 v + a2 v^2) = XI*a2 + a0 v + a1 v^2."""
    return (f2_mul(XI, a[2]), a[0], a[1])


def f6_neg(a):
    return tuple(f2_neg(x) for x in a)


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_inv(a):
    """Inverse in Fp2[v]/(v^3 - XI) (one Fp2 inversion)."""
    a0, a1, a2 = a
    t0 = f2_sub(f2_sqr(a0), f2_mul(XI, f2_mul(a1, a2)))
    t1 = f2_sub(f2_mul(XI, f2_sqr(a2)), f2_mul(a0, a1))
    t2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    norm = f2_add(f2_mul(a0, t0),
                  f2_mul(XI, f2_add(f2_mul(a2, t1), f2_mul(a1, t2))))
    ninv = f2_inv(norm)
    return (f2_mul(t0, ninv), f2_mul(t1, ninv), f2_mul(t2, ninv))


def f12_inv(a):
    """Tower inversion: a = g + h*w with g, h in Fp6 and w^2 = v;
    (g + h w)^-1 = (g - h w) / (g^2 - h^2 v)."""
    g = (a[0], a[2], a[4])
    h = (a[1], a[3], a[5])
    d = f6_sub(f6_mul(g, g), f6_mul_by_v(f6_mul(h, h)))
    dinv = f6_inv(d)
    gi = f6_mul(g, dinv)
    hi = f6_neg(f6_mul(h, dinv))
    return (gi[0], hi[0], gi[1], hi[1], gi[2], hi[2])


_P12M2 = P**12 - 2


def f12_pow_fermat(a):
    return f12_pow_raw(a, _P12M2)


def f12_pow_raw(a, e: int):
    result = F12_ONE
    base = a
    while e:
        if e & 1:
            result = f12_mul(result, base)
        base = f12_sqr(base)
        e >>= 1
    return result


# -- curves ------------------------------------------------------------------

# G1: y^2 = x^3 + 2 over Fp; generator per AMCL BN254 (x=-1 family): the
# point (1, y) with y^2 = 3... b=2: x=1 -> y^2 = 3; is 3 a QR mod p?
# Derive a generator deterministically instead of hardcoding.

def _sqrt_fp(a: int) -> Optional[int]:
    # p % 4 == 3
    y = pow(a, (P + 1) // 4, P)
    return y if y * y % P == a % P else None


def _g1_gen() -> Tuple[int, int]:
    x = 0
    while True:
        x += 1
        y = _sqrt_fp((x * x * x + B_COEFF) % P)
        if y is not None:
            # #E(Fp) = r (prime): any finite point generates
            return (x, min(y, P - y))


G1_GEN = _g1_gen()

# G1 arithmetic (affine, python ints)

G1Point = Optional[Tuple[int, int]]     # None = infinity


def g1_add(a: G1Point, b: G1Point) -> G1Point:
    if a is None:
        return b
    if b is None:
        return a
    if a[0] == b[0]:
        if (a[1] + b[1]) % P == 0:
            return None
        lam = (3 * a[0] * a[0]) * pow(2 * a[1], P - 2, P) % P
    else:
        lam = (b[1] - a[1]) * pow(b[0] - a[0], P - 2, P) % P
    x3 = (lam * lam - a[0] - b[0]) % P
    return (x3, (lam * (a[0] - x3) - a[1]) % P)


def _jac_dbl(p):
    X1, Y1, Z1 = p
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = B * B % P
    Dv = 2 * ((X1 + B) * (X1 + B) - A - C) % P
    E = 3 * A % P
    X3 = (E * E - 2 * Dv) % P
    Y3 = (E * (Dv - X3) - 8 * C) % P
    Z3 = 2 * Y1 * Z1 % P
    return X3, Y3, Z3


def _jac_add_aff(p, q):
    """Jacobian + affine (q), None handling by the caller."""
    X1, Y1, Z1 = p
    x2, y2 = q
    Z1Z1 = Z1 * Z1 % P
    U2 = x2 * Z1Z1 % P
    S2 = y2 * Z1 * Z1Z1 % P
    H = (U2 - X1) % P
    r = (S2 - Y1) % P
    if H == 0:
        if r == 0:
            return _jac_dbl(p)
        return None
    HH = H * H % P
    HHH = H * HH % P
    V = X1 * HH % P
    X3 = (r * r - HHH - 2 * V) % P
    Y3 = (r * (V - X3) - Y1 * HHH) % P
    Z3 = Z1 * H % P
    return X3, Y3, Z3


def g1_mul(k: int, pt: G1Point) -> G1Point:
    """Scalar mult with Jacobian accumulation and a single final
    inversion (the round-3 affine double-and-add paid a ~256-bit modexp
    inversion per BIT — the t1/t2 recomputation of every idemix
    presentation runs ~8 of these, so this is the host hot path)."""
    k %= R
    if k == 0 or pt is None:
        return None
    acc = None                       # jacobian accumulator, MSB-first
    for bit in bin(k)[2:]:
        if acc is not None:
            acc = _jac_dbl(acc)
        if bit == "1":
            acc = ((pt[0], pt[1], 1) if acc is None
                   else _jac_add_aff(acc, pt))
    if acc is None:
        return None
    X, Y, Z = acc
    if Z == 0:
        return None
    zi = pow(Z, P - 2, P)
    zi2 = zi * zi % P
    return X * zi2 % P, Y * zi2 % P * zi % P


def g1_neg(a: G1Point) -> G1Point:
    return None if a is None else (a[0], (-a[1]) % P)


def g1_on_curve(a: G1Point) -> bool:
    """Membership check for attacker-supplied points: y^2 == x^3 + b over
    Fp with canonical coordinates.  g1_add/g1_mul and the pairing operate
    blindly on off-curve coordinates (invalid-curve attacks void the
    scheme's soundness), so every deserialized/verification input MUST be
    gated through this.  Cofactor 1: on-curve implies order r."""
    if a is None:
        return True
    x, y = a
    if not (0 <= x < P and 0 <= y < P):
        return False
    return (y * y - (x * x * x + B_COEFF)) % P == 0


def hash_to_g1(data: bytes) -> Tuple[int, int]:
    """Try-and-increment hash to a G1 point (cofactor 1)."""
    ctr = 0
    while True:
        h = hashlib.sha256(data + ctr.to_bytes(4, "big")).digest()
        x = int.from_bytes(h, "big") % P
        y = _sqrt_fp((x * x * x + B_COEFF) % P)
        if y is not None:
            return (x, y if h[0] & 1 else P - y)
        ctr += 1


# G2: on the twist E'/Fp2: y^2 = x^3 + b', with b' = B / XI (D-type) or
# B * XI (M-type) — select whichever twist order is divisible by r.

def _twist_orders():
    """Candidate orders of the sextic twists of E over Fp2
    (Hess-Smart-Vercauteren): with q = p^2, trace t2 = t^2 - 2p and
    4q - t2^2 = 3 f^2 (CM discriminant -3), the six twists have orders
    q + 1 -/+ t2 and q + 1 -/+ (t2 +/- 3f)/2."""
    q = P * P
    t2 = T_TRACE * T_TRACE - 2 * P
    f_sq = (4 * q - t2 * t2) // 3
    f = math.isqrt(f_sq)
    assert f * f == f_sq
    cands = [q + 1 - t2, q + 1 + t2]
    for sf in (3 * f, -3 * f):
        if (t2 + sf) % 2 == 0:
            cands.append(q + 1 - (t2 + sf) // 2)
            cands.append(q + 1 + (t2 + sf) // 2)
    return cands


_B_D = f2_mul((B_COEFF, 0), f2_inv(XI))    # b/xi (D-twist)
_B_M = f2_mul((B_COEFF, 0), XI)            # b*xi (M-twist)


def _on_twist(pt, b2):
    x, y = pt
    return f2_sub(f2_sqr(y), f2_add(f2_mul(f2_sqr(x), x), b2)) == F2_ZERO


G2Point = Optional[Tuple[Fp2, Fp2]]


def _g2_add_raw(a, b):
    if a is None:
        return b
    if b is None:
        return a
    if a[0] == b[0]:
        if f2_add(a[1], b[1]) == F2_ZERO:
            return None
        lam = f2_mul(f2_mul_scalar(f2_sqr(a[0]), 3), f2_inv(f2_mul_scalar(a[1], 2)))
    else:
        lam = f2_mul(f2_sub(b[1], a[1]), f2_inv(f2_sub(b[0], a[0])))
    x3 = f2_sub(f2_sub(f2_sqr(lam), a[0]), b[0])
    return (x3, f2_sub(f2_mul(lam, f2_sub(a[0], x3)), a[1]))


def g2_add(a: G2Point, b: G2Point) -> G2Point:
    return _g2_add_raw(a, b)


def g2_mul_raw(k: int, pt: G2Point) -> G2Point:
    """Scalar multiply WITHOUT reducing k mod r — required wherever the
    point's order is not (yet) known to be r: cofactor clearing and
    order checks."""
    if k < 0:
        return g2_neg(g2_mul_raw(-k, pt))
    acc = None
    while k:
        if k & 1:
            acc = g2_add(acc, pt)
        pt = g2_add(pt, pt)
        k >>= 1
    return acc


def g2_mul(k: int, pt: G2Point) -> G2Point:
    """Scalar multiply for r-torsion points (k reduced mod r)."""
    return g2_mul_raw(k % R, pt)


def g2_neg(a: G2Point) -> G2Point:
    return None if a is None else (a[0], f2_neg(a[1]))


def _sqrt_fp2(a: Fp2) -> Optional[Fp2]:
    """Square root in Fp2 via the norm trick (p % 4 == 3)."""
    if a == F2_ZERO:
        return F2_ZERO
    # candidate: a^((p^2+7)/8)-style doesn't apply; use generic: solve
    # via writing sqrt = (x, y): brute via Fp: norm = a0^2 + a1^2 must be
    # a QR; alpha = sqrt(norm); then x^2 = (a0 + alpha)/2 (or other sign)
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    alpha = _sqrt_fp(norm)
    if alpha is None:
        return None
    for sgn in (1, -1):
        half = (a[0] + sgn * alpha) * pow(2, P - 2, P) % P
        x = _sqrt_fp(half)
        if x is None:
            continue
        if x == 0:
            continue
        y = a[1] * pow(2 * x, P - 2, P) % P
        cand = (x, y)
        if f2_sqr(cand) == a:
            return cand
    return None


def _derive_g2():
    """Find the r-torsion twist + generator: try both twist coefficients;
    hash to a point, clear the cofactor, demand order exactly r."""
    orders = _twist_orders()
    for b2, order in [(b, n) for b in (_B_D, _B_M) for n in orders]:
        if order % R != 0:
            continue
        cof = order // R
        ctr = 0
        while ctr < 64:
            h = hashlib.sha512(b"fabric-tpu-g2" + ctr.to_bytes(2, "big")).digest()
            x = (int.from_bytes(h[:32], "big") % P,
                 int.from_bytes(h[32:], "big") % P)
            rhs = f2_add(f2_mul(f2_sqr(x), x), b2)
            y = _sqrt_fp2(rhs)
            ctr += 1
            if y is None:
                continue
            cand = g2_mul_raw(cof, (x, y))
            if cand is None:
                continue
            if g2_mul_raw(R, cand) is None and _on_twist(cand, b2):
                return b2, cand
    raise AssertionError("no r-torsion sextic twist found")


B_TWIST, G2_GEN = _derive_g2()
IS_D_TWIST = B_TWIST == _B_D


# -- untwist E'(Fp2) -> E(Fp12) ----------------------------------------------
# D-twist untwist: (x, y) -> (x * w^2, y * w^3)  with w^6 = XI
# M-twist untwist: (x, y) -> (x / w^2, y / w^3) == (x * w^4 / XI, y * w^3 / XI)

def _emb(c: Fp2, k: int) -> Fp12:
    out = [F2_ZERO] * 6
    out[k] = c
    return tuple(out)


def untwist(pt: G2Point) -> Optional[Tuple[Fp12, Fp12]]:
    if pt is None:
        return None
    x, y = pt
    if IS_D_TWIST:
        return (_emb(x, 2), _emb(y, 3))
    xi_inv = f2_inv(XI)
    return (_emb(f2_mul(x, xi_inv), 4), _emb(f2_mul(y, xi_inv), 3))


# -- Tate pairing ------------------------------------------------------------

def _line(Tx, Ty, Qx12, Qy12, Rx=None, Ry=None):
    """Line through T (and R, or tangent at T) on E(Fp), evaluated at the
    Fp12 point Q.  T, R are G1 points (Fp); Q is untwisted (Fp12)."""
    if Rx is None:   # tangent at T
        lam_num = 3 * Tx * Tx % P
        lam_den = 2 * Ty % P
    elif Tx == Rx:   # vertical
        # line: x - Tx
        return f12_sub(Qx12, _emb((Tx, 0), 0))
    else:
        lam_num = (Ry - Ty) % P
        lam_den = (Rx - Tx) % P
    lam = lam_num * pow(lam_den, P - 2, P) % P
    # l(Q) = (Qy - Ty) - lam * (Qx - Tx)
    t1 = f12_sub(Qy12, _emb((Ty, 0), 0))
    t2 = f12_sub(Qx12, _emb((Tx, 0), 0))
    return f12_sub(t1, f12_mul(_emb((lam, 0), 0), t2))


_HARD = (P**4 - P**2 + 1) // R


# -- Frobenius via coefficient constants -------------------------------------
# f^(p^i) for f = sum c_k w^k: c_k -> conj^i(c_k) * GAMMA[i][k] where
# GAMMA[i][k] = XI^(k*(p^i-1)/6) (standard tower Frobenius; w^p =
# XI^((p-1)/6) * w).  Replaces the ~500-squaring f^(p^2) chains.

GAMMA = {
    i: tuple(f2_pow(XI, k * (P**i - 1) // 6) for k in range(6))
    for i in (1, 2, 3)
}


def f12_frobenius(a: Fp12, power: int) -> Fp12:
    g = GAMMA[power]
    if power % 2 == 0:
        return tuple(f2_mul(c, g[k]) for k, c in enumerate(a))
    return tuple(f2_mul(f2_conj(c), g[k]) for k, c in enumerate(a))


def _pow_abs_u(m: Fp12) -> Fp12:
    return f12_pow_raw(m, -X_BN)         # |u| (X_BN < 0)


def _pow_u(m: Fp12) -> Fp12:
    """m^u for the BN parameter u (negative): conj = inversion in the
    cyclotomic subgroup (valid only AFTER the easy part)."""
    return f12_conj(_pow_abs_u(m))


def final_exp_hard(m: Fp12) -> Fp12:
    """m^((p^4 - p^2 + 1)/r) for m in the cyclotomic subgroup — the
    Devegili-Scott-Dominguez vectorial addition chain (the BN-specific
    hard part; ~3 |u|-exponentiations + 13 mult/sqr instead of a
    ~2500-bit generic ladder)."""
    f1 = _pow_u(m)                       # m^u
    f2_ = _pow_u(f1)                     # m^(u^2)
    f3 = _pow_u(f2_)                     # m^(u^3)
    y0 = f12_mul(f12_mul(f12_frobenius(m, 1), f12_frobenius(m, 2)),
                 f12_frobenius(m, 3))
    y1 = f12_conj(m)
    y2 = f12_frobenius(f2_, 2)
    y3 = f12_conj(f12_frobenius(f1, 1))
    y4 = f12_conj(f12_mul(f1, f12_frobenius(f2_, 1)))
    y5 = f12_conj(f2_)
    y6 = f12_conj(f12_mul(f3, f12_frobenius(f3, 1)))
    t0 = f12_sqr(y6)
    t0 = f12_mul(t0, y4)
    t0 = f12_mul(t0, y5)
    t1 = f12_mul(y3, y5)
    t1 = f12_mul(t1, t0)
    t0 = f12_mul(t0, y2)
    t1 = f12_sqr(t1)
    t1 = f12_mul(t1, t0)
    t1 = f12_sqr(t1)
    t0 = f12_mul(t1, y1)
    t1 = f12_mul(t1, y0)
    t0 = f12_sqr(t0)
    return f12_mul(t0, t1)


def _final_exp(f: Fp12) -> Fp12:
    # easy part: f^(p^6-1) = conj(f) * f^-1 (tower inversion); then
    # ^(p^2+1) via the coefficient Frobenius
    f = f12_mul(f12_conj(f), f12_inv(f))
    f = f12_mul(f12_frobenius(f, 2), f)
    # hard part: BN-specific chain
    return final_exp_hard(f)


# -- ate pairing with precomputed lines (the TPU-batch structure) ------------
#
# The ate Miller loop runs over multiples of the FIXED G2 point Q, so for
# a fixed Q every step's line function reduces to constants: evaluated at
# a G1 point P = (xP, yP), each line is the sparse Fp12 element
#     l(P) = yP            (component 0, Fp)
#          + A * xP        (component 1, A in Fp2)
#          + B              (component 3, B in Fp2)
# (D-twist untwisting puts the slope in the w^1 component and the
# constant term in w^3).  ate_precompute emits the flat step list
# [(is_dbl_step, A, B), ...] that both the host oracle below and the
# batched TPU kernel (fabric_tpu/ops/bn254_batch.py) consume — the
# device differentially matches this host implementation bit-for-bit.

ATE_LAMBDA = (T_TRACE - 1) % R        # lambda = t-1 == p (mod r)


def ate_precompute(Qpt: G2Point):
    """-> list of (flag, A, B): flag 1 = this step also squares f (a
    Miller doubling step), 0 = extra addition step; A, B in Fp2."""
    if not IS_D_TWIST:
        raise NotImplementedError("line precompute assumes the D-twist")
    steps = []
    Tx, Ty = Qpt

    def dbl_line():
        nonlocal Tx, Ty
        lam = f2_mul(f2_mul_scalar(f2_sqr((Tx)), 3),
                     f2_inv(f2_mul_scalar(Ty, 2)))
        A = f2_neg(lam)
        B = f2_sub(f2_mul(lam, Tx), Ty)
        x3 = f2_sub(f2_sqr(lam), f2_mul_scalar(Tx, 2))
        Ty = f2_sub(f2_mul(lam, f2_sub(Tx, x3)), Ty)
        Tx = x3
        return A, B

    def add_line(Qx, Qy):
        nonlocal Tx, Ty
        lam = f2_mul(f2_sub(Ty, Qy), f2_inv(f2_sub(Tx, Qx)))
        A = f2_neg(lam)
        B = f2_sub(f2_mul(lam, Tx), Ty)
        x3 = f2_sub(f2_sub(f2_sqr(lam), Tx), Qx)
        Ty = f2_sub(f2_mul(lam, f2_sub(Tx, x3)), Ty)
        Tx = x3
        return A, B

    bits = bin(ATE_LAMBDA)[2:]
    for bit in bits[1:]:
        A, B = dbl_line()
        steps.append((1, A, B))
        if bit == "1":
            A, B = add_line(*Qpt)
            steps.append((0, A, B))
    return steps


def _sparse013(yP: int, A: Fp2, xP: int, B: Fp2) -> Fp12:
    out = [F2_ZERO] * 6
    out[0] = (yP % P, 0)
    out[1] = f2_mul_scalar(A, xP)
    out[3] = B
    return tuple(out)


def ate_pairing_lines(Ppt: G1Point, steps) -> Fp12:
    """Reduced ate pairing from precomputed lines (host oracle for the
    batched kernel)."""
    if Ppt is None:
        return F12_ONE
    xP, yP = Ppt
    f = F12_ONE
    for flag, A, B in steps:
        if flag:
            f = f12_sqr(f)
        f = f12_mul(f, _sparse013(yP, A, xP, B))
    return _final_exp(f)


def ate_pairing(Ppt: G1Point, Qpt: G2Point) -> Fp12:
    if Ppt is None or Qpt is None:
        return F12_ONE
    return ate_pairing_lines(Ppt, ate_precompute(Qpt))


def pairing(Ppt: G1Point, Qpt: G2Point) -> Fp12:
    """Reduced Tate pairing e(P, Q): P in G1 = E(Fp)[r], Q on the twist.

    Numerator/denominator accumulation: one Fp12 inversion total instead
    of two per Miller iteration."""
    if Ppt is None or Qpt is None:
        return F12_ONE
    Qx12, Qy12 = untwist(Qpt)
    f_num = F12_ONE
    f_den = F12_ONE
    Tx, Ty = Ppt
    for bit in bin(R)[3:]:
        f_num = f12_mul(f12_sqr(f_num), _line(Tx, Ty, Qx12, Qy12))
        f_den = f12_sqr(f_den)
        T2 = g1_add((Tx, Ty), (Tx, Ty))
        if T2 is not None:          # T never hits infinity mid-loop (k < r)
            f_den = f12_mul(f_den, f12_sub(Qx12, _emb((T2[0], 0), 0)))
            Tx, Ty = T2
        if bit == "1":
            f_num = f12_mul(f_num, _line(Tx, Ty, Qx12, Qy12,
                                         Ppt[0], Ppt[1]))
            TA = g1_add((Tx, Ty), Ppt)
            if TA is not None:
                f_den = f12_mul(f_den, f12_sub(Qx12, _emb((TA[0], 0), 0)))
                Tx, Ty = TA
    f = f12_mul(f_num, f12_inv(f_den))
    return _final_exp(f)
