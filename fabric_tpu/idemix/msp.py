"""Idemix MSP: anonymous identities as a first-class membership provider.

Reference parity: /root/reference/msp/idemixmsp.go + bccsp/idemix — an
MSP whose identities are fresh unlinkable BBS+ presentations instead of
X.509 certificates.  The identity BYTES disclose only (mspid, OU, role);
the SIGNATURE over a payload is a presentation whose Fiat-Shamir nonce
is the payload digest, proving possession of an issuer credential whose
hidden attributes include the enrollment id and the revocation handle
(checked against the channel's revocation epoch when configured).

Attribute convention (idemixmsp.go's four attributes):
    [0] OU, [1] role (1 = member, 2 = admin), [2] enrollment id, [3] rh
OU/role are DISCLOSED in every presentation; EID and RH never are.

This MSP plugs into the same surfaces as the X.509 MSP: the validator's
deserialize_from_msps, policy principals, and the provider batch-verify
plane (scheme "idemix", host-verified — the TPU pairing batch is the
BASELINE config-4 target tracked in COVERAGE.md).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from fabric_tpu.bccsp.provider import SCHEME_IDEMIX, VerifyItem
from fabric_tpu.utils import serde

from . import bn254 as bn
from . import credential as cred
from . import revocation as rev

ATTR_OU, ATTR_ROLE, ATTR_EID, ATTR_RH = 0, 1, 2, 3
N_ATTRS = 4
ROLE_MEMBER, ROLE_ADMIN = 1, 2


# -- serialization -----------------------------------------------------------

def _g1_l(pt) -> list:
    return [int(pt[0]), int(pt[1])]


def _g1_t(v) -> Tuple[int, int]:
    return (int(v[0]), int(v[1]))


def serialize_ipk(ipk: cred.IssuerPublicKey) -> bytes:
    (xa, xb), (ya, yb) = ipk.w
    return serde.encode({
        "w": [xa, xb, ya, yb],
        "h": [_g1_l(p) for p in ipk.h],
        "n_attrs": ipk.n_attrs,
    })


def deserialize_ipk(raw: bytes) -> cred.IssuerPublicKey:
    d = serde.decode(raw)
    w = ((d["w"][0], d["w"][1]), (d["w"][2], d["w"][3]))
    h = [_g1_t(p) for p in d["h"]]
    for p in h:
        if not bn.g1_on_curve(p):
            raise ValueError("ipk base off-curve")
    return cred.IssuerPublicKey(w, h, int(d["n_attrs"]))


def serialize_presentation(p: cred.Presentation) -> bytes:
    return serde.encode({
        "ap": _g1_l(p.A_prime), "ab": _g1_l(p.A_bar), "d": _g1_l(p.d),
        "c": p.c, "ze": p.z_e, "zr2": p.z_r2, "zr3": p.z_r3,
        "zs": p.z_sprime,
        "zh": {str(k): v for k, v in p.z_hidden.items()},
        "disc": {str(k): v for k, v in p.disclosed.items()},
        "nonrev": p.nonrev if p.nonrev is not None else {},
    })


def deserialize_presentation(raw: bytes) -> cred.Presentation:
    d = serde.decode(raw)
    return cred.Presentation(
        A_prime=_g1_t(d["ap"]), A_bar=_g1_t(d["ab"]), d=_g1_t(d["d"]),
        c=int(d["c"]), z_e=int(d["ze"]), z_r2=int(d["zr2"]),
        z_r3=int(d["zr3"]), z_sprime=int(d["zs"]),
        z_hidden={int(k): int(v) for k, v in d["zh"].items()},
        disclosed={int(k): int(v) for k, v in d["disc"].items()},
        # attacker-typed: only a non-empty dict is a proof
        nonrev=(d["nonrev"] if isinstance(d.get("nonrev"), dict)
                and d["nonrev"] else None),
    )


def attr_int(value: bytes) -> int:
    return cred.attr_to_zr(value)


def serialize_credential(c: cred.Credential) -> bytes:
    return serde.encode({"a": _g1_l(c.A), "e": c.e, "s": c.s,
                         "attrs": list(c.attrs)})


def deserialize_credential(raw: bytes) -> cred.Credential:
    d = serde.decode(raw)
    return cred.Credential(_g1_t(d["a"]), int(d["e"]), int(d["s"]),
                           [int(a) for a in d["attrs"]])


# -- config ------------------------------------------------------------------

@dataclass
class IdemixMSPConfig:
    """idemixmsp config: issuer public key + optional revocation data."""
    mspid: str
    ipk_bytes: bytes
    ra_public_key_pem: bytes = b""
    epoch_pk: Optional[rev.EpochPK] = None      # current revocation epoch


# -- identities --------------------------------------------------------------

class IdemixIdentity:
    """A deserialized idemix identity: only (mspid, ou, role) are known;
    signature verification carries the cryptographic weight."""

    def __init__(self, mspid: str, ou: str, role: int, config_key: bytes):
        self.mspid = mspid
        self.ou = ou
        self.role = role
        self._config_key = config_key      # pubkey field of VerifyItems

    def serialize(self) -> bytes:
        return serde.encode({"mspid": self.mspid, "fmt": "idemix",
                             "ou": self.ou, "role": self.role})

    def verify_item(self, payload: bytes, signature: bytes) -> VerifyItem:
        """The batchable verification unit: payload digest is the
        presentation nonce (identities.go:178 digest-only parity).

        The identity's CLAIMED (ou, role) ride in the item so the
        verifier checks them against the presentation's disclosed
        attributes — otherwise a member credential could claim admin in
        its identity bytes and policy evaluation would believe it."""
        digest = hashlib.sha256(payload).digest()
        pk = serde.encode({"cfg": self._config_key, "ou": self.ou,
                           "role": self.role})
        return VerifyItem(SCHEME_IDEMIX, pk, signature, digest)

    def verify(self, payload: bytes, signature: bytes) -> bool:
        return verify_item_host(self.verify_item(payload, signature))


class IdemixSigningIdentity(IdemixIdentity):
    """Holder side: a credential + the per-epoch non-revocation data."""

    def __init__(self, mspid: str, config: IdemixMSPConfig,
                 credential: cred.Credential, ou: str, role: int,
                 handle_sig=None):
        super().__init__(mspid, ou, role, _config_key(config))
        self._config = config
        self._cred = credential
        self._handle_sig = handle_sig      # weak-BB sig for this epoch

    def sign(self, payload: bytes) -> bytes:
        ipk = deserialize_ipk(self._config.ipk_bytes)
        nonce = hashlib.sha256(payload).digest()
        nonrev = None
        epk = self._config.epoch_pk
        if epk is not None and epk.alg == rev.ALG_PLAIN_SIGNATURE:
            if self._handle_sig is None:
                raise PermissionError("no non-revocation credential for "
                                      "the current epoch")
            nonrev = rev.NonRevProver(epk, self._handle_sig,
                                      self._cred.attrs[ATTR_RH])
        pres = cred.present(ipk, self._cred,
                            disclose=[ATTR_OU, ATTR_ROLE], nonce=nonce,
                            nonrev=nonrev, rh_index=ATTR_RH)
        return serialize_presentation(pres)


# -- the verification core (shared by providers and the MSP) -----------------

_CONFIGS: Dict[bytes, IdemixMSPConfig] = {}


def _config_key(config: IdemixMSPConfig) -> bytes:
    """VerifyItem.pubkey for this MSP's items: a self-contained serde of
    the verification material (registered for host lookup)."""
    key = serde.encode({
        "ipk": config.ipk_bytes,
        "ra": config.ra_public_key_pem,
        "epoch": (serde.encode({
            "epoch": config.epoch_pk.epoch, "alg": config.epoch_pk.alg,
            "w": config.epoch_pk.w_e, "sig": config.epoch_pk.signature})
            if config.epoch_pk is not None else b""),
    })
    _CONFIGS.setdefault(key, config)
    return key


def verify_item_host(item: VerifyItem) -> bool:
    """Host-side verification of one idemix VerifyItem (the provider
    plane's scheme handler)."""
    ok, ipk_bytes, pair = collect_item_parts(item)
    if not ok:
        return False
    from . import bn254 as bn
    ipk = deserialize_ipk(ipk_bytes)
    a_prime, a_bar = pair
    return bn.pairing(a_prime, ipk.w) == bn.pairing(a_bar, bn.G2_GEN)


def collect_item_parts(item: VerifyItem):
    """Everything host-side EXCEPT the pairing equation.

    -> (ok, ipk_bytes, (A_prime, A_bar)).  When ok, the item is valid
    iff e(A_prime, w_ipk) == e(A_bar, g2) — the TPU provider batches
    that check per issuer (ops/bn254_batch.pairing_check_batch,
    BASELINE config 4); verify_item_host checks it with host ints.
    """
    try:
        outer = serde.decode(item.pubkey)
        kd = serde.decode(outer["cfg"])
        claimed_ou = str(outer["ou"])
        claimed_role = int(outer["role"])
        ipk = deserialize_ipk(kd["ipk"])
        pres = deserialize_presentation(item.signature)
    except Exception:
        return False, None, None
    epoch_pk = None
    if kd.get("epoch"):
        try:
            ed = serde.decode(kd["epoch"])
            epoch_pk = rev.EpochPK(int(ed["epoch"]), int(ed["alg"]),
                                   ed["w"], ed["sig"])
        except Exception:
            return False, None, None
        if not rev.verify_epoch_pk(epoch_pk, kd["ra"]):
            return False, None, None
    # the presentation must disclose exactly OU+role, and they must
    # MATCH the identity's claims — the binding between the anonymous
    # credential and what policy evaluation believes about it
    if pres.disclosed != {ATTR_OU: attr_int(claimed_ou.encode()),
                          ATTR_ROLE: claimed_role}:
        return False, None, None
    try:
        ok, pair = cred.verify_presentation_parts(
            ipk, pres, item.payload, epoch_pk=epoch_pk, rh_index=ATTR_RH)
    except Exception:
        # attacker-shaped structures must yield False, never crash the
        # batch path (policy.go:390-393 per-signature failure semantics)
        return False, None, None
    if not ok:
        return False, None, None
    return True, kd["ipk"], pair


# -- the MSP -----------------------------------------------------------------

class IdemixMSP:
    """msp.MSP surface for idemix identities (idemixmsp.go)."""

    def __init__(self, config: IdemixMSPConfig):
        self.mspid = config.mspid
        self.config = config
        self._key = _config_key(config)

    def deserialize_identity(self, data: bytes) -> IdemixIdentity:
        d = serde.decode(data)
        if d.get("fmt") != "idemix" or d.get("mspid") != self.mspid:
            raise ValueError("not an idemix identity of this MSP")
        role = int(d.get("role", 0))
        if role not in (ROLE_MEMBER, ROLE_ADMIN):
            raise ValueError("bad idemix role")
        return IdemixIdentity(self.mspid, str(d.get("ou", "")), role,
                              self._key)

    def is_valid(self, ident) -> bool:
        # structural only: an idemix identity has no certificate chain;
        # the presentation carried as its signature proves membership,
        # and verify_item_host re-checks the disclosed (ou, role)
        return isinstance(ident, IdemixIdentity) and ident.mspid == self.mspid

    def validate(self, ident) -> None:
        if not self.is_valid(ident):
            raise ValueError("invalid idemix identity")

    def satisfies_principal(self, ident, principal) -> bool:
        if getattr(principal, "mspid", None) != self.mspid:
            return False
        role = getattr(principal, "role", "member")
        if role == "member":
            return True
        if role == "admin":
            return ident.role == ROLE_ADMIN
        if role == "ou":
            return ident.ou == getattr(principal, "ou", None)
        return False


# -- issuance helper (idemixgen's core) --------------------------------------

def enroll(isk: cred.IssuerKey, config: IdemixMSPConfig, ou: str,
           role: int, enrollment_id: str,
           ra: Optional[rev.RevocationAuthority] = None,
           rh: Optional[int] = None) -> IdemixSigningIdentity:
    """Issue a credential over the 4-attribute convention and wrap it as
    a signing identity (idemixgen signerconfig)."""
    import secrets
    rh = rh if rh is not None else secrets.randbelow(bn.R - 1) + 1
    attrs = [attr_int(ou.encode()), role,
             attr_int(enrollment_id.encode()), rh % bn.R]
    credential = cred.issue(isk, attrs)
    handle_sig = None
    epk = config.epoch_pk
    if (ra is not None and epk is not None
            and epk.alg == rev.ALG_PLAIN_SIGNATURE):
        handle_sig = ra.sign_handle(epk.epoch, rh)
    return IdemixSigningIdentity(config.mspid, config, credential, ou,
                                 role, handle_sig=handle_sig)
