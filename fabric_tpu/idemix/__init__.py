"""Idemix anonymous-credential plane (host-side oracle).

Re-design of /root/reference/idemix + bccsp/idemix (VERDICT.md missing
#4): BN254 pairing math built from scratch (bn254.py) and the BBS+
credential scheme with zero-knowledge selective-disclosure presentations
(credential.py).  The TPU batched pairing kernel (BASELINE config 4)
lands in a later round and will be differentially tested against this.
"""

from .credential import (
    Credential,
    IssuerKey,
    IssuerPublicKey,
    Presentation,
    attr_to_zr,
    issue,
    present,
    verify_credential,
    verify_presentation,
)

__all__ = ["IssuerKey", "IssuerPublicKey", "Credential", "Presentation",
           "issue", "present", "verify_credential", "verify_presentation",
           "attr_to_zr"]
