"""Credential revocation: epoch CRIs + weak-BB non-revocation proofs.

Reference parity: /root/reference/idemix/revocation_authority.go (the RA
signs per-epoch credential revocation information with a long-term ECDSA
key) and nonrevocation-prover.go / nonrevocation-verifier.go (per
algorithm: ALG_NO_REVOCATION — the epoch attests an empty revocation
set — and a signature-based scheme where the holder proves, in zero
knowledge, possession of the RA's weak Boneh-Boyen signature on its
hidden revocation-handle attribute).

The weak-BB construction here:
  per epoch e the RA samples x_e, publishes W_e = g2^x_e inside an
  ECDSA-signed epoch record, and signs each UNREVOKED handle rh as
    A_rh = g1^(1/(x_e + rh)).
  The holder randomizes A' = A_rh^t and proves knowledge of (rh, t) with
    e(A', W_e) * e(A', g2)^rh = e(g1, g2)^t
  via a Schnorr proof over GT whose response for rh is THE SAME response
  the BBS+ presentation uses for the hidden rh attribute (joint
  Fiat-Shamir challenge) — so the proven-unrevoked handle is exactly the
  credential's handle, not some other value the prover knows a
  signature for.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Dict, Optional, Set, Tuple

from fabric_tpu.utils import serde

from . import bn254 as bn

ALG_NO_REVOCATION = 0
ALG_PLAIN_SIGNATURE = 1

# GT bases reused by every proof
_GT_G = None


def _gt_gen():
    global _GT_G
    if _GT_G is None:
        _GT_G = bn.pairing(bn.G1_GEN, bn.G2_GEN)
    return _GT_G


def _g2_ser(pt) -> bytes:
    (xa, xb), (ya, yb) = pt
    return b"".join(v.to_bytes(32, "big") for v in (xa, xb, ya, yb))


def _g2_deser(raw: bytes):
    if len(raw) != 128:
        raise ValueError("bad G2 encoding")
    vs = [int.from_bytes(raw[i * 32:(i + 1) * 32], "big") for i in range(4)]
    return ((vs[0], vs[1]), (vs[2], vs[3]))


@dataclass(frozen=True)
class EpochPK:
    """The verifier-side CRI: per-epoch revocation public data, bound to
    the RA's long-term key (revocation_authority.go CRI)."""
    epoch: int
    alg: int
    w_e: bytes              # serialized G2 (empty for ALG_NO_REVOCATION)
    signature: bytes        # RA long-term ECDSA over the canonical body

    def body(self) -> bytes:
        return serde.encode({"epoch": self.epoch, "alg": self.alg,
                             "w": self.w_e})


class RevocationAuthority:
    """Issues epoch records and per-handle weak-BB signatures."""

    def __init__(self):
        from fabric_tpu.crypto import ec
        self._lt_key = ec.generate_private_key(ec.SECP256R1())
        self._epochs: Dict[int, int] = {}       # epoch -> x_e
        self.revoked: Set[int] = set()

    # -- long-term key -------------------------------------------------------

    def public_key_pem(self) -> bytes:
        from fabric_tpu.crypto import serialization
        return self._lt_key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo)

    def _sign(self, body: bytes) -> bytes:
        from fabric_tpu.crypto import hashes
        from fabric_tpu.crypto import ec
        return self._lt_key.sign(body, ec.ECDSA(hashes.SHA256()))

    # -- epochs --------------------------------------------------------------

    def revoke(self, rh: int) -> None:
        self.revoked.add(rh % bn.R)

    def epoch_pk(self, epoch: int,
                 alg: int = ALG_PLAIN_SIGNATURE) -> EpochPK:
        if alg == ALG_NO_REVOCATION:
            rec = EpochPK(epoch, alg, b"", b"")
            return EpochPK(epoch, alg, b"", self._sign(rec.body()))
        x_e = self._epochs.get(epoch)
        if x_e is None:
            x_e = secrets.randbelow(bn.R - 2) + 1
            self._epochs[epoch] = x_e
        w = _g2_ser(bn.g2_mul(x_e, bn.G2_GEN))
        rec = EpochPK(epoch, alg, w, b"")
        return EpochPK(epoch, alg, w, self._sign(rec.body()))

    def sign_handle(self, epoch: int, rh: int):
        """Weak-BB signature on an unrevoked handle for this epoch (the
        holder's per-epoch non-revocation credential)."""
        rh %= bn.R
        if rh in self.revoked:
            raise PermissionError(f"handle revoked")
        if epoch not in self._epochs:
            self.epoch_pk(epoch)
        x_e = self._epochs[epoch]
        inv = pow((x_e + rh) % bn.R, -1, bn.R)
        return bn.g1_mul(inv, bn.G1_GEN)


def verify_epoch_pk(epk: EpochPK, ra_public_key_pem: bytes) -> bool:
    from fabric_tpu.crypto import InvalidSignature
    from fabric_tpu.crypto import hashes, serialization
    from fabric_tpu.crypto import ec
    try:
        pub = serialization.load_pem_public_key(ra_public_key_pem)
        pub.verify(epk.signature, epk.body(), ec.ECDSA(hashes.SHA256()))
        return True
    except (InvalidSignature, ValueError, TypeError):
        return False


# ---------------------------------------------------------------------------
# prover / verifier halves (joined into the BBS+ presentation by
# credential.present / credential.verify_presentation)
# ---------------------------------------------------------------------------

class NonRevProver:
    """Holder-side context: commits before the joint challenge, responds
    after."""

    def __init__(self, epk: EpochPK, handle_sig, rh: int):
        if epk.alg != ALG_PLAIN_SIGNATURE:
            raise ValueError("prover only needed for ALG_PLAIN_SIGNATURE")
        self.epk = epk
        self.rh = rh % bn.R
        self.t = secrets.randbelow(bn.R - 2) + 1
        self.a_prime = bn.g1_mul(self.t, handle_sig)
        self._r_t = secrets.randbelow(bn.R - 2) + 1
        self._r_rh: Optional[int] = None

    def commit(self, r_rh: int) -> Tuple:
        """r_rh: the BBS+ proof's randomizer for the hidden rh attribute
        (shared — this is the binding).  Returns hashable commitment
        parts for the joint Fiat-Shamir challenge."""
        self._r_rh = r_rh
        b1 = bn.pairing(self.a_prime, bn.G2_GEN)
        t3 = bn.f12_mul(bn.f12_pow_raw(_gt_gen(), self._r_t),
                        bn.f12_pow_raw(bn.f12_inv(b1), r_rh))
        return (self.epk.epoch, self.epk.w_e, self.a_prime,
                repr(t3).encode())

    def respond(self, c: int) -> dict:
        return {"epoch": self.epk.epoch, "a_prime": list(self.a_prime),
                "z_t": (self._r_t + c * self.t) % bn.R}


def nonrev_commitment_parts(epk: EpochPK, proof: dict, c: int,
                            z_rh: int) -> Optional[Tuple]:
    """Verifier half: recompute the commitment parts from the responses
    (T3' = B2^z_t * B1^(-z_rh) * P1^(-c)) for the joint-challenge
    re-derivation.  Returns None when the proof is structurally invalid."""
    try:
        a_prime = (int(proof["a_prime"][0]), int(proof["a_prime"][1]))
        z_t = int(proof["z_t"]) % bn.R
    except (KeyError, TypeError, ValueError, IndexError):
        return None
    if not bn.g1_on_curve(a_prime) or a_prime is None:
        return None
    w_e = _g2_deser(epk.w_e)
    p1 = bn.pairing(a_prime, w_e)
    b1 = bn.pairing(a_prime, bn.G2_GEN)
    t3 = bn.f12_mul(
        bn.f12_mul(bn.f12_pow_raw(_gt_gen(), z_t),
                   bn.f12_pow_raw(bn.f12_inv(b1), z_rh % bn.R)),
        bn.f12_pow_raw(bn.f12_inv(p1), c % bn.R))
    return (epk.epoch, epk.w_e, a_prime, repr(t3).encode())
