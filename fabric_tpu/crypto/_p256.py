"""Pure-Python NIST P-256 (secp256r1) group + ECDSA host operations.

Fallback engine for hosts without the `cryptography` package.  Built for
correctness first, then for "fast enough to run the test topology":
Jacobian coordinates throughout, a fixed-comb table for base-point
multiples (built once at first use) and a per-call 4-bit window for
arbitrary points.  A sign is ~64 mixed additions; a verify is ~320
point ops — around a millisecond each on a laptop-class core, which is
plenty for dev topologies (the batch-verify hot path runs on the
JAX/TPU provider, never here).

Private keys are plain ints; public keys are affine (x, y) int pairs.
"""

from __future__ import annotations

import hmac
import hashlib
import secrets
from typing import Optional, Tuple

# curve parameters (FIPS 186-4, D.1.2.3)
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
HALF_N = N // 2

Affine = Tuple[int, int]
# Jacobian point (X, Y, Z); Z == 0 is the point at infinity
_Jac = Tuple[int, int, int]
_INF: _Jac = (0, 1, 0)


def _jac_double(pt: _Jac) -> _Jac:
    X1, Y1, Z1 = pt
    if not Z1 or not Y1:
        return _INF
    # dbl-2001-b (a = -3)
    delta = Z1 * Z1 % P
    gamma = Y1 * Y1 % P
    beta = X1 * gamma % P
    alpha = 3 * (X1 - delta) * (X1 + delta) % P
    X3 = (alpha * alpha - 8 * beta) % P
    Z3 = ((Y1 + Z1) * (Y1 + Z1) - gamma - delta) % P
    Y3 = (alpha * (4 * beta - X3) - 8 * gamma * gamma) % P
    return (X3, Y3, Z3)


def _jac_add(p1: _Jac, p2: _Jac) -> _Jac:
    if not p1[2]:
        return p2
    if not p2[2]:
        return p1
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    H = (U2 - U1) % P
    R = (S2 - S1) % P
    if not H:
        if not R:
            return _jac_double(p1)
        return _INF
    HH = H * H % P
    HHH = H * HH % P
    V = U1 * HH % P
    X3 = (R * R - HHH - 2 * V) % P
    Y3 = (R * (V - X3) - S1 * HHH) % P
    Z3 = Z1 * Z2 * H % P
    return (X3, Y3, Z3)


def _jac_add_affine(p1: _Jac, p2: Affine) -> _Jac:
    """Mixed addition: Jacobian + affine (Z2 == 1)."""
    if not p1[2]:
        return (p2[0], p2[1], 1)
    X1, Y1, Z1 = p1
    X2, Y2 = p2
    Z1Z1 = Z1 * Z1 % P
    U2 = X2 * Z1Z1 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    H = (U2 - X1) % P
    R = (S2 - Y1) % P
    if not H:
        if not R:
            return _jac_double(p1)
        return _INF
    HH = H * H % P
    HHH = H * HH % P
    V = X1 * HH % P
    X3 = (R * R - HHH - 2 * V) % P
    Y3 = (R * (V - X3) - Y1 * HHH) % P
    Z3 = Z1 * H % P
    return (X3, Y3, Z3)


def _to_affine(pt: _Jac) -> Optional[Affine]:
    X, Y, Z = pt
    if not Z:
        return None
    zi = pow(Z, -1, P)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 * zi % P)


def is_on_curve(x: int, y: int) -> bool:
    if not (0 <= x < P and 0 <= y < P):
        return False
    return (y * y - (x * x * x + A * x + B)) % P == 0


# ---------------------------------------------------------------------------
# fixed-comb table for G: _GTBL[w][d-1] = (d << (4*w)) * G in affine,
# for w in 0..63, d in 1..15.  Built lazily on first scalar_base_mult;
# one batch inversion (Montgomery's trick) converts the whole table.

_GTBL: Optional[list] = None


def _batch_to_affine(pts: list) -> list:
    zs = [pt[2] for pt in pts]
    # prefix products
    acc = 1
    pre = []
    for z in zs:
        pre.append(acc)
        acc = acc * z % P
    inv = pow(acc, -1, P)
    out = [None] * len(pts)
    for i in range(len(pts) - 1, -1, -1):
        zi = inv * pre[i] % P
        inv = inv * zs[i] % P
        X, Y, _ = pts[i]
        zi2 = zi * zi % P
        out[i] = (X * zi2 % P, Y * zi2 * zi % P)
    return out


def _build_gtbl() -> list:
    rows = []
    flat: list = []
    base: _Jac = (GX, GY, 1)
    for _ in range(64):
        row = [base]
        for _ in range(14):
            row.append(_jac_add(row[-1], base))
        rows.append(row)
        flat.extend(row)
        base = row[-1]
        base = _jac_add(base, rows[-1][0])  # 16 * (16^w * G)
    aff = _batch_to_affine(flat)
    return [aff[i * 15:(i + 1) * 15] for i in range(64)]


def scalar_base_mult(k: int) -> Optional[Affine]:
    """k*G in affine coordinates (None for the point at infinity)."""
    global _GTBL
    if _GTBL is None:
        _GTBL = _build_gtbl()
    k %= N
    acc = _INF
    w = 0
    while k:
        d = k & 0xF
        if d:
            acc = _jac_add_affine(acc, _GTBL[w][d - 1])
        k >>= 4
        w += 1
    return _to_affine(acc)


def scalar_mult(k: int, pt: Affine) -> Optional[Affine]:
    """k*pt for an arbitrary affine point, 4-bit fixed window."""
    k %= N
    if not k:
        return None
    # window table 1..15 in Jacobian via mixed adds
    tbl: list = [(pt[0], pt[1], 1)]
    for _ in range(14):
        tbl.append(_jac_add_affine(tbl[-1], pt))
    acc = _INF
    nibbles = []
    while k:
        nibbles.append(k & 0xF)
        k >>= 4
    for d in reversed(nibbles):
        for _ in range(4):
            acc = _jac_double(acc)
        if d:
            acc = _jac_add(acc, tbl[d - 1])
    return _to_affine(acc)


def _double_mult(u1: int, u2: int, q: Affine) -> Optional[Affine]:
    """u1*G + u2*Q — comb for G, windowed for Q, shared accumulator."""
    global _GTBL
    if _GTBL is None:
        _GTBL = _build_gtbl()
    u1 %= N
    u2 %= N
    tbl: list = [(q[0], q[1], 1)]
    for _ in range(14):
        tbl.append(_jac_add_affine(tbl[-1], q))
    acc = _INF
    started = False
    for w in range(63, -1, -1):
        if started:
            for _ in range(4):
                acc = _jac_double(acc)
        d2 = (u2 >> (4 * w)) & 0xF
        if d2:
            acc = _jac_add(acc, tbl[d2 - 1])
        started = started or acc[2] != 0
    # add u1*G via the comb (no doublings needed)
    w = 0
    while u1:
        d = u1 & 0xF
        if d:
            acc = _jac_add_affine(acc, _GTBL[w][d - 1])
        u1 >>= 4
        w += 1
    return _to_affine(acc)


# ---------------------------------------------------------------------------
# key + ECDSA operations

def generate_private_scalar() -> int:
    while True:
        d = secrets.randbelow(N)
        if d:
            return d


def public_from_scalar(d: int) -> Affine:
    pt = scalar_base_mult(d)
    if pt is None:
        raise ValueError("invalid private scalar")
    return pt


def _rfc6979_k(d: int, e: int) -> int:
    """Deterministic nonce (RFC 6979, SHA-256) — no RNG misuse possible."""
    holen = 32
    x = d.to_bytes(32, "big")
    h1 = (e % N).to_bytes(32, "big")
    V = b"\x01" * holen
    K = b"\x00" * holen
    K = hmac.new(K, V + b"\x00" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    K = hmac.new(K, V + b"\x01" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    while True:
        V = hmac.new(K, V, hashlib.sha256).digest()
        k = int.from_bytes(V, "big")
        if 1 <= k < N:
            return k
        K = hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = hmac.new(K, V, hashlib.sha256).digest()


def sign_digest(d: int, digest: bytes) -> Tuple[int, int]:
    """ECDSA over a 32-byte digest; returns (r, s) (s NOT low-S
    normalized — callers that care, normalize)."""
    e = int.from_bytes(digest, "big")
    while True:
        k = _rfc6979_k(d, e)
        pt = scalar_base_mult(k)
        if pt is None:
            continue
        r = pt[0] % N
        if not r:
            continue
        s = pow(k, -1, N) * (e + r * d) % N
        if not s:
            continue
        return r, s


def verify_digest(q: Affine, digest: bytes, r: int, s: int) -> bool:
    if not (1 <= r < N and 1 <= s < N):
        return False
    if not is_on_curve(*q):
        return False
    e = int.from_bytes(digest, "big")
    w = pow(s, -1, N)
    pt = _double_mult(e * w % N, r * w % N, q)
    if pt is None:
        return False
    return pt[0] % N == r


# ---------------------------------------------------------------------------
# SEC1 point codec

def encode_point(q: Affine) -> bytes:
    return b"\x04" + q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")


def decode_point(data: bytes) -> Affine:
    if len(data) != 65 or data[0] != 0x04:
        raise ValueError("only 65-byte uncompressed SEC1 points supported")
    x = int.from_bytes(data[1:33], "big")
    y = int.from_bytes(data[33:65], "big")
    if not is_on_curve(x, y):
        raise ValueError("point not on curve")
    return (x, y)
