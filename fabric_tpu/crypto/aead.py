"""AEAD + HKDF for the secure channel, mode-aware.

`hkdf_sha256` is RFC 5869 over stdlib hmac/hashlib in BOTH modes —
it's deterministic and byte-identical to cryptography's HKDF, so key
schedules never depend on which mode a process runs in.

`Aead` wraps ChaCha20-Poly1305 when the real library is present.  The
fallback is encrypt-then-MAC over a SHA-256 counter keystream with an
HMAC-SHA256 tag (truncated to 16 bytes, like Poly1305's).  That keeps
hot bytes on C-speed hashlib instead of a pure-Python ChaCha core; it
is integrity+confidentiality sound for the dev topologies the fallback
serves, but it is NOT wire-compatible with the real mode — which is
fine, because every process in a topology shares one environment.
"""

from __future__ import annotations

import hashlib
import hmac
import struct

try:  # pragma: no cover - environment probe
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    _HAVE_CHACHA = True
except ImportError:
    ChaCha20Poly1305 = None
    _HAVE_CHACHA = False

_TAG_LEN = 16
_U64 = struct.Struct("<Q")


def hkdf_sha256(secret: bytes, salt: bytes, info: bytes,
                length: int = 32) -> bytes:
    """RFC 5869 HKDF-Extract + Expand with SHA-256."""
    if length > 255 * 32:
        raise ValueError("hkdf output too long")
    prk = hmac.new(salt or b"\x00" * 32, secret, hashlib.sha256).digest()
    okm = b""
    block = b""
    counter = 1
    while len(okm) < length:
        block = hmac.new(prk, block + info + bytes([counter]),
                         hashlib.sha256).digest()
        okm += block
        counter += 1
    return okm[:length]


class Aead:
    """ChaCha20-Poly1305 when available; hashlib-based AEAD otherwise.
    API: encrypt(nonce12, plaintext, aad) / decrypt(nonce12, ct, aad),
    decrypt raises ValueError on authentication failure."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("Aead keys are 32 bytes")
        if _HAVE_CHACHA:
            self._impl = ChaCha20Poly1305(key)
            self._enc_key = self._mac_key = None
        else:
            self._impl = None
            self._enc_key = hashlib.sha256(b"ftpu-aead-enc" + key).digest()
            self._mac_key = hashlib.sha256(b"ftpu-aead-mac" + key).digest()

    def _keystream_xor(self, nonce: bytes, data: bytes) -> bytes:
        out = bytearray(len(data))
        view = memoryview(data)
        for i in range(0, len(data), 32):
            block = hashlib.sha256(
                self._enc_key + nonce + _U64.pack(i // 32)).digest()
            chunk = view[i:i + 32]
            out[i:i + len(chunk)] = bytes(
                a ^ b for a, b in zip(chunk, block))
        return bytes(out)

    def _tag(self, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
        mac = hmac.new(self._mac_key, digestmod=hashlib.sha256)
        mac.update(_U64.pack(len(aad)))
        mac.update(aad)
        mac.update(nonce)
        mac.update(ct)
        return mac.digest()[:_TAG_LEN]

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes = b"") -> bytes:
        if self._impl is not None:
            return self._impl.encrypt(nonce, data, aad or None)
        ct = self._keystream_xor(nonce, data)
        return ct + self._tag(nonce, aad or b"", ct)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes = b"") -> bytes:
        if self._impl is not None:
            try:
                return self._impl.decrypt(nonce, data, aad or None)
            except Exception as exc:
                raise ValueError("AEAD authentication failed") from exc
        if len(data) < _TAG_LEN:
            raise ValueError("AEAD ciphertext too short")
        ct, tag = data[:-_TAG_LEN], data[-_TAG_LEN:]
        if not hmac.compare_digest(tag, self._tag(nonce, aad or b"", ct)):
            raise ValueError("AEAD authentication failed")
        return self._keystream_xor(nonce, ct)
