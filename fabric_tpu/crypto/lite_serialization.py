"""Key (de)serialization enums + loaders for the fallback.

API parity with cryptography.hazmat.primitives.serialization for the
subset the framework uses.  Private keys serialize as a serde dict
{"scheme", "secret"} in FABRICTPU PRIVATE KEY armor; public keys as
{"scheme", "pub"} (armored for PEM, bare serde bytes for "DER").
"""

from __future__ import annotations

import enum

from fabric_tpu.crypto import _pem
from fabric_tpu.utils import serde

PRIVATE_LABEL = "FABRICTPU PRIVATE KEY"
PUBLIC_LABEL = "FABRICTPU PUBLIC KEY"


class Encoding(enum.Enum):
    PEM = "PEM"
    DER = "DER"
    X962 = "X962"
    Raw = "Raw"


class PublicFormat(enum.Enum):
    SubjectPublicKeyInfo = "SubjectPublicKeyInfo"
    UncompressedPoint = "UncompressedPoint"
    Raw = "Raw"


class PrivateFormat(enum.Enum):
    PKCS8 = "PKCS8"
    Raw = "Raw"


class NoEncryption:
    pass


def serialize_private(scheme: str, secret: bytes) -> bytes:
    return _pem.armor(PRIVATE_LABEL,
                      serde.encode({"scheme": scheme, "secret": secret}))


def serialize_public(scheme: str, pub: bytes, encoding: Encoding) -> bytes:
    der = serde.encode({"scheme": scheme, "pub": pub})
    if encoding == Encoding.DER:
        return der
    return _pem.armor(PUBLIC_LABEL, der)


def _public_from_fields(scheme: str, pub: bytes):
    from fabric_tpu.crypto import lite_ec, lite_ed25519
    if scheme == "p256":
        return lite_ec.EllipticCurvePublicKey.from_encoded_point(
            lite_ec.SECP256R1(), pub)
    if scheme == "ed25519":
        return lite_ed25519.Ed25519PublicKey.from_public_bytes(pub)
    raise ValueError("unsupported key scheme: %r" % scheme)


def load_pem_private_key(data: bytes, password=None, backend=None):
    if password is not None:
        raise ValueError("fallback keys are never encrypted")
    d = serde.decode(_pem.dearmor(data, PRIVATE_LABEL))
    scheme, secret = d["scheme"], d["secret"]
    from fabric_tpu.crypto import lite_ec, lite_ed25519
    if scheme == "p256":
        return lite_ec.derive_private_key(
            int.from_bytes(secret, "big"), lite_ec.SECP256R1())
    if scheme == "ed25519":
        return lite_ed25519.Ed25519PrivateKey.from_private_bytes(secret)
    raise ValueError("unsupported key scheme: %r" % scheme)


def load_der_public_key(data: bytes, backend=None):
    d = serde.decode(bytes(data))
    return _public_from_fields(d["scheme"], d["pub"])


def load_pem_public_key(data: bytes, backend=None):
    return load_der_public_key(_pem.dearmor(data, PUBLIC_LABEL))
