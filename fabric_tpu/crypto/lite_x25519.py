"""Fallback X25519 API with cryptography-compatible surface (the
subset comm/secure.py uses for ephemeral key agreement)."""

from __future__ import annotations

import secrets

from fabric_tpu.crypto import _x25519


class X25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 public keys are 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "X25519PublicKey":
        return cls(bytes(data))

    def public_bytes_raw(self) -> bytes:
        return self._raw


class X25519PrivateKey:
    def __init__(self, scalar: bytes):
        if len(scalar) != 32:
            raise ValueError("X25519 scalars are 32 bytes")
        self._scalar = bytes(scalar)
        self._pub = X25519PublicKey(_x25519.public_from_scalar(self._scalar))

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(secrets.token_bytes(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "X25519PrivateKey":
        return cls(bytes(data))

    def public_key(self) -> X25519PublicKey:
        return self._pub

    def exchange(self, peer_public_key: X25519PublicKey) -> bytes:
        shared = _x25519.x25519(self._scalar, peer_public_key._raw)
        if shared == b"\x00" * 32:
            raise ValueError("X25519 exchange produced the zero point")
        return shared
