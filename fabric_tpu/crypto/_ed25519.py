"""Pure-Python Ed25519 (RFC 8032) host operations — fallback engine.

Extended homogeneous coordinates, a lazily-built 4-bit fixed-comb table
for the base point, and a per-verify window for the public-key point.
Same performance envelope as _p256: ~1 ms/op, dev-topology grade (the
batched hot path lives on the JAX provider).
"""

from __future__ import annotations

import hashlib
from typing import Optional, Tuple

P = 2 ** 255 - 19
L = 2 ** 252 + 27742317777372353535851937790883648493
D = -121665 * pow(121666, -1, P) % P
_SQRT_M1 = pow(2, (P - 1) // 4, P)

# extended coords (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z
_Ext = Tuple[int, int, int, int]
_ID: _Ext = (0, 1, 1, 0)

_BY = 4 * pow(5, -1, P) % P
_BX = 0


def _recover_x(y: int, sign: int) -> Optional[int]:
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, -1, P) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P:
        x = x * _SQRT_M1 % P
    if (x * x - x2) % P:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
_BASE: _Ext = (_BX, _BY, 1, _BX * _BY % P)


def _add(p: _Ext, q: _Ext) -> _Ext:
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = (Y1 - X1) * (Y2 - X2) % P
    b = (Y1 + X1) * (Y2 + X2) % P
    c = 2 * T1 * T2 * D % P
    d = 2 * Z1 * Z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _double(p: _Ext) -> _Ext:
    return _add(p, p)


def _mult(k: int, pt: _Ext) -> _Ext:
    tbl = [pt]
    for _ in range(14):
        tbl.append(_add(tbl[-1], pt))
    acc = _ID
    nibbles = []
    while k:
        nibbles.append(k & 0xF)
        k >>= 4
    for d in reversed(nibbles):
        acc = _double(_double(_double(_double(acc))))
        if d:
            acc = _add(acc, tbl[d - 1])
    return acc


_BTBL: Optional[list] = None


def _mult_base(k: int) -> _Ext:
    global _BTBL
    if _BTBL is None:
        tbl = []
        base = _BASE
        for _ in range(64):
            row = [base]
            for _ in range(14):
                row.append(_add(row[-1], base))
            tbl.append(row)
            base = _add(row[-1], base)  # 16 * (16^w * B)
        _BTBL = tbl
    acc = _ID
    w = 0
    k %= L
    while k:
        d = k & 0xF
        if d:
            acc = _add(acc, _BTBL[w][d - 1])
        k >>= 4
        w += 1
    return acc


def _compress(p: _Ext) -> bytes:
    X, Y, Z, _ = p
    zi = pow(Z, -1, P)
    x = X * zi % P
    y = Y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(data: bytes) -> Optional[_Ext]:
    if len(data) != 32:
        return None
    n = int.from_bytes(data, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def _h(data: bytes) -> int:
    return int.from_bytes(hashlib.sha512(data).digest(), "little")


def _clamp(seed_hash: bytes) -> int:
    a = int.from_bytes(seed_hash[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def public_from_seed(seed: bytes) -> bytes:
    if len(seed) != 32:
        raise ValueError("Ed25519 seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    return _compress(_mult_base(_clamp(h)))


def sign(seed: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    prefix = h[32:]
    A = _compress(_mult_base(a))
    r = _h(prefix + msg) % L
    R = _compress(_mult_base(r))
    k = _h(R + A + msg) % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def verify(pub: bytes, sig: bytes, msg: bytes) -> bool:
    if len(sig) != 64 or len(pub) != 32:
        return False
    A = _decompress(pub)
    R = _decompress(sig[:32])
    if A is None or R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = _h(sig[:32] + pub + msg) % L
    # [s]B == R + [k]A  <=>  [s]B + [k](-A) == R
    nA = (P - A[0], A[1], A[2], P - A[3])
    lhs = _add(_mult_base(s), _mult(k, nA))
    return _compress(lhs) == sig[:32]
