"""Minimal DER codec for ECDSA signatures: SEQUENCE of two INTEGERs.

API parity with cryptography.hazmat.primitives.asymmetric.utils'
encode_dss_signature / decode_dss_signature.  Strict DER: minimal
integer encodings, definite short/long lengths, no trailing bytes.
"""

from __future__ import annotations

from typing import Tuple


def _enc_int(v: int) -> bytes:
    if v < 0:
        raise ValueError("negative integers not supported")
    n = max(1, (v.bit_length() + 7) // 8)
    body = v.to_bytes(n, "big")
    if body[0] & 0x80:
        body = b"\x00" + body
    return b"\x02" + _enc_len(len(body)) + body


def _enc_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def encode_dss_signature(r: int, s: int) -> bytes:
    body = _enc_int(r) + _enc_int(s)
    return b"\x30" + _enc_len(len(body)) + body


def _dec_len(data: bytes, off: int) -> Tuple[int, int]:
    first = data[off]
    off += 1
    if first < 0x80:
        return first, off
    n = first & 0x7F
    if not n or n > 8:
        raise ValueError("bad DER length")
    val = int.from_bytes(data[off:off + n], "big")
    if len(data[off:off + n]) != n or val < 0x80:
        raise ValueError("non-minimal DER length")
    return val, off + n


def _dec_int(data: bytes, off: int) -> Tuple[int, int]:
    if off >= len(data) or data[off] != 0x02:
        raise ValueError("expected DER INTEGER")
    ln, off = _dec_len(data, off + 1)
    body = data[off:off + ln]
    if len(body) != ln or not ln:
        raise ValueError("truncated DER INTEGER")
    if ln > 1 and body[0] == 0 and not (body[1] & 0x80):
        raise ValueError("non-minimal DER INTEGER")
    if body[0] & 0x80:
        raise ValueError("negative DER INTEGER")
    return int.from_bytes(body, "big"), off + ln


def decode_dss_signature(sig: bytes) -> Tuple[int, int]:
    if not sig or sig[0] != 0x30:
        raise ValueError("expected DER SEQUENCE")
    ln, off = _dec_len(sig, 1)
    if off + ln != len(sig):
        raise ValueError("trailing bytes after DER SEQUENCE")
    r, off = _dec_int(sig, off)
    s, off = _dec_int(sig, off)
    if off != len(sig):
        raise ValueError("trailing bytes inside DER SEQUENCE")
    return r, s
