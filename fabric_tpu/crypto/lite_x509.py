"""Fallback "X.509" identity certificates for hosts without the
`cryptography` package.

API parity with the subset of cryptography.x509 the MSP layer uses
(builders, Name/NameAttribute, BasicConstraints/KeyUsage extensions,
CRLs, verify_directly_issued_by).  The encoding is NOT ASN.1: the TBS
is a canonical serde dict and certs travel in FABRICTPU CERTIFICATE
armor, so real X.509 material and fallback material can never be
confused.  All trust decisions in the framework go through MSPs built
from certs minted by msp/ca.py in the SAME process environment, so the
two modes never need to interoperate on the wire.
"""

from __future__ import annotations

import datetime
import secrets
from typing import List, Optional

from fabric_tpu.crypto import _pem, lite_serialization as _ser
from fabric_tpu.crypto._errors import InvalidSignature
from fabric_tpu.utils import serde

CERT_LABEL = "FABRICTPU CERTIFICATE"
CRL_LABEL = "FABRICTPU CRL"


class NameOID:
    COMMON_NAME = "CN"
    ORGANIZATION_NAME = "O"
    ORGANIZATIONAL_UNIT_NAME = "OU"
    COUNTRY_NAME = "C"
    LOCALITY_NAME = "L"
    STATE_OR_PROVINCE_NAME = "ST"


class ExtensionNotFound(Exception):
    def __init__(self, msg, oid=None):
        super().__init__(msg)
        self.oid = oid


class NameAttribute:
    def __init__(self, oid: str, value: str):
        self.oid = oid
        self.value = value

    def __eq__(self, other):
        return (isinstance(other, NameAttribute)
                and (self.oid, self.value) == (other.oid, other.value))

    def __hash__(self):
        return hash((self.oid, self.value))


class Name:
    def __init__(self, attributes: List[NameAttribute]):
        self._attrs = list(attributes)

    def public_bytes(self, backend=None) -> bytes:
        return serde.encode([[a.oid, a.value] for a in self._attrs])

    def rfc4514_string(self) -> str:
        return ",".join("%s=%s" % (a.oid, a.value)
                        for a in reversed(self._attrs))

    def get_attributes_for_oid(self, oid: str) -> List[NameAttribute]:
        return [a for a in self._attrs if a.oid == oid]

    @staticmethod
    def _from_wire(pairs) -> "Name":
        return Name([NameAttribute(o, v) for o, v in pairs])

    def _wire(self):
        return [[a.oid, a.value] for a in self._attrs]

    def __eq__(self, other):
        return isinstance(other, Name) and self._wire() == other._wire()

    def __hash__(self):
        return hash(self.public_bytes())

    def __iter__(self):
        return iter(self._attrs)


class BasicConstraints:
    oid = "basicConstraints"

    def __init__(self, ca: bool, path_length: Optional[int]):
        self.ca = bool(ca)
        self.path_length = path_length


class KeyUsage:
    oid = "keyUsage"

    _FIELDS = ("digital_signature", "content_commitment", "key_encipherment",
               "data_encipherment", "key_agreement", "key_cert_sign",
               "crl_sign", "encipher_only", "decipher_only")

    def __init__(self, digital_signature=False, content_commitment=False,
                 key_encipherment=False, data_encipherment=False,
                 key_agreement=False, key_cert_sign=False, crl_sign=False,
                 encipher_only=False, decipher_only=False):
        self.digital_signature = digital_signature
        self.content_commitment = content_commitment
        self.key_encipherment = key_encipherment
        self.data_encipherment = data_encipherment
        self.key_agreement = key_agreement
        self.key_cert_sign = key_cert_sign
        self.crl_sign = crl_sign
        self.encipher_only = encipher_only
        self.decipher_only = decipher_only


class Extension:
    def __init__(self, value, critical: bool):
        self.value = value
        self.critical = critical


class Extensions:
    def __init__(self, exts: List[Extension]):
        self._exts = exts

    def get_extension_for_class(self, extclass) -> Extension:
        for ext in self._exts:
            if isinstance(ext.value, extclass):
                return ext
        raise ExtensionNotFound(
            "no %s extension" % extclass.__name__,
            getattr(extclass, "oid", None))

    def __iter__(self):
        return iter(self._exts)


def random_serial_number() -> int:
    return secrets.randbits(63) | 1


def _ts(dt: datetime.datetime) -> float:
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.timestamp()


def _dt(ts: float) -> datetime.datetime:
    return datetime.datetime.fromtimestamp(ts, datetime.timezone.utc)


def _key_scheme_and_wire(public_key):
    from fabric_tpu.crypto import lite_ec, lite_ed25519
    if isinstance(public_key, lite_ec.EllipticCurvePublicKey):
        return "p256", public_key.public_bytes(
            _ser.Encoding.X962, _ser.PublicFormat.UncompressedPoint)
    if isinstance(public_key, lite_ed25519.Ed25519PublicKey):
        return "ed25519", public_key.public_bytes_raw()
    raise ValueError("unsupported public key type for certificates")


def _public_from_wire(scheme: str, wire: bytes):
    return _ser._public_from_fields(scheme, wire)


def _sign_payload(private_key, payload: bytes) -> bytes:
    from fabric_tpu.crypto import lite_ec, lite_hashes
    if isinstance(private_key, lite_ec.EllipticCurvePrivateKey):
        return private_key.sign(payload, lite_ec.ECDSA(lite_hashes.SHA256()))
    return private_key.sign(payload)


def _verify_payload(public_key, signature: bytes, payload: bytes) -> None:
    from fabric_tpu.crypto import lite_ec, lite_hashes
    if isinstance(public_key, lite_ec.EllipticCurvePublicKey):
        public_key.verify(signature, payload,
                          lite_ec.ECDSA(lite_hashes.SHA256()))
    else:
        public_key.verify(signature, payload)


class Certificate:
    def __init__(self, der: bytes):
        outer = serde.decode(der)
        self._der = bytes(der)
        self._tbs = outer["tbs"]
        self._sig = outer["sig"]
        self._sig_scheme = outer["sig_scheme"]
        t = serde.decode(self._tbs)
        self.subject = Name._from_wire(t["subject"])
        self.issuer = Name._from_wire(t["issuer"])
        self.serial_number = t["serial"]
        self._nbf = t["nbf"]
        self._naf = t["naf"]
        self._scheme = t["scheme"]
        self._pub = t["pub"]
        exts = []
        if t["bc"] is not None:
            ca, pl = t["bc"]
            exts.append(Extension(BasicConstraints(ca, pl), critical=True))
        if t["ku"] is not None:
            exts.append(Extension(
                KeyUsage(**dict(zip(KeyUsage._FIELDS, t["ku"]))),
                critical=True))
        self.extensions = Extensions(exts)

    @property
    def not_valid_before_utc(self) -> datetime.datetime:
        return _dt(self._nbf)

    @property
    def not_valid_after_utc(self) -> datetime.datetime:
        return _dt(self._naf)

    # naive variants for older-cryptography-style callers
    @property
    def not_valid_before(self) -> datetime.datetime:
        return _dt(self._nbf).replace(tzinfo=None)

    @property
    def not_valid_after(self) -> datetime.datetime:
        return _dt(self._naf).replace(tzinfo=None)

    def public_key(self):
        return _public_from_wire(self._scheme, self._pub)

    def public_bytes(self, encoding=_ser.Encoding.PEM) -> bytes:
        if encoding == _ser.Encoding.DER:
            return self._der
        return _pem.armor(CERT_LABEL, self._der)

    def verify_directly_issued_by(self, issuer_cert: "Certificate") -> None:
        if self.issuer != issuer_cert.subject:
            raise ValueError("issuer name does not match candidate subject")
        try:
            _verify_payload(issuer_cert.public_key(), self._sig, self._tbs)
        except InvalidSignature:
            raise
        except Exception as exc:
            raise InvalidSignature(str(exc)) from exc

    def __eq__(self, other):
        return isinstance(other, Certificate) and self._der == other._der

    def __hash__(self):
        return hash(self._der)


class CertificateBuilder:
    def __init__(self):
        self._subject = None
        self._issuer = None
        self._pub = None
        self._serial = None
        self._nbf = None
        self._naf = None
        self._bc = None
        self._ku = None

    def subject_name(self, name: Name) -> "CertificateBuilder":
        self._subject = name
        return self

    def issuer_name(self, name: Name) -> "CertificateBuilder":
        self._issuer = name
        return self

    def public_key(self, key) -> "CertificateBuilder":
        self._pub = key
        return self

    def serial_number(self, sn: int) -> "CertificateBuilder":
        self._serial = sn
        return self

    def not_valid_before(self, dt: datetime.datetime) -> "CertificateBuilder":
        self._nbf = _ts(dt)
        return self

    def not_valid_after(self, dt: datetime.datetime) -> "CertificateBuilder":
        self._naf = _ts(dt)
        return self

    def add_extension(self, ext, critical: bool) -> "CertificateBuilder":
        if isinstance(ext, BasicConstraints):
            self._bc = ext
        elif isinstance(ext, KeyUsage):
            self._ku = ext
        else:
            raise ValueError("unsupported extension type")
        return self

    def sign(self, private_key, algorithm, backend=None) -> Certificate:
        if None in (self._subject, self._issuer, self._pub,
                    self._serial, self._nbf, self._naf):
            raise ValueError("certificate builder is missing fields")
        scheme, wire = _key_scheme_and_wire(self._pub)
        tbs = serde.encode({
            "v": 1,
            "subject": self._subject._wire(),
            "issuer": self._issuer._wire(),
            "serial": self._serial,
            "nbf": int(self._nbf),
            "naf": int(self._naf),
            "scheme": scheme,
            "pub": wire,
            "bc": ([self._bc.ca, self._bc.path_length]
                   if self._bc is not None else None),
            "ku": ([bool(getattr(self._ku, f)) for f in KeyUsage._FIELDS]
                   if self._ku is not None else None),
        })
        signer_scheme = ("p256" if hasattr(private_key, "curve")
                         else "ed25519")
        sig = _sign_payload(private_key, tbs)
        return Certificate(serde.encode(
            {"tbs": tbs, "sig": sig, "sig_scheme": signer_scheme}))


def load_pem_x509_certificate(data: bytes, backend=None) -> Certificate:
    return Certificate(_pem.dearmor(data, CERT_LABEL))


def load_der_x509_certificate(data: bytes, backend=None) -> Certificate:
    return Certificate(bytes(data))


# ---------------------------------------------------------------------------
# CRLs

class RevokedCertificate:
    def __init__(self, serial_number: int, revocation_date_ts: float):
        self.serial_number = serial_number
        self.revocation_date_utc = _dt(revocation_date_ts)


class RevokedCertificateBuilder:
    def __init__(self):
        self._serial = None
        self._date = None

    def serial_number(self, sn: int) -> "RevokedCertificateBuilder":
        self._serial = sn
        return self

    def revocation_date(self, dt) -> "RevokedCertificateBuilder":
        self._date = _ts(dt)
        return self

    def build(self, backend=None) -> RevokedCertificate:
        if self._serial is None:
            raise ValueError("revoked certificate needs a serial number")
        return RevokedCertificate(self._serial, self._date or 0.0)


class CertificateRevocationList:
    def __init__(self, der: bytes):
        d = serde.decode(der)
        self._der = bytes(der)
        self.issuer = Name._from_wire(d["issuer"])
        self._revoked = [RevokedCertificate(sn, ts)
                         for sn, ts in d["revoked"]]

    def public_bytes(self, encoding=_ser.Encoding.PEM) -> bytes:
        if encoding == _ser.Encoding.DER:
            return self._der
        return _pem.armor(CRL_LABEL, self._der)

    def __iter__(self):
        return iter(self._revoked)

    def __len__(self):
        return len(self._revoked)


class CertificateRevocationListBuilder:
    def __init__(self):
        self._issuer = None
        self._last = None
        self._next = None
        self._revoked: List[RevokedCertificate] = []

    def issuer_name(self, name: Name) -> "CertificateRevocationListBuilder":
        self._issuer = name
        return self

    def last_update(self, dt) -> "CertificateRevocationListBuilder":
        self._last = _ts(dt)
        return self

    def next_update(self, dt) -> "CertificateRevocationListBuilder":
        self._next = _ts(dt)
        return self

    def add_revoked_certificate(
            self, rc: RevokedCertificate
    ) -> "CertificateRevocationListBuilder":
        self._revoked.append(rc)
        return self

    def sign(self, private_key, algorithm,
             backend=None) -> CertificateRevocationList:
        if self._issuer is None:
            raise ValueError("CRL builder needs an issuer name")
        return CertificateRevocationList(serde.encode({
            "issuer": self._issuer._wire(),
            "last": int(self._last or 0),
            "next": int(self._next or 0),
            "revoked": [[rc.serial_number,
                         int(rc.revocation_date_utc.timestamp())]
                        for rc in self._revoked],
        }))


def load_pem_x509_crl(data: bytes, backend=None) -> CertificateRevocationList:
    return CertificateRevocationList(_pem.dearmor(data, CRL_LABEL))
