"""PEM-style armor for the fallback's serde-encoded key/cert blobs.

Labels use a FABRICTPU prefix on purpose: these blobs are NOT ASN.1 and
must never be mistaken for real X.509 / PKCS8 material by other tools.
"""

from __future__ import annotations

import base64


def armor(label: str, der: bytes) -> bytes:
    b64 = base64.b64encode(der).decode()
    lines = [b64[i:i + 64] for i in range(0, len(b64), 64)] or [""]
    return ("-----BEGIN %s-----\n%s\n-----END %s-----\n"
            % (label, "\n".join(lines), label)).encode()


def dearmor(pem: bytes, label: str) -> bytes:
    text = pem.decode() if isinstance(pem, (bytes, bytearray)) else str(pem)
    begin = "-----BEGIN %s-----" % label
    end = "-----END %s-----" % label
    try:
        start = text.index(begin) + len(begin)
        stop = text.index(end, start)
    except ValueError:
        raise ValueError("no %s PEM block found" % label) from None
    return base64.b64decode("".join(text[start:stop].split()))


def first_label(pem: bytes) -> str:
    text = pem.decode() if isinstance(pem, (bytes, bytearray)) else str(pem)
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("-----BEGIN ") and line.endswith("-----"):
            return line[len("-----BEGIN "):-len("-----")]
    raise ValueError("no PEM block found")
