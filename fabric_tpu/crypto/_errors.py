"""Exception types shared by the pure-Python crypto fallback modules."""


class InvalidSignature(Exception):
    """Raised when a signature fails verification (API parity with
    cryptography.exceptions.InvalidSignature)."""


class UnsupportedAlgorithm(Exception):
    """Raised for algorithm/format combinations outside the fallback's
    supported subset."""
