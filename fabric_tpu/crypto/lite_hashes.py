"""Hash algorithm descriptors (API parity with
cryptography.hazmat.primitives.hashes for the subset the framework
uses).  These are descriptors only — actual hashing runs on hashlib."""

from __future__ import annotations

import hashlib


class SHA256:
    name = "sha256"
    digest_size = 32
    block_size = 64


class SHA384:
    name = "sha384"
    digest_size = 48
    block_size = 128


class SHA512:
    name = "sha512"
    digest_size = 64
    block_size = 128


class Hash:
    def __init__(self, algorithm):
        self.algorithm = algorithm
        self._h = hashlib.new(algorithm.name)

    def update(self, data: bytes) -> None:
        self._h.update(data)

    def finalize(self) -> bytes:
        return self._h.digest()
