"""Fallback Ed25519 API with cryptography-compatible surface."""

from __future__ import annotations

import secrets

from fabric_tpu.crypto import _ed25519, lite_serialization as _ser
from fabric_tpu.crypto._errors import InvalidSignature


class Ed25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("Ed25519 public keys are 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "Ed25519PublicKey":
        return cls(bytes(data))

    def public_bytes(self, encoding, format) -> bytes:
        if (encoding == _ser.Encoding.Raw
                and format == _ser.PublicFormat.Raw):
            return self._raw
        if format == _ser.PublicFormat.SubjectPublicKeyInfo:
            return _ser.serialize_public("ed25519", self._raw, encoding)
        raise ValueError("unsupported Ed25519 public_bytes format")

    def public_bytes_raw(self) -> bytes:
        return self._raw

    def verify(self, signature: bytes, data: bytes) -> None:
        if not _ed25519.verify(self._raw, bytes(signature), bytes(data)):
            raise InvalidSignature("Ed25519 verification failed")

    def __eq__(self, other):
        return (isinstance(other, Ed25519PublicKey)
                and self._raw == other._raw)

    def __hash__(self):
        return hash(("ed-pub", self._raw))


class Ed25519PrivateKey:
    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("Ed25519 seeds are 32 bytes")
        self._seed = bytes(seed)
        self._pub = Ed25519PublicKey(_ed25519.public_from_seed(self._seed))

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(secrets.token_bytes(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "Ed25519PrivateKey":
        return cls(bytes(data))

    def public_key(self) -> Ed25519PublicKey:
        return self._pub

    def sign(self, data: bytes) -> bytes:
        return _ed25519.sign(self._seed, bytes(data))

    def private_bytes(self, encoding, format, encryption_algorithm) -> bytes:
        if (encoding == _ser.Encoding.Raw
                and format == _ser.PrivateFormat.Raw):
            return self._seed
        if encoding != _ser.Encoding.PEM:
            raise ValueError("fallback Ed25519 keys serialize as PEM or Raw")
        return _ser.serialize_private("ed25519", self._seed)

    def private_bytes_raw(self) -> bytes:
        return self._seed
