"""Pure-Python X25519 Diffie-Hellman (RFC 7748) — fallback engine for
the secure channel's ephemeral key agreement."""

from __future__ import annotations

P = 2 ** 255 - 19
_A24 = 121665


def _decode_u(data: bytes) -> int:
    if len(data) != 32:
        raise ValueError("X25519 coordinates are 32 bytes")
    return int.from_bytes(data, "little") & ((1 << 255) - 1)


def _decode_scalar(data: bytes) -> int:
    if len(data) != 32:
        raise ValueError("X25519 scalars are 32 bytes")
    k = int.from_bytes(data, "little")
    k &= (1 << 254) - 8
    k |= 1 << 254
    return k


def x25519(scalar: bytes, u_bytes: bytes) -> bytes:
    k = _decode_scalar(scalar)
    u = _decode_u(u_bytes)
    x1 = u
    x2, z2 = 1, 0
    x3, z3 = u, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (k >> t) & 1
        if swap ^ kt:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % P
        aa = a * a % P
        b = (x2 - z2) % P
        bb = b * b % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = d * a % P
        cb = c * b % P
        x3 = (da + cb) % P
        x3 = x3 * x3 % P
        z3 = (da - cb) % P
        z3 = x1 * z3 * z3 % P
        x2 = aa * bb % P
        z2 = e * (aa + _A24 * e) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, -1, P) % P
    return out.to_bytes(32, "little")


BASE_U = (9).to_bytes(32, "little")


def public_from_scalar(scalar: bytes) -> bytes:
    return x25519(scalar, BASE_U)
