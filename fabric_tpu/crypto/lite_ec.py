"""Fallback EC API (secp256r1 only) with cryptography-compatible
surface: generate/derive_private_key, ECDSA with SHA-256 (plain or
Prehashed), X962 uncompressed public bytes, PEM private keys."""

from __future__ import annotations

import hashlib

from fabric_tpu.crypto import _p256, lite_serialization as _ser
from fabric_tpu.crypto._der import decode_dss_signature, encode_dss_signature
from fabric_tpu.crypto._errors import InvalidSignature


class SECP256R1:
    name = "secp256r1"
    key_size = 256


class Prehashed:
    def __init__(self, algorithm):
        self.algorithm = algorithm


class ECDSA:
    def __init__(self, algorithm):
        self.algorithm = algorithm


def _digest_for(signature_algorithm, data: bytes) -> bytes:
    algo = getattr(signature_algorithm, "algorithm", signature_algorithm)
    if isinstance(algo, Prehashed):
        if len(data) != algo.algorithm.digest_size:
            raise ValueError("prehashed data has wrong length")
        return bytes(data)
    if getattr(algo, "name", None) != "sha256":
        raise ValueError("fallback ECDSA supports SHA-256 only")
    return hashlib.sha256(data).digest()


class EllipticCurveNumbers:
    def __init__(self, x: int, y: int):
        self.x = x
        self.y = y


class EllipticCurvePublicKey:
    def __init__(self, point):
        self._q = point
        self.curve = SECP256R1()

    @classmethod
    def from_encoded_point(cls, curve, data: bytes):
        return cls(_p256.decode_point(bytes(data)))

    def public_numbers(self) -> EllipticCurveNumbers:
        return EllipticCurveNumbers(*self._q)

    def public_bytes(self, encoding, format) -> bytes:
        if (encoding == _ser.Encoding.X962
                and format == _ser.PublicFormat.UncompressedPoint):
            return _p256.encode_point(self._q)
        if format == _ser.PublicFormat.SubjectPublicKeyInfo:
            return _ser.serialize_public(
                "p256", _p256.encode_point(self._q), encoding)
        raise ValueError("unsupported EC public_bytes format")

    def verify(self, signature: bytes, data: bytes,
               signature_algorithm) -> None:
        digest = _digest_for(signature_algorithm, data)
        try:
            r, s = decode_dss_signature(signature)
        except ValueError:
            raise InvalidSignature("malformed DER signature") from None
        if not _p256.verify_digest(self._q, digest, r, s):
            raise InvalidSignature("ECDSA verification failed")

    def __eq__(self, other):
        return (isinstance(other, EllipticCurvePublicKey)
                and self._q == other._q)

    def __hash__(self):
        return hash(("p256-pub", self._q))


class EllipticCurvePrivateKey:
    def __init__(self, d: int):
        if not (1 <= d < _p256.N):
            raise ValueError("private scalar out of range")
        self._d = d
        self._pub = EllipticCurvePublicKey(_p256.public_from_scalar(d))
        self.curve = SECP256R1()

    def public_key(self) -> EllipticCurvePublicKey:
        return self._pub

    def sign(self, data: bytes, signature_algorithm) -> bytes:
        digest = _digest_for(signature_algorithm, data)
        r, s = _p256.sign_digest(self._d, digest)
        return encode_dss_signature(r, s)

    def private_bytes(self, encoding, format, encryption_algorithm) -> bytes:
        if encoding != _ser.Encoding.PEM:
            raise ValueError("fallback EC private keys serialize as PEM only")
        return _ser.serialize_private("p256", self._d.to_bytes(32, "big"))

    def private_numbers(self):
        key = self

        class _Numbers:
            private_value = key._d
        return _Numbers()


def generate_private_key(curve, backend=None) -> EllipticCurvePrivateKey:
    return EllipticCurvePrivateKey(_p256.generate_private_scalar())


def derive_private_key(private_value: int, curve,
                       backend=None) -> EllipticCurvePrivateKey:
    return EllipticCurvePrivateKey(private_value % _p256.N or 1)
