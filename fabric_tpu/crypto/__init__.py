"""Crypto shim: one import surface for host-side cryptography.

Every module in the framework that needs host crypto (sign, X.509-style
identity certs, ECDH transport keys) imports it from HERE instead of
from `cryptography` directly.  When the real `cryptography` package is
installed, this module re-exports it verbatim, so behavior (and wire
formats: real X.509 PEM, PKCS8, DER ECDSA) is exactly the upstream
library's.  When it is missing — common on minimal TPU pods and CI
hosts — a pure-Python fallback with the same API subset takes over:

  * P-256 ECDSA + keygen       (fabric_tpu.crypto._p256)
  * Ed25519 / X25519           (_ed25519 / _x25519, RFC 8032 / 7748)
  * DER ECDSA sig codec        (_der — used in BOTH modes is fine; we
                                re-export the C one when present)
  * lite "X.509" identity certs (lite_x509 — serde-encoded TBS in a
                                FABRICTPU PEM armor; NOT ASN.1)

The two modes are NOT wire-compatible with each other (lite certs are
not ASN.1 X.509), but a deployment is always homogeneous: every node in
a dev/test topology runs from the same environment, and all framework
trust decisions flow through MSPs built from certs minted in-process by
msp/ca.py.  `HAVE_CRYPTOGRAPHY` tells callers (and tests) which mode
is active.

AEAD + HKDF for the secure channel live in `fabric_tpu.crypto.aead`
and are re-exported here; HKDF is pure-Python in both modes (RFC 5869
over hashlib, deterministic, identical output either way).
"""

from __future__ import annotations

try:  # pragma: no cover - environment probe
    import cryptography  # noqa: F401
    HAVE_CRYPTOGRAPHY = True
except ImportError:
    HAVE_CRYPTOGRAPHY = False

if HAVE_CRYPTOGRAPHY:  # pragma: no cover - exercised only with the real lib
    from cryptography import x509
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec, ed25519
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey, Ed25519PublicKey)
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed, decode_dss_signature, encode_dss_signature)
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey)
    from cryptography.hazmat.primitives.serialization import (
        Encoding, NoEncryption, PrivateFormat, PublicFormat,
        load_der_public_key, load_pem_private_key, load_pem_public_key)
    from cryptography.x509.oid import NameOID
else:
    from fabric_tpu.crypto import lite_ec as ec
    from fabric_tpu.crypto import lite_ed25519 as ed25519
    from fabric_tpu.crypto import lite_hashes as hashes
    from fabric_tpu.crypto import lite_serialization as serialization
    from fabric_tpu.crypto import lite_x509 as x509
    from fabric_tpu.crypto._der import (decode_dss_signature,
                                        encode_dss_signature)
    from fabric_tpu.crypto._errors import InvalidSignature
    from fabric_tpu.crypto.lite_ec import Prehashed
    from fabric_tpu.crypto.lite_ed25519 import (Ed25519PrivateKey,
                                                Ed25519PublicKey)
    from fabric_tpu.crypto.lite_serialization import (
        Encoding, NoEncryption, PrivateFormat, PublicFormat,
        load_der_public_key, load_pem_private_key, load_pem_public_key)
    from fabric_tpu.crypto.lite_x25519 import (X25519PrivateKey,
                                               X25519PublicKey)
    from fabric_tpu.crypto.lite_x509 import NameOID

from fabric_tpu.crypto.aead import Aead, hkdf_sha256

__all__ = [
    "HAVE_CRYPTOGRAPHY",
    "x509", "ec", "ed25519", "hashes", "serialization", "NameOID",
    "InvalidSignature", "Prehashed",
    "decode_dss_signature", "encode_dss_signature",
    "Ed25519PrivateKey", "Ed25519PublicKey",
    "X25519PrivateKey", "X25519PublicKey",
    "Encoding", "NoEncryption", "PrivateFormat", "PublicFormat",
    "load_der_public_key", "load_pem_private_key", "load_pem_public_key",
    "Aead", "hkdf_sha256",
]
