"""Per-channel message admission filters for the ordering service.

Reference parity: orderer/common/msgprocessor/*.go —
  classify (normal vs config)         standardchannel.go ClassifyMsg
  EmptyRejectRule                     filter.go
  SizeFilter                          sizefilter.go
  SigFilter (submitter policy check)  sigfilter.go
  expiration check (cert expiry)      expiration.go
  maintenance filter (consensus-type
  migration guard)                    maintenancefilter.go

The sig filter is the orderer's per-envelope signature verify — on the
TPU path these checks are batchable (the broadcast handler may collect
VerifyItems across queued envelopes and dispatch once), but the admission
decision itself stays host-side and per-envelope.
"""

from __future__ import annotations

import datetime
import enum
from typing import Dict, Optional

from fabric_tpu.msp import deserialize_from_msps
from fabric_tpu.policy import PolicyEvaluator, SignaturePolicy, SignedData
from fabric_tpu.protocol import Envelope
from fabric_tpu.protocol.types import TX_CONFIG


class MsgClass(enum.Enum):
    NORMAL = "normal"
    CONFIG = "config"


class MsgProcessorError(Exception):
    """Envelope rejected by an admission filter."""


def classify(env: Envelope) -> MsgClass:
    """standardchannel.go ClassifyMsg — by channel-header type."""
    if env.header().channel_header.type == TX_CONFIG:
        return MsgClass.CONFIG
    return MsgClass.NORMAL


class StandardChannelProcessor:
    """Filter chain for one application channel (standardchannel.go).

    ProcessNormalMsg = empty-reject -> expiration -> size -> sig-filter.
    Config messages additionally go through the config plane's validation
    (channelconfig.validate_config_update) before ordering.
    """

    def __init__(self, channel_id: str, msps: Dict[str, object], provider,
                 writers_policy: SignaturePolicy,
                 absolute_max_bytes: int = 10 * 1024 * 1024,
                 now=None, bundle_source=None, verify_cache=None,
                 trust_attestations: bool = False, attestors=None,
                 attestor_trust=None):
        self.channel_id = channel_id
        self._static_msps = msps
        self._static_writers = writers_policy
        self._static_max_bytes = absolute_max_bytes
        self.provider = provider
        self.bundle_source = bundle_source
        # verify-once plane: when a VerdictCache is attached, the sig
        # filter's batch_verify consults/extends it (duplicate
        # submissions and retried batches stop re-verifying), and — with
        # trust_attestations (OFF by default: an explicit trust
        # decision) — an authorized gateway's verdict attestation seeds
        # it so the orderer's device verify is skipped entirely.  The
        # attestation digest itself is a public hash anyone can compute,
        # so it carries no authority: it is only honoured when ALL of
        # (a) the transport handshake authenticated the submitting
        # peer, (b) that peer's (mspid, cert sha256) is in the
        # configured attestor set — the integrity-protected channel
        # makes the vouch unforgeable by third parties — and (c) the
        # attested digest matches the item this orderer derives itself
        # from the envelope bytes it holds.
        self.verify_cache = verify_cache
        self.trust_attestations = bool(trust_attestations)
        self.attestors = self._normalize_attestors(attestors)
        # per-identity standing on top of the allowlist (verify_plane/
        # trust.py): an attestor whose digest ever mismatched is revoked
        # — still allowlisted, no longer honoured.  None = membership only.
        self.attestor_trust = attestor_trust
        self._now = now or (lambda: datetime.datetime.now(datetime.timezone.utc))

    # -- live config resolution (channelconfig bundle when attached) --------

    @property
    def msps(self):
        if self.bundle_source is not None:
            return self.bundle_source.current().msps
        return self._static_msps

    @property
    def writers_policy(self):
        if self.bundle_source is not None:
            b = self.bundle_source.current()
            return b.policy("Writers") or self._static_writers
        return self._static_writers

    @property
    def absolute_max_bytes(self):
        if self.bundle_source is not None:
            return self.bundle_source.current().batch.absolute_max_bytes
        return self._static_max_bytes

    @absolute_max_bytes.setter
    def absolute_max_bytes(self, v):
        self._static_max_bytes = v

    @staticmethod
    def _normalize_attestors(attestors) -> frozenset:
        """Attestor bindings -> frozenset of (mspid, cert sha256 hex).
        Accepts {"mspid":..., "cert_fp":...} dicts or (mspid, fp)
        pairs — the consenter-binding idiom: CN strings are forgeable
        by any org's CA, the certificate hash is not."""
        out = set()
        for a in attestors or ():
            if isinstance(a, dict):
                mspid, fp = a.get("mspid"), a.get("cert_fp")
            else:
                mspid, fp = a
            if mspid and fp:
                out.add((str(mspid), str(fp).lower()))
        return frozenset(out)

    @property
    def evaluator(self):
        provider = self.provider
        if self.verify_cache is not None:
            from fabric_tpu.verify_plane import CachingProvider
            provider = CachingProvider(provider, self.verify_cache,
                                       site="orderer",
                                       scope=self.channel_id)
        return PolicyEvaluator(self.msps, provider)

    def process(self, env: Envelope, raw_size: Optional[int] = None,
                attest: Optional[str] = None,
                attestor=None) -> MsgClass:
        """Admit or raise. Returns the message class for routing.

        The envelope header is decoded ONCE here and threaded through the
        rules; `raw_size` lets the caller pass the on-the-wire byte count
        so the size filter need not re-serialize.
        """
        if not env.payload:
            raise MsgProcessorError("empty payload (EmptyRejectRule)")
        try:
            header = env.header()
        except Exception:
            raise MsgProcessorError("undecodable envelope header")
        ch, sh = header.channel_header, header.signature_header
        cls = (MsgClass.CONFIG if ch.type == TX_CONFIG else MsgClass.NORMAL)

        if ch.channel_id != self.channel_id:
            raise MsgProcessorError(
                f"envelope for channel {ch.channel_id!r} sent to "
                f"{self.channel_id!r}")
        self._expiration(sh.creator)
        if (raw_size if raw_size is not None
                else len(env.serialize())) > self.absolute_max_bytes:
            raise MsgProcessorError(
                f"message larger than AbsoluteMaxBytes "
                f"({self.absolute_max_bytes})")
        if self.verify_cache is not None:
            if self.bundle_source is not None:
                try:
                    self.verify_cache.set_epoch(
                        self.bundle_source.current().sequence,
                        scope=self.channel_id)
                except Exception:
                    pass
            if (attest and self.trust_attestations
                    and self._attestor_authorized(attestor)):
                self._accept_attestation(env, sh.creator, attest, attestor)
        self._sig_filter(env, sh.creator)
        if cls is MsgClass.CONFIG and self.bundle_source is not None:
            # config-plane validation BEFORE ordering (reference:
            # msgprocessor ProcessConfigUpdateMsg -> configtx validation);
            # malformed/unauthorized config updates are rejected here, not
            # written as config blocks.
            from fabric_tpu.config import ConfigError, validate_config_update
            try:
                validate_config_update(self.bundle_source.current(), env,
                                       self.provider)
            except ConfigError as exc:
                raise MsgProcessorError(f"config update rejected: {exc}")
        return cls

    # -- individual rules ---------------------------------------------------

    def _attestor_authorized(self, attestor) -> bool:
        """Is this transport-authenticated identity allowed to vouch?

        The attestation digest is a public hash — any submitter can
        compute it over its own (possibly garbage) signature — so the
        authority comes entirely from WHO delivered it: the handshake-
        verified peer identity of the frame it rode in on, pinned here
        by (mspid, cert sha256) against the operator-configured
        attestor set.  No attestor set configured means nobody may
        vouch."""
        if attestor is None or not self.attestors:
            return False
        try:
            from fabric_tpu.orderer.cluster import cert_fingerprint
            binding = (attestor.mspid, cert_fingerprint(attestor.cert))
        except Exception:
            return False
        if binding not in self.attestors:
            return False
        # allowlisted but revoked (a past digest mismatch) = not honoured
        return (self.attestor_trust is None
                or self.attestor_trust.allowed(binding))

    def _accept_attestation(self, env: Envelope, creator: bytes,
                            attest: str, attestor=None) -> None:
        """Seed the verdict cache from an AUTHORIZED gateway's verdict
        attestation (the caller already ran _attestor_authorized).

        The gateway already ran this creator signature on its device and
        sends the cache-key digest of the VerifyItem it verified.  This
        orderer re-derives the item from the envelope it actually holds
        — identity from ITS msps, payload/signature from the wire bytes
        — and only accepts the attestation when the digests are
        bit-identical, so a mismatched attestation can never vouch for
        different bytes than the ones being admitted.  A mismatch also
        revokes the vouching identity's standing (attestor_trust): an
        honest attestor cannot produce one, since the digest is a pure
        function of bytes both sides hold.  Policy evaluation, expiry,
        and config checks still run live below."""
        try:
            from fabric_tpu.verify_plane import item_digest
            ident = deserialize_from_msps(self.msps, creator)
            if ident is None:
                return
            item = ident.verify_item(env.payload, env.signature)
            if item_digest(item).hex() != attest:
                self._note_attestor(attestor, ok=False)
                return
            self.verify_cache.put(item, True, scope=self.channel_id)
            self._note_attestor(attestor, ok=True)
            from fabric_tpu.verify_plane.cache import _m
            _m()["attested"].add(1)
        except Exception:
            pass

    def _note_attestor(self, attestor, ok: bool) -> None:
        """Record an authorized attestor's outcome in the standing
        registry (no-op without one)."""
        if self.attestor_trust is None or attestor is None:
            return
        try:
            from fabric_tpu.orderer.cluster import cert_fingerprint
            binding = (attestor.mspid, cert_fingerprint(attestor.cert))
            if ok:
                self.attestor_trust.note_accepted(binding)
            else:
                self.attestor_trust.note_mismatch(binding)
        except Exception:
            pass

    def _expiration(self, creator: bytes) -> None:
        """expiration.go — reject envelopes signed with an expired cert."""
        ident = deserialize_from_msps(self.msps, creator)
        if ident is None:
            raise MsgProcessorError("undeserializable creator identity")
        if ident.expires_at() is not None and ident.expires_at() < self._now():
            raise MsgProcessorError("creator certificate expired")

    def _sig_filter(self, env: Envelope, creator: bytes) -> None:
        """sigfilter.go — submitter must satisfy the channel Writers policy."""
        sd = SignedData(data=env.payload, identity=creator,
                        signature=env.signature)
        if not self.evaluator.evaluate_signed_data(self.writers_policy, [sd]):
            raise MsgProcessorError(
                "submitter does not satisfy channel Writers policy "
                "(SigFilter)")
