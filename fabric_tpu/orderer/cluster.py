"""Socket transport for the Raft orderer cluster.

Round-1 left raft messaging in test callbacks (VERDICT.md component #43);
this promotes it to a production transport: each orderer exposes a
`raft.step` cast over the authenticated RPC plane
(fabric_tpu/comm/{secure,rpc}.py — the slot of the reference's
orderer/common/cluster/comm.go:116 Step RPC over mTLS gRPC), with lazy
dialing, reconnection, and a driver thread that runs the chain clock
(raft ticks, batch timeouts) and ships Ready messages.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from fabric_tpu.comm.rpc import RpcServer, connect
from fabric_tpu.orderer import raft as raftmod

logger = logging.getLogger("fabric_tpu.orderer.cluster")


def cert_fingerprint(cert) -> str:
    """sha256 hex of the DER certificate — the consenter binding token.

    CN strings are forgeable by any org's CA; the full certificate hash
    is not (the reference authenticates the sender's actual TLS cert
    against the consenter set, cluster/comm.go).
    """
    import hashlib
    from fabric_tpu.crypto import serialization
    der = cert.public_bytes(serialization.Encoding.DER)
    return hashlib.sha256(der).hexdigest()


class _PeerSender:
    """Queue + thread per peer: dials with backoff off the driver thread,
    drops raft messages when the peer is unreachable (raft retransmits),
    and always closes replaced connections (no fd/thread leaks)."""

    MAX_QUEUE = 256

    def __init__(self, nid: int, addr, signer, msps):
        self.nid = nid
        self.addr = tuple(addr)
        self.signer = signer
        self.msps = msps
        self._queue = []
        self._cond = threading.Condition()
        self._stopped = False
        self._conn = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def enqueue(self, body: dict) -> None:
        with self._cond:
            if len(self._queue) >= self.MAX_QUEUE:
                self._queue.pop(0)     # raft tolerates loss; keep newest
            self._queue.append(body)
            self._cond.notify()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify()
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _loop(self) -> None:
        backoff = 0.1
        while True:
            with self._cond:
                self._cond.wait_for(lambda: self._queue or self._stopped)
                if self._stopped:
                    return
                body = self._queue.pop(0)
            if self._conn is None:
                try:
                    self._conn = connect(self.addr, self.signer, self.msps,
                                         timeout=2.0)
                    backoff = 0.1
                except Exception:
                    time.sleep(min(backoff, 1.0))
                    backoff *= 2
                    continue   # message dropped; raft resends
            try:
                self._conn.cast("raft.step", body)
            except Exception:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None


# -- raft message serde ------------------------------------------------------

def msg_to_dict(m: raftmod.Message) -> dict:
    ents = []
    for e in m.entries:
        ent = {"term": e.term, "index": e.index, "data": e.data,
               "kind": e.kind}
        if e.sig:
            ent["proposer"], ent["sig"] = e.proposer, e.sig
        ents.append(ent)
    d = {"type": m.type, "frm": m.frm, "to": m.to, "term": m.term,
         "index": m.index, "log_term": m.log_term, "commit": m.commit,
         "reject": 1 if m.reject else 0, "hint": m.hint,
         "entries": ents}
    if m.snapshot is not None:
        d["snapshot"] = {"index": m.snapshot.index, "term": m.snapshot.term,
                         "data": m.snapshot.data,
                         "nodes": list(m.snapshot.nodes)}
    return d


def msg_from_dict(d: dict) -> raftmod.Message:
    snap = None
    if "snapshot" in d:
        s = d["snapshot"]
        snap = raftmod.Snapshot(s["index"], s["term"], s["data"],
                                tuple(s["nodes"]))
    return raftmod.Message(
        type=d["type"], frm=d["frm"], to=d["to"], term=d["term"],
        index=d["index"], log_term=d["log_term"],
        entries=tuple(raftmod.Entry(e["term"], e["index"], e["data"],
                                    e["kind"], e.get("proposer", b""),
                                    e.get("sig", b""))
                      for e in d["entries"]),
        commit=d["commit"], reject=bool(d["reject"]), hint=d["hint"],
        snapshot=snap)


class EntryVerifier:
    """Per-channel signed-raft-entry guard.

    Every appended entry must carry a proposer identity that (a)
    deserializes and validates against the channel MSPs, (b) binds — by
    full cert hash, never a CN string — to SOME consenter of THIS
    channel (the proposer may legitimately differ from the transport
    sender: a new leader relays its predecessor's entries), and (c)
    actually signed the (term, index, kind, data) slot.  A second
    payload under the same (term, index, proposer) slot is an
    equivocation crime attributable to the proposer from the entries
    alone: both signatures are self-incriminating, so the evidence is a
    portable fraud proof mintable AT THE ORDERER, no peer witness
    needed.

    Legitimate raft behaviours never trip this: conflict truncation
    replaces a slot under a HIGHER term (different cache key), and
    retransmits carry byte-identical payloads (digest match).
    """

    CACHE_MAX = 1024

    def __init__(self, channel_id: str, msps, consenters):
        self.channel_id = channel_id
        self.msps = msps
        self.bindings = {f"{m}|{f}" for m, f in consenters.values()}
        # (term, index, binding) -> first-seen payload record
        self._seen: Dict[tuple, dict] = {}
        self._order: List[tuple] = []
        # proposer bytes -> (binding, identity): one deserialize per
        # consenter, not per retransmitted entry
        self._idents: Dict[bytes, tuple] = {}

    def set_consenters(self, consenters) -> None:
        """Rebind after a committed membership change.  The proposer
        cache is cleared so a RETIRED consenter's cached (binding,
        identity) cannot keep vouching for its entries — from the commit
        point forward its proposals fail the binding check.  The _seen
        slot cache survives: equivocation evidence keyed by (term,
        index, binding) stays valid across reconfigs."""
        self.bindings = {f"{m}|{f}" for m, f in consenters.values()}
        self._idents.clear()

    def _proposer(self, raw: bytes):
        cached = self._idents.get(raw)
        if cached is not None:
            return cached
        from fabric_tpu.msp import deserialize_from_msps
        ident = deserialize_from_msps(self.msps, raw, validate=True)
        binding = f"{ident.mspid}|{cert_fingerprint(ident.cert)}"
        if binding not in self.bindings:
            raise ValueError(f"proposer {binding} is not a consenter "
                             f"of {self.channel_id!r}")
        if len(self._idents) > self.CACHE_MAX:
            self._idents.clear()
        self._idents[raw] = (binding, ident)
        return binding, ident

    def check(self, entries) -> Tuple[bool, Optional[str], List[dict]]:
        """-> (ok, reject_reason, crimes).  `ok` False rejects the whole
        message (raft retransmits; an honest leader never mixes good and
        bad entries).  `crimes` are equivocation evidence dicts, each
        carrying BOTH signed payloads for independent re-verification."""
        import hashlib
        crimes: List[dict] = []
        for e in entries:
            if not e.sig or not e.proposer:
                return False, "unsigned_entry", crimes
            try:
                binding, ident = self._proposer(e.proposer)
            except Exception as exc:
                logger.warning("[%s] entry %d/%d proposer rejected: %s",
                               self.channel_id, e.term, e.index, exc)
                return False, "bad_proposer", crimes
            digest = hashlib.sha256(
                e.kind.encode() + b"\x00" + e.data).hexdigest()
            key = (e.term, e.index, binding)
            prior = self._seen.get(key)
            if prior is not None and prior["digest"] == digest:
                continue             # retransmit: already verified
            try:
                ok = ident.verify(
                    raftmod.entry_signed_bytes(e.term, e.index, e.data,
                                               e.kind), e.sig)
            except Exception:
                ok = False
            if not ok:
                return False, "bad_entry_sig", crimes
            rec = {"digest": digest, "kind": e.kind, "data": e.data,
                   "sig": e.sig}
            if prior is not None:
                # same slot, same signer, two valid signatures over two
                # different payloads: equivocation, proven by the pair
                crimes.append({
                    "kind": "raft_entry_equivocation",
                    "channel": self.channel_id,
                    "term": e.term, "index": e.index,
                    "binding": binding, "proposer": e.proposer.hex(),
                    "a": {"entry_kind": prior["kind"],
                          "data": prior["data"].hex(),
                          "sig": prior["sig"].hex()},
                    "b": {"entry_kind": e.kind, "data": e.data.hex(),
                          "sig": e.sig.hex()}})
                return False, "entry_equivocation", crimes
            self._seen[key] = rec
            self._order.append(key)
            while len(self._order) > self.CACHE_MAX:
                self._seen.pop(self._order.pop(0), None)
        return True, None, crimes


class ClusterService:
    """Drives the node's RaftChains over the network — MULTI-CHANNEL:
    each channel's chain is registered under its id and raft messages
    carry the channel tag (the reference's cluster comm dispatches by
    channel + sender cert, orderer/common/cluster/comm.go:116).

    peers: raft node id -> (host, port).  The service registers the
    `raft.step` cast on the node's RpcServer and runs a driver thread:
      every tick_ms: per-chain election/heartbeat tick + batch-timeout
      tick; after every step/tick: process_ready() and ship outbound
      messages.
    """

    def __init__(self, rpc: RpcServer, signer, msps,
                 peers: Dict[int, Tuple[str, int]],
                 tick_s: float = 0.05,
                 consenters: Dict[int, Tuple[str, str]] = None,
                 chain=None, channel_id: str = None):
        self.chains: Dict[str, object] = {}
        self.rpc = rpc
        self.signer = signer
        self.msps = msps
        self.peers = dict(peers)
        # consenter authorization: raft id -> (mspid, sha256 cert
        # fingerprint).  MANDATORY — without it any channel member could
        # forge raft traffic claiming to be a consenter (cluster/comm.go
        # authenticates the sender's actual cert against the consenter
        # set).  Bound to the full cert hash, not a forgeable CN string.
        # This map is the BOOTSTRAP-channel set; channels registered via
        # add_chain may carry their own set (the reference keys consenter
        # authorization per channel, cluster/comm.go stub-per-channel) —
        # a node authorized on one channel is NOT thereby authorized to
        # step raft on another.
        if not consenters:
            raise ValueError(
                "ClusterService requires the consenter identity map "
                "(raft id -> (mspid, cert sha256)); refusing to run an "
                "unauthenticated raft transport")
        self.consenters = dict(consenters)
        self.tick_s = tick_s
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._drive, daemon=True)
        # per-channel overrides: channel -> (consenters map, peer addrs)
        self._chan_consenters: Dict[str, Dict[int, Tuple[str, str]]] = {}
        self._chan_peers: Dict[str, Dict[int, Tuple[str, int]]] = {}
        # signed-entry enforcement, per channel: installed by add_chain
        # only when the channel's own chain signs its entries (legacy /
        # test chains without an entry signer stay unenforced)
        self._verifiers: Dict[str, EntryVerifier] = {}
        # byzantine hooks (wired by the owning node, both optional):
        #   on_entry_offense(channel_id, frm_node, reason)
        #   on_entry_crime(channel_id, binding, evidence)
        self.on_entry_offense = None
        self.on_entry_crime = None
        # per-ADDRESS sender threads (shared across channels): dial/retry
        # must never block the raft clock (a blackholed peer would
        # otherwise starve heartbeats)
        self._senders: Dict[Tuple[str, int], _PeerSender] = {}
        with self._lock:
            for nid, addr in self.peers.items():
                self._sender_for(tuple(addr))
        rpc.serve_cast("raft.step", self._on_step)
        if chain is not None:
            self.add_chain(channel_id or "ch", chain)

    def _sender_for(self, addr: Tuple[str, int]) -> Optional["_PeerSender"]:
        """Get-or-create the sender thread for an address.  Callers must
        hold self._lock (dynamic growth from add_chain/_send).  Returns
        None once the service is stopping."""
        if self._stop.is_set():
            return None
        addr = tuple(addr)
        sender = self._senders.get(addr)
        if sender is None:
            sender = _PeerSender(0, addr, self.signer, self.msps)
            self._senders[addr] = sender
        return sender

    def peers_for(self, channel_id: str) -> Dict[int, Tuple[str, int]]:
        """THIS channel's raft-id -> address map (bootstrap fallback)."""
        with self._lock:
            return dict(self._chan_peers.get(channel_id, self.peers))

    def consenter_binding(self, channel_id: str,
                          raft_id: int) -> Optional[str]:
        """'mspid|cert-sha256' quarantine key for a channel consenter,
        or None for an unknown raft id."""
        with self._lock:
            consenters = self._chan_consenters.get(channel_id,
                                                   self.consenters)
        ent = consenters.get(raft_id)
        if ent is None:
            return None
        return f"{ent[0]}|{ent[1]}"

    # -- chain registry (multichannel/registrar.go dynamic chains) -----------

    def add_chain(self, channel_id: str, chain,
                  consenters: Dict[int, Tuple[str, str]] = None,
                  peers: Dict[int, Tuple[str, int]] = None) -> None:
        """Register a channel's chain.  `consenters`/`peers` are that
        CHANNEL's consenter identity map and node addresses; when omitted
        the bootstrap channel's maps apply (single-channel deployments)."""
        with self._lock:
            self.chains[channel_id] = chain
            if consenters is not None:
                self._chan_consenters[channel_id] = dict(consenters)
            if peers is not None:
                self._chan_peers[channel_id] = {
                    nid: tuple(a) for nid, a in peers.items()}
            for addr in (peers or self.peers).values():
                self._sender_for(tuple(addr))
            node = getattr(chain, "node", None)
            if getattr(node, "entry_signer", None) is not None:
                self._verifiers[channel_id] = EntryVerifier(
                    channel_id, self.msps,
                    consenters if consenters is not None
                    else self.consenters)
        self._wake.set()

    def update_membership(self, channel_id: str,
                          consenters: Dict[int, Tuple[str, str]],
                          peers: Dict[int, Tuple[str, int]]) -> None:
        """Atomically swap a channel's consenter identity map + peer
        address map and rebind its EntryVerifier — called when a
        membership config entry COMMITS (never on mere proposal).  One
        lock scope so _on_step can never observe a new consenter set
        with a stale verifier (or vice versa): a removed consenter's
        messages are rejected at the consenter-lookup gate and its
        entries at the binding check from the same instant.

        Outbound ADDRESSES merge instead of replacing: the address map
        is plumbing, not authorization (inbound is gated on the
        consenter map above), and the leader's farewell append to a
        just-removed server — the one message that lets it observe its
        own removal and self-evict — must still be deliverable after
        the commit that removed it.  Nothing else addresses a node
        outside the raft node set, so a retired address is inert; a
        re-added node id takes the fresh address."""
        with self._lock:
            self._chan_consenters[channel_id] = dict(consenters)
            merged = dict(self._chan_peers.get(channel_id, {}))
            merged.update({nid: tuple(a) for nid, a in peers.items()})
            self._chan_peers[channel_id] = merged
            verifier = self._verifiers.get(channel_id)
            if verifier is not None:
                verifier.set_consenters(consenters)
            for addr in peers.values():
                self._sender_for(tuple(addr))
        self._wake.set()

    def remove_chain(self, channel_id: str) -> None:
        with self._lock:
            self.chains.pop(channel_id, None)
            self._chan_consenters.pop(channel_id, None)
            self._chan_peers.pop(channel_id, None)
            self._verifiers.pop(channel_id, None)

    @property
    def chain(self):
        """Single-channel convenience: the only (or first) chain."""
        with self._lock:
            for ch in self.chains.values():
                return ch
        return None

    # -- inbound -------------------------------------------------------------

    def _on_step(self, body: dict, peer_identity) -> None:
        msg = msg_from_dict(body["msg"])
        channel_id = body.get("channel", "ch")
        with self._lock:
            chain = self.chains.get(channel_id)
            consenters = self._chan_consenters.get(channel_id,
                                                   self.consenters)
            peers = self._chan_peers.get(channel_id, self.peers)
        if chain is None:
            return       # unknown channel (not yet joined): drop
        if msg.frm not in peers and msg.frm != chain.node.id:
            logger.warning("raft message from unknown node %s", msg.frm)
            return
        # authorization is per CHANNEL: the sender must be in THIS
        # channel's consenter set (not merely some channel's)
        expected = consenters.get(msg.frm)
        if expected is None:
            logger.warning("[%s] raft message from non-consenter node %s "
                           "— dropped", channel_id, msg.frm)
            return
        mspid, fp = expected
        got_msp = getattr(peer_identity, "mspid", None)
        got_fp = cert_fingerprint(peer_identity.cert)
        if got_msp != mspid or got_fp != fp:
            logger.warning(
                "raft message claiming node %s from identity %s/%s... — "
                "dropped (consenter authorization)", msg.frm, got_msp,
                got_fp[:16])
            return
        with self._lock:
            verifier = self._verifiers.get(channel_id)
        if verifier is not None and msg.entries:
            ok, reason, crimes = verifier.check(msg.entries)
            for ev in crimes:
                logger.warning(
                    "[%s] raft entry equivocation by %s at term=%d "
                    "index=%d — fraud proof minted at the orderer",
                    channel_id, ev["binding"], ev["term"], ev["index"])
                if self.on_entry_crime is not None:
                    try:
                        self.on_entry_crime(channel_id, ev["binding"], ev)
                    except Exception:
                        logger.exception("entry-crime hook failed")
            if not ok:
                logger.warning(
                    "[%s] raft append from node %s rejected: %s",
                    channel_id, msg.frm, reason)
                if self.on_entry_offense is not None:
                    try:
                        self.on_entry_offense(channel_id, msg.frm, reason)
                    except Exception:
                        logger.exception("entry-offense hook failed")
                return
        chain.step(msg)
        self._wake.set()

    # -- outbound ------------------------------------------------------------

    def _send(self, channel_id: str, msg: raftmod.Message) -> None:
        with self._lock:
            peers = self._chan_peers.get(channel_id, self.peers)
            addr = peers.get(msg.to)
            sender = self._sender_for(addr) if addr is not None else None
        if sender is not None:
            sender.enqueue({"channel": channel_id,
                            "msg": msg_to_dict(msg)})

    # -- driver --------------------------------------------------------------

    def start(self) -> "ClusterService":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2.0)
        # snapshot under the lock: _senders grows dynamically (_send /
        # add_chain), and _sender_for refuses creation once _stop is set
        with self._lock:
            senders = list(self._senders.values())
        for s in senders:
            s.stop()

    def _drive(self) -> None:
        last_tick = time.monotonic()
        while not self._stop.is_set():
            self._wake.wait(timeout=self.tick_s / 2)
            self._wake.clear()
            now = time.monotonic()
            do_tick = now - last_tick >= self.tick_s
            if do_tick:
                last_tick = now
            with self._lock:
                chains = list(self.chains.items())
            for channel_id, chain in chains:
                if do_tick:
                    try:
                        chain.tick()
                    except Exception:
                        logger.exception("[%s] raft tick failed", channel_id)
                    try:
                        chain.tick_batch(now)
                    except Exception:
                        logger.exception("[%s] batch tick failed", channel_id)
                try:
                    ready = chain.process_ready()
                except Exception:
                    logger.exception("[%s] process_ready failed", channel_id)
                    continue
                for m in ready.messages:
                    self._send(channel_id, m)
