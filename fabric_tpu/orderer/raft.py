"""Raft consensus core: a deterministic tick/step/ready state machine.

Reference parity: orderer/consensus/etcdraft/{chain,node,storage}.go, which
drive the vendored etcd/raft library.  This is a from-scratch Raft in the
same architectural style as etcd/raft — a *pure* state machine advanced by
`tick()` and `step(msg)`, with all I/O (message sends, disk writes, entry
application) drained through `ready()` — because that style is what makes
consensus testable without a cluster (SURVEY.md §4.2) and lets the orderer
own its WAL/snapshot persistence exactly like etcdraft/storage.go:19-24.

Implements: leader election with randomized timeouts and pre-vote-free
up-to-date checks, log replication with conflict-hint backtracking, commit
via quorum match + current-term guard (§5.4.2 of the Raft paper), snapshot
install for lagging followers, and single-server membership changes.
Persistence: `WAL` (append-only hard-state+entry records, torn-write
tolerant) and `SnapshotFile`, both fsync'd before messages leave the node.
"""

from __future__ import annotations

import os
import random
import struct
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_tpu.utils import serde

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

# message types
MSG_VOTE = "vote"
MSG_VOTE_RESP = "vote_resp"
MSG_APP = "app"            # AppendEntries (heartbeat when entries empty)
MSG_APP_RESP = "app_resp"
MSG_SNAP = "snap"          # InstallSnapshot
MSG_TIMEOUT_NOW = "timeout_now"  # leadership transfer: campaign NOW

ENTRY_NORMAL = "normal"
ENTRY_CONF = "conf"        # data: serde{"op": "add"|"remove", "node": id}
ENTRY_SNAPSHOT = "snapshot"  # pseudo-entry surfacing an installed snapshot


@dataclass(frozen=True)
class Entry:
    term: int
    index: int
    data: bytes = b""
    kind: str = ENTRY_NORMAL
    # Consenter attribution: the proposing consenter's serialized
    # identity plus its signature over entry_signed_bytes().  Both empty
    # on legacy/unsigned entries — whether that is acceptable is the
    # cluster service's call (it only enforces on channels whose local
    # chain signs its own entries).
    proposer: bytes = b""
    sig: bytes = b""


def entry_signed_bytes(term: int, index: int, data: bytes,
                       kind: str) -> bytes:
    """Canonical byte string a consenter signs for one entry.  Covers
    (term, index, kind, data) — the full identity of a log slot — so the
    same signer producing two different payloads for one slot yields two
    valid signatures over DIFFERENT canonical bytes: a self-incriminating
    equivocation pair, attributable from the entries alone."""
    return (b"raft-ent\x00" + struct.pack("<QQ", term, index)
            + kind.encode("utf-8") + b"\x00" + data)


@dataclass(frozen=True)
class Snapshot:
    index: int
    term: int
    data: bytes          # application state at `index` (e.g. last block info)
    nodes: Tuple[int, ...]


@dataclass(frozen=True)
class Message:
    type: str
    frm: int
    to: int
    term: int
    index: int = 0       # prev_log_index for APP; candidate last index for VOTE
    log_term: int = 0    # prev_log_term for APP; candidate last term for VOTE
    entries: Tuple[Entry, ...] = ()
    commit: int = 0
    reject: bool = False
    hint: int = 0        # follower's suggested next_index on reject
    snapshot: Optional[Snapshot] = None


@dataclass
class Ready:
    """What the container must do after step/tick: persist happened
    already (storage is injected); send messages; apply entries."""
    messages: List[Message] = field(default_factory=list)
    committed: List[Entry] = field(default_factory=list)
    became_leader: bool = False
    lost_leadership: bool = False


# ---------------------------------------------------------------------------
# persistence


_REC = struct.Struct("<I")


class WAL:
    """Append-only log of hard-state + entry records (etcdraft's wal dir).

    Record = u32 length ‖ serde{kind: "hs"|"ent"|"trunc", ...}; a torn
    trailing record is dropped on replay (crash during append).
    `trunc` records mark logical truncation points (conflict overwrite or
    snapshot compaction) so replay reconstructs the exact final log.
    """

    def __init__(self, path: Optional[str]):
        self.path = path
        self._f = None
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._f = open(path, "ab")

    def append(self, rec: dict) -> None:
        if self._f is None:
            return
        raw = serde.encode(rec)
        self._f.write(_REC.pack(len(raw)) + raw)

    def sync(self) -> None:
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())

    def rewrite(self, records: Sequence[dict]) -> None:
        """Atomically replace the WAL with `records` (post-compaction)."""
        if self.path is None:
            return
        self._f.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for rec in records:
                raw = serde.encode(rec)
                f.write(_REC.pack(len(raw)) + raw)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    @staticmethod
    def replay(path: str) -> List[dict]:
        recs = []
        if not os.path.exists(path):
            return recs
        with open(path, "rb") as f:
            raw = f.read()
        off = 0
        while off + _REC.size <= len(raw):
            (n,) = _REC.unpack_from(raw, off)
            if off + _REC.size + n > len(raw):
                break  # torn write
            try:
                recs.append(serde.decode(raw[off + _REC.size:off + _REC.size + n]))
            except ValueError:
                break
            off += _REC.size + n
        return recs

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class SnapshotFile:
    """Atomic snapshot persistence (etcdraft's snap dir)."""

    def __init__(self, path: Optional[str]):
        self.path = path

    def save(self, snap: Snapshot) -> None:
        if self.path is None:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(serde.encode({
                "index": snap.index, "term": snap.term,
                "data": snap.data, "nodes": list(snap.nodes)}))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[Snapshot]:
        if self.path is None or not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            d = serde.decode(f.read())
        return Snapshot(d["index"], d["term"], d["data"],
                        tuple(d["nodes"]))


# ---------------------------------------------------------------------------
# the node


class RaftNode:
    """One Raft participant.  Drive with tick()/step()/propose(), then
    drain `take_ready()` — messages in it are only handed out after the
    triggering state was persisted to the WAL."""

    def __init__(self, node_id: int, peers: Sequence[int],
                 wal_path: Optional[str] = None,
                 snap_path: Optional[str] = None,
                 election_tick: int = 10, heartbeat_tick: int = 1,
                 snapshot_interval: int = 0,
                 snapshot_data: Callable[[int], bytes] = lambda idx: b"",
                 entry_signer: Optional[
                     Callable[[int, int, bytes, str],
                              Tuple[bytes, bytes]]] = None):
        self.id = node_id
        # entry_signer(term, index, data, kind) -> (proposer, sig): signs
        # every locally-appended entry (client proposals, conf changes,
        # AND the new-leader no-op) with the consenter's identity
        self.entry_signer = entry_signer
        self.nodes: Tuple[int, ...] = tuple(sorted(set(peers) | {node_id}))
        self.election_tick = election_tick
        self.heartbeat_tick = heartbeat_tick
        self.snapshot_interval = snapshot_interval
        self.snapshot_data = snapshot_data

        self.term = 0
        self.voted_for: Optional[int] = None
        self.role = FOLLOWER
        self.leader_id: Optional[int] = None
        # log[i] has index snap_index + 1 + i
        self.log: List[Entry] = []
        self.snap_index = 0
        self.snap_term = 0
        self.snap_data = b""  # app state AT snap_index, fixed at compact time
        self.commit_index = 0
        self.applied_index = 0

        self._elapsed = 0
        self._timeout = self._rand_timeout()
        self._votes: Dict[int, bool] = {}
        self.next_index: Dict[int, int] = {}
        self.match_index: Dict[int, int] = {}
        self._ready = Ready()

        self._wal = WAL(wal_path)
        self._snapfile = SnapshotFile(snap_path)
        if wal_path is not None:
            self._recover(wal_path)

    # -- persistence --------------------------------------------------------

    def _recover(self, wal_path: str) -> None:
        snap = self._snapfile.load()
        if snap is not None:
            self.snap_index, self.snap_term = snap.index, snap.term
            self.snap_data = snap.data
            self.commit_index = self.applied_index = snap.index
            self.nodes = snap.nodes
        for rec in WAL.replay(wal_path):
            if rec["k"] == "hs":
                self.term, self.voted_for = rec["t"], rec.get("v")
            elif rec["k"] == "trunc":
                upto = rec["i"]  # keep entries with index < upto
                if upto <= self.snap_index:
                    self.log = []
                elif upto - self.snap_index - 1 < len(self.log):
                    self.log = self.log[:upto - self.snap_index - 1]
            elif rec["k"] == "ent":
                e = Entry(rec["t"], rec["i"], rec["d"], rec["kd"],
                          rec.get("pr", b""), rec.get("sg", b""))
                if e.index > self.snap_index:
                    # replayed entries are contiguous post-trunc
                    pos = e.index - self.snap_index - 1
                    self.log = self.log[:pos] + [e]
            elif rec["k"] == "commit":
                self.commit_index = max(self.commit_index, rec["i"])
        self.commit_index = min(self.commit_index, self.last_index())
        # committed-but-unapplied entries re-apply on restart (the app's
        # commit path must be idempotent, like kvledger recovery)

    def _persist_hard_state(self) -> None:
        self._wal.append({"k": "hs", "t": self.term, "v": self.voted_for})

    def _persist_entries(self, entries: Sequence[Entry]) -> None:
        for e in entries:
            rec = {"k": "ent", "t": e.term, "i": e.index,
                   "d": e.data, "kd": e.kind}
            if e.sig:
                rec["pr"], rec["sg"] = e.proposer, e.sig
            self._wal.append(rec)

    def _persist_commit(self) -> None:
        self._wal.append({"k": "commit", "i": self.commit_index})

    # -- log accessors -------------------------------------------------------

    def last_index(self) -> int:
        return self.snap_index + len(self.log)

    def _term_at(self, index: int) -> Optional[int]:
        if index == self.snap_index:
            return self.snap_term
        if index < self.snap_index or index > self.last_index():
            return None
        return self.log[index - self.snap_index - 1].term

    def _entries_from(self, index: int, max_n: int = 64) -> List[Entry]:
        start = index - self.snap_index - 1
        return self.log[start:start + max_n]

    # -- public API ----------------------------------------------------------

    def take_ready(self) -> Ready:
        self._wal.sync()  # nothing leaves the node before the WAL is durable
        r, self._ready = self._ready, Ready()
        # hand out committed-but-unapplied entries
        while self.applied_index < self.commit_index:
            self.applied_index += 1
            e = self.log[self.applied_index - self.snap_index - 1]
            if e.kind == ENTRY_CONF:
                self._apply_conf(e)
            r.committed.append(e)
        # messages minted while applying (the farewell append to a
        # removed consenter) must ride THIS ready: the application's
        # conf hook runs on r.committed and drops the removed node's
        # transport address — a later ready could no longer reach it
        if self._ready.messages:
            r.messages.extend(self._ready.messages)
            self._ready.messages = []
        return r

    def maybe_compact(self) -> None:
        """Periodic compaction.  Call AFTER the application has applied the
        entries from take_ready(), so snapshot_data(applied_index) reflects
        them (the etcdraft chain calls this from its run loop post-apply)."""
        if (self.snapshot_interval
                and self.applied_index - self.snap_index >= self.snapshot_interval):
            self.compact(self.applied_index)

    def _new_entry(self, data: bytes, kind: str = ENTRY_NORMAL) -> Entry:
        """Next local entry, signed by the consenter when a signer is
        configured (the only path that mints proposer/sig pairs)."""
        term, index = self.term, self.last_index() + 1
        if self.entry_signer is None:
            return Entry(term, index, data, kind)
        proposer, sig = self.entry_signer(term, index, data, kind)
        return Entry(term, index, data, kind, proposer, sig)

    def propose(self, data: bytes) -> int:
        """Leader-only: append + replicate. Returns the entry index."""
        if self.role != LEADER:
            raise NotLeaderError(self.leader_id)
        e = self._new_entry(data)
        self.log.append(e)
        self._persist_entries([e])
        self.match_index[self.id] = e.index
        self._broadcast_append()
        self._maybe_commit()  # single-node cluster commits immediately
        return e.index

    def propose_conf(self, op: str, node: int, **meta) -> int:
        """Single-server membership change through the log itself.
        Extra keyword payload (host/port/mspid/cert_fp for an added
        consenter) rides inside the entry so every replica — including
        ones that restart and re-apply — learns the full transport +
        identity binding from the SAME committed record; _apply_conf
        only reads op/node, so old replicas ignore the extras."""
        if self.role != LEADER:
            raise NotLeaderError(self.leader_id)
        data = serde.encode({"op": op, "node": node, **meta})
        e = self._new_entry(data, ENTRY_CONF)
        self.log.append(e)
        self._persist_entries([e])
        self.match_index[self.id] = e.index
        self._broadcast_append()
        self._maybe_commit()
        return e.index

    def transfer_leadership(self, to: int) -> bool:
        """Graceful handover (etcd/raft MsgTransferLeader): tell an
        up-to-date follower to campaign NOW.  Only fires when `to`'s
        match index is caught up to our last entry — transferring to a
        lagging follower would force an availability gap while it
        catches up.  Returns True when the order was sent; the caller
        polls role/leader_id for the outcome (the transferee's higher
        term deposes us via the normal vote path)."""
        if self.role != LEADER or to == self.id or to not in self.nodes:
            return False
        if self.match_index.get(to, 0) < self.last_index():
            self._send_append(to)   # nudge replication along
            return False
        self._send(Message(MSG_TIMEOUT_NOW, self.id, to, self.term))
        return True

    def tick(self) -> None:
        self._elapsed += 1
        if self.role == LEADER:
            if self._elapsed >= self.heartbeat_tick:
                self._elapsed = 0
                self._broadcast_append()
        elif self._elapsed >= self._timeout:
            self._campaign()

    def step(self, m: Message) -> None:
        if m.term > self.term:
            self._become_follower(m.term,
                                  m.frm if m.type == MSG_APP
                                  or m.type == MSG_SNAP else None)
        if m.term < self.term:
            # stale sender: tell it about the newer term
            if m.type in (MSG_VOTE, MSG_APP, MSG_SNAP):
                self._send(Message(MSG_APP_RESP, self.id, m.frm, self.term,
                                   reject=True))
            return
        handler = {MSG_VOTE: self._on_vote,
                   MSG_VOTE_RESP: self._on_vote_resp,
                   MSG_APP: self._on_append,
                   MSG_APP_RESP: self._on_append_resp,
                   MSG_SNAP: self._on_snapshot,
                   MSG_TIMEOUT_NOW: self._on_timeout_now}[m.type]
        handler(m)

    def _on_timeout_now(self, m: Message) -> None:
        """Leadership-transfer order from the current leader: campaign
        immediately, without waiting out the election timeout.  The
        up-to-date check in _campaign's voters still applies, so a
        stale transferee cannot steal the log."""
        if m.frm != self.leader_id or self.role == LEADER:
            return
        self._campaign()

    def compact(self, index: int) -> None:
        """Take a snapshot at `index` and drop the log prefix."""
        if index <= self.snap_index:
            return
        term = self._term_at(index)
        snap = Snapshot(index, term, self.snapshot_data(index), self.nodes)
        self._snapfile.save(snap)
        self.log = self.log[index - self.snap_index:]
        self.snap_index, self.snap_term = index, term
        self.snap_data = snap.data
        # rewrite the WAL: replay after compaction is O(post-snapshot log),
        # not O(all history) — etcd's segment-release equivalent
        self._wal.rewrite(self._wal_records())

    def _wal_records(self) -> List[dict]:
        recs = [{"k": "hs", "t": self.term, "v": self.voted_for}]
        for e in self.log:
            rec = {"k": "ent", "t": e.term, "i": e.index, "d": e.data,
                   "kd": e.kind}
            if e.sig:
                rec["pr"], rec["sg"] = e.proposer, e.sig
            recs.append(rec)
        recs.append({"k": "commit", "i": self.commit_index})
        return recs

    # -- roles ---------------------------------------------------------------

    def _rand_timeout(self) -> int:
        # deterministic per (id, term): reproducible tests, no tie storms
        return self.election_tick + \
            random.Random(f"{self.id}:{self.term}").randint(0, self.election_tick)

    def _become_follower(self, term: int, leader: Optional[int]) -> None:
        lost = self.role == LEADER
        self.role = FOLLOWER
        if term != self.term:
            self.voted_for = None  # a vote binds to its term (Raft §5.2)
        self.term = term
        self.leader_id = leader
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        self._persist_hard_state()
        if lost:
            self._ready.lost_leadership = True

    def _campaign(self) -> None:
        if self.id not in self.nodes:
            return  # removed from membership
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self.leader_id = None
        self._votes = {self.id: True}
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        self._persist_hard_state()
        if self._quorum(sum(self._votes.values())):
            self._become_leader()  # single-node cluster
            return
        for n in self.nodes:
            if n != self.id:
                self._send(Message(MSG_VOTE, self.id, n, self.term,
                                   index=self.last_index(),
                                   log_term=self._term_at(self.last_index()) or 0))

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_id = self.id
        self._elapsed = 0
        self.next_index = {n: self.last_index() + 1 for n in self.nodes}
        self.match_index = {n: 0 for n in self.nodes}
        self._ready.became_leader = True
        # Append an empty entry for the new term (etcd/raft becomeLeader):
        # without it, the §5.4.2 current-term commit guard in _maybe_commit
        # would leave a deposed leader's replicated entries uncommitted
        # until new client traffic arrives — stalling idle channels.
        e = self._new_entry(b"")
        self.log.append(e)
        self._persist_entries([e])
        self.match_index[self.id] = e.index
        self._broadcast_append()
        self._maybe_commit()  # single-node cluster commits immediately

    def _quorum(self, count: int) -> bool:
        return count > len(self.nodes) // 2

    # -- vote handling -------------------------------------------------------

    def _on_vote(self, m: Message) -> None:
        my_last_term = self._term_at(self.last_index()) or 0
        up_to_date = (m.log_term, m.index) >= (my_last_term, self.last_index())
        grant = up_to_date and self.voted_for in (None, m.frm) \
            and self.role == FOLLOWER
        if grant:
            self.voted_for = m.frm
            self._elapsed = 0
            self._persist_hard_state()
        self._send(Message(MSG_VOTE_RESP, self.id, m.frm, self.term,
                           reject=not grant))

    def _on_vote_resp(self, m: Message) -> None:
        if self.role != CANDIDATE:
            return
        self._votes[m.frm] = not m.reject
        if self._quorum(sum(self._votes.values())):
            self._become_leader()

    # -- replication ---------------------------------------------------------

    def _broadcast_append(self) -> None:
        for n in self.nodes:
            if n != self.id:
                self._send_append(n)

    def _send_append(self, to: int) -> None:
        next_idx = self.next_index.get(to, self.last_index() + 1)
        if next_idx <= self.snap_index:
            # follower is behind the compacted prefix: install the snapshot
            # fixed at compact time (NOT re-derived from current app state)
            snap = Snapshot(self.snap_index, self.snap_term,
                            self.snap_data, self.nodes)
            self._send(Message(MSG_SNAP, self.id, to, self.term,
                               snapshot=snap))
            return
        prev = next_idx - 1
        self._send(Message(
            MSG_APP, self.id, to, self.term, index=prev,
            log_term=self._term_at(prev) or 0,
            entries=tuple(self._entries_from(next_idx)),
            commit=self.commit_index))

    def _on_append(self, m: Message) -> None:
        self._elapsed = 0
        self.leader_id = m.frm
        if self.role != FOLLOWER:
            self._become_follower(m.term, m.frm)
        prev_term = self._term_at(m.index)
        if prev_term is None or prev_term != m.log_term:
            # conflict: hint leader to back up to our last plausible index
            hint = min(m.index, self.last_index())
            # skip back over our conflicting term in one step
            while hint > self.commit_index and \
                    (self._term_at(hint) or 0) != m.log_term:
                hint -= 1
            self._send(Message(MSG_APP_RESP, self.id, m.frm, self.term,
                               index=m.index, reject=True,
                               hint=max(hint, self.commit_index)))
            return
        # append, truncating conflicts
        new_entries = []
        for e in m.entries:
            existing = self._term_at(e.index)
            if existing is None:
                new_entries.append(e)
            elif existing != e.term:
                # conflict: truncate from here, keep the leader's entries
                self.log = self.log[:e.index - self.snap_index - 1]
                self._wal.append({"k": "trunc", "i": e.index})
                new_entries.append(e)
        for e in new_entries:
            self.log.append(e)
        if new_entries:
            self._persist_entries(new_entries)
        last_new = m.index + len(m.entries)
        # clamp BOTH ways: never past what this message proves replicated,
        # never backwards on duplicated/reordered deliveries
        new_commit = max(self.commit_index,
                         min(m.commit, last_new, self.last_index()))
        if new_commit != self.commit_index:
            self.commit_index = new_commit
            self._persist_commit()
        self._send(Message(MSG_APP_RESP, self.id, m.frm, self.term,
                           index=last_new))

    def _on_append_resp(self, m: Message) -> None:
        if self.role != LEADER:
            return
        if m.reject:
            self.next_index[m.frm] = max(1, min(
                m.hint + 1, self.next_index.get(m.frm, 1) - 1))
            self._send_append(m.frm)
            return
        if m.index > self.match_index.get(m.frm, 0):
            self.match_index[m.frm] = m.index
        self.next_index[m.frm] = m.index + 1
        self._maybe_commit()
        if self.next_index[m.frm] <= self.last_index():
            self._send_append(m.frm)  # keep streaming the backlog

    def _maybe_commit(self) -> None:
        for idx in range(self.last_index(), self.commit_index, -1):
            if (self._term_at(idx) == self.term and
                    self._quorum(sum(1 for n in self.nodes
                                     if self.match_index.get(n, 0) >= idx))):
                self.commit_index = idx
                self._persist_commit()
                self._broadcast_append()  # propagate the new commit index
                break

    # -- snapshot install ----------------------------------------------------

    def _on_snapshot(self, m: Message) -> None:
        self._elapsed = 0
        self.leader_id = m.frm
        snap = m.snapshot
        if snap.index <= self.commit_index:
            self._send(Message(MSG_APP_RESP, self.id, m.frm, self.term,
                               index=self.commit_index))
            return
        self.log = []
        self.snap_index, self.snap_term = snap.index, snap.term
        self.snap_data = snap.data
        self.commit_index = self.applied_index = snap.index
        self.nodes = snap.nodes
        self._snapfile.save(snap)
        self._wal.append({"k": "trunc", "i": snap.index + 1})
        # surface the snapshot to the application as a pseudo-entry so the
        # container can restore app state (etcdraft chain.go catch-up path)
        self._ready.committed.append(
            Entry(snap.term, snap.index, snap.data, ENTRY_SNAPSHOT))
        self._send(Message(MSG_APP_RESP, self.id, m.frm, self.term,
                           index=snap.index))

    # -- membership ----------------------------------------------------------

    def _apply_conf(self, e: Entry) -> None:
        d = serde.decode(e.data)
        nodes = set(self.nodes)
        if d["op"] == "add":
            nodes.add(d["node"])
        elif d["op"] == "remove":
            nodes.discard(d["node"])
        self.nodes = tuple(sorted(nodes))
        if self.role == LEADER:
            for n in self.nodes:
                self.next_index.setdefault(n, self.last_index() + 1)
                self.match_index.setdefault(n, 0)
            if self.id not in self.nodes:
                self._become_follower(self.term, None)  # self-eviction
            elif d["op"] == "remove" and d["node"] != self.id:
                # farewell append: replication to the removed server
                # stops the instant its removal commits, so without one
                # last append carrying the new commit index it never
                # learns it was removed and can never self-evict (the
                # classic removed-server problem)
                self._send_append(int(d["node"]))

    # -- plumbing ------------------------------------------------------------

    def _send(self, m: Message) -> None:
        self._ready.messages.append(m)

    def close(self) -> None:
        self._wal.sync()
        self._wal.close()


class NotLeaderError(Exception):
    def __init__(self, leader_id: Optional[int]):
        super().__init__(f"not leader (leader={leader_id})")
        self.leader_id = leader_id
