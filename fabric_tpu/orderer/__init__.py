"""Ordering service plane.

Reference parity (SURVEY.md §2 "Ordering service", §3.4):
  orderer/common/blockcutter   -> blockcutter.BlockCutter
  orderer/common/msgprocessor  -> msgprocessor.{StandardChannelProcessor,...}
  orderer/common/multichannel  -> blockwriter.BlockWriter, registrar.Registrar
  orderer/consensus/solo       -> consensus.SoloChain
  orderer/consensus/etcdraft   -> raft.RaftNode + consensus.RaftChain
  orderer/common/broadcast     -> broadcast.BroadcastHandler
  common/deliver               -> deliver.DeliverHandler
"""

from fabric_tpu.orderer.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.orderer.blockwriter import (
    BlockWriter,
    block_signed_bytes,
    block_signature_items,
)
from fabric_tpu.orderer.msgprocessor import (
    MsgClass,
    MsgProcessorError,
    StandardChannelProcessor,
    classify,
)
from fabric_tpu.orderer.consensus import Chain, SoloChain
from fabric_tpu.orderer.broadcast import BroadcastHandler, BroadcastResponse
from fabric_tpu.orderer.deliver import DeliverHandler, SeekInfo
from fabric_tpu.orderer.registrar import ChainSupport, Registrar

__all__ = [
    "BatchConfig", "BlockCutter", "BlockWriter", "block_signed_bytes",
    "block_signature_items", "MsgClass", "MsgProcessorError",
    "StandardChannelProcessor", "classify", "Chain", "SoloChain",
    "BroadcastHandler", "BroadcastResponse", "DeliverHandler", "SeekInfo",
    "ChainSupport", "Registrar",
]
