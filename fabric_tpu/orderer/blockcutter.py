"""Batching of ordered envelopes into blocks.

Reference parity: orderer/common/blockcutter/blockcutter.go —
`Ordered` (:69) accumulates envelopes and cuts batches on
MaxMessageCount / PreferredMaxBytes; `Cut` (:127) flushes the pending
batch (driven by the consenter's batch timeout).

TPU-native twist (SURVEY.md §7 step 5): the batch size is a
*performance-coupled* knob — blocks sized to the TPU verify batch sweet
spot keep the commit-side dispatch (committer/txvalidator.py) at full
MXU occupancy, so `BatchConfig.max_message_count` defaults to a
TPU-friendly size rather than the reference's 10.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from fabric_tpu.protocol import Envelope


@dataclass(frozen=True)
class BatchConfig:
    """Orderer.BatchSize equivalent (sampleconfig/orderer.yaml)."""
    max_message_count: int = 512
    absolute_max_bytes: int = 10 * 1024 * 1024
    preferred_max_bytes: int = 2 * 1024 * 1024
    # Orderer.BatchTimeout (seconds) — enforced by the chain loop, not here
    batch_timeout_s: float = 2.0


class BlockCutter:
    """One channel's receiver (blockcutter.go receiver struct)."""

    def __init__(self, config: BatchConfig, config_source=None):
        self._static_config = config
        # optional callable returning the live BatchConfig (channel bundle);
        # committed config changes to batch limits then take effect on the
        # next ordered envelope, like the reference re-reads SharedConfig
        self._config_source = config_source
        self._pending: List[bytes] = []
        self._pending_bytes = 0

    @property
    def config(self) -> BatchConfig:
        if self._config_source is not None:
            cfg = self._config_source()
            if cfg is not None:
                return cfg
        return self._static_config

    def ordered(self, env: Envelope) -> Tuple[List[List[bytes]], bool]:
        """Enqueue one envelope; returns (cut_batches, pending_remaining).

        Semantics mirror blockcutter.go:69-125:
        - an envelope larger than preferred_max_bytes is cut as its own
          batch (isolated message), after first cutting any pending batch;
        - appending past preferred_max_bytes cuts the pending batch first;
        - reaching max_message_count cuts immediately.
        """
        raw = env.serialize()
        size = len(raw)
        batches: List[List[bytes]] = []

        if size > self.config.preferred_max_bytes:
            if self._pending:
                batches.append(self.cut())
            batches.append([raw])
            return batches, False

        if self._pending_bytes + size > self.config.preferred_max_bytes \
                and self._pending:
            batches.append(self.cut())

        self._pending.append(raw)
        self._pending_bytes += size

        if len(self._pending) >= self.config.max_message_count:
            batches.append(self.cut())

        return batches, bool(self._pending)

    def cut(self) -> List[bytes]:
        """Flush the pending batch (blockcutter.go:127 Cut)."""
        batch, self._pending, self._pending_bytes = self._pending, [], 0
        return batch

    @property
    def pending_count(self) -> int:
        return len(self._pending)
