"""Block delivery streams (server side).

Reference parity: common/deliver/deliver.go — Handle (:157) parses a
SeekInfo envelope and deliverBlocks (:199) streams blocks from the
channel ledger, blocking at the chain tip when behavior=BLOCK_UNTIL_READY.
The reader ACL (deliver/acl.go re-evaluated on config change) maps to the
readers-policy check in `authorize`.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Iterator, Optional

from fabric_tpu.policy import SignedData
from fabric_tpu.protocol import Block

SEEK_OLDEST = "oldest"
SEEK_NEWEST = "newest"

BEHAVIOR_BLOCK_UNTIL_READY = "block_until_ready"
BEHAVIOR_FAIL_IF_NOT_READY = "fail_if_not_ready"


class DeliverError(Exception):
    pass


class NotReadyError(DeliverError):
    """Seek past the tip with FAIL_IF_NOT_READY."""


@dataclass(frozen=True)
class SeekInfo:
    """ab.SeekInfo: start/stop positions. int = specified block number."""
    start: object = SEEK_OLDEST        # int | "oldest" | "newest"
    stop: Optional[object] = None      # int | "newest" | None (= stream forever)
    behavior: str = BEHAVIOR_BLOCK_UNTIL_READY


class DeliverHandler:
    """deliver.Handler bound to a registrar of channels."""

    def __init__(self, registrar):
        self.registrar = registrar

    def deliver(self, channel_id: str, seek: SeekInfo,
                signed: Optional[SignedData] = None,
                timeout_s: Optional[float] = None) -> Iterator[Block]:
        """Generator of blocks per the seek request.

        `signed` is the deliver request's creator triple, checked against
        the channel Readers policy when the channel enforces one.

        When the request rode in on a traced RPC (the req frame carried
        a traceparent — e.g. a leader peer's gossip.pull_window), the
        stream is timed under an `orderer.deliver` child span; untraced
        traffic records nothing (require_parent).
        """
        from fabric_tpu.ops_plane import tracing
        span = tracing.tracer.start_span(
            "orderer.deliver", require_parent=True,
            attributes={"channel": channel_id})
        sent = 0
        status = "OK"
        try:
            support = self.registrar.get(channel_id)
            if support is None:
                raise DeliverError(f"unknown channel {channel_id!r}")
            support.authorize_read(signed)

            height = support.ledger.height
            start = self._resolve(seek.start, height)
            stop = (self._resolve(seek.stop, height)
                    if seek.stop is not None else None)
            if stop is not None and stop < start:
                raise DeliverError(f"seek stop {stop} < start {start}")
            span.set_attribute("start", start)

            num = start
            while stop is None or num <= stop:
                if num >= support.ledger.height:
                    if seek.behavior == BEHAVIOR_FAIL_IF_NOT_READY:
                        raise NotReadyError(
                            f"block {num} past tip {support.ledger.height}")
                    if not support.wait_for_height(num + 1, timeout_s):
                        return  # timed out waiting at the tip
                yield support.ledger.get_by_number(num)
                sent += 1
                num += 1
        except NotReadyError:
            raise    # at-tip is the normal end of a window pull
        except BaseException as e:   # incl. GeneratorExit on client cancel
            span.set_attribute("error", repr(e))
            status = "ERROR"
            raise
        finally:
            span.set_attribute("blocks", sent)
            span.end(status=status)

    @staticmethod
    def _resolve(pos, height: int) -> int:
        if pos == SEEK_OLDEST:
            return 0
        if pos == SEEK_NEWEST:
            return max(0, height - 1)
        return int(pos)
