"""Broadcast ingestion: envelope -> filters -> consenter.

Reference parity: orderer/common/broadcast/broadcast.go —
Handle (:66) reads envelopes off the stream, ProcessMessage (:136)
classifies + runs msgprocessor filters, then calls processor.Order /
Configure (:176) on the channel's chain.  Streaming is a transport
concern here; `handle` takes one envelope and returns a response the
way each stream iteration does.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from fabric_tpu.ops_plane import tracing
from fabric_tpu.orderer.consensus import ChainHaltedError
from fabric_tpu.orderer.msgprocessor import MsgClass, MsgProcessorError
from fabric_tpu.orderer.raft import NotLeaderError
from fabric_tpu.protocol import Envelope

STATUS_SUCCESS = 200
STATUS_BAD_REQUEST = 400
STATUS_FORBIDDEN = 403
STATUS_NOT_FOUND = 404
STATUS_UNAVAILABLE = 503


@dataclass(frozen=True)
class BroadcastResponse:
    status: int
    info: str = ""
    leader_hint: int = 0   # raft id of the current leader, when known


class BroadcastHandler:
    """broadcast.Handler bound to a registrar of channels."""

    def __init__(self, registrar):
        self.registrar = registrar

    def handle(self, env: Envelope,
               attest: Optional[str] = None,
               attestor=None) -> BroadcastResponse:
        resp = None
        with tracing.tracer.start_span("orderer.broadcast",
                                       require_parent=True) as span:
            resp = self._handle_inner(env, span, attest, attestor)
            if span.recording:
                span.set_attribute("status", resp.status)
                if resp.status != STATUS_SUCCESS:
                    span.status = "ERROR"
        return resp

    def _handle_inner(self, env: Envelope, span,
                      attest: Optional[str] = None,
                      attestor=None) -> BroadcastResponse:
        try:
            channel_id = env.header().channel_header.channel_id
        except Exception:
            return BroadcastResponse(STATUS_BAD_REQUEST,
                                     "undecodable envelope header")
        if span.recording:
            span.set_attribute("channel", channel_id)
        support = self.registrar.get(channel_id)
        if support is None:
            return BroadcastResponse(STATUS_NOT_FOUND,
                                     f"unknown channel {channel_id!r}")
        try:
            cls = support.processor.process(env, attest=attest,
                                            attestor=attestor)
        except MsgProcessorError as e:
            return BroadcastResponse(STATUS_FORBIDDEN, str(e))
        try:
            if cls is MsgClass.CONFIG:
                support.chain.configure(env)
            else:
                support.chain.order(env)
        except NotLeaderError as e:
            # SERVICE_UNAVAILABLE + leader hint so clients re-submit there
            return BroadcastResponse(STATUS_UNAVAILABLE, str(e),
                                     leader_hint=e.leader_id or 0)
        except ChainHaltedError as e:
            return BroadcastResponse(STATUS_UNAVAILABLE, str(e))
        return BroadcastResponse(STATUS_SUCCESS)

    def handle_batch(
            self, envs: Sequence[Envelope],
            tps: Optional[Sequence[str]] = None,
            attests: Optional[Sequence[str]] = None,
            attestor=None
    ) -> List[BroadcastResponse]:
        """Ingest a coalesced batch in one call (the gateway's admission
        queue ships these).  Envelopes are independent — each routes by
        its own channel header and gets its own response, exactly as if
        streamed one by one; the batching only amortizes the RPC round
        trip and handshake-authenticated framing.

        `tps`, when given, aligns a traceparent with each envelope: the
        gateway batches many client txs into one frame, so per-tx trace
        context rides next to the envelopes instead of on the frame.
        `attests` aligns the gateway's verdict attestations the same
        way (verify-once plane); `attestor` is the frame's handshake-
        verified sender identity — the msgprocessor only honours the
        attestations when that identity is in the channel's configured
        attestor set."""
        out = []
        for i, env in enumerate(envs):
            ctx = None
            if tps and i < len(tps) and tps[i]:
                ctx = tracing.tracer.context_from(tps[i])
            attest = attests[i] if attests and i < len(attests) else None
            with tracing.tracer.activate(ctx):
                out.append(self.handle(env, attest=attest,
                                       attestor=attestor))
        return out
