"""Consenter chains: the ordering state machines.

Reference parity: orderer/consensus/consensus.go Chain interface
(Order/Configure/WaitReady/Start/Halt) and orderer/consensus/solo —
a single-node chain that cuts batches by count/bytes/timeout and hands
them to the block writer.  The Raft-replicated chain lives in
fabric_tpu/orderer/raft.py + RaftChain below it in registrar wiring.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from fabric_tpu.ops_plane import tracing
from fabric_tpu.orderer.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.protocol import Envelope


class ChainHaltedError(Exception):
    pass


class Chain:
    """consensus.Chain — what broadcast dispatches into."""

    def order(self, env: Envelope) -> None:
        raise NotImplementedError

    def configure(self, env: Envelope) -> None:
        raise NotImplementedError

    def start(self) -> None:
        pass

    def halt(self) -> None:
        pass


class SoloChain(Chain):
    """Single-consenter dev chain (orderer/consensus/solo/consensus.go).

    Envelopes are cut into blocks synchronously by count/bytes; the batch
    timeout is enforced either by `tick(now)` (deterministic tests) or by
    the optional background timer thread started with `start()`.
    Config envelopes always cut the pending batch first and are written
    as single-tx config blocks, mirroring solo's main loop.
    """

    def __init__(self, cutter: BlockCutter, writer: BlockWriter,
                 on_block: Optional[Callable] = None):
        self.cutter = cutter
        self.writer = writer
        self.on_block = on_block or (lambda block: None)
        self._lock = threading.RLock()
        self._halted = False
        self._timer: Optional[threading.Thread] = None
        self._batch_deadline: Optional[float] = None

    # -- Chain interface ----------------------------------------------------

    def order(self, env: Envelope) -> None:
        with self._lock:
            self._check_running()
            batches, pending = self.cutter.ordered(env)
            for batch in batches:
                self._write(batch)
            self._restart_deadline(bool(batches), pending)

    def configure(self, env: Envelope) -> None:
        with self._lock:
            self._check_running()
            pending = self.cutter.cut()
            if pending:
                self._write(pending)
            self._write([env.serialize()], is_config=True)
            self._batch_deadline = None

    def tick(self, now: Optional[float] = None) -> bool:
        """Cut the pending batch if the batch timeout expired; returns
        whether a block was written."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._halted or self._batch_deadline is None \
                    or now < self._batch_deadline:
                return False
            batch = self.cutter.cut()
            self._batch_deadline = None
            if not batch:
                return False
            self._write(batch)
            return True

    def start(self) -> None:
        if self._timer is not None:
            return
        self._halted = False

        def loop():
            while not self._halted:
                time.sleep(self.cutter.config.batch_timeout_s / 4)
                self.tick()

        self._timer = threading.Thread(target=loop, daemon=True)
        self._timer.start()

    def halt(self) -> None:
        with self._lock:
            self._halted = True
        if self._timer is not None:
            self._timer.join(timeout=2)
            self._timer = None

    # -- internals ----------------------------------------------------------

    def _check_running(self) -> None:
        if self._halted:
            raise ChainHaltedError("chain is halted")

    def _restart_deadline(self, cut_happened: bool, pending: bool) -> None:
        """The batch timer restarts on every cut (the reference resets its
        timer whenever a batch is cut); it only keeps running for an
        already-pending batch when nothing was cut."""
        if not pending:
            self._batch_deadline = None
        elif cut_happened or self._batch_deadline is None:
            self._batch_deadline = (time.monotonic()
                                    + self.cutter.config.batch_timeout_s)

    def _write(self, batch: List[bytes], is_config: bool = False) -> None:
        # consensus cut: spans only when ordered under a traced broadcast
        # (timer-thread cuts have no ambient context and record nothing)
        with tracing.tracer.start_span(
                "orderer.cut_block", require_parent=True,
                attributes={"batch_size": len(batch),
                            "is_config": is_config}) as span:
            block = self.writer.create_next_block(batch)
            if span.recording:
                span.set_attribute("block", int(block.header.number))
            self.writer.write_block(block, is_config=is_config)
            self.on_block(block)


# ---------------------------------------------------------------------------
# Raft-replicated chain (orderer/consensus/etcdraft/chain.go equivalent)

META_RAFT_INDEX = "raft_index"


def make_entry_signer(signer):
    """Build a RaftNode entry_signer from a consenter signing identity:
    returns (serialized identity, signature over the canonical entry
    bytes) — what EntryVerifier checks on the receiving side."""
    from fabric_tpu.orderer import raft as raftmod
    raw = signer.serialize()

    def sign(term: int, index: int, data: bytes, kind: str):
        return raw, signer.sign(
            raftmod.entry_signed_bytes(term, index, data, kind))

    return sign


class RaftChain(Chain):
    """Crash-fault-tolerant ordering over fabric_tpu.orderer.raft.

    Design deviation from the reference (etcdraft/chain.go:378,782): the
    leader proposes the *cut batch* (serialized envelopes + config flag),
    not a pre-built block; every node deterministically builds + signs the
    block at apply time.  Same total order => same block numbers and data
    hashes on every node, with no in-flight block-number tracking and no
    leader-change block reconstruction.

    Replay idempotency: each block records the raft entry index that
    produced it; on restart, re-delivered committed entries at or below
    the recovered index are skipped (the ledger *is* the applied-state
    checkpoint, mirroring SURVEY.md §5 checkpoint/resume).
    """

    def __init__(self, node, cutter: BlockCutter, writer: BlockWriter,
                 on_block: Optional[Callable] = None, entry_signer=None,
                 on_conf: Optional[Callable] = None):
        from fabric_tpu.utils import serde as _serde
        self._serde = _serde
        self.node = node
        self.cutter = cutter
        self.writer = writer
        # membership hook: called with the decoded conf payload
        # ({"op","node",...}) each time a membership entry COMMITS.  Conf
        # entries do not advance _last_applied, so they replay on restart
        # — the hook MUST be idempotent.
        self.on_conf = on_conf or (lambda conf: None)
        # consenter entry signing (round 14): install the signer on the
        # raft node so every local append — proposals, conf changes, the
        # new-leader no-op — carries (proposer, sig); the cluster service
        # enforces the chain on channels whose own chain signs
        if entry_signer is not None:
            node.entry_signer = entry_signer
        self.on_block = on_block or (lambda block: None)
        self._lock = threading.RLock()
        self._halted = False
        self._batch_deadline: Optional[float] = None
        self._last_applied = self._recover_applied_index()
        self.catchup_target: Optional[dict] = None  # set on snapshot install
        self._held_entries: List = []  # entries arriving while catching up
        node.snapshot_data = self._snapshot_state
        # crash window: snapshot installed but catch_up never ran.  The
        # node's persisted snapshot state knows the cluster ledger height;
        # if our ledger is shorter we must re-enter catch-up, else entries
        # after snap_index would land at wrong block numbers.
        if node.snap_data:
            self._maybe_enter_catchup(node.snap_data, fallback_index=0)

    def _recover_applied_index(self) -> int:
        lg = self.writer.ledger
        if lg.height == 0:
            return 0
        tip = lg.get_by_number(lg.height - 1)
        return int(tip.metadata.items.get(META_RAFT_INDEX, 0))

    def _snapshot_state(self, index: int) -> bytes:
        # called from node.maybe_compact() AFTER process_ready applied all
        # entries <= index, so _last_applied/height describe state AT index
        return self._serde.encode({
            "raft_index": self._last_applied,
            "height": self.writer.height,
        })

    # -- Chain interface ----------------------------------------------------

    def order(self, env: Envelope) -> None:
        with self._lock:
            self._check_running()
            self._check_leader()  # followers redirect Submit (chain.go:378)
            batches, pending = self.cutter.ordered(env)
            for batch in batches:
                self._propose(batch, is_config=False)
            self._restart_deadline(bool(batches), pending)

    def configure(self, env: Envelope) -> None:
        with self._lock:
            self._check_running()
            self._check_leader()
            pending = self.cutter.cut()
            if pending:
                self._propose(pending, is_config=False)
            self._propose([env.serialize()], is_config=True)
            self._batch_deadline = None

    def _check_leader(self) -> None:
        from fabric_tpu.orderer import raft as raftmod
        if self.node.role != raftmod.LEADER:
            raise raftmod.NotLeaderError(self.node.leader_id)

    def propose_membership(self, op: str, node_id: int, **meta) -> int:
        """Propose an add/remove-consenter config entry through the log
        (leader only).  Returns the entry's raft index; the change takes
        effect — on every replica, including this one — when the entry
        commits and on_conf fires."""
        with self._lock:
            self._check_running()
            self._check_leader()
            return self.node.propose_conf(op, node_id, **meta)

    def transfer_leadership(self, to: int) -> bool:
        """Ask raft to hand leadership to `to` (drain path)."""
        with self._lock:
            return self.node.transfer_leadership(to)

    def tick_batch(self, now: Optional[float] = None) -> bool:
        """Cut + propose the pending batch when the batch timeout fires."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._halted or self._batch_deadline is None \
                    or now < self._batch_deadline:
                return False
            batch = self.cutter.cut()
            self._batch_deadline = None
            if not batch:
                return False
            from fabric_tpu.orderer import raft as raftmod
            try:
                self._propose(batch, is_config=False)
            except raftmod.NotLeaderError:
                # deposed between the deadline being set and firing: the
                # batch is discarded (clients retry against the new leader)
                return False
            return True

    def halt(self) -> None:
        with self._lock:
            self._halted = True

    def _check_running(self) -> None:
        if self._halted:
            raise ChainHaltedError("chain is halted")

    _restart_deadline = SoloChain._restart_deadline

    # -- raft plumbing -------------------------------------------------------
    # RaftNode has no internal locking; every access — propose (via
    # order/configure), transport-driven step, clock-driven tick, and the
    # ready drain — must hold self._lock.  Transports call chain.step, not
    # node.step.

    def step(self, msg) -> None:
        with self._lock:
            self.node.step(msg)

    def tick(self) -> None:
        """Advance the raft election/heartbeat clock."""
        with self._lock:
            self.node.tick()

    def _propose(self, batch, is_config: bool) -> None:
        with tracing.tracer.start_span(
                "orderer.cut_propose", require_parent=True,
                attributes={"batch_size": len(batch),
                            "is_config": is_config}):
            self.node.propose(self._serde.encode(
                {"cfg": is_config, "batch": list(batch)}))

    def process_ready(self):
        """Drain the raft node: apply committed entries to the ledger and
        return the outbound messages for the cluster transport to send."""
        from fabric_tpu.orderer import raft as raftmod
        with self._lock:
            r = self.node.take_ready()
            if r.lost_leadership:
                # discard the pending batch and stop the batch timer
                # (reference etcdraft chain.go:604-607 becomeFollower):
                # stale envelopes must not be proposed if leadership is
                # later regained, and the timer path must not fire.
                self.cutter.cut()
                self._batch_deadline = None
            for e in r.committed:
                if e.kind == raftmod.ENTRY_SNAPSHOT:
                    self._on_snapshot_entry(e)
                elif e.kind == raftmod.ENTRY_NORMAL:
                    self._apply(e)
                elif e.kind == raftmod.ENTRY_CONF:
                    # the raft-internal effect (node set change) already
                    # ran inside take_ready; surface the full payload so
                    # the owning node can follow — consenter identity
                    # maps, transport addresses, persisted channel state
                    try:
                        self.on_conf(self._serde.decode(e.data))
                    except Exception:
                        import logging
                        logging.getLogger(
                            "fabric_tpu.orderer.consensus").exception(
                            "membership conf hook failed")
            # compact only after the entries above hit the ledger — and
            # never while catching up, when _last_applied/height lag the
            # raft applied index and would bake stale state into the snap
            if self.catchup_target is None:
                self.node.maybe_compact()
        return r

    def _apply(self, entry) -> None:
        if self.catchup_target is not None:
            # ledger is behind the snapshot: hold entries until the missing
            # blocks arrive (replication), else block numbers would skew
            self._held_entries.append(entry)
            return
        if entry.index <= self._last_applied:
            return  # replayed on restart; ledger already has the block
        if not entry.data:
            # leader-change no-op entry (raft _become_leader): no block
            self._last_applied = entry.index
            return
        d = self._serde.decode(entry.data)
        block = self.writer.create_next_block(d["batch"])
        block.metadata.items[META_RAFT_INDEX] = entry.index
        self.writer.write_block(block, is_config=d["cfg"])
        self._last_applied = entry.index
        self.on_block(block)

    def _on_snapshot_entry(self, e) -> None:
        """A snapshot was installed: this node is behind the compacted log
        and must catch up its *ledger* from a peer (the reference's
        orderer/common/cluster/replication.go pull path)."""
        self._maybe_enter_catchup(e.data, fallback_index=e.index)

    def _maybe_enter_catchup(self, state_bytes: bytes,
                             fallback_index: int) -> None:
        """Decode chain snapshot state; if the cluster ledger is ahead of
        ours, enter catch-up.  Tolerates opaque/non-dict app state (raw
        RaftNode snapshots) by doing nothing."""
        try:
            state = self._serde.decode(state_bytes) if state_bytes else {}
        except ValueError:
            return
        if not isinstance(state, dict):
            return
        self._last_applied = max(
            self._last_applied, int(state.get("raft_index", fallback_index)))
        if int(state.get("height", 0)) > self.writer.ledger.height:
            self.catchup_target = state

    def catch_up(self, blocks) -> None:
        """Install blocks fetched from a peer (replication.go equivalent)."""
        with self._lock:
            for block in blocks:
                if block.header.number < self.writer.ledger.height:
                    continue
                self.writer.ledger.add_block(block)
            self.writer.resync()
            # the installed tip's raft index supersedes the snapshot's, or
            # re-delivered entries would re-apply as duplicate blocks
            self._last_applied = max(self._last_applied,
                                     self._recover_applied_index())
            if self.catchup_target and \
                    self.writer.ledger.height >= self.catchup_target["height"]:
                self.catchup_target = None
                held, self._held_entries = self._held_entries, []
                for entry in held:
                    self._apply(entry)
