"""Consenter chains: the ordering state machines.

Reference parity: orderer/consensus/consensus.go Chain interface
(Order/Configure/WaitReady/Start/Halt) and orderer/consensus/solo —
a single-node chain that cuts batches by count/bytes/timeout and hands
them to the block writer.  The Raft-replicated chain lives in
fabric_tpu/orderer/raft.py + RaftChain below it in registrar wiring.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from fabric_tpu.orderer.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.protocol import Envelope


class ChainHaltedError(Exception):
    pass


class Chain:
    """consensus.Chain — what broadcast dispatches into."""

    def order(self, env: Envelope) -> None:
        raise NotImplementedError

    def configure(self, env: Envelope) -> None:
        raise NotImplementedError

    def start(self) -> None:
        pass

    def halt(self) -> None:
        pass


class SoloChain(Chain):
    """Single-consenter dev chain (orderer/consensus/solo/consensus.go).

    Envelopes are cut into blocks synchronously by count/bytes; the batch
    timeout is enforced either by `tick(now)` (deterministic tests) or by
    the optional background timer thread started with `start()`.
    Config envelopes always cut the pending batch first and are written
    as single-tx config blocks, mirroring solo's main loop.
    """

    def __init__(self, cutter: BlockCutter, writer: BlockWriter,
                 on_block: Optional[Callable] = None):
        self.cutter = cutter
        self.writer = writer
        self.on_block = on_block or (lambda block: None)
        self._lock = threading.RLock()
        self._halted = False
        self._timer: Optional[threading.Thread] = None
        self._batch_deadline: Optional[float] = None

    # -- Chain interface ----------------------------------------------------

    def order(self, env: Envelope) -> None:
        with self._lock:
            self._check_running()
            batches, pending = self.cutter.ordered(env)
            for batch in batches:
                self._write(batch)
            if pending and self._batch_deadline is None:
                self._batch_deadline = (time.monotonic()
                                        + self.cutter.config.batch_timeout_s)
            elif not pending:
                self._batch_deadline = None

    def configure(self, env: Envelope) -> None:
        with self._lock:
            self._check_running()
            pending = self.cutter.cut()
            if pending:
                self._write(pending)
            self._write([env.serialize()], is_config=True)
            self._batch_deadline = None

    def tick(self, now: Optional[float] = None) -> bool:
        """Cut the pending batch if the batch timeout expired; returns
        whether a block was written."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._halted or self._batch_deadline is None \
                    or now < self._batch_deadline:
                return False
            batch = self.cutter.cut()
            self._batch_deadline = None
            if not batch:
                return False
            self._write(batch)
            return True

    def start(self) -> None:
        if self._timer is not None:
            return
        self._halted = False

        def loop():
            while not self._halted:
                time.sleep(self.cutter.config.batch_timeout_s / 4)
                self.tick()

        self._timer = threading.Thread(target=loop, daemon=True)
        self._timer.start()

    def halt(self) -> None:
        with self._lock:
            self._halted = True
        if self._timer is not None:
            self._timer.join(timeout=2)
            self._timer = None

    # -- internals ----------------------------------------------------------

    def _check_running(self) -> None:
        if self._halted:
            raise ChainHaltedError("chain is halted")

    def _write(self, batch: List[bytes], is_config: bool = False) -> None:
        block = self.writer.create_next_block(batch)
        self.writer.write_block(block, is_config=is_config)
        self.on_block(block)
