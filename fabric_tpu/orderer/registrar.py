"""Multichannel registrar: per-channel ordering resources.

Reference parity: orderer/common/multichannel/registrar.go +
chainsupport.go — one ChainSupport per channel bundling the msg
processor, block cutter, block writer, and consenter chain; the
registrar creates channels from genesis blocks and routes broadcast/
deliver traffic to them.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.orderer.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.orderer.consensus import Chain, SoloChain
from fabric_tpu.orderer.msgprocessor import StandardChannelProcessor
from fabric_tpu.policy import PolicyEvaluator, SignaturePolicy, SignedData
from fabric_tpu.protocol import Block


logger = logging.getLogger("fabric_tpu.orderer.registrar")


class ChainSupport:
    """chainsupport.go ChainSupport: everything one channel needs."""

    def __init__(self, channel_id: str, ledger: BlockStore,
                 processor: StandardChannelProcessor, cutter: BlockCutter,
                 writer: BlockWriter, chain_factory: Callable[..., Chain],
                 readers_policy: Optional[SignaturePolicy] = None,
                 bundle_source=None):
        self.channel_id = channel_id
        self.ledger = ledger
        self.processor = processor
        self.cutter = cutter
        self.writer = writer
        self.readers_policy = readers_policy
        self.bundle_source = bundle_source
        self._tip_cond = threading.Condition()
        self.chain = chain_factory(cutter=cutter, writer=writer,
                                   on_block=self._on_block)

    def _on_block(self, block: Block) -> None:
        if self.bundle_source is not None:
            # orderer-side config application: a written config block
            # atomically swaps the channel bundle (the reference updates the
            # bundle in multichannel BlockWriter for config blocks).
            try:
                from fabric_tpu.config import apply_config_block
                apply_config_block(self.bundle_source, block,
                                   self.processor.provider)
            except Exception:
                logger.exception("config block application failed")
        with self._tip_cond:
            self._tip_cond.notify_all()

    def wait_for_height(self, height: int,
                        timeout_s: Optional[float] = None) -> bool:
        """Block until ledger height >= height (deliver tip waiting)."""
        with self._tip_cond:
            return self._tip_cond.wait_for(
                lambda: self.ledger.height >= height, timeout=timeout_s)

    def authorize_read(self, signed: Optional[SignedData]) -> None:
        """deliver/acl.go sessionAC equivalent: Readers policy check,
        re-resolved from the live bundle on every call (the reference
        re-evaluates the ACL on config changes, deliver/acl.go)."""
        readers = self.readers_policy
        if self.bundle_source is not None:
            readers = (self.bundle_source.current().policy("Readers")
                       or readers)
        if readers is None:
            return
        from fabric_tpu.orderer.deliver import DeliverError
        if signed is None:
            raise DeliverError("deliver request not signed and channel "
                               "enforces a Readers policy")
        if not self.processor.evaluator.evaluate_signed_data(
                readers, [signed]):
            raise DeliverError("deliver request does not satisfy channel "
                               "Readers policy")


class Registrar:
    """registrar.go Registrar: channel_id -> ChainSupport."""

    def __init__(self):
        self._channels: Dict[str, ChainSupport] = {}
        self._lock = threading.RLock()

    def create_channel(self, channel_id: str, msps: Dict[str, object],
                       provider, writers_policy: SignaturePolicy,
                       readers_policy: Optional[SignaturePolicy] = None,
                       signer=None, batch_config: Optional[BatchConfig] = None,
                       ledger: Optional[BlockStore] = None,
                       genesis: Optional[Block] = None,
                       chain_factory: Callable[..., Chain] = SoloChain,
                       bundle_source=None) -> ChainSupport:
        with self._lock:
            if channel_id in self._channels:
                raise ValueError(f"channel {channel_id!r} already exists")
            ledger = ledger if ledger is not None else BlockStore()
            if genesis is not None and ledger.height == 0:
                ledger.add_block(genesis)
            cfg = batch_config or BatchConfig()
            config_source = None
            if bundle_source is not None:
                def config_source(_src=bundle_source):
                    b = _src.current().batch
                    return BatchConfig(
                        max_message_count=b.max_message_count,
                        absolute_max_bytes=b.absolute_max_bytes,
                        preferred_max_bytes=b.preferred_max_bytes,
                        batch_timeout_s=getattr(b, "timeout_s", 2.0))
            cutter = BlockCutter(cfg, config_source=config_source)
            writer = BlockWriter(channel_id, ledger, signer)
            processor = StandardChannelProcessor(
                channel_id, msps, provider, writers_policy,
                absolute_max_bytes=cfg.absolute_max_bytes,
                bundle_source=bundle_source)
            support = ChainSupport(channel_id, ledger, processor, cutter,
                                   writer, chain_factory, readers_policy,
                                   bundle_source=bundle_source)
            self._channels[channel_id] = support
            return support

    def get(self, channel_id: str) -> Optional[ChainSupport]:
        with self._lock:
            return self._channels.get(channel_id)

    def remove(self, channel_id: str) -> None:
        """channelparticipation Remove: drop the chain from this node
        (the ledger files remain on disk; rejoining resumes them)."""
        with self._lock:
            self._channels.pop(channel_id, None)

    def channels(self) -> Dict[str, ChainSupport]:
        with self._lock:
            return dict(self._channels)

    def halt_all(self) -> None:
        with self._lock:
            for support in self._channels.values():
                support.chain.halt()
