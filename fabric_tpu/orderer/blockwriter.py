"""Block assembly + orderer block signature.

Reference parity: orderer/common/multichannel/blockwriter.go —
CreateNextBlock assembles the next block from a batch of envelopes;
WriteBlock stamps last-config metadata, signs the block with the
orderer's identity, and appends to the orderer blockledger.  The peer
later verifies exactly this signature (internal/peer/gossip/mcs.go:124
VerifyBlock) — `block_signature_items` emits that check as VerifyItems
so the delivery plane can fold orderer-sig verification into the same
TPU batch as the endorsement signatures (SURVEY.md §7 step 6).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from fabric_tpu.bccsp import VerifyItem
from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.msp import SigningIdentity, deserialize_from_msps
from fabric_tpu.protocol import Block
from fabric_tpu.protocol.build import new_nonce
from fabric_tpu.protocol.types import (
    BlockHeader,
    BlockMetadata,
    block_data_hash,
)
from fabric_tpu.protocol.types import (
    META_LAST_CONFIG,
    META_SIGNATURES,
)
from fabric_tpu.utils import serde


def block_signed_bytes(block: Block, sig_header: dict, last_config: int) -> bytes:
    """The bytes the orderer signature covers: header ‖ sig-header ‖
    last-config (protoutil/blockutils.go SignatureHeader+BlockHeaderBytes)."""
    return serde.encode({
        "header": block.header.to_dict(),
        "sig_header": sig_header,
        "last_config": last_config,
    })


def block_signature_items(block: Block, msps: Dict[str, object]
                          ) -> Optional[List[VerifyItem]]:
    """MCS.VerifyBlock as batchable work: one VerifyItem per block
    signature, or None when the metadata is malformed / signer unknown."""
    sigs = block.metadata.items.get(META_SIGNATURES)
    last_config = block.metadata.items.get(META_LAST_CONFIG, 0)
    if not sigs:
        return None
    items: List[VerifyItem] = []
    for entry in sigs:
        try:
            sig_header = entry["sig_header"]
            ident = deserialize_from_msps(msps, sig_header["creator"],
                                          validate=True)
            if ident is None:
                return None
            msg = block_signed_bytes(block, sig_header, last_config)
            items.append(ident.verify_item(msg, entry["signature"]))
        except Exception:
            return None
    return items


class BlockWriter:
    """One channel's block producer (multichannel/blockwriter.go)."""

    def __init__(self, channel_id: str, ledger: BlockStore,
                 signer: Optional[SigningIdentity] = None):
        self.channel_id = channel_id
        self.ledger = ledger
        self.signer = signer
        info = ledger.chain_info()
        self._next_number = info.height
        self._prev_hash = info.current_hash if info.height else b"\x00" * 32
        self._last_config = self._recover_last_config()

    def _recover_last_config(self) -> int:
        if self.ledger.height == 0:
            return 0
        last = self.ledger.get_by_number(self.ledger.height - 1)
        return int(last.metadata.items.get(META_LAST_CONFIG, 0))

    def create_next_block(self, envelopes: Sequence[bytes]) -> Block:
        """blockwriter.go CreateNextBlock (input: serialized envelopes)."""
        data = list(envelopes)
        header = BlockHeader(self._next_number, self._prev_hash,
                             block_data_hash(data))
        return Block(header, data, BlockMetadata())

    def write_block(self, block: Block, is_config: bool = False) -> Block:
        """blockwriter.go WriteBlock/WriteConfigBlock: stamp last-config,
        sign, append.  Must be called with consecutive block numbers."""
        if block.header.number != self._next_number:
            raise ValueError(
                f"out-of-order write: got block {block.header.number}, "
                f"expected {self._next_number}")
        if is_config:
            self._last_config = block.header.number
        block.metadata.items[META_LAST_CONFIG] = self._last_config
        if self.signer is not None:
            sig_header = {"creator": self.signer.serialize(),
                          "nonce": new_nonce()}
            msg = block_signed_bytes(block, sig_header, self._last_config)
            block.metadata.items[META_SIGNATURES] = [{
                "sig_header": sig_header,
                "signature": self.signer.sign(msg),
            }]
        self.ledger.add_block(block)
        self._next_number += 1
        self._prev_hash = block.hash()
        return block

    def resync(self) -> None:
        """Re-derive position from the ledger after out-of-band appends
        (raft catch-up replication writes blocks directly to the store)."""
        info = self.ledger.chain_info()
        self._next_number = info.height
        self._prev_hash = info.current_hash if info.height else b"\x00" * 32
        self._last_config = self._recover_last_config()

    @property
    def height(self) -> int:
        return self._next_number
