"""cscc — configuration system contract.

Reference parity: /root/reference/core/scc/cscc/configure.go —
GetChannels, GetConfigBlock/GetChannelConfig, JoinChain.  Joining wires a
new channel kernel (ledger + validator + committer surface) from a
genesis/config source, the role core/peer/peer.go CreateChannel plays.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from fabric_tpu.policy import SignedData


class CsccError(Exception):
    pass


class Cscc:
    """Peer-level channel directory."""

    def __init__(self, create_channel: Optional[Callable] = None):
        # create_channel(channel_id, channel_config) -> channel object
        self._create = create_channel
        self._channels: Dict[str, object] = {}

    def join_chain(self, channel_id: str, channel_config,
                   signed: Optional[SignedData] = None, **kw):
        if channel_id in self._channels:
            raise CsccError(f"already joined {channel_id!r}")
        if self._create is None:
            raise CsccError("no channel factory wired")
        ch = self._create(channel_id, channel_config, **kw)
        self._channels[channel_id] = ch
        return ch

    def register(self, channel_id: str, channel) -> None:
        """For channels created outside cscc (e.g. at node bootstrap)."""
        self._channels[channel_id] = channel

    def get_channels(self, signed: Optional[SignedData] = None) -> List[str]:
        return sorted(self._channels)

    def get(self, channel_id: str):
        return self._channels.get(channel_id)

    def get_channel_config(self, channel_id: str,
                           signed: Optional[SignedData] = None):
        ch = self._channels.get(channel_id)
        if ch is None:
            raise CsccError(f"unknown channel {channel_id!r}")
        src = getattr(ch, "bundle_source", None)
        if src is None:
            raise CsccError(f"channel {channel_id!r} has no config bundle")
        return src.current().config
