"""System chaincodes + discovery service.

Re-design of /root/reference/core/scc/{qscc,cscc} and discovery/
(VERDICT.md missing #7): in-process system contracts for ledger and
config queries, and an endorser-discovery service computing endorsement
layouts from policies + live membership.
"""

from .qscc import Qscc
from .cscc import Cscc
from .discovery import DiscoveryService, Layout

__all__ = ["Qscc", "Cscc", "DiscoveryService", "Layout"]
