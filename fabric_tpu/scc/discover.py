"""`discover` client CLI — service-discovery queries over the RPC plane.

Reference parity: /root/reference/cmd/discover/main.go + discovery/client
(`discover peers|config|endorsers` against a peer's discovery service).

    python -m fabric_tpu.scc.discover --client client.json \
        --msp-config <node.json|channel_config.bin> \
        --peer 127.0.0.1:7051 [--channel ch] \
        endorsers --chaincode asset
        peers
        config

Output is one JSON document per query, like the reference CLI's
--json mode.
"""

from __future__ import annotations

import argparse
import json
import sys

from fabric_tpu.node.admin import _connect, _load_client, _load_msps


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fabric-tpu-discover")
    ap.add_argument("--client", required=True)
    ap.add_argument("--msp-config", required=True)
    ap.add_argument("--peer", required=True)
    ap.add_argument("--channel", default=None)
    sub = ap.add_subparsers(dest="verb", required=True)
    e = sub.add_parser("endorsers")
    e.add_argument("--chaincode", required=True)
    sub.add_parser("peers")
    sub.add_parser("config")

    args = ap.parse_args(argv)
    signer = _load_client(args.client)
    msps = _load_msps(args.msp_config)
    body = {}
    if args.channel:
        body["channel"] = args.channel

    conn = _connect(args.peer, signer, msps)
    try:
        if args.verb == "endorsers":
            out = conn.call("discovery.endorsers",
                            {**body, "namespace": args.chaincode},
                            timeout=15.0)
        elif args.verb == "peers":
            out = conn.call("discovery.peers", body, timeout=15.0)
        else:
            out = conn.call("discovery.config", body, timeout=15.0)
    finally:
        conn.close()
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
