"""Endorser discovery: which peers can satisfy a chaincode's policy?

Reference parity: /root/reference/discovery/service.go:67 +
discovery/endorsement/endorsement.go + common/graph (VERDICT.md missing
#7): given a chaincode's endorsement policy and live channel membership,
compute LAYOUTS — the minimal principal combinations that satisfy the
policy — and the live peers implementing each principal group.

Policy trees here are the framework's NOutOf/SignedBy AST; a layout maps
principal-group key (mspid:role) -> how many endorsements needed from
that group, plus the live peers available per group.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from fabric_tpu.policy import SignaturePolicy


@dataclass(frozen=True)
class Layout:
    """One way to satisfy the policy: {group_key: required_count}."""
    quantities: Tuple[Tuple[str, int], ...]

    def as_dict(self) -> Dict[str, int]:
        return dict(self.quantities)


def _group_key(principal) -> str:
    return f"{principal.mspid}:{principal.role or principal.kind}"


def _combinations(policy: SignaturePolicy) -> List[Dict[str, int]]:
    """All minimal principal-count multisets satisfying the policy tree
    (common/graph/choose.go layout enumeration, depth-first)."""
    if policy.kind == "signed_by":
        return [{_group_key(policy.principal): 1}]
    # n_out_of: choose every n-subset of rules, merge their layouts
    import itertools
    out: List[Dict[str, int]] = []
    for subset in itertools.combinations(policy.rules, policy.n):
        partials: List[Dict[str, int]] = [{}]
        for rule in subset:
            nxt = []
            for combo in _combinations(rule):
                for p in partials:
                    merged = dict(p)
                    for k, v in combo.items():
                        merged[k] = merged.get(k, 0) + v
                    nxt.append(merged)
            partials = nxt
        out.extend(partials)
    # dedup
    seen, uniq = set(), []
    for c in out:
        key = tuple(sorted(c.items()))
        if key not in seen:
            seen.add(key)
            uniq.append(c)
    return uniq


class DiscoveryService:
    """membership: callable() -> list of peers, each a dict with at least
    {"id": str, "mspid": str, "roles": [..]} (live gossip membership in
    the reference, discovery/support/gossip)."""

    def __init__(self, membership, policy_for):
        self.membership = membership       # () -> List[dict]
        self.policy_for = policy_for       # namespace -> SignaturePolicy|None

    def endorsers(self, namespace: str) -> dict:
        """service.go Process for an endorsement query: layouts + the live
        peers per principal group.  Layouts whose groups lack enough live
        peers are filtered out (endorsement.go computePrincipalSets)."""
        policy = self.policy_for(namespace)
        if policy is None:
            raise ValueError(f"no endorsement policy for {namespace!r}")
        peers = self.membership()
        by_group: Dict[str, List[dict]] = {}
        for p in peers:
            for role in ("member", "admin", "peer"):
                if role == "member" or role in p.get("roles", ()):
                    by_group.setdefault(f"{p['mspid']}:{role}", []).append(p)
        layouts = []
        for combo in _combinations(policy):
            if all(len(by_group.get(g, ())) >= n for g, n in combo.items()):
                layouts.append(Layout(tuple(sorted(combo.items()))))
        return {
            "chaincode": namespace,
            "layouts": layouts,
            "peers_by_group": {g: [p["id"] for p in ps]
                               for g, ps in by_group.items()},
        }
