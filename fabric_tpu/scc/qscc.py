"""qscc — ledger query system contract.

Reference parity: /root/reference/core/scc/qscc/query.go — GetChainInfo,
GetBlockByNumber, GetBlockByHash, GetTransactionByID, with the read ACL
evaluated against the channel Readers policy before serving.
"""

from __future__ import annotations

from typing import Dict, Optional

from fabric_tpu.policy import SignedData


class QsccError(Exception):
    pass


class Qscc:
    """Bound to one channel's block store (+ optional ACL hooks)."""

    def __init__(self, channel_id: str, blockstore,
                 authorize=None, acl=None):
        self.channel_id = channel_id
        self.blockstore = blockstore
        # authorize: callable(SignedData|None) raising on deny — usually
        # ChainSupport.authorize_read (the Readers policy).  When an
        # aclmgmt provider is given instead, each method checks its OWN
        # named resource (core/scc/qscc/query.go per-function ACLs via
        # core/aclmgmt resources), so a config-tx ACL change retargets
        # individual queries.
        self.acl = acl
        self.authorize = authorize or (lambda sd: None)

    def _check(self, resource: str, signed) -> None:
        if self.acl is not None:
            self.acl.check(resource, signed)
        else:
            self.authorize(signed)

    def get_chain_info(self, signed: Optional[SignedData] = None) -> Dict:
        self._check("qscc/GetChainInfo", signed)
        info = self.blockstore.chain_info()
        return {"height": info.height,
                "current_hash": info.current_hash,
                "previous_hash": info.previous_hash}

    def get_block_by_number(self, number: int,
                            signed: Optional[SignedData] = None):
        self._check("qscc/GetBlockByNumber", signed)
        try:
            return self.blockstore.get_by_number(number)
        except Exception as exc:
            raise QsccError(f"block {number}: {exc}") from exc

    def get_block_by_hash(self, block_hash: bytes,
                          signed: Optional[SignedData] = None):
        self._check("qscc/GetBlockByHash", signed)
        try:
            return self.blockstore.get_by_hash(block_hash)
        except Exception as exc:
            raise QsccError(f"block by hash: {exc}") from exc

    def get_transaction_by_id(self, txid: str,
                              signed: Optional[SignedData] = None):
        self._check("qscc/GetTransactionByID", signed)
        try:
            block = self.blockstore.get_by_txid(txid)
        except Exception as exc:
            raise QsccError(f"transaction {txid!r}: {exc}") from exc
        from fabric_tpu.protocol import Envelope
        for env_bytes in block.data:
            env = Envelope.deserialize(env_bytes)
            if env.header().channel_header.txid == txid:
                return env
        raise QsccError(f"transaction {txid!r} not found")
