"""Authenticated communication plane (the reference's internal/pkg/comm).

SecureChannel: mutually authenticated AEAD connections bound to MSP
identities; RpcServer/RpcConnection: unary + streaming + one-way RPC on
top — the transport under Broadcast/Deliver/cluster/gossip.
"""

from . import faults
from .secure import HandshakeError, SecureChannel, SecureServer, dial
from .rpc import (RpcClosed, RpcConnection, RpcError, RpcServer,
                  RpcTimeout, connect)
from .faults import FaultPlan, FaultRule, FaultSchedule

__all__ = ["SecureChannel", "SecureServer", "HandshakeError", "dial",
           "RpcConnection", "RpcServer", "RpcError", "RpcTimeout",
           "RpcClosed", "connect", "faults", "FaultPlan", "FaultRule",
           "FaultSchedule"]
