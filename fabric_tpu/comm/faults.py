"""Deterministic, seeded fault-injection plane for the comm layer.

The robustness analogue of the tracing plane: a process-global
`FaultPlan` that the RPC transport consults on every outbound frame and
every dial.  A plan holds an ordered list of `FaultRule`s — matched by
RPC method, remote endpoint, and frame kind — whose actions model the
failure modes a real network serves up:

  drop      the frame never leaves (the caller sees an RpcTimeout)
  delay     the frame is held for a fixed latency before sending
  dup       the frame is sent twice (duplicate delivery; downstream
            dedup — gateway txid window, committer replay guard — must
            absorb it)
  reorder   the frame is parked and released AFTER the next frame on
            the same channel (adjacent swap)
  error     the injection site raises RpcError (a loud transport fault)

plus connection-level faults: `sever(addr)` refuses new dials to an
endpoint and closes the live channels already dialed to it, and
`isolate(addrs)` does the same for a node group (the reachable half of
a network partition — in-process nodes share one address space, so the
partition is expressed as "this group is unreachable"; `heal()`
restores it).

Determinism: every probabilistic decision consumes one draw from ONE
seeded PRNG under the plan lock, in frame-send order.  A test that
replays the same workload single-threaded against the same seed sees
the same fault sequence; concurrent topologies stay statistically
reproducible (same fault mix and rates) which is what the convergence
assertions need.

Production cost: the hot path is a single module-attribute load
(`faults._PLAN is None`) per frame — no plan, no work.  `install()` is
for tests and chaos drills only.

Observability: every fired fault bumps `fault_injected_total` in the
ops-plane registry, emits a `fault.<action>` span event into the
ambient trace (so /traces/<id> shows WHY a tx was slow under chaos),
and is counted in the plan's own snapshot, exported by `GET /faults`.
"""

from __future__ import annotations

import fnmatch
import logging
import random
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("fabric_tpu.comm.faults")

# THE hot-path gate: transport code checks `faults._PLAN is not None`
# (one attribute load) before doing anything else.
_PLAN: Optional["FaultPlan"] = None
_INSTALL_LOCK = threading.Lock()

# Every dial-side channel registers here (a WeakSet.add, off the frame
# hot path) so a plan installed LATER can still sever pre-existing
# connections.
_DIALED: "weakref.WeakSet" = weakref.WeakSet()
_DIALED_LOCK = threading.Lock()

ACTIONS = ("drop", "delay", "dup", "reorder", "error")


def register_channel(ch) -> None:
    with _DIALED_LOCK:
        _DIALED.add(ch)


def _addr_str(addr) -> str:
    if isinstance(addr, str):
        return addr
    try:
        host, port = addr[0], addr[1]
        return f"{host}:{port}"
    except Exception:
        return str(addr)


@dataclass
class FaultRule:
    """One match+action rule.  Probabilities are independent per action;
    at most one action fires per frame (first match in ACTIONS order
    wins, so a rule with drop=1.0 never also duplicates)."""

    method: str = "*"            # fnmatch pattern on the RPC method
    peer: Optional[str] = None   # fnmatch on "host:port" (None = any)
    kind: str = "*"              # "req" | "cast" | "resp" | "stream" | "*"
    drop: float = 0.0
    delay: float = 0.0           # probability of delaying
    delay_s: float = 0.01        # how long a delayed frame is held
    dup: float = 0.0
    reorder: float = 0.0
    error: float = 0.0
    max_fires: Optional[int] = None   # stop firing after N faults
    fires: int = field(default=0, compare=False)

    def matches(self, method: str, peer: str, kind: str) -> bool:
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if not fnmatch.fnmatchcase(kind, self.kind):
            return False
        if not fnmatch.fnmatchcase(method, self.method):
            return False
        if self.peer is not None and not fnmatch.fnmatchcase(
                peer, self.peer):
            return False
        return True

    def as_dict(self) -> dict:
        return {"method": self.method, "peer": self.peer, "kind": self.kind,
                "drop": self.drop, "delay": self.delay,
                "delay_s": self.delay_s, "dup": self.dup,
                "reorder": self.reorder, "error": self.error,
                "max_fires": self.max_fires, "fires": self.fires}


class FaultInjected(Exception):
    """Raised at an injection site for `error` faults.  Transport code
    re-raises it as RpcError so callers exercise their normal failure
    handling — the type exists so logs can tell injected faults from
    organic ones."""


class FaultPlan:
    """A seeded set of fault rules + connection-level faults.

    Build one, add rules (chainable), then `faults.install(plan)`:

        plan = (FaultPlan(seed=7)
                .rule(method="broadcast*", drop=0.2, delay=0.3,
                      delay_s=0.05, dup=0.2))
        faults.install(plan)
        ...
        faults.uninstall()
    """

    def __init__(self, seed: int = 0, name: str = ""):
        self.seed = int(seed)
        self.name = name or f"plan-{seed}"
        self._rand = random.Random(self.seed)
        self._lock = threading.Lock()
        self.rules: List[FaultRule] = []
        self._severed: set = set()              # "host:port" strings
        # per-channel parked frame for `reorder` (adjacent swap)
        self._held: Dict[int, Callable[[], None]] = {}
        self.fired: Dict[str, int] = {a: 0 for a in ACTIONS}
        self.fired["sever_refused"] = 0
        self.installed_at: Optional[float] = None

    # -- building -----------------------------------------------------------

    def rule(self, **kw) -> "FaultPlan":
        self.rules.append(FaultRule(**kw))
        return self

    # -- connection-level faults --------------------------------------------

    def sever(self, addr) -> "FaultPlan":
        """Refuse new dials to `addr` and cut live channels dialed to it."""
        a = _addr_str(addr)
        with self._lock:
            self._severed.add(a)
        with _DIALED_LOCK:
            victims = [ch for ch in _DIALED
                       if getattr(ch, "remote_addr_str", None) == a]
        for ch in victims:
            try:
                ch.close()
            except Exception:
                pass
        logger.info("fault plan %s: severed %s (%d live channels cut)",
                    self.name, a, len(victims))
        return self

    def isolate(self, addrs: Sequence) -> "FaultPlan":
        """Sever a node group: the reachable expression of a partition."""
        for a in addrs:
            self.sever(a)
        return self

    def heal(self, addr=None) -> "FaultPlan":
        """Clear severs (one endpoint, or all) and release parked frames."""
        with self._lock:
            if addr is None:
                self._severed.clear()
            else:
                self._severed.discard(_addr_str(addr))
            held = list(self._held.values())
            self._held.clear()
        for send in held:
            try:
                send()
            except Exception:
                pass
        return self

    def is_severed(self, addr) -> bool:
        with self._lock:
            return _addr_str(addr) in self._severed

    # -- the frame hook ------------------------------------------------------

    def apply(self, channel_key: int, method: str, peer, kind: str,
              send: Callable[[], None]) -> None:
        """Decide and apply faults for one outbound frame.  `send` is a
        closure performing the actual transmission; it is called 0, 1 or
        2 times depending on the decision."""
        peer_s = _addr_str(peer) if peer is not None else ""
        action = None
        delay_s = 0.0
        with self._lock:
            for r in self.rules:
                if not r.matches(method, peer_s, kind):
                    continue
                # one PRNG draw per candidate action, in fixed order
                for a in ACTIONS:
                    p = getattr(r, a if a != "delay" else "delay")
                    if p > 0.0 and self._rand.random() < p:
                        action = a
                        delay_s = r.delay_s
                        r.fires += 1
                        break
                if action is not None:
                    break
            if action is not None:
                self.fired[action] += 1
            # reorder bookkeeping happens under the lock
            if action == "reorder":
                prev = self._held.pop(channel_key, None)
                self._held[channel_key] = send
            elif self._held:
                prev = self._held.pop(channel_key, None)
            else:
                prev = None
        if action is not None:
            self._observe(action, method, peer_s)
        if action is None or action == "dup":
            send()
            if action == "dup":
                send()
        elif action == "drop":
            pass                      # the frame dies here
        elif action == "delay":
            time.sleep(delay_s)
            send()
        elif action == "error":
            if prev is not None:
                prev()
            raise FaultInjected(
                f"injected transport error on {method!r} -> {peer_s}")
        # action == "reorder": this frame stays parked; fall through
        if prev is not None and action != "error":
            prev()                    # released AFTER the newer frame

    def _observe(self, action: str, method: str, peer: str) -> None:
        try:
            from fabric_tpu.ops_plane import registry, tracing
            registry.counter(
                "fault_injected_total",
                "frames faulted by the injection plane").add(
                    1, action=action, method=method)
            # annotate the ambient trace: /traces/<id> shows why a tx
            # crawled under chaos
            tracing.event("fault." + action, method=method, peer=peer)
        except Exception:
            pass                      # observability never breaks the plane

    # -- introspection (GET /faults) ----------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "seed": self.seed,
                    "installed_at": self.installed_at,
                    "rules": [r.as_dict() for r in self.rules],
                    "severed": sorted(self._severed),
                    "held_frames": len(self._held),
                    "fired": dict(self.fired)}


# -- process-global install ---------------------------------------------------


def install(plan: FaultPlan) -> FaultPlan:
    """Make `plan` the process-global fault plan (tests/chaos only)."""
    global _PLAN
    with _INSTALL_LOCK:
        plan.installed_at = time.time()
        _PLAN = plan
    logger.warning("fault plan %s INSTALLED (seed=%d, %d rules)",
                   plan.name, plan.seed, len(plan.rules))
    return plan


def uninstall() -> None:
    global _PLAN
    with _INSTALL_LOCK:
        plan, _PLAN = _PLAN, None
    if plan is not None:
        # release parked frames so no call wedges past the drill
        plan.heal()
        logger.warning("fault plan %s removed; fired=%s",
                       plan.name, plan.fired)


def active() -> Optional[FaultPlan]:
    return _PLAN


# -- ops-plane surface --------------------------------------------------------


def register_routes(ops) -> None:
    """Mount `GET /faults` on an OperationsServer: the active plan's
    snapshot, or {"active": false} in production (no plan)."""

    def _faults(path: str, body: bytes):
        plan = _PLAN
        if plan is None:
            return 200, {"active": False}
        out = plan.snapshot()
        out["active"] = True
        return 200, out

    ops.register_route("GET", "/faults", _faults)
