"""Deterministic, seeded fault-injection plane for the comm layer.

The robustness analogue of the tracing plane: a process-global
`FaultPlan` that the RPC transport consults on every outbound frame and
every dial.  A plan holds an ordered list of `FaultRule`s — matched by
RPC method, remote endpoint, and frame kind — whose actions model the
failure modes a real network serves up:

  drop      the frame never leaves (the caller sees an RpcTimeout)
  delay     the frame is held for a fixed latency before sending
  dup       the frame is sent twice (duplicate delivery; downstream
            dedup — gateway txid window, committer replay guard — must
            absorb it)
  reorder   the frame is parked and released AFTER the next frame on
            the same channel (adjacent swap)
  error     the injection site raises RpcError (a loud transport fault)

plus connection-level faults: `sever(addr)` refuses new dials to an
endpoint and closes the live channels already dialed to it, and
`isolate(addrs)` does the same for a node group (the reachable half of
a network partition — in-process nodes share one address space, so the
partition is expressed as "this group is unreachable"; `heal()`
restores it).

Schedules: a rule may carry a `FaultSchedule` — a wall-time envelope
(ramp, burst, window) over elapsed plan time that MULTIPLIES the rule's
action probabilities, so a chaos drill can say "fault probability ramps
up over 10s" or "faults fire only during 3s bursts every 10s" and run
composably alongside always-on rules.  The envelope scales the
probability before the PRNG compare and never consumes extra draws, so
scheduled plans keep the draw-sequence determinism below.

Determinism: every probabilistic decision consumes one draw from ONE
seeded PRNG under the plan lock, in frame-send order.  A test that
replays the same workload single-threaded against the same seed sees
the same fault sequence; concurrent topologies stay statistically
reproducible (same fault mix and rates) which is what the convergence
assertions need.

Production cost: the hot path is a single module-attribute load
(`faults._PLAN is None`) per frame — no plan, no work.  `install()` is
for tests and chaos drills only.

Addressable targets: unary requests match their RPC method name
(kind="req"), deliver stream frames match method="deliver"
(kind="stream"), and multiplexed gossip casts are addressable by their
INNER message type via the transport's fault_label —
method="gossip.msg/<type>" (e.g. "gossip.msg/gossip.block",
"gossip.msg/gossip.pull_req"), kind="cast".  Snapshot state-transfer
chunks match method="state.snapshot_chunk" (kind="req"), so a chaos
drill can drop/delay/dup the transfer itself.

Observability: every fired fault bumps `fault_injected_total` in the
ops-plane registry, emits a `fault.<action>` span event into the
ambient trace (so /traces/<id> shows WHY a tx was slow under chaos),
and is counted in the plan's own snapshot, exported by `GET /faults`.
"""

from __future__ import annotations

import fnmatch
import logging
import random
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("fabric_tpu.comm.faults")

# THE hot-path gate: transport code checks `faults._PLAN is not None`
# (one attribute load) before doing anything else.
_PLAN: Optional["FaultPlan"] = None
_INSTALL_LOCK = threading.Lock()

# Every dial-side channel registers here (a WeakSet.add, off the frame
# hot path) so a plan installed LATER can still sever pre-existing
# connections.
_DIALED: "weakref.WeakSet" = weakref.WeakSet()
_DIALED_LOCK = threading.Lock()

ACTIONS = ("drop", "delay", "dup", "reorder", "error")


def register_channel(ch) -> None:
    with _DIALED_LOCK:
        _DIALED.add(ch)


def _addr_str(addr) -> str:
    if isinstance(addr, str):
        return addr
    try:
        host, port = addr[0], addr[1]
        return f"{host}:{port}"
    except Exception:
        return str(addr)


@dataclass
class FaultSchedule:
    """A wall-time envelope over elapsed plan time that multiplies a
    rule's action probabilities by `factor(t)` in [floor, 1]:

      constant   1.0 always (the implicit default when a rule has none)
      ramp       floor -> 1.0 linearly over `ramp_s` starting at
                 `start_s`, then hold (chaos that builds with the load
                 ramp instead of arriving full-strength at t=0)
      burst      1.0 for the first `duty` fraction of every `period_s`,
                 `floor` otherwise (fault bursts riding a load burst)
      window     1.0 inside [start_s, end_s), `floor` outside

    `end_s` bounds every kind; outside it the factor is `floor`.  Pure
    function of t, so a seeded plan with an injected clock replays the
    exact same fault sequence."""

    kind: str = "constant"       # constant | ramp | burst | window
    start_s: float = 0.0
    ramp_s: float = 10.0
    period_s: float = 10.0
    duty: float = 0.3
    end_s: Optional[float] = None
    floor: float = 0.0           # factor outside the active phase

    def factor(self, t: float) -> float:
        if t < self.start_s or (self.end_s is not None
                                and t >= self.end_s):
            return self.floor
        t = t - self.start_s
        if self.kind == "ramp":
            if self.ramp_s <= 0.0:
                return 1.0
            f = min(1.0, t / self.ramp_s)
            return self.floor + (1.0 - self.floor) * f
        if self.kind == "burst":
            if self.period_s <= 0.0:
                return 1.0
            phase = (t % self.period_s) / self.period_s
            return 1.0 if phase < self.duty else self.floor
        return 1.0                # constant / window (inside the window)

    def as_dict(self) -> dict:
        return {"kind": self.kind, "start_s": self.start_s,
                "ramp_s": self.ramp_s, "period_s": self.period_s,
                "duty": self.duty, "end_s": self.end_s,
                "floor": self.floor}


@dataclass
class FaultRule:
    """One match+action rule.  Probabilities are independent per action;
    at most one action fires per frame (first match in ACTIONS order
    wins, so a rule with drop=1.0 never also duplicates)."""

    method: str = "*"            # fnmatch pattern on the RPC method
    peer: Optional[str] = None   # fnmatch on "host:port" (None = any)
    kind: str = "*"              # "req" | "cast" | "resp" | "stream" | "*"
    src: str = "*"               # fnmatch on the dialing identity's
                                 # mspid ("*" = any, incl. untagged)
    drop: float = 0.0
    delay: float = 0.0           # probability of delaying
    delay_s: float = 0.01        # how long a delayed frame is held
    dup: float = 0.0
    reorder: float = 0.0
    error: float = 0.0
    max_fires: Optional[int] = None   # stop firing after N faults
    # wall-time envelope multiplying every probability (None = always on)
    schedule: Optional[FaultSchedule] = None
    fires: int = field(default=0, compare=False)

    def matches(self, method: str, peer: str, kind: str,
                src: str = "") -> bool:
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if not fnmatch.fnmatchcase(kind, self.kind):
            return False
        if not fnmatch.fnmatchcase(method, self.method):
            return False
        if self.peer is not None and not fnmatch.fnmatchcase(
                peer, self.peer):
            return False
        if self.src != "*" and not fnmatch.fnmatchcase(
                src or "", self.src):
            return False
        return True

    def as_dict(self) -> dict:
        return {"method": self.method, "peer": self.peer, "kind": self.kind,
                "src": self.src,
                "drop": self.drop, "delay": self.delay,
                "delay_s": self.delay_s, "dup": self.dup,
                "reorder": self.reorder, "error": self.error,
                "max_fires": self.max_fires,
                "schedule": (self.schedule.as_dict()
                             if self.schedule is not None else None),
                "fires": self.fires}


class FaultInjected(Exception):
    """Raised at an injection site for `error` faults.  Transport code
    re-raises it as RpcError so callers exercise their normal failure
    handling — the type exists so logs can tell injected faults from
    organic ones."""


class FaultPlan:
    """A seeded set of fault rules + connection-level faults.

    Build one, add rules (chainable), then `faults.install(plan)`:

        plan = (FaultPlan(seed=7)
                .rule(method="broadcast*", drop=0.2, delay=0.3,
                      delay_s=0.05, dup=0.2))
        faults.install(plan)
        ...
        faults.uninstall()
    """

    def __init__(self, seed: int = 0, name: str = "", clock=None):
        self.seed = int(seed)
        self.name = name or f"plan-{seed}"
        # schedule time base: elapsed wall time since install();
        # injectable so tests replay envelopes without sleeping
        self._clock = clock or time.time
        self._rand = random.Random(self.seed)
        self._lock = threading.Lock()
        self.rules: List[FaultRule] = []
        self._severed: set = set()              # "host:port" strings
        # per-channel parked frame for `reorder` (adjacent swap)
        self._held: Dict[int, Callable[[], None]] = {}
        self.fired: Dict[str, int] = {a: 0 for a in ACTIONS}
        self.fired["sever_refused"] = 0
        self.installed_at: Optional[float] = None

    # -- building -----------------------------------------------------------

    def rule(self, **kw) -> "FaultPlan":
        sched = kw.pop("schedule", None)
        if isinstance(sched, dict):
            sched = FaultSchedule(**sched)
        self.rules.append(FaultRule(schedule=sched, **kw))
        return self

    def links(self, matrix: Dict, schedule=None) -> "FaultPlan":
        """Compile a per-link latency/loss matrix into rules.

        `matrix` maps (src, dst) -> link properties, where `src` is an
        fnmatch pattern on the dialing identity's mspid, `dst` one on
        the remote "host:port", and the properties are:

          latency_s   one-way propagation delay added to EVERY frame
                      on the link (delay probability 1.0)
          loss        frame loss probability in [0, 1]
          jitter_s    reserved label, recorded but not yet modeled

        Direction matters — (A, B) and (B, A) are independent links, so
        asymmetric paths (fast A->B, slow trans-oceanic B->A) are one
        entry each.  Entries compile in sorted order so rule order (and
        with it the PRNG draw sequence) is independent of dict
        insertion order; an optional `schedule` envelope is attached to
        every link rule and — like all schedules — scales probabilities
        BEFORE the compare without consuming extra draws.
        """
        sched = schedule
        if isinstance(sched, dict):
            sched = FaultSchedule(**sched)
        for (src, dst) in sorted(matrix):
            props = dict(matrix[(src, dst)])
            latency = float(props.get("latency_s", 0.0))
            loss = float(props.get("loss", 0.0))
            self.rules.append(FaultRule(
                src=str(src), peer=str(dst),
                drop=loss,
                delay=1.0 if latency > 0.0 else 0.0,
                delay_s=latency,
                schedule=sched))
        return self

    # -- connection-level faults --------------------------------------------

    def sever(self, addr) -> "FaultPlan":
        """Refuse new dials to `addr` and cut live channels dialed to it."""
        a = _addr_str(addr)
        with self._lock:
            self._severed.add(a)
        with _DIALED_LOCK:
            victims = [ch for ch in _DIALED
                       if getattr(ch, "remote_addr_str", None) == a]
        for ch in victims:
            try:
                ch.close()
            except Exception:
                pass
        logger.info("fault plan %s: severed %s (%d live channels cut)",
                    self.name, a, len(victims))
        return self

    def isolate(self, addrs: Sequence) -> "FaultPlan":
        """Sever a node group: the reachable expression of a partition."""
        for a in addrs:
            self.sever(a)
        return self

    def heal(self, addr=None) -> "FaultPlan":
        """Clear severs (one endpoint, or all) and release parked frames."""
        with self._lock:
            if addr is None:
                self._severed.clear()
            else:
                self._severed.discard(_addr_str(addr))
            held = list(self._held.values())
            self._held.clear()
        for send in held:
            try:
                send()
            except Exception:
                pass
        return self

    def is_severed(self, addr) -> bool:
        with self._lock:
            return _addr_str(addr) in self._severed

    # -- the frame hook ------------------------------------------------------

    def apply(self, channel_key: int, method: str, peer, kind: str,
              send: Callable[[], None], src: str = "") -> None:
        """Decide and apply faults for one outbound frame.  `send` is a
        closure performing the actual transmission; it is called 0, 1 or
        2 times depending on the decision.  `src` is the dialing
        identity's mspid when the transport tagged the channel (link-
        matrix rules match on it; untagged frames only match src="*")."""
        peer_s = _addr_str(peer) if peer is not None else ""
        action = None
        delay_s = 0.0
        now = self._clock()
        elapsed = now - (self.installed_at
                         if self.installed_at is not None else now)
        with self._lock:
            for r in self.rules:
                if not r.matches(method, peer_s, kind, src):
                    continue
                # the wall-time envelope scales every probability; a
                # candidate action with p > 0 still consumes exactly one
                # draw even at factor 0, so the draw sequence is the
                # same in and out of the envelope's active phase
                factor = (r.schedule.factor(elapsed)
                          if r.schedule is not None else 1.0)
                # one PRNG draw per candidate action, in fixed order
                for a in ACTIONS:
                    p = getattr(r, a if a != "delay" else "delay")
                    if p > 0.0 and self._rand.random() < p * factor:
                        action = a
                        delay_s = r.delay_s
                        r.fires += 1
                        break
                if action is not None:
                    break
            if action is not None:
                self.fired[action] += 1
            # reorder bookkeeping happens under the lock
            if action == "reorder":
                prev = self._held.pop(channel_key, None)
                self._held[channel_key] = send
            elif self._held:
                prev = self._held.pop(channel_key, None)
            else:
                prev = None
        if action is not None:
            self._observe(action, method, peer_s)
        if action is None or action == "dup":
            send()
            if action == "dup":
                send()
        elif action == "drop":
            pass                      # the frame dies here
        elif action == "delay":
            time.sleep(delay_s)
            send()
        elif action == "error":
            if prev is not None:
                prev()
            raise FaultInjected(
                f"injected transport error on {method!r} -> {peer_s}")
        # action == "reorder": this frame stays parked; fall through
        if prev is not None and action != "error":
            prev()                    # released AFTER the newer frame

    def _observe(self, action: str, method: str, peer: str) -> None:
        try:
            from fabric_tpu.ops_plane import registry, tracing
            registry.counter(
                "fault_injected_total",
                "frames faulted by the injection plane").add(
                    1, action=action, method=method)
            # annotate the ambient trace: /traces/<id> shows why a tx
            # crawled under chaos
            tracing.event("fault." + action, method=method, peer=peer)
        except Exception:
            pass                      # observability never breaks the plane

    # -- introspection (GET /faults) ----------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "seed": self.seed,
                    "installed_at": self.installed_at,
                    "rules": [r.as_dict() for r in self.rules],
                    "severed": sorted(self._severed),
                    "held_frames": len(self._held),
                    "fired": dict(self.fired)}


# -- process-global install ---------------------------------------------------


def install(plan: FaultPlan) -> FaultPlan:
    """Make `plan` the process-global fault plan (tests/chaos only)."""
    global _PLAN
    with _INSTALL_LOCK:
        plan.installed_at = plan._clock()   # schedule t=0 (time.time
        _PLAN = plan                        # unless a clock is injected)
    logger.warning("fault plan %s INSTALLED (seed=%d, %d rules)",
                   plan.name, plan.seed, len(plan.rules))
    return plan


def uninstall() -> None:
    global _PLAN
    with _INSTALL_LOCK:
        plan, _PLAN = _PLAN, None
    if plan is not None:
        # release parked frames so no call wedges past the drill
        plan.heal()
        logger.warning("fault plan %s removed; fired=%s",
                       plan.name, plan.fired)


def active() -> Optional[FaultPlan]:
    return _PLAN


# -- ops-plane surface --------------------------------------------------------


def register_routes(ops) -> None:
    """Mount `GET /faults` on an OperationsServer: the active plan's
    snapshot, or {"active": false} in production (no plan)."""

    def _faults(path: str, body: bytes):
        plan = _PLAN
        if plan is None:
            return 200, {"active": False}
        out = plan.snapshot()
        out["active"] = True
        return 200, out

    ops.register_route("GET", "/faults", _faults)
