"""Request/response + one-way messaging over SecureChannel.

The service plane of the framework: Broadcast/Deliver/Endorser/cluster
RPCs all speak this little protocol, the role the reference gives gRPC
(/root/reference/internal/pkg/comm/server.go, orderer/common/cluster/comm.go:116).

Frames (inside the encrypted channel) are serde dicts:
  {"kind": "req",  "id": n, "method": str, "body": dict}
  {"kind": "resp", "id": n, "ok": bool, "body": dict | "error": str}
  {"kind": "cast", "method": str, "body": dict}      (one-way)
Responses may be streamed: {"kind": "stream", "id": n, "body": dict,
"done": bool} — used by Deliver.
"""

from __future__ import annotations

import logging
import time as _time
import threading
import weakref
from typing import Callable, Dict, Optional

from fabric_tpu.ops_plane import tracing
from fabric_tpu.utils import serde

from . import faults as _faults
from .secure import SecureChannel, SecureServer, dial

logger = logging.getLogger("fabric_tpu.comm.rpc")


class RpcError(Exception):
    pass


class RpcTimeout(RpcError):
    """No response within the deadline (frame lost, peer wedged, or the
    reply is still in flight)."""


class RpcClosed(RpcError):
    """The underlying channel is gone — retry means re-dialing, not
    waiting.  Replaces the old string-matched 'connection closed'."""


def _send_frame(ch: SecureChannel, frame: dict, method: str,
                kind: str) -> None:
    """All outbound frames funnel through here so the fault plane sees
    them.  Production cost: one module-attribute load when no plan is
    installed."""
    data = serde.encode(frame)
    plan = _faults._PLAN
    if plan is None:
        ch.send(data)
    else:
        plan.apply(id(ch), method, getattr(ch, "remote_addr_str", None),
                   kind, lambda: ch.send(data),
                   src=getattr(ch, "local_src_str", ""))


class RpcConnection:
    """Client side: concurrent requests over one channel.

    stream_views=True decodes incoming frames with serde.decode_views:
    bytes values arrive as read-only memoryviews into the received frame
    buffer instead of copies.  Opt-in per connection — only consumers
    that treat frame bytes as immutable spans (the deliver stream's
    zero-copy block ingest) should ask for it.
    """

    def __init__(self, channel: SecureChannel, stream_views: bool = False):
        self.channel = channel
        self.stream_views = bool(stream_views)
        self._next_id = 1
        self._lock = threading.Lock()
        self._waiters: Dict[int, "_Waiter"] = {}
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        decode = serde.decode_views if self.stream_views else serde.decode
        try:
            while True:
                msg = decode(self.channel.recv())
                wid = msg.get("id")
                with self._lock:
                    w = self._waiters.get(wid)
                if w is not None:
                    w.push(msg)
        except Exception:
            with self._lock:
                self._closed = True
                waiters = list(self._waiters.values())
            for w in waiters:
                w.push({"kind": "resp", "ok": False, "closed": True,
                        "error": "connection closed"})

    def call(self, method: str, body: dict, timeout: float = 30.0) -> dict:
        w = self._start(method, body)
        msg = w.next(timeout)
        self._finish(w)
        if msg.get("kind") == "resp" and not msg.get("ok", False):
            if msg.get("closed"):
                raise RpcClosed(msg.get("error", "connection closed"))
            raise RpcError(msg.get("error", "remote error"))
        return msg.get("body", {})

    def call_stream(self, method: str, body: dict):
        """Generator of streamed bodies until done.  Abandoning the
        generator sends a cancel so the server stops producing."""
        w = self._start(method, body)
        finished = False
        try:
            while True:
                msg = w.next(timeout=60.0)
                if msg.get("kind") == "resp":
                    finished = True
                    if not msg.get("ok", False):
                        if msg.get("closed"):
                            raise RpcClosed(
                                msg.get("error", "connection closed"))
                        raise RpcError(msg.get("error", "remote error"))
                    return
                yield msg.get("body", {})
                if msg.get("done"):
                    finished = True
                    return
        finally:
            self._finish(w)
            if not finished:
                try:
                    self.channel.send(serde.encode(
                        {"kind": "cancel", "id": w.rid}))
                except Exception:
                    pass

    def cast(self, method: str, body: dict,
             fault_label: Optional[str] = None) -> None:
        """fault_label refines what the fault plane matches as the
        `method` of this frame (e.g. "gossip.msg/gossip.block" for a
        multiplexed gossip cast) — the wire method is unchanged."""
        frame = {"kind": "cast", "method": method, "body": body}
        tp = tracing.tracer.traceparent()
        if tp:
            frame["tp"] = tp
        try:
            _send_frame(self.channel, frame, fault_label or method, "cast")
        except _faults.FaultInjected as exc:
            raise RpcError(str(exc)) from None
        except OSError as exc:
            raise RpcClosed(f"connection closed: {exc}") from None

    def _start(self, method, body) -> "_Waiter":
        with self._lock:
            if self._closed:
                raise RpcClosed("connection closed")
            rid = self._next_id
            self._next_id += 1
            w = _Waiter(rid)
            self._waiters[rid] = w
        frame = {"kind": "req", "id": rid, "method": method, "body": body}
        tp = tracing.tracer.traceparent()
        if tp:
            frame["tp"] = tp
        try:
            _send_frame(self.channel, frame, method, "req")
        except _faults.FaultInjected as exc:
            self._finish(w)
            raise RpcError(str(exc)) from None
        except OSError as exc:
            self._finish(w)
            raise RpcClosed(f"connection closed: {exc}") from None
        return w

    def _finish(self, w: "_Waiter") -> None:
        with self._lock:
            self._waiters.pop(w.rid, None)

    def close(self) -> None:
        self.channel.close()


class _Waiter:
    def __init__(self, rid: int):
        self.rid = rid
        self._cond = threading.Condition()
        self._queue = []

    def push(self, msg) -> None:
        with self._cond:
            self._queue.append(msg)
            self._cond.notify()

    def next(self, timeout: float):
        with self._cond:
            if not self._cond.wait_for(lambda: self._queue, timeout=timeout):
                raise RpcTimeout("rpc timeout")
            return self._queue.pop(0)


class RpcServer:
    """Server side: SecureServer + method dispatch.

    handler(method, body, peer_identity) -> dict           (unary)
    stream handlers yield dicts; register with `serve_stream`.
    cast handlers return None; register with `serve_cast`.
    """

    def __init__(self, host: str, port: int, signer, msps: Dict):
        self._unary: Dict[str, Callable] = {}
        self._stream: Dict[str, Callable] = {}
        self._cast: Dict[str, Callable] = {}
        self._cancelled: dict = {}         # (channel id, rid) -> True
        self._cancel_lock = threading.Lock()
        # accepted channels, so stop() can tear down live connections —
        # without this a stopped server's port stays claimed by
        # ESTABLISHED sockets and a restart on the same port fails
        self._channels: "weakref.WeakSet" = weakref.WeakSet()
        self.server = SecureServer(host, port, signer, msps, self._on_channel)

    @property
    def addr(self):
        return self.server.addr

    def serve(self, method: str, fn: Callable) -> None:
        self._unary[method] = fn

    def serve_stream(self, method: str, fn: Callable) -> None:
        self._stream[method] = fn

    def serve_cast(self, method: str, fn: Callable) -> None:
        self._cast[method] = fn

    def start(self) -> "RpcServer":
        self.server.start()
        return self

    def stop(self) -> None:
        self.server.stop()
        for ch in list(self._channels):
            try:
                ch.close()
            except OSError:
                pass

    def _on_channel(self, ch: SecureChannel) -> None:
        self._channels.add(ch)
        threading.Thread(target=self._conn_loop, args=(ch,),
                         daemon=True).start()

    def _conn_loop(self, ch: SecureChannel) -> None:
        try:
            while True:
                msg = serde.decode(ch.recv())
                kind = msg.get("kind")
                if kind == "cast":
                    fn = self._cast.get(msg["method"])
                    if fn is not None:
                        ctx = tracing.tracer.context_from(msg.get("tp"))
                        try:
                            with tracing.tracer.start_span(
                                    "rpc." + msg["method"], parent=ctx,
                                    require_parent=True):
                                fn(msg.get("body", {}), ch.peer_identity)
                        except Exception:
                            logger.exception("cast handler %s failed",
                                             msg["method"])
                    continue
                if kind == "cancel":
                    with self._cancel_lock:
                        self._cancelled[(id(ch), msg.get("id"))] = True
                    continue
                if kind != "req":
                    continue
                threading.Thread(
                    target=self._handle_req, args=(ch, msg), daemon=True
                ).start()
        except Exception:
            ch.close()

    def _handle_req(self, ch: SecureChannel, msg: dict) -> None:
        rid = msg["id"]
        method = msg["method"]
        body = msg.get("body", {})
        t0 = _time.perf_counter()
        ok = True
        # continue the caller's trace (W3C traceparent carried in the
        # frame's "tp" field); no tp => no span, untraced traffic is free
        ctx = tracing.tracer.context_from(msg.get("tp"))
        span = tracing.tracer.start_span("rpc." + method, parent=ctx,
                                         require_parent=True)
        span.__enter__()
        try:
            if method in self._stream:
                key = (id(ch), rid)
                for item in self._stream[method](body, ch.peer_identity):
                    with self._cancel_lock:
                        if self._cancelled.pop(key, False):
                            return
                    _send_frame(ch, {"kind": "stream", "id": rid,
                                     "body": item, "done": False},
                                method, "stream")
                _send_frame(ch, {"kind": "resp", "id": rid, "ok": True,
                                 "body": {}}, method, "resp")
                return
            fn = self._unary.get(method)
            if fn is None:
                raise RpcError(f"unknown method {method!r}")
            out = fn(body, ch.peer_identity)
            _send_frame(ch, {"kind": "resp", "id": rid, "ok": True,
                             "body": out or {}}, method, "resp")
        except Exception as exc:
            ok = False
            if span.recording:
                span.set_attribute("error", str(exc)[:200])
            try:
                ch.send(serde.encode({"kind": "resp", "id": rid, "ok": False,
                                      "error": str(exc)[:500]}))
            except Exception:
                pass
        finally:
            if span.recording:
                span.set_attribute("ok", ok)
                span.status = "OK" if ok else "ERROR"
            span.__exit__(None, None, None)
            _observe_rpc(method, ok, _time.perf_counter() - t0)


def _observe_rpc(method: str, ok: bool, seconds: float) -> None:
    """RPC interceptor metrics (the reference's grpcmetrics unary/stream
    interceptors, common/grpcmetrics/interceptor.go): per-method request
    counts by outcome + duration histograms into the ops-plane registry."""
    try:
        from fabric_tpu.ops_plane import registry
        registry.counter(
            "rpc_requests_total", "RPC requests served").add(
                1, method=method, code="OK" if ok else "ERROR")
        registry.histogram(
            "rpc_request_duration_seconds",
            "RPC handler wall time").observe(seconds, method=method)
    except Exception:
        pass      # metrics must never break the request path


def connect(addr, signer, msps: Dict, timeout: float = 10.0,
            stream_views: bool = False) -> RpcConnection:
    return RpcConnection(dial(addr, signer, msps, timeout=timeout),
                         stream_views=stream_views)
