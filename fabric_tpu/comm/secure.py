"""Authenticated, encrypted point-to-point channels over TCP.

Reference parity (VERDICT.md missing #3 / weak #4): the reference runs
every plane over gRPC with mutual TLS plus, for gossip, a signed
connection handshake binding the TLS channel to the peer's MSP identity
(/root/reference/internal/pkg/comm/creds.go, gossip/comm/comm_impl.go:134-169).

TPU-native redesign rather than a TLS stack: a direct mutually
authenticated key agreement using the framework's own identity plane —
  1. each side sends  hello = {identity: <serialized MSP identity>,
     eph: <X25519 public>, nonce}
  2. each side signs the transcript hash H(client_hello || server_hello)
     with its MSP signing key and sends the signature,
  3. both verify the peer's certificate chain against the channel MSPs
     and the transcript signature with the certificate's key — the
     channel is now bound to the MSP identity (no unknown-org peers),
  4. traffic keys = HKDF(X25519 shared secret, transcript hash), one
     ChaCha20-Poly1305 key per direction, counter nonces; frames are
     length-prefixed ciphertexts.

This gives the same guarantees the reference's mTLS+handshake does
(mutual authentication to the MSP trust roots, confidentiality,
integrity, replay protection within a connection) with one fewer
moving part (no X.509-for-TLS second certificate hierarchy).
"""

from __future__ import annotations

import hashlib
import os
import socket
import struct
import threading
from typing import Callable, Dict, Optional

from fabric_tpu.crypto import (
    Aead,
    X25519PrivateKey,
    X25519PublicKey,
    hkdf_sha256,
)

from fabric_tpu.utils import serde

from . import faults as _faults

_FRAME = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024


class HandshakeError(Exception):
    pass


def _hkdf(secret: bytes, transcript: bytes, label: bytes) -> bytes:
    return hkdf_sha256(secret, salt=transcript, info=label, length=32)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _read_frame(sock) -> bytes:
    (ln,) = _FRAME.unpack(_read_exact(sock, 4))
    if ln > MAX_FRAME:
        raise ConnectionError("oversized frame")
    return _read_exact(sock, ln)


def _write_frame(sock, data: bytes) -> None:
    sock.sendall(_FRAME.pack(len(data)) + data)


class SecureChannel:
    """One established, authenticated connection."""

    def __init__(self, sock: socket.socket, peer_identity, send_key: bytes,
                 recv_key: bytes):
        self._sock = sock
        self.peer_identity = peer_identity      # verified msp Identity
        self._send = Aead(send_key)
        self._recv = Aead(recv_key)
        self._send_ctr = 0
        self._recv_ctr = 0
        self._wlock = threading.Lock()
        # "host:port" this channel was dialed to (None on the accept side);
        # lets the fault plane sever by endpoint
        self.remote_addr_str: Optional[str] = None

    def send(self, payload: bytes) -> None:
        with self._wlock:
            nonce = self._send_ctr.to_bytes(12, "little")
            self._send_ctr += 1
            _write_frame(self._sock, self._send.encrypt(nonce, payload, b""))

    def recv(self) -> bytes:
        ct = _read_frame(self._sock)
        nonce = self._recv_ctr.to_bytes(12, "little")
        self._recv_ctr += 1
        return self._recv.decrypt(nonce, ct, b"")

    def close(self) -> None:
        # shutdown BEFORE close: a reader thread blocked in recv()
        # keeps the kernel file alive through close(), so bare close()
        # never sends FIN — the reader (and the peer's) blocks forever
        # and the socket + thread pair leaks.  shutdown() wakes it.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def _verify_peer(hello: dict, transcript: bytes, sig: bytes, msps: Dict):
    """Deserialize + chain-validate the peer identity against the channel
    MSPs, then check the transcript signature.  Returns the Identity."""
    from fabric_tpu.msp import deserialize_from_msps

    ident = deserialize_from_msps(msps, hello["identity"])
    if ident is None:
        raise HandshakeError("peer identity not valid in any channel MSP")
    from fabric_tpu.bccsp.factory import get_default
    item = ident.verify_item(transcript, sig)
    ok = get_default().batch_verify([item])
    if not bool(ok[0]):
        raise HandshakeError("bad handshake transcript signature")
    return ident


def _handshake(sock: socket.socket, signer, msps: Dict,
               initiator: bool) -> SecureChannel:
    eph = X25519PrivateKey.generate()
    my_hello = serde.encode({
        "identity": signer.serialize(),
        "eph": eph.public_key().public_bytes_raw(),
        "nonce": os.urandom(16),
    })
    if initiator:
        _write_frame(sock, my_hello)
        peer_hello_b = _read_frame(sock)
        transcript = hashlib.sha256(my_hello + peer_hello_b).digest()
    else:
        peer_hello_b = _read_frame(sock)
        _write_frame(sock, my_hello)
        transcript = hashlib.sha256(peer_hello_b + my_hello).digest()
    peer_hello = serde.decode(peer_hello_b)

    my_sig = signer.sign(transcript)
    _write_frame(sock, my_sig)
    peer_sig = _read_frame(sock)
    ident = _verify_peer(peer_hello, transcript, peer_sig, msps)

    shared = eph.exchange(X25519PublicKey.from_public_bytes(peer_hello["eph"]))
    k_init = _hkdf(shared, transcript, b"fabric-tpu-i2r")
    k_resp = _hkdf(shared, transcript, b"fabric-tpu-r2i")
    if initiator:
        return SecureChannel(sock, ident, k_init, k_resp)
    return SecureChannel(sock, ident, k_resp, k_init)


def dial(addr, signer, msps: Dict, timeout: float = 10.0) -> SecureChannel:
    plan = _faults._PLAN
    if plan is not None and plan.is_severed(addr):
        plan.fired["sever_refused"] += 1
        raise ConnectionRefusedError(
            f"fault plane: endpoint {_faults._addr_str(addr)} is severed")
    sock = socket.create_connection(addr, timeout=timeout)
    sock.settimeout(timeout)
    ch = _handshake(sock, signer, msps, initiator=True)
    sock.settimeout(None)
    ch.remote_addr_str = _faults._addr_str(addr)
    # source tag for per-link fault matrices: the dialing identity's
    # mspid (the only source name available at dial time — in-process
    # topologies share one fault plan, so link rules are scoped
    # src=mspid -> dst="host:port")
    ch.local_src_str = getattr(signer, "mspid", "") or ""
    _faults.register_channel(ch)
    return ch


class SecureServer:
    """Accept loop running handshakes; hands channels to `on_channel`."""

    def __init__(self, host: str, port: int, signer, msps: Dict,
                 on_channel: Callable[[SecureChannel], None]):
        self.signer = signer
        self.msps = msps
        self.on_channel = on_channel
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(64)
        self.addr = self._lsock.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "SecureServer":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._lsock.accept()
            except OSError:
                return
            threading.Thread(target=self._accept_one, args=(sock,),
                             daemon=True).start()

    def _accept_one(self, sock) -> None:
        try:
            sock.settimeout(10.0)
            ch = _handshake(sock, self.signer, self.msps, initiator=False)
            sock.settimeout(None)
        except Exception:
            try:
                sock.close()
            except OSError:
                pass
            return
        self.on_channel(ch)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
