"""Device-resident block validation: one fused XLA dispatch per block.

Today a block's journey is C parse -> device sig-verify -> host gate ->
host MVCC.  This module closes the loop on-device (ROADMAP direction #1,
Blockchain Machine arxiv 2104.06968): the policy-gate verdict fold AND
MVCC conflict detection run as ONE jit-compiled program per block,
sharded over the parallel/mesh.py batch mesh, so the only host work
between wire intake and commit-apply is the final state write.

Inputs come from the zero-copy lane tables emitted by
native/fastparse.c `rwset_lanes` (protocol/wire.BlockView.rwset_lanes):
rw-set keys hashed to uint64 and interned to dense slots, read versions
and write spans as fixed-width integer lanes.  The host never builds an
Envelope, a TxRwSet, or a conflict graph on this path.

Correctness contract (the round-8 serial oracle is the bit-identity
gate): flags, UpdateBatch insertion order, state/history rows, and the
commit-hash must be literally identical to
`ledger/mvcc.validate_and_prepare_batch` run after `fastcollect.gate`.
Correctness never depends on key-hash uniqueness: a uint64 collision is
detected host-side while interning (byte compare under equal hash) and
the block DEMOTES to the host path.  Every other inexpressible shape
(range queries, non-i32 versions, >8-wide policy sig-sets, stale
savepoint...) demotes the same way, counted per reason in
`validator_device_demotions_total`.

Policy equivalence: fastcollect.gate evaluates `plugin(policy,
valid_idents, evaluator)` per plan entry with a per-block memo keyed
`(id(policy), *map(id, valid))`.  A sig-set of k live items has only
2^k possible valid subsets, so the fold is expressible as a k-bit
truth table per entry (k <= 8, else demote): the device ORs verdict
bits into a mask and gathers table[mask].  Tables are built host-side
with the same memo key shape, so an impure-but-memoised plugin sees
the same call pattern per unique subset.

Exactly-one-dispatch: all demotion checks run BEFORE the program call;
a device-validated block therefore issues exactly one dispatch
(`validator_device_dispatches_total`), asserted by the smoke gate.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from fabric_tpu.protocol import Version
from fabric_tpu.protocol.txflags import TxFlags

# lane status codes (native/fastparse.c rwset_lanes / wire.LANE_*)
_OK, _SKIP, _BAD, _RANGE, _UNKNOWN = 0, 1, 2, 3, 4

_I32_MIN, _I32_MAX = -(1 << 31), (1 << 31) - 1


class _Demote(Exception):
    """Block cannot (or must not) take the device path; fall back to the
    host gate + serial/wavefront MVCC.  Never an error: the host path is
    always correct."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _note(kind: str, n: int = 1, **labels) -> None:
    try:
        from fabric_tpu.ops_plane import registry
        registry.counter(*kind).add(n, **labels)
    except Exception:
        pass


_C_DISPATCH = ("validator_device_dispatches_total",
               "fused gate+MVCC device dispatches (one per "
               "device-validated block)")
_C_BLOCKS = ("validator_device_blocks_total",
             "blocks fully validated by the fused device program")
_C_DEMOTE = ("validator_device_demotions_total",
             "blocks demoted to the host validation path, by reason")
_C_STASH_MISS = ("validator_device_stash_misses_total",
                 "prepared-batch stash lookups that missed (flags or "
                 "savepoint changed between validate and commit)")


# jitted programs depend only on bucket shapes + the device set, so the
# cache is process-wide: many DeviceValidator instances (one per channel,
# or per test stack) share compilations
_PROGRAMS: Dict[tuple, object] = {}


def _pow2(n: int, floor: int) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def _pad(a: np.ndarray, size: int, fill) -> np.ndarray:
    if a.shape[0] == size:
        return a
    out = np.full(size, fill, dtype=a.dtype)
    out[:a.shape[0]] = a
    return out


class DeviceValidator:
    """Per-channel fused gate+MVCC validator.

    Wiring (node/peer.py): construct one per channel, pass it to
    TxValidator(device_validate=...) and register `take_prepared` with
    KVLedger.set_prepared_source so commit() can consume the prepared
    UpdateBatch instead of re-running host MVCC.
    """

    # stash of prepared commits awaiting ledger consumption
    _STASH_CAP = 16

    def __init__(self, statedb, channel_id: str = "",
                 devices=None, window: int = 4096):
        self.statedb = statedb
        self.channel_id = channel_id
        self.window = window          # max txs per fused program
        self._devices = devices
        self._mesh = None
        self._mesh_built = False
        self._stash: Dict[int, tuple] = {}
        self._lock = threading.Lock()

    # -- mesh ---------------------------------------------------------------

    def _get_mesh(self):
        """1-D batch mesh over the configured devices; None (single-
        device jit) when the device count is 1 or not a power of two."""
        if self._mesh_built:
            return self._mesh
        import jax
        from fabric_tpu.parallel import mesh as meshmod
        devs = self._devices if self._devices is not None else jax.devices()
        n = len(devs)
        if n > 1 and (n & (n - 1)) == 0:
            self._mesh = meshmod.make_mesh(list(devs))
        self._mesh_built = True
        return self._mesh

    def _mesh_floor(self) -> int:
        mesh = self._get_mesh()
        return max(8, mesh.devices.size) if mesh is not None else 8

    # -- lane extraction ----------------------------------------------------

    @staticmethod
    def _lanes_of(block):
        """(lanes_tuple, base_bytes) for a BlockView (zero-copy) or a
        materialized protocol Block (spans synthesized once)."""
        lanes = getattr(block, "rwset_lanes", None)
        if lanes is not None:
            return lanes, block.raw
        from fabric_tpu.protocol import wire
        parts: List[bytes] = []
        spans = bytearray()
        off = 0
        for raw in block.data:
            if not isinstance(raw, (bytes, bytearray, memoryview)):
                raw = raw.serialize()
            raw = bytes(raw)
            spans += struct.pack("QQ", off, len(raw))
            parts.append(raw)
            off += len(raw)
        base = b"".join(parts)
        return wire.rwset_lanes(base, bytes(spans)), base

    # -- the public entry points --------------------------------------------

    def run(self, state: dict, verdict, plugin, evaluator
            ) -> Optional[TxFlags]:
        """Validate one deep-collected block on-device.

        Returns the post-gate (pre-MVCC) TxFlags the txvalidator should
        stamp into block metadata — exactly what fastcollect.gate would
        have produced — or None to demote to the host path.  On success
        the final flags + prepared UpdateBatch/history rows are stashed
        for the ledger (take_prepared)."""
        block = state["block"]
        num = int(block.header.number)
        try:
            return self._run_inner(state, verdict, plugin, evaluator, num)
        except _Demote as d:
            _note(_C_DEMOTE, channel=self.channel_id, reason=d.reason)
            return None
        except Exception:
            # correctness never depends on this path existing
            _note(_C_DEMOTE, channel=self.channel_id, reason="error")
            return None

    def take_prepared(self, number: int, flags_bytes: bytes,
                      savepoint) -> Optional[tuple]:
        """Ledger-side consumption: (final_flags_bytes, batch, history)
        for `number` iff the metadata flags and the statedb savepoint
        still match what the device program validated against; else None
        (host MVCC re-runs — always safe)."""
        with self._lock:
            ent = self._stash.pop(number, None)
        if ent is None:
            return None
        gate_bytes, sp, final_bytes, batch, history = ent
        if bytes(flags_bytes) != gate_bytes or savepoint != sp:
            _note(_C_STASH_MISS, channel=self.channel_id)
            return None
        return final_bytes, batch, history

    # -- the block walk -----------------------------------------------------

    def _run_inner(self, state, verdict, plugin, evaluator, num):
        db = self.statedb
        sp = db.savepoint
        if (-1 if sp is None else sp) != num - 1:
            raise _Demote("savepoint")
        if not (0 <= num <= _I32_MAX):
            raise _Demote("block_num")

        block = state["block"]
        pre = np.frombuffer(bytes(state["codes"]), dtype=np.uint8)
        T = pre.shape[0]
        if T == 0 or T > self.window:
            raise _Demote("window")

        lanes, base = self._lanes_of(block)
        if lanes is None:
            raise _Demote("extract")
        lflags, lt, lk, lr, lw, arena = lanes
        if lflags:
            raise _Demote("hash_collision")
        if lt != T:
            raise _Demote("extract")

        arr = np.frombuffer(arena, dtype=np.uint64)
        o = 0
        tx_sec = arr[o:o + 3 * lt].reshape(lt, 3); o += 3 * lt
        rd = arr[o:o + 5 * lr].reshape(lr, 5); o += 5 * lr
        wr = arr[o:o + 5 * lw].reshape(lw, 5); o += 5 * lw
        ky = arr[o:o + 5 * lk].reshape(lk, 5)
        status = tx_sec[:, 0].astype(np.int32)

        plans = state["plans"]
        for plan in plans:
            st = status[plan[0]]
            if st == _RANGE:
                raise _Demote("range_query")
            if st == _UNKNOWN:
                raise _Demote("inexpressible")

        gate_in = self._build_gate(plans, verdict, plugin, evaluator, T)
        key_strs, c_arrs = self._gather_committed(db, ky, base, lk)

        gate_bytes, final = self._dispatch(
            pre, status, gate_in, rd, wr, c_arrs, num, lr, lw, lk)

        batch, history = self._rebuild(final, tx_sec, wr, key_strs,
                                       base, num, lw)
        # pre-split by state shard off the commit lock path; the
        # ledger's apply_updates consumes the cached split
        batch.preshard(getattr(self.statedb, "n_shards", 1))
        final_bytes = bytes(final)
        with self._lock:
            self._stash[num] = (gate_bytes, sp, final_bytes, batch, history)
            while len(self._stash) > self._STASH_CAP:
                self._stash.pop(min(self._stash))
        _note(_C_BLOCKS, channel=self.channel_id)
        return TxFlags.from_bytes(gate_bytes)

    # -- gate plan -> truth tables ------------------------------------------

    @staticmethod
    def _build_gate(plans, verdict, plugin, evaluator, T):
        """Flatten fastcollect.assemble plans into entry/sig lanes plus
        per-entry truth tables.  Memo key shape matches gate()'s
        per-block memo: (id(policy), *map(id, valid))."""
        nv = len(verdict)
        has_plan = np.zeros(T, dtype=np.int32)
        c_idx = np.zeros(T, dtype=np.int32)
        c_live = np.zeros(T, dtype=np.int32)
        ent_tx: List[int] = []
        ent_off: List[int] = []
        sig_ent: List[int] = []
        sig_item: List[int] = []
        sig_bit: List[int] = []
        tables: List[np.ndarray] = []
        tbl_off = 0
        memo: dict = {}
        for tx, cidx, entries in plans:
            has_plan[tx] = 1
            if 0 <= cidx < nv:
                c_idx[tx] = cidx
                c_live[tx] = 1
            for pol, sigset in entries:
                live = [(idx, ident) for idx, ident in sigset
                        if 0 <= idx < nv]
                k = len(live)
                if k > 8:
                    raise _Demote("policy_width")
                tbl = np.zeros(1 << k, dtype=np.int32)
                for mask in range(1 << k):
                    valid = [ident for i, (_idx, ident) in enumerate(live)
                             if (mask >> i) & 1]
                    mkey = (id(pol),) + tuple(map(id, valid))
                    r = memo.get(mkey)
                    if r is None:
                        try:
                            r = 1 if plugin(pol, valid, evaluator) else 0
                        except Exception:
                            raise _Demote("policy_error")
                        memo[mkey] = r
                    tbl[mask] = r
                erow = len(ent_tx)
                ent_tx.append(tx)
                ent_off.append(tbl_off)
                for i, (idx, _ident) in enumerate(live):
                    sig_ent.append(erow)
                    sig_item.append(idx)
                    sig_bit.append(i)
                tables.append(tbl)
                tbl_off += tbl.shape[0]
        cat = (np.concatenate(tables) if tables
               else np.zeros(1, dtype=np.int32))
        return {"has_plan": has_plan, "c_idx": c_idx, "c_live": c_live,
                "ent_tx": np.asarray(ent_tx, dtype=np.int32),
                "ent_off": np.asarray(ent_off, dtype=np.int32),
                "sig_ent": np.asarray(sig_ent, dtype=np.int32),
                "sig_item": np.asarray(sig_item, dtype=np.int32),
                "sig_bit": np.asarray(sig_bit, dtype=np.int32),
                "tables": cat,
                "verdict": np.asarray(verdict, dtype=np.int32)}

    # -- committed-state gather ---------------------------------------------

    @staticmethod
    def _gather_committed(db, ky, base, K):
        """Decode each interned key slot once and snapshot its committed
        version as i32 lanes; out-of-range versions demote."""
        key_strs: List[Tuple[str, str]] = []
        c_has = np.zeros(K, dtype=np.int32)
        c_blk = np.zeros(K, dtype=np.int32)
        c_txn = np.zeros(K, dtype=np.int32)
        for s in range(K):
            _h, no, nn, ko, kn = (int(x) for x in ky[s])
            ns = bytes(base[no:no + nn]).decode("utf-8")
            key = bytes(base[ko:ko + kn]).decode("utf-8")
            key_strs.append((ns, key))
            vv = db.get(ns, key)
            if vv is None:
                continue
            bn, tn = vv.version.block_num, vv.version.tx_num
            if not (_I32_MIN <= bn <= _I32_MAX
                    and _I32_MIN <= tn <= _I32_MAX):
                raise _Demote("version_range")
            c_has[s] = 1
            c_blk[s] = bn
            c_txn[s] = tn
        return key_strs, (c_has, c_blk, c_txn)

    # -- the fused program ---------------------------------------------------

    @staticmethod
    def _i32(col: np.ndarray) -> np.ndarray:
        # u64 lane -> i32 (two's complement; walkers enforce i32 range
        # for version fields, and offsets/slots are small positives)
        return col.astype(np.int64).astype(np.int32)

    def _dispatch(self, pre, status, g, rd, wr, c_arrs, num, R, W, K):
        floor = self._mesh_floor()
        Tb = _pow2(pre.shape[0], 8)
        Eb = _pow2(max(g["ent_tx"].shape[0], 1), 8)
        Sb = _pow2(max(g["sig_ent"].shape[0], 1), floor)
        Rb = _pow2(max(R, 1), floor)
        Wb = _pow2(max(W, 1), 8)
        Kb = _pow2(max(K, 1), 8)
        TBb = _pow2(g["tables"].shape[0], 8)
        Vb = _pow2(max(g["verdict"].shape[0], 1), 8)

        args = (
            _pad(pre.astype(np.int32), Tb, 255),
            _pad(status, Tb, _SKIP),
            _pad(g["has_plan"], Tb, 0),
            _pad(g["c_idx"], Tb, 0),
            _pad(g["c_live"], Tb, 0),
            _pad(g["ent_tx"], Eb, 0),
            _pad(g["ent_off"], Eb, 0),
            _pad(np.ones(g["ent_tx"].shape[0], dtype=np.int32), Eb, 0),
            _pad(g["sig_ent"], Sb, 0),
            _pad(g["sig_item"], Sb, 0),
            _pad(g["sig_bit"], Sb, 0),
            _pad(np.ones(g["sig_ent"].shape[0], dtype=np.int32), Sb, 0),
            _pad(self._i32(rd[:, 0]), Rb, -1),
            _pad(self._i32(rd[:, 1]), Rb, 0),
            _pad(self._i32(rd[:, 2]), Rb, 0),
            _pad(self._i32(rd[:, 3]), Rb, 0),
            _pad(self._i32(rd[:, 4]), Rb, 0),
            _pad(self._i32(wr[:, 0]), Wb, -1),
            _pad(self._i32(wr[:, 1]), Wb, 0),
            _pad(self._i32(wr[:, 2]), Wb, 0),
            _pad(g["tables"], TBb, 0),
            _pad(g["verdict"], Vb, 0),
            _pad(c_arrs[0], Kb, 0),
            _pad(c_arrs[1], Kb, 0),
            _pad(c_arrs[2], Kb, 0),
            np.int32(num),
        )
        prog = self._program((Tb, Eb, Sb, Rb, Wb, Kb, TBb, Vb))
        _note(_C_DISPATCH, channel=self.channel_id)
        gate_codes, final = prog(*args)
        T = pre.shape[0]
        return (bytes(np.asarray(gate_codes)[:T]),
                np.asarray(final)[:T])

    def _program(self, key):
        mesh0 = self._get_mesh()
        ckey = (key, None if mesh0 is None
                else tuple(d.id for d in mesh0.devices.flat))
        prog = _PROGRAMS.get(ckey)
        if prog is not None:
            return prog
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as PSpec
        from fabric_tpu.parallel.mesh import BATCH_AXIS, _shard_map

        mesh = self._get_mesh()
        use_mesh = mesh is not None

        def local(pre, status, has_plan, c_idx, c_live,
                  ent_tx, ent_off, ent_live,
                  sig_ent, sig_item, sig_bit, sig_live,
                  r_tx, r_slot, r_has, r_blk, r_txn,
                  w_tx, w_slot, w_del,
                  tables, verdict, c_has, c_blk, c_txn, blk_num):
            def ps(x):
                return jax.lax.psum(x, BATCH_AXIS) if use_mesh else x

            Tb, Eb = pre.shape[0], ent_tx.shape[0]
            Wb, Kb = w_tx.shape[0], c_has.shape[0]
            # -- verdict fold: OR verdict bits into per-entry masks,
            #    gather each entry's truth table (fastcollect.gate) -----
            contrib = jnp.where(sig_live != 0,
                                jnp.left_shift(verdict[sig_item], sig_bit),
                                0)
            m = ps(jnp.zeros(Eb, jnp.int32).at[sig_ent].add(contrib))
            ent_ok = jnp.where(ent_live != 0, tables[ent_off + m] != 0,
                               True)
            ent_fail = jnp.zeros(Tb, jnp.int32).at[ent_tx].add(
                jnp.where((ent_live != 0) & ~ent_ok, 1, 0))
            cre_ok = (c_live != 0) & (verdict[c_idx] != 0)
            gate_code = jnp.where(~cre_ok, 4,
                                  jnp.where(ent_fail > 0, 10, 0))
            gate_codes = jnp.where(has_plan != 0, gate_code, pre)
            # the serial oracle stamps BAD_RWSET on gate-valid txs whose
            # rwset walk raises (lane status BAD) during MVCC, not gate
            code0 = jnp.where((gate_codes == 0) & (status == _BAD),
                              22, gate_codes)

            # -- MVCC: in-block last-writer state per key slot ----------
            # wseq[slot] = 1 + global write-lane index of the last
            # applied write (0 = none): exactly the batch-merged view
            # the oracle reads, because lanes are emitted in oracle
            # insertion order and only applied for still-valid txs.
            ch = c_has[r_slot]
            cb = c_blk[r_slot]
            ct = c_txn[r_slot]
            widx = jnp.arange(Wb, dtype=jnp.int32) + 1

            def body(t, carry):
                codes, wseq = carry
                valid = codes[t] == 0
                seq = wseq[r_slot]
                wj = jnp.maximum(seq - 1, 0)
                inb = seq > 0
                deleted = w_del[wj] != 0
                obs_has = jnp.where(inb, jnp.where(deleted, 0, 1), ch)
                obs_blk = jnp.where(inb, blk_num, cb)
                obs_txn = jnp.where(inb, w_tx[wj], ct)
                ok = jnp.where(r_has != 0,
                               (obs_has != 0) & (obs_blk == r_blk)
                               & (obs_txn == r_txn),
                               obs_has == 0)
                nfail = ps(jnp.sum(((r_tx == t) & ~ok)
                                   .astype(jnp.int32)))
                codes = codes.at[t].set(
                    jnp.where(valid & (nfail > 0), 11, codes[t]))
                wm = (w_tx == t) & valid & (nfail == 0)
                wseq = wseq.at[w_slot].max(jnp.where(wm, widx, 0))
                return codes, wseq

            final, _ = jax.lax.fori_loop(
                0, Tb, body, (code0, jnp.zeros(Kb, jnp.int32)))
            return gate_codes.astype(jnp.uint8), final.astype(jnp.uint8)

        if use_mesh:
            rep, sh = PSpec(), PSpec(BATCH_AXIS)
            in_specs = ((rep,) * 5 + (rep,) * 3 + (sh,) * 4 + (sh,) * 5
                        + (rep,) * 3 + (rep,) * 6)
            # check_rep=False: the rep-checker mis-types the fori_loop
            # carry (wseq is replicated — every cross-shard sum is
            # psum'd before it feeds the carry — but the 0.4.x checker
            # can't prove it and rejects the program)
            fn = _shard_map(local, mesh=mesh, in_specs=in_specs,
                            out_specs=(rep, rep), check_rep=False)
        else:
            fn = local
        prog = jax.jit(fn)
        _PROGRAMS[ckey] = prog
        return prog

    # -- batch / history rebuild (oracle insertion order) --------------------

    @staticmethod
    def _rebuild(final, tx_sec, wr, key_strs, base, num, W):
        """Replay the write lanes of final-valid txs in global lane
        order: identical put/delete call sequence (and therefore
        identical UpdateBatch dict order) and identical history rows to
        validate_and_prepare_batch."""
        from fabric_tpu.ledger.statedb import UpdateBatch
        batch = UpdateBatch()
        history: List[tuple] = []
        txids: Dict[int, str] = {}
        for j in range(W):
            t = int(wr[j, 0])
            if final[t] != 0:
                continue
            txid = txids.get(t)
            if txid is None:
                toff, tlen = int(tx_sec[t, 1]), int(tx_sec[t, 2])
                txid = bytes(base[toff:toff + tlen]).decode("utf-8")
                txids[t] = txid
            slot = int(wr[j, 1])
            is_del = bool(wr[j, 2])
            voff, vlen = int(wr[j, 3]), int(wr[j, 4])
            ns, key = key_strs[slot]
            value = bytes(base[voff:voff + vlen])
            version = Version(num, t)
            if is_del:
                batch.delete(ns, key, version)
            else:
                batch.put(ns, key, value, version)
            history.append((t, txid, ns, key, value, is_del))
        return batch, history
