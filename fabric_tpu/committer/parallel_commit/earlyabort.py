"""Early-abort analysis: doom MVCC losers before device dispatch.

A transaction reading (ns, key) at version V can only survive the MVCC
pass if the version it observes at validation time equals V.  What it
can possibly observe is bounded before any signature work happens:

    M = {committed version of (ns, key)}
      ∪ {Version(block, j) : j < tx, j puts (ns, key) in this block}
      ∪ {None               if any j < tx deletes (ns, key)}

— the committed version if no preceding in-block writer lands, or one of
the preceding writers' versions if one does.  M is computed as a
SUPERSET of the observable set (writers that will themselves fail the
gate are still included — that only enlarges M and suppresses dooming),
so V ∉ M proves the tx loses MVCC no matter which txs turn out valid.
Such a tx is flagged MVCC_READ_CONFLICT by the txvalidator before its
VerifyItems are ever enqueued.

Scope guards (all conservative — any doubt means "doom nothing"):
  - only endorser txs that parse cleanly; parse failures stay on the
    BAD_RWSET path;
  - txs with range queries are never doomed (interval phantoms depend
    on which writers land);
  - the committed version must be exactly the pre-block state:
    statedb.savepoint == block_num - 1, which holds under the standard
    Committer.store_block driver (validate runs strictly after the
    previous block's state commit).  A pipelined driver that begins
    block N+1 before block N's state lands fails the guard and gets no
    early aborts for that block — never a wrong flag.

Consensus note: the final flag byte of a doomed tx is MVCC_READ_CONFLICT
even when the skipped signature gate would have said BAD_CREATOR_
SIGNATURE / ENDORSEMENT_POLICY_FAILURE — the tx is invalid either way,
but the byte feeds the commit hash, so `parallel_commit.early_abort`
must be configured uniformly across peers of a channel (README
"Parallel commit").
"""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple

from fabric_tpu.protocol import Envelope
from fabric_tpu.protocol.txflags import ValidationCode

from fabric_tpu.ledger.mvcc import parse_endorser_tx
from fabric_tpu.ledger.statedb import StateDB


class EarlyAbortAnalyzer:
    """Bound to one channel's state DB; stateless across blocks."""

    def __init__(self, statedb: StateDB, channel_id: str = ""):
        self.statedb = statedb
        self.channel_id = channel_id

    def doomed(self, block) -> Dict[int, ValidationCode]:
        """tx_num -> MVCC_READ_CONFLICT for txs that cannot win MVCC.
        Empty when the savepoint guard fails (see module docstring)."""
        db = self.statedb
        blk = int(block.header.number)
        sp = db.savepoint
        if (-1 if sp is None else sp) != blk - 1:
            return {}

        doomed: Dict[int, ValidationCode] = {}
        puts: Dict[Tuple[str, str], Set[Tuple[int, int]]] = {}
        deleted: Set[Tuple[str, str]] = set()
        committed_memo: Dict[Tuple[str, str],
                             Optional[Tuple[int, int]]] = {}

        def committed(k: Tuple[str, str]) -> Optional[Tuple[int, int]]:
            if k not in committed_memo:
                vv = db.get(k[0], k[1])
                committed_memo[k] = (None if vv is None else
                                     (vv.version.block_num,
                                      vv.version.tx_num))
            return committed_memo[k]

        for tx_num, raw in enumerate(block.data):
            try:
                parsed = parse_endorser_tx(Envelope.deserialize(raw))
            except Exception:
                continue
            if parsed is None:
                continue
            _txid, rwset = parsed
            if any(ns_rw.range_queries for ns_rw in rwset.ns_rwsets):
                continue                 # ranges: never doomed here
            dead = False
            for ns_rw in rwset.ns_rwsets:
                ns = ns_rw.namespace
                for read in ns_rw.reads:
                    k = (ns, read.key)
                    v = read.version
                    vt = None if v is None else (v.block_num, v.tx_num)
                    if vt == committed(k):
                        continue
                    if vt is None:
                        if k in deleted:
                            continue
                    elif vt in puts.get(k, ()):
                        continue
                    dead = True
                    break
                if dead:
                    break
            if dead:
                doomed[tx_num] = ValidationCode.MVCC_READ_CONFLICT
                continue                 # a doomed tx's writes never land
            for ns_rw in rwset.ns_rwsets:
                ns = ns_rw.namespace
                for w in ns_rw.writes:
                    k = (ns, w.key)
                    if w.is_delete:
                        deleted.add(k)
                    else:
                        puts.setdefault(k, set()).add((blk, tx_num))
        return doomed
