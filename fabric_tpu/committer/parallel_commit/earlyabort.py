"""Early-abort analysis: doom MVCC losers before device dispatch.

A transaction reading (ns, key) at version V can only survive the MVCC
pass if the version it observes at validation time equals V.  What it
can possibly observe is bounded before any signature work happens:

    M = {committed version of (ns, key)}
      ∪ {Version(block, j) : j < tx, j puts (ns, key) in this block}
      ∪ {None               if any j < tx deletes (ns, key)}

— the committed version if no preceding in-block writer lands, or one of
the preceding writers' versions if one does.  M is computed as a
SUPERSET of the observable set (writers that will themselves fail the
gate are still included — that only enlarges M and suppresses dooming),
so V ∉ M proves the tx loses MVCC no matter which txs turn out valid.
Such a tx is flagged MVCC_READ_CONFLICT by the txvalidator before its
VerifyItems are ever enqueued.

Range queries doom too, when decidable: a scanned interval that is
provably untouched by every preceding in-block write (no recorded
put/delete key falls in [start, end) of that namespace) merges to
exactly the committed range no matter which writers land, so replaying
it against committed state alone decides the oracle's verdict — a
mismatch dooms the tx PHANTOM_READ_CONFLICT.  A touched interval stays
undecidable and suppresses dooming, never flags.

Because the oracle stamps the code of the FIRST failing check in rwset
walk order (reads then ranges, per namespace), a certain failure only
dooms when no EARLIER check of the OTHER kind is uncertain: an
uncertain read before a certainly-failing range could fail first with
MVCC_READ_CONFLICT (and vice versa with PHANTOM_READ_CONFLICT), so
such a tx is known dead but its code byte is not — it is skipped (its
writes still never land) rather than doomed with a guess.

Scope guards (all conservative — any doubt means "doom nothing"):
  - only endorser txs that parse cleanly; parse failures stay on the
    BAD_RWSET path;
  - range queries over intervals touched by any preceding in-block
    write are never doomed (interval phantoms then depend on which
    writers land);
  - the committed version must be accounted for exactly.  Serially that
    means statedb.savepoint == block_num - 1 (validate runs strictly
    after the previous block's state commit).  Under the pipelined
    commit window the savepoint may lag anywhere in [N-W, N-1]; the
    guard then accepts a PendingOverlay covering every block of the gap
    (the window's frozen in-flight write set), and any key or interval
    the overlay touches is judged UNCERTAIN — the observable version
    depends on writes that are still in flight — which suppresses both
    the certainly-passes and the certainly-fails verdicts for it.  A
    gap the overlay does not fully cover fails the guard and gets no
    early aborts for that block — never a wrong flag.

Consensus note: the final flag byte of a doomed tx is MVCC_READ_CONFLICT
(or PHANTOM_READ_CONFLICT for a doomed range) even when the skipped
signature gate would have said BAD_CREATOR_
SIGNATURE / ENDORSEMENT_POLICY_FAILURE — the tx is invalid either way,
but the byte feeds the commit hash, so `parallel_commit.early_abort`
must be configured uniformly across peers of a channel (README
"Parallel commit").
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Set, Tuple

from fabric_tpu.protocol import Envelope
from fabric_tpu.protocol.txflags import ValidationCode

from fabric_tpu.ledger.mvcc import _validate_range_query, parse_endorser_tx
from fabric_tpu.ledger.statedb import StateDB, UpdateBatch

from .graph import PendingOverlay


class EarlyAbortAnalyzer:
    """Bound to one channel's state DB; stateless across blocks.

    `overlay_source` (e.g. KVLedger.pending_overlay on a windowed
    ledger) supplies the in-flight write-set snapshot that lets dooming
    keep working while the savepoint lags mid-window; without one the
    analyzer falls back to the strict savepoint == block-1 guard."""

    def __init__(self, statedb: StateDB, channel_id: str = "",
                 overlay_source: Optional[
                     Callable[[], Optional[PendingOverlay]]] = None):
        self.statedb = statedb
        self.channel_id = channel_id
        self.overlay_source = overlay_source

    def doomed(self, block,
               overlay: Optional[PendingOverlay] = None
               ) -> Dict[int, ValidationCode]:
        """tx_num -> MVCC_READ_CONFLICT for txs that cannot win MVCC.
        Empty when the savepoint guard fails (see module docstring)."""
        db = self.statedb
        blk = int(block.header.number)
        if overlay is None and self.overlay_source is not None:
            try:
                overlay = self.overlay_source()
            except Exception:
                overlay = None
        # snapshot the overlay BEFORE reading the savepoint: retirement
        # applies a block and only then pops it, so a savepoint read
        # second can only have advanced — the overlay stays a superset
        # of the real gap and the guard stays conservative
        sp = db.savepoint
        sp = -1 if sp is None else sp
        if sp != blk - 1:
            if (overlay is None
                    or not overlay.covers(sp + 1, blk - 1)
                    or any(b >= blk for b in overlay.blocks)):
                return {}
        pending = overlay.keys if overlay is not None else frozenset()

        def pending_interval(ns2: str, start2: str, end2: str) -> bool:
            return (overlay is not None
                    and overlay.touches_interval(ns2, start2, end2))

        doomed: Dict[int, ValidationCode] = {}
        puts: Dict[Tuple[str, str], Set[Tuple[int, int]]] = {}
        deleted: Set[Tuple[str, str]] = set()
        touched_keys: Set[Tuple[str, str]] = set()  # puts ∪ deleted
        committed_memo: Dict[Tuple[str, str],
                             Optional[Tuple[int, int]]] = {}

        def committed(k: Tuple[str, str]) -> Optional[Tuple[int, int]]:
            if k not in committed_memo:
                vv = db.get(k[0], k[1])
                committed_memo[k] = (None if vv is None else
                                     (vv.version.block_num,
                                      vv.version.tx_num))
            return committed_memo[k]

        for tx_num, raw in enumerate(block.data):
            try:
                parsed = parse_endorser_tx(Envelope.deserialize(raw))
            except Exception:
                continue
            if parsed is None:
                continue
            _txid, rwset = parsed
            dead = False
            dead_code: Optional[ValidationCode] = None
            read_unc = False    # an earlier read COULD fail (code 11)
            range_unc = False   # an earlier range COULD fail (code 12)
            for ns_rw in rwset.ns_rwsets:
                ns = ns_rw.namespace
                for read in ns_rw.reads:
                    k = (ns, read.key)
                    if k in pending:
                        # an in-flight predecessor writes this key: the
                        # observable version depends on a write that has
                        # not landed — could pass, could fail first
                        read_unc = True
                        continue
                    v = read.version
                    vt = None if v is None else (v.block_num, v.tx_num)
                    touched = k in deleted or k in puts
                    if vt == committed(k) and not touched:
                        continue         # certainly passes
                    in_m = (vt == committed(k)
                            or (vt is None and k in deleted)
                            or (vt is not None and vt in puts.get(k, ())))
                    if in_m:
                        read_unc = True  # outcome depends on writers
                        continue
                    dead = True          # V ∉ M: certainly fails
                    if not range_unc:
                        dead_code = ValidationCode.MVCC_READ_CONFLICT
                    break
                if dead:
                    break
                for rq in ns_rw.range_queries:
                    start, end = rq.start_key, rq.end_key
                    if any(ns2 == ns and k2 >= start
                           and (not end or k2 < end)
                           for ns2, k2 in touched_keys):
                        range_unc = True  # interval touched: undecidable
                        continue
                    if pending_interval(ns, start, end):
                        range_unc = True  # in-flight write in interval
                        continue
                    # untouched interval: the oracle's merged range IS
                    # the committed range — replay decides the verdict
                    if _validate_range_query(db, UpdateBatch(), ns, rq):
                        continue         # certainly passes
                    dead = True
                    if not read_unc:
                        dead_code = ValidationCode.PHANTOM_READ_CONFLICT
                    break
                if dead:
                    break
            if dead:
                # dead_code None: certainly invalid but the first-failure
                # code is ambiguous (earlier uncertain check of the other
                # kind) — don't doom, but its writes still never land
                if dead_code is not None:
                    doomed[tx_num] = dead_code
                continue
            for ns_rw in rwset.ns_rwsets:
                ns = ns_rw.namespace
                for w in ns_rw.writes:
                    k = (ns, w.key)
                    touched_keys.add(k)
                    if w.is_delete:
                        deleted.add(k)
                    else:
                        puts.setdefault(k, set()).add((blk, tx_num))
        return doomed
