"""Per-block read/write-set conflict graph + wavefront leveling.

Two transactions conflict when they touch a common (ns, key) and at
least one of them writes it — ww, wr (an earlier write feeding a later
read), and rw (an earlier read that a later write must not overtake:
waves reorder execution across tx order, so a later tx's write may be
applied to the working batch before an earlier tx validates unless an
edge orders them).  Range queries are pinned conservatively to their
namespace key-interval [start_key, end_key) (end_key "" = unbounded):
any write landing inside the interval, before or after the querying tx,
gets an edge.

Edges only ever point from a lower tx_num to a higher one, so the graph
is a DAG by construction; `level[j] = 1 + max(level[preds])` partitions
the block into waves — every transaction in a wave is independent of
every other, and all of a transaction's conflicting predecessors sit in
strictly earlier waves.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set, Tuple

EDGE_KINDS = ("ww", "wr", "rw", "range")


@dataclass
class TxFootprint:
    """The MVCC-relevant key touches of one parsed endorser tx."""
    tx_num: int
    reads: Set[Tuple[str, str]] = field(default_factory=set)
    writes: Set[Tuple[str, str]] = field(default_factory=set)
    # (ns, start_key, end_key); end_key "" = unbounded
    ranges: List[Tuple[str, str, str]] = field(default_factory=list)


def footprint_of(tx_num: int, rwset) -> TxFootprint:
    fp = TxFootprint(tx_num)
    for ns_rw in rwset.ns_rwsets:
        ns = ns_rw.namespace
        for r in ns_rw.reads:
            fp.reads.add((ns, r.key))
        for w in ns_rw.writes:
            fp.writes.add((ns, w.key))
        for rq in ns_rw.range_queries:
            fp.ranges.append((ns, rq.start_key, rq.end_key))
    return fp


def _in_interval(key: str, start_key: str, end_key: str) -> bool:
    """Same interval semantics as mvcc._merged_range: [start, end),
    falsy end_key = scan to the end of the namespace."""
    return key >= start_key and (not end_key or key < end_key)


class ConflictGraph:
    """Built once per block from the participating tx footprints
    (block order).  Exposes `preds` (tx_num -> conflicting lower
    tx_nums), `waves` (lists of tx_nums, block-ordered within each
    wave), and per-kind deduplicated `edge_counts`."""

    def __init__(self, footprints: Sequence[TxFootprint]):
        self.preds: Dict[int, Set[int]] = {fp.tx_num: set()
                                           for fp in footprints}
        self.edge_counts: Dict[str, int] = {k: 0 for k in EDGE_KINDS}
        self._seen_pairs: Set[Tuple[int, int]] = set()
        self._build(footprints)
        self.waves: List[List[int]] = self._level(footprints)

    def _edge(self, a: int, b: int, kind: str) -> None:
        if a == b:
            return
        lo, hi = (a, b) if a < b else (b, a)
        if (lo, hi) in self._seen_pairs:
            return
        self._seen_pairs.add((lo, hi))
        self.preds[hi].add(lo)
        self.edge_counts[kind] += 1

    def _build(self, footprints: Sequence[TxFootprint]) -> None:
        # per-key chains: a writer links to the previous writer (ww) and
        # to every reader since it (rw); a reader links to the previous
        # writer (wr).  Transitivity through levels covers the rest.
        last_writer: Dict[Tuple[str, str], int] = {}
        readers_since: Dict[Tuple[str, str], List[int]] = {}
        all_writes: Dict[str, List[Tuple[str, int]]] = {}   # ns -> [(key, tx)]
        for fp in footprints:
            tx = fp.tx_num
            for k in fp.reads:
                if k not in fp.writes:        # read-write handled below
                    w = last_writer.get(k)
                    if w is not None:
                        self._edge(w, tx, "wr")
                    readers_since.setdefault(k, []).append(tx)
            for k in fp.writes:
                w = last_writer.get(k)
                if w is not None:
                    self._edge(w, tx, "ww" if k not in fp.reads else "wr")
                elif k in fp.reads:
                    pass                      # first toucher, no pred
                for r in readers_since.pop(k, ()):
                    self._edge(r, tx, "rw")
                last_writer[k] = tx
                all_writes.setdefault(k[0], []).append((k[1], tx))
        # range intervals vs every overlapping write, both directions
        for fp in footprints:
            for ns, start_key, end_key in fp.ranges:
                for key, wtx in all_writes.get(ns, ()):
                    if _in_interval(key, start_key, end_key):
                        self._edge(fp.tx_num, wtx, "range")

    def _level(self, footprints: Sequence[TxFootprint]) -> List[List[int]]:
        level: Dict[int, int] = {}
        by_level: Dict[int, List[int]] = {}
        for fp in footprints:                 # block order -> preds done
            lv = 1 + max((level[p] for p in self.preds[fp.tx_num]),
                         default=0)
            level[fp.tx_num] = lv
            by_level.setdefault(lv, []).append(fp.tx_num)
        return [by_level[lv] for lv in sorted(by_level)]

    @property
    def n_edges(self) -> int:
        return len(self._seen_pairs)

    @property
    def max_wave_width(self) -> int:
        return max((len(w) for w in self.waves), default=0)
