"""Per-block read/write-set conflict graph + wavefront leveling.

Two transactions conflict when they touch a common (ns, key) and at
least one of them writes it — ww, wr (an earlier write feeding a later
read), and rw (an earlier read that a later write must not overtake:
waves reorder execution across tx order, so a later tx's write may be
applied to the working batch before an earlier tx validates unless an
edge orders them).  Range queries are pinned conservatively to their
namespace key-interval [start_key, end_key) (end_key "" = unbounded):
any write landing inside the interval, before or after the querying tx,
gets an edge.

Edges only ever point from a lower tx_num to a higher one, so the graph
is a DAG by construction; `level[j] = 1 + max(level[preds])` partitions
the block into waves — every transaction in a wave is independent of
every other, and all of a transaction's conflicting predecessors sit in
strictly earlier waves.

Cross-block extension (the commit window): a `PendingOverlay` freezes
the write keys of blocks that are admitted to the pipelined commit
window but whose state apply has not landed yet.  Building block N+1's
graph against that overlay adds virtual edges from the pending blocks
into N+1: a pending write feeding one of N+1's reads (cross-block wr)
or falling inside one of its scanned intervals (cross-block range)
makes the tx's verdict depend on state that is still in flight, so the
tx — and transitively everything ordered after it — is DEFERRED until
the overlay retires.  Cross-block ww hits are counted but never defer:
retirement is strictly in order, so same-key writes serialize at apply
time regardless of when the later block validated.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, \
    Set, Tuple

EDGE_KINDS = ("ww", "wr", "rw", "range")
# cross-block edge kinds: an in-flight predecessor block's pending write
# vs this block's footprint (wr = feeds a read, range = lands inside a
# scanned interval — both defer; ww = same-key write, informational)
XBLOCK_KINDS = ("xww", "xwr", "xrange")


@dataclass
class TxFootprint:
    """The MVCC-relevant key touches of one parsed endorser tx."""
    tx_num: int
    reads: Set[Tuple[str, str]] = field(default_factory=set)
    writes: Set[Tuple[str, str]] = field(default_factory=set)
    # (ns, start_key, end_key); end_key "" = unbounded
    ranges: List[Tuple[str, str, str]] = field(default_factory=list)


def footprint_of(tx_num: int, rwset) -> TxFootprint:
    fp = TxFootprint(tx_num)
    for ns_rw in rwset.ns_rwsets:
        ns = ns_rw.namespace
        for r in ns_rw.reads:
            fp.reads.add((ns, r.key))
        for w in ns_rw.writes:
            fp.writes.add((ns, w.key))
        for rq in ns_rw.range_queries:
            fp.ranges.append((ns, rq.start_key, rq.end_key))
    return fp


def _in_interval(key: str, start_key: str, end_key: str) -> bool:
    """Same interval semantics as mvcc._merged_range: [start, end),
    falsy end_key = scan to the end of the namespace."""
    return key >= start_key and (not end_key or key < end_key)


class PendingOverlay:
    """Frozen write-key snapshot of the commit window's in-flight blocks.

    `blocks` are the block numbers admitted to the window whose state
    apply has not retired yet; `keys` is the UNION of their (ns, key)
    write sets, taken as a SUPERSET: every write of every tx still
    flagged valid at admit time is included, even ones that later lose
    MVCC — over-inclusion only defers more and is always safe, while a
    missed key could let a dependent tx validate against stale state.
    The snapshot is immutable: the window re-snapshots per admit."""

    __slots__ = ("blocks", "keys", "_by_ns")

    def __init__(self, blocks: Iterable[int],
                 keys: Iterable[Tuple[str, str]]):
        self.blocks: Tuple[int, ...] = tuple(sorted(int(b) for b in blocks))
        self.keys: FrozenSet[Tuple[str, str]] = frozenset(keys)
        by_ns: Dict[str, List[str]] = {}
        for ns, key in self.keys:
            by_ns.setdefault(ns, []).append(key)
        self._by_ns = {ns: sorted(ks) for ns, ks in by_ns.items()}

    @property
    def empty(self) -> bool:
        return not self.blocks

    def covers(self, lo: int, hi: int) -> bool:
        """True when every block of [lo, hi] is represented (the early-
        abort analyzer's guard: the gap between the state savepoint and
        the block under analysis must be exactly the in-flight set)."""
        return set(range(lo, hi + 1)) <= set(self.blocks)

    def touches_interval(self, ns: str, start_key: str,
                         end_key: str) -> bool:
        """Any pending write inside [start_key, end_key) of `ns`
        (mvcc._merged_range interval semantics)."""
        ks = self._by_ns.get(ns)
        if not ks:
            return False
        i = bisect.bisect_left(ks, start_key)
        return i < len(ks) and (not end_key or ks[i] < end_key)

    def conflicts(self, fp: TxFootprint) -> Optional[str]:
        """First DEFERRING cross-block hazard for `fp`, or None: "xwr"
        (a pending write feeds one of fp's reads — the observed version
        depends on whether/when the overlay lands) or "xrange" (a
        pending write lands inside a scanned interval — phantom verdict
        depends on the overlay).  Write-write overlap is NOT a deferral
        hazard — see module docstring."""
        for k in fp.reads:
            if k in self.keys:
                return "xwr"
        for ns, start_key, end_key in fp.ranges:
            if self.touches_interval(ns, start_key, end_key):
                return "xrange"
        return None

    def ww_hits(self, fp: TxFootprint) -> int:
        return sum(1 for k in fp.writes if k in self.keys)


class ConflictGraph:
    """Built once per block from the participating tx footprints
    (block order).  Exposes `preds` (tx_num -> conflicting lower
    tx_nums), `waves` (lists of tx_nums, block-ordered within each
    wave), and per-kind deduplicated `edge_counts`.

    With `overlay` set (pipelined commit window), also computes
    `deferred`: txs with a cross-block wr/range edge into the overlay,
    closed transitively over in-block successors — an early (non-
    deferred) tx therefore has ONLY early predecessors, so the early
    waves are a self-contained prefix projection that validates
    identically before or after the overlay's apply lands."""

    def __init__(self, footprints: Sequence[TxFootprint],
                 overlay: Optional[PendingOverlay] = None):
        self.preds: Dict[int, Set[int]] = {fp.tx_num: set()
                                           for fp in footprints}
        self.edge_counts: Dict[str, int] = {k: 0 for k in EDGE_KINDS}
        self.xblock_counts: Dict[str, int] = {k: 0 for k in XBLOCK_KINDS}
        self.deferred: Set[int] = set()
        self._seen_pairs: Set[Tuple[int, int]] = set()
        self._build(footprints)
        self.waves: List[List[int]] = self._level(footprints)
        if overlay is not None and not overlay.empty:
            self._cross_block(footprints, overlay)

    def _cross_block(self, footprints: Sequence[TxFootprint],
                     overlay: PendingOverlay) -> None:
        # direct hits, then transitive closure over preds: footprints
        # arrive in block order and edges point low -> high, so one
        # forward pass resolves every predecessor before its successors
        for fp in footprints:
            kind = overlay.conflicts(fp)
            if kind is not None:
                self.xblock_counts[kind] += 1
                self.deferred.add(fp.tx_num)
            ww = overlay.ww_hits(fp)
            if ww:
                self.xblock_counts["xww"] += ww
        for fp in footprints:
            if fp.tx_num in self.deferred:
                continue
            if any(p in self.deferred for p in self.preds[fp.tx_num]):
                self.deferred.add(fp.tx_num)

    def split_waves(self) -> Tuple[List[List[int]], List[List[int]]]:
        """(early_waves, deferred_waves), each preserving wave level
        order and in-wave block order.  Early waves may validate while
        the overlay's apply is still in flight; deferred waves run only
        after every in-flight predecessor block retires."""
        early: List[List[int]] = []
        late: List[List[int]] = []
        for wave in self.waves:
            e = [t for t in wave if t not in self.deferred]
            d = [t for t in wave if t in self.deferred]
            if e:
                early.append(e)
            if d:
                late.append(d)
        return early, late

    def _edge(self, a: int, b: int, kind: str) -> None:
        if a == b:
            return
        lo, hi = (a, b) if a < b else (b, a)
        if (lo, hi) in self._seen_pairs:
            return
        self._seen_pairs.add((lo, hi))
        self.preds[hi].add(lo)
        self.edge_counts[kind] += 1

    def _build(self, footprints: Sequence[TxFootprint]) -> None:
        # per-key chains: a writer links to the previous writer (ww) and
        # to every reader since it (rw); a reader links to the previous
        # writer (wr).  Transitivity through levels covers the rest.
        last_writer: Dict[Tuple[str, str], int] = {}
        readers_since: Dict[Tuple[str, str], List[int]] = {}
        all_writes: Dict[str, List[Tuple[str, int]]] = {}   # ns -> [(key, tx)]
        for fp in footprints:
            tx = fp.tx_num
            for k in fp.reads:
                if k not in fp.writes:        # read-write handled below
                    w = last_writer.get(k)
                    if w is not None:
                        self._edge(w, tx, "wr")
                    readers_since.setdefault(k, []).append(tx)
            for k in fp.writes:
                w = last_writer.get(k)
                if w is not None:
                    self._edge(w, tx, "ww" if k not in fp.reads else "wr")
                elif k in fp.reads:
                    pass                      # first toucher, no pred
                for r in readers_since.pop(k, ()):
                    self._edge(r, tx, "rw")
                last_writer[k] = tx
                all_writes.setdefault(k[0], []).append((k[1], tx))
        # range intervals vs every overlapping write, both directions
        for fp in footprints:
            for ns, start_key, end_key in fp.ranges:
                for key, wtx in all_writes.get(ns, ()):
                    if _in_interval(key, start_key, end_key):
                        self._edge(fp.tx_num, wtx, "range")

    def _level(self, footprints: Sequence[TxFootprint]) -> List[List[int]]:
        level: Dict[int, int] = {}
        by_level: Dict[int, List[int]] = {}
        for fp in footprints:                 # block order -> preds done
            lv = 1 + max((level[p] for p in self.preds[fp.tx_num]),
                         default=0)
            level[fp.tx_num] = lv
            by_level.setdefault(lv, []).append(fp.tx_num)
        return [by_level[lv] for lv in sorted(by_level)]

    @property
    def n_edges(self) -> int:
        return len(self._seen_pairs)

    @property
    def max_wave_width(self) -> int:
        return max((len(w) for w in self.waves), default=0)
