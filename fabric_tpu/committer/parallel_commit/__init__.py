"""Parallel MVCC commit plane.

The serial block-ordered MVCC walk (fabric_tpu/ledger/mvcc.py) stays the
oracle; this package replaces it at commit time with a dependency-graph
scheduler that validates non-conflicting transactions concurrently while
preserving bit-identical flags, update batch, and history writes — plus
an early-abort analyzer the txvalidator consults to skip device dispatch
for transactions that are already doomed by a preceding same-block write.
"""

from .earlyabort import EarlyAbortAnalyzer
from .graph import ConflictGraph, PendingOverlay, TxFootprint, footprint_of
from .scheduler import CommitWindow, ParallelCommitScheduler, WindowEntry

__all__ = ["ConflictGraph", "PendingOverlay", "TxFootprint",
           "footprint_of", "ParallelCommitScheduler", "CommitWindow",
           "WindowEntry", "EarlyAbortAnalyzer"]
