"""Wavefront-parallel MVCC validation + batched prepare.

Drop-in replacement for `mvcc.validate_and_prepare_batch` (same
signature, same mutation contract on `flags`, same return value — the
differential tests in tests/test_parallel_commit.py hold it to
bit-identity against the serial oracle):

  1. parse every still-valid tx once (BAD_RWSET parity with the oracle's
     lazy walk — parsing is state-independent, so hoisting it is exact);
  2. build the block's conflict graph and partition it into waves
     (graph.py): every tx's conflicting predecessors sit in strictly
     earlier waves;
  3. validate each wave's txs concurrently against the shared working
     batch — the batch is only ever mutated BETWEEN waves (valid writes
     applied in tx order), so wave workers see a frozen snapshot that,
     for the keys and ranges in their own footprint, is exactly the
     state the serial walk would have shown them;
  4. rebuild the returned UpdateBatch + history list in strict tx order
     from the per-tx write lists, so even dict insertion order matches
     the oracle's output literally.

Thread safety: wave workers only call UpdateBatch.get / .items() and
StateDB reads (lock-guarded); TxFlags is written by the coordinating
thread only.
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from fabric_tpu.protocol import Version
from fabric_tpu.protocol.txflags import TxFlags, ValidationCode

from fabric_tpu.ledger.mvcc import (
    _validate_range_query,
    _validate_read,
    parse_endorser_tx,
)
from fabric_tpu.ledger.statedb import StateDB, UpdateBatch

from .graph import ConflictGraph, footprint_of

_WIDTH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                  1024.0, float("inf"))


def _validate_tx(db: StateDB, batch: UpdateBatch, rwset) -> Optional[int]:
    """One tx's MVCC check against a frozen batch — the exact walk order
    of the oracle's inner loop (per ns_rw: reads, then range queries;
    first failure decides the code)."""
    for ns_rw in rwset.ns_rwsets:
        ns = ns_rw.namespace
        for read in ns_rw.reads:
            if not _validate_read(db, batch, ns, read):
                return int(ValidationCode.MVCC_READ_CONFLICT)
        for rq in ns_rw.range_queries:
            if not _validate_range_query(db, batch, ns, rq):
                return int(ValidationCode.PHANTOM_READ_CONFLICT)
    return None


class ParallelCommitScheduler:
    """One per ledger (channel); owns the worker pool.

    Pool sizing is adaptive: `max_workers` is the static OVERRIDE CAP,
    and the pool actually provisioned tracks the rolling maximum of the
    observed conflict-graph wave widths (workers beyond the widest wave
    can never have work).  Low-contention channels whose blocks fan out
    wide grow toward the cap; serial workloads (chained writes, single
    hot key) idle at a one-thread pool instead of parking cap-1 threads
    per channel.  `adaptive=False` pins the pool at the cap (the
    pre-adaptive behavior)."""

    def __init__(self, max_workers: int = 4, channel_id: str = "",
                 adaptive: bool = True, width_window: int = 32):
        self.max_workers = max(1, int(max_workers))
        self.channel_id = channel_id
        self.adaptive = bool(adaptive)
        # rolling window of per-block max wave widths (the demand signal)
        self._widths: deque = deque(maxlen=max(1, int(width_window)))
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_size = 0
        # last-block stats, surfaced by the committer
        self.last_waves = 0
        self.last_edges = 0
        self.last_max_width = 0

    def target_workers(self, width: int) -> int:
        """Worker count for a block whose widest wave is `width`: the
        rolling demand maximum, clamped to [1, max_workers]."""
        self._widths.append(int(width))
        if not self.adaptive:
            return self.max_workers
        return max(1, min(self.max_workers, max(self._widths)))

    def _executor(self, workers: int) -> ThreadPoolExecutor:
        if self._pool is not None and self._pool_size != workers:
            # ThreadPoolExecutor cannot resize: swap pools.  The rolling
            # window damps churn — shrink happens only after width_window
            # consecutive narrower blocks age the wide ones out.
            self._pool.shutdown(wait=False)
            self._pool = None
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=workers,
                thread_name_prefix=f"mvcc-{self.channel_id}")
            self._pool_size = workers
            try:
                from fabric_tpu.ops_plane import registry
                registry.gauge(
                    "commit_workers_effective",
                    "adaptive MVCC pool size (cap: commit_workers)").set(
                        workers, channel=self.channel_id)
            except Exception:
                pass
        return self._pool

    def close(self) -> None:
        pool, self._pool = self._pool, None
        self._pool_size = 0
        if pool is not None:
            pool.shutdown(wait=False)

    # -- the entry point (signature-compatible with the serial oracle) ------

    def validate_and_prepare_batch(
            self, db: StateDB, block_num: int, envelopes, flags: TxFlags,
    ) -> Tuple[UpdateBatch, List[Tuple[int, str, str, str, bytes, bool]]]:
        from fabric_tpu.ops_plane import tracing

        # pass 0: parse still-valid txs once (oracle's lazy-parse parity)
        parsed: List[Tuple[int, str, object, list]] = []
        for tx_num, env in enumerate(envelopes):
            if env is None or not flags.is_valid(tx_num):
                continue
            try:
                p = parse_endorser_tx(env)
            except Exception:
                flags.set(tx_num, ValidationCode.BAD_RWSET)
                continue
            if p is None:
                continue                    # config txs etc.
            txid, rwset = p
            writes = [(ns_rw.namespace, w.key, w.value, w.is_delete)
                      for ns_rw in rwset.ns_rwsets for w in ns_rw.writes]
            parsed.append((tx_num, txid, rwset, writes))

        t0 = time.perf_counter()
        graph = ConflictGraph(
            [footprint_of(tx_num, rwset)
             for tx_num, _txid, rwset, _w in parsed])
        t1 = time.perf_counter()
        tracing.tracer.record_span(
            "mvcc.graph", t0, t1,
            attributes={"block": int(block_num), "txs": len(parsed),
                        "edges": graph.n_edges,
                        "waves": len(graph.waves)})

        by_tx = {tx_num: (txid, rwset, writes)
                 for tx_num, txid, rwset, writes in parsed}
        working = UpdateBatch()
        valid: Dict[int, bool] = {}
        workers = self.target_workers(graph.max_wave_width)
        pool = (self._executor(workers)
                if workers > 1 and graph.max_wave_width > 1
                else None)
        for wave in graph.waves:
            tw = time.perf_counter()
            if pool is not None and len(wave) > 1:
                codes = list(pool.map(
                    lambda tx: _validate_tx(db, working, by_tx[tx][1]),
                    wave))
            else:
                codes = [_validate_tx(db, working, by_tx[tx][1])
                         for tx in wave]
            # apply this wave's outcomes in tx order, between waves only
            for tx, code in zip(wave, codes):
                if code is not None:
                    flags.set(tx, ValidationCode(code))
                    valid[tx] = False
                    continue
                valid[tx] = True
                version = Version(block_num, tx)
                for ns, key, value, is_delete in by_tx[tx][2]:
                    if is_delete:
                        working.delete(ns, key, version)
                    else:
                        working.put(ns, key, value, version)
            tracing.tracer.record_span(
                "mvcc.wave", tw, time.perf_counter(),
                attributes={"block": int(block_num), "width": len(wave)})

        # final batch + history rebuilt in strict tx order: literal
        # (insertion-order included) identity with the serial oracle
        batch = UpdateBatch()
        history: List[Tuple[int, str, str, str, bytes, bool]] = []
        for tx_num, txid, _rwset, writes in parsed:
            if not valid.get(tx_num, False):
                continue
            version = Version(block_num, tx_num)
            for ns, key, value, is_delete in writes:
                if is_delete:
                    batch.delete(ns, key, version)
                else:
                    batch.put(ns, key, value, version)
                history.append((tx_num, txid, ns, key, value, is_delete))

        self.last_waves = len(graph.waves)
        self.last_edges = graph.n_edges
        self.last_max_width = graph.max_wave_width
        self._observe(graph)
        # pre-split the batch by state shard here, off the ledger's
        # commit lock path — apply_updates consumes the cached split
        batch.preshard(getattr(db, "n_shards", 1))
        return batch, history

    def _observe(self, graph: ConflictGraph) -> None:
        try:
            from fabric_tpu.ops_plane import registry
            ch = self.channel_id
            edges = registry.counter(
                "commit_graph_edges_total",
                "MVCC conflict-graph edges by kind")
            for kind, n in graph.edge_counts.items():
                if n:
                    edges.add(n, kind=kind, channel=ch)
            registry.counter(
                "commit_graph_waves_total",
                "MVCC wavefront count").add(len(graph.waves), channel=ch)
            width = registry.histogram(
                "commit_graph_wave_width",
                "txs per MVCC validation wave", buckets=_WIDTH_BUCKETS)
            for wave in graph.waves:
                width.observe(float(len(wave)), channel=ch)
        except Exception:
            pass
